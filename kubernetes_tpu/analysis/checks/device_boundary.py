"""Device-boundary checks on the interprocedural dataflow engine.

Five checks over analysis/dataflow.py's taint lattice + call graph — the
machine-checkable form of the bug class PR 4 kept rediscovering by hand
(mid-window recompiles from lazy table growth, accidental device→host
syncs on the cycle path, impure traced closures):

  host-sync          a tainted (device) value concretized on host —
                     ``bool()/int()/float()``, ``np.asarray``, ``.item()``,
                     iteration, or branching — outside an explicit
                     ``block_until_ready``/fetch site
  vmap-purity        functions reachable from vmap/jit/shard_map call
                     sites that mutate captured state, write globals, do
                     I/O, or call a known-impure function
  donation-aliasing  donated jit arguments re-used after the call, and
                     jitted-program builders rebuilt per call across
                     module boundaries (PR 2's uncached-builder rule,
                     interprocedural)
  shape-drift        device arrays whose shape derives from a Python
                     ``len()``/container size inside a loop — the lazy-
                     growth recompile hazard (pow2_round_up-bucketized
                     shapes are exempt: that IS the mitigation)
  blocking-in-cycle  any call-graph path from the scheduling cycle to a
                     synchronous fetch not routed through the packed
                     decision-fetch

Deliberate device→host crossings are enumerated in FETCH_BOUNDARIES below
(reviewable config, the analog of trace_safety.TRACED_SEEDS) — NOT
inline-suppressed: the acceptance contract is that hot-cycle modules are
clean with zero suppressions.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Finding, ModuleInfo, Project, dotted_name
from ..dataflow import DataflowAnalysis, FunctionNode, analysis_for
from ..registry import Check, register_check
from .recompile_hazard import RecompileHazardCheck
from .trace_safety import _close_over_calls, _jit_roots, _seeded

# --- sanctioned fetch sites --------------------------------------------------
# (path suffix, qualname, why this function is ALLOWED to cross the device
# boundary).  A function listed here — and anything nested inside it — is
# an explicit fetch site: host-sync skips it, and blocking-in-cycle's
# reachability does not traverse INTO it.  Keep each entry justified; this
# list is the design's fetch surface, so growth here is a review event the
# same way a suppression is.
FETCH_BOUNDARIES: Tuple[Tuple[str, str, str], ...] = (
    ("scheduler.py", "TPUScheduler._dispatch_batch_traced._bg_fetch",
     "THE packed decision-fetch: the background thread that owns the "
     "device→host round so the cycle never blocks on it (the body lives "
     "in _dispatch_batch_traced since the round-14 span failure guard "
     "split _dispatch_batch)"),
    ("scheduler.py", "TPUScheduler._complete",
     "decision-fetch join: normally consumes the background fetch's host "
     "copy; the blocking fallback is the documented degraded path"),
    ("scheduler.py", "TPUScheduler._bind_phase",
     "runs AFTER decisions are host-side; its failure-diagnosis fetch is "
     "one sync per FAILING batch by design (not fused into every cycle)"),
    ("scheduler.py", "TPUScheduler._assign_with_extenders",
     "round-based extender protocol: each round's packed mask+scores "
     "fetch IS the callout input — synchronous by contract"),
    ("scheduler.py", "TPUScheduler._run_post_filter",
     "preemption post-filter for a failed pod — off the dispatch "
     "critical path, one fetch per preemption attempt"),
    ("scheduler.py", "TPUScheduler._try_nominated_fast_bind",
     "nominated-node fast path re-check: single-pod feasibility fetch "
     "after a preemption nomination, not in the batched cycle"),
    ("scheduler.py", "TPUScheduler._diagnose",
     "per-pod failure diagnosis (unschedulable reporting) — explicitly "
     "the slow path"),
    ("whatif/engine.py", "WhatIfEngine.evaluate",
     "the counterfactual solve's single result fetch; controllers "
     "consume host-side Predictions"),
    ("whatif/dryrun.py", "sweep_and_rank",
     "preemption dry-run fan-out: ranks candidate sets on host from one "
     "batched device sweep — the fetch is the API"),
    ("preemption.py", "",
     "preemption orchestration is host-side triage of fetched "
     "candidates; its device work goes through whatif/dryrun"),
)

SYNC_METHODS = {"item", "tolist"}
IMPURE_HEADS = {"time", "random"}
IO_CALLS = {"print", "open", "input"}
IO_HEADS = {"klog", "logging", "warnings"}
# shape constructors whose first/`shape=` argument is a (re)compile key
SHAPE_CTORS = {"zeros", "ones", "full", "empty", "arange"}
# shape bucketing helpers — routing a len() through one of these is the
# FIX for shape drift, not an instance of it
POW2_HELPERS = {"pow2_round_up", "_pow2"}


def _boundary_quals(mod: ModuleInfo) -> Set[str]:
    out: Set[str] = set()
    for suffix, qual, _why in FETCH_BOUNDARIES:
        if not mod.path.endswith(suffix):
            continue
        if qual == "":
            out.update(mod.functions)
        else:
            out.update(q for q in mod.functions
                       if q == qual or q.startswith(qual + "."))
    return out


def _traced_quals(mod: ModuleInfo) -> Set[str]:
    """Same-module traced closure (trace_safety's definition): these run
    under trace, where host-sync is trace-safety's business, not ours."""
    roots = _jit_roots(mod) | _seeded(mod)
    return _close_over_calls(mod, roots) if roots else set()


def _block_until_ready_names(fn_node: ast.AST) -> Set[str]:
    """Names explicitly synchronized via jax.block_until_ready within the
    function: subsequent host reads of them are explicit fetch sites."""
    out: Set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call) and \
                dotted_name(node.func).endswith("block_until_ready"):
            for a in node.args:
                if isinstance(a, ast.Name):
                    out.add(a.id)
    return out


# --- host-sync ---------------------------------------------------------------


@register_check
class HostSyncCheck(Check):
    name = "host-sync"
    description = ("device values concretized on host (bool/int/float/"
                   "np.asarray/.item()/iteration/branch) outside an "
                   "explicit block_until_ready/fetch site")

    def run(self, project: Project) -> Iterable[Finding]:
        dfa = analysis_for(project)
        findings: List[Finding] = []
        for mod in project.modules:
            boundaries = _boundary_quals(mod)
            traced = _traced_quals(mod)
            table = dfa.imports.get(mod.path)
            np_aliases = table.np_aliases() if table else set()
            for (path, qual), fn in dfa.functions.items():
                if path != mod.path:
                    continue
                if qual in traced or any(
                        qual == b or qual.startswith(b + ".")
                        for b in boundaries):
                    continue
                findings.extend(
                    self._scan(dfa, fn, np_aliases))
        return findings

    def _scan(self, dfa: DataflowAnalysis, fn: FunctionNode,
              np_aliases: Set[str]) -> Iterable[Finding]:
        mod, qual = fn.mod, fn.qual
        fetched = _block_until_ready_names(fn.node)

        def tainted(e: ast.AST) -> bool:
            if isinstance(e, ast.Name) and e.id in fetched:
                return False  # explicitly synchronized upstream
            return dfa.expr_device(fn, e)

        for node in ast.walk(fn.node):
            if mod.scope_of(node) != qual:
                continue
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                head = name.split(".")[0] if name else ""
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in SYNC_METHODS and \
                        tainted(node.func.value):
                    yield mod.finding(
                        self.name, "sync-method", node,
                        f".{node.func.attr}() on a device value in "
                        f"`{qual}` forces a device→host sync outside any "
                        f"fetch site")
                elif head in np_aliases and \
                        name.rsplit(".", 1)[-1] in ("asarray", "array") \
                        and node.args and tainted(node.args[0]):
                    yield mod.finding(
                        self.name, "implicit-transfer", node,
                        f"{name}(...) on a device value in `{qual}` is a "
                        f"hidden blocking transfer — fetch at a "
                        f"sanctioned fetch site or keep the value on "
                        f"device")
                elif name in ("bool", "int", "float") and node.args and \
                        tainted(node.args[0]):
                    yield mod.finding(
                        self.name, "concretize", node,
                        f"{name}(...) on a device value in `{qual}` "
                        f"blocks on the device — hoist the fetch to an "
                        f"explicit site")
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if tainted(node.iter):
                    yield mod.finding(
                        self.name, "iterate-device", node.iter,
                        f"iterating a device array in `{qual}` syncs one "
                        f"element per step — fetch once, then iterate "
                        f"the host copy")
            elif isinstance(node, (ast.If, ast.While)):
                if tainted(node.test):
                    yield mod.finding(
                        self.name, "branch-on-device", node.test,
                        f"branching on a device value in `{qual}` forces "
                        f"a sync at the branch — fetch explicitly or "
                        f"fold the predicate into the program")
            elif isinstance(node, ast.comprehension):
                if tainted(node.iter):
                    yield mod.finding(
                        self.name, "iterate-device", node.iter,
                        f"comprehension over a device array in `{qual}` "
                        f"syncs per element — fetch once first")


# --- vmap-purity -------------------------------------------------------------


def _transform_roots(dfa: DataflowAnalysis) -> Set[Tuple[str, str]]:
    """(path, qual) of every function passed to vmap/jit/shard_map/pmap —
    including functools.partial-wrapped and aliased forms — project-wide."""
    wrap_names = {"jax.jit", "jit", "jax.vmap", "vmap", "shard_map",
                  "jax.pmap", "pmap"}
    roots: Set[Tuple[str, str]] = set()

    def unwrap(e: ast.AST) -> Optional[ast.AST]:
        # functools.partial(f, ...) → f
        if isinstance(e, ast.Call) and \
                dotted_name(e.func).rsplit(".", 1)[-1] == "partial" and \
                e.args:
            return e.args[0]
        return e

    # decorator forms: @jax.jit / @partial(jax.jit, ...) / @alias
    for (path, qual), fn in dfa.functions.items():
        for dec in getattr(fn.node, "decorator_list", ()):
            target = dec.func if isinstance(dec, ast.Call) else dec
            names = {dotted_name(target)}
            if isinstance(dec, ast.Call):
                names |= {dotted_name(a) for a in dec.args}
            if names & wrap_names:
                roots.add((path, qual))
    # call forms, ANYWHERE in the module (incl. module-level program
    # tables): jax.vmap(f) / jit(partial(f, ...)) / partial(jax.jit,
    # **opts)(f) / jax.jit(alias_of_f)
    for mod in dfa.project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func_name = dotted_name(node.func)
            is_wrap = func_name in wrap_names
            if not is_wrap and isinstance(node.func, ast.Call):
                # partial(jax.jit, **opts)(f)
                inner = dotted_name(node.func.func)
                if inner.rsplit(".", 1)[-1] == "partial" and any(
                        dotted_name(a) in wrap_names
                        for a in node.func.args):
                    is_wrap = True
            if not is_wrap or not node.args:
                continue
            qual = mod.scope_of(node)
            arg = unwrap(node.args[0])
            if isinstance(arg, ast.Lambda):
                roots.add((mod.path, mod.scope_of(arg)))
            elif arg is not None:
                fake = ast.Call(func=arg, args=[], keywords=[])
                for key in dfa.resolve_call(mod, qual, fake):
                    roots.add(key)
                if isinstance(arg, ast.Name):
                    # alias: g = f; jax.jit(g) — resolve through a
                    # straight rebind in the enclosing function
                    host = dfa.functions.get((mod.path, qual))
                    if host is not None:
                        tgt = _alias_target(mod, host, arg.id)
                        if tgt is not None:
                            fake = ast.Call(func=tgt, args=[], keywords=[])
                            for key in dfa.resolve_call(mod, qual, fake):
                                roots.add(key)
    return roots


def _alias_target(mod: ModuleInfo, fn: FunctionNode,
                  name: str) -> Optional[ast.AST]:
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == name and \
                isinstance(node.value, (ast.Name, ast.Attribute)):
            return node.value
    return None


@register_check
class VmapPurityCheck(Check):
    name = "vmap-purity"
    description = ("captured-state mutation, global writes, I/O, and "
                   "impure calls in functions reachable from "
                   "vmap/jit/shard_map call sites (interprocedural)")

    def run(self, project: Project) -> Iterable[Finding]:
        dfa = analysis_for(project)
        roots = _transform_roots(dfa)
        traced = dfa.reachable_from(roots)
        findings: List[Finding] = []
        for key in sorted(traced):
            fn = dfa.functions.get(key)
            if fn is not None:
                findings.extend(self._scan(dfa, fn))
        return findings

    def _scan(self, dfa: DataflowAnalysis,
              fn: FunctionNode) -> Iterable[Finding]:
        mod, qual = fn.mod, fn.qual
        locals_: Set[str] = set(fn.params)
        for node in ast.walk(fn.node):
            if mod.scope_of(node) != qual:
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    # only BARE name targets bind locals — a name reached
                    # through a subscript/attribute target is the mutated
                    # container itself, not a new binding
                    if isinstance(t, ast.Name):
                        locals_.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        for e in t.elts:
                            if isinstance(e, ast.Starred):
                                e = e.value
                            if isinstance(e, ast.Name):
                                locals_.add(e.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        locals_.add(n.id)
            elif isinstance(node, ast.comprehension):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        locals_.add(n.id)
        params = set(fn.params)
        for node in ast.walk(fn.node):
            if mod.scope_of(node) != qual:
                continue
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                yield mod.finding(
                    self.name, "global-write", node,
                    f"`{qual}` is traced (reachable from a vmap/jit call "
                    f"site) but declares "
                    f"{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                    f" state — the write happens once at trace time")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Attribute):
                        root = base
                        while isinstance(root, ast.Attribute):
                            root = root.value
                        if isinstance(root, ast.Name):
                            yield mod.finding(
                                self.name, "captured-mutation", t,
                                f"`{qual}` is traced but mutates "
                                f"`{dotted_name(base)}` — object state "
                                f"written under trace is applied once at "
                                f"trace time, then silently never again")
                    elif isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id not in locals_ and \
                            t.value.id not in params:
                        yield mod.finding(
                            self.name, "captured-mutation", t,
                            f"`{qual}` is traced but writes into captured "
                            f"container `{t.value.id}` — a trace-time "
                            f"side effect invisible to later calls")
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                head = name.split(".")[0] if name else ""
                if name in IO_CALLS or head in IO_HEADS:
                    yield mod.finding(
                        self.name, "io", node,
                        f"{name}(...) in traced `{qual}` runs only at "
                        f"trace time — I/O under vmap/jit never fires "
                        f"per call")
                elif head in IMPURE_HEADS and not name.startswith(
                        ("jax.random", "random_")):
                    yield mod.finding(
                        self.name, "impure-call", node,
                        f"{name}() in traced `{qual}` executes once at "
                        f"trace time and bakes that value into the "
                        f"compiled program")


# --- donation-aliasing -------------------------------------------------------


def _donate_positions(call: ast.Call) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
    return ()


@register_check
class DonationAliasingCheck(Check):
    name = "donation-aliasing"
    description = ("donated jit arguments re-used after the call; jitted "
                   "program builders invoked uncached across module "
                   "boundaries")

    def run(self, project: Project) -> Iterable[Finding]:
        dfa = analysis_for(project)
        findings: List[Finding] = []
        for mod in project.modules:
            findings.extend(self._scan_donation(mod))
        findings.extend(self._scan_cross_module_builders(dfa))
        return findings

    def _scan_donation(self, mod: ModuleInfo) -> Iterable[Finding]:
        # local name → donated positions, per enclosing function
        for qual, fn in mod.functions.items():
            donated: Dict[str, Tuple[int, ...]] = {}
            for node in ast.walk(fn):
                if mod.scope_of(node) != qual:
                    continue
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        dotted_name(node.value.func) in ("jax.jit", "jit"):
                    pos = _donate_positions(node.value)
                    if pos and isinstance(node.targets[0], ast.Name):
                        donated[node.targets[0].id] = pos
            if not donated:
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in donated):
                    continue
                # a read is "after the call" only past the call's LAST
                # line, and never a node of the call itself — a donated
                # argument formatted onto its own line must not read as
                # its own use-after-donate
                call_nodes = {id(n) for n in ast.walk(node)}
                call_end = getattr(node, "end_lineno", node.lineno)
                for pos in donated[node.func.id]:
                    if pos >= len(node.args) or not isinstance(
                            node.args[pos], ast.Name):
                        continue
                    arg = node.args[pos].id
                    for later in ast.walk(fn):
                        if isinstance(later, ast.Name) and \
                                later.id == arg and \
                                id(later) not in call_nodes and \
                                isinstance(later.ctx, ast.Load) and \
                                later.lineno > call_end:
                            yield mod.finding(
                                self.name, "donated-reuse", later,
                                f"`{arg}` was donated to "
                                f"`{node.func.id}` (donate_argnums) at "
                                f"line {node.lineno} — its buffer may "
                                f"already be aliased; this read is "
                                f"use-after-donate")
                            break

    def _scan_cross_module_builders(
            self, dfa: DataflowAnalysis) -> Iterable[Finding]:
        """PR 2's uncached-builder rule, across module boundaries: a
        function in module A that builds-and-returns jit programs, called
        from module B without an init-time cache.  Builders that memoize
        INTO self state before returning are their own cache — exempt."""
        builders: Dict[Tuple[str, str], str] = {}
        for (path, qual), fn in dfa.functions.items():
            mod = fn.mod
            jit_locals: Set[str] = set()
            escapes = False
            for node in ast.walk(fn.node):
                if not (isinstance(node, ast.Call) and
                        dotted_name(node.func) in ("jax.jit", "jit")):
                    continue
                if mod.scope_of(node) != qual:
                    continue
                if RecompileHazardCheck._escapes_via_return(
                        mod, node, fn.node):
                    escapes = True
                parent = mod.parents.get(node)
                # track locals holding the jit result or a container of it
                while isinstance(parent, (ast.Dict, ast.List, ast.Tuple)):
                    parent = mod.parents.get(parent)
                if isinstance(parent, ast.Assign) and \
                        isinstance(parent.targets[0], ast.Name):
                    jit_locals.add(parent.targets[0].id)
            if not escapes:
                continue
            self_caching = False
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and mod.scope_of(
                        node) == qual:
                    tgt = node.targets[0]
                    base = tgt
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Attribute) and any(
                            isinstance(n, ast.Name) and n.id in jit_locals
                            for n in ast.walk(node.value)):
                        self_caching = True
            if not self_caching:
                builders[(path, qual)] = qual.rsplit(".", 1)[-1]
        if not builders:
            return
        for (cpath, cqual), fn in dfa.functions.items():
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call) or \
                        fn.mod.scope_of(node) != cqual:
                    continue
                for key in dfa.resolve_call(fn.mod, cqual, node):
                    if key not in builders or key[0] == cpath:
                        continue  # same-module sites are PR 2's check
                    if not RecompileHazardCheck._cached_at_init(
                            fn.mod, node):
                        yield fn.mod.finding(
                            self.name, "uncached-builder", node,
                            f"`{builders[key]}` (defined in {key[0]}) "
                            f"builds jax.jit programs; this cross-module "
                            f"call site does not cache the result at "
                            f"init — every call compiles fresh "
                            f"executables")


# --- shape-drift -------------------------------------------------------------


def _contains_len(expr: ast.AST) -> Optional[ast.Call]:
    """The len()/size-derived subexpression, skipping pow2-bucketized
    ones (routing through pow2_round_up IS the mitigation)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func).rsplit(".", 1)[-1]
            if name in POW2_HELPERS:
                return None  # bucketized: exempt the whole expression
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and \
                dotted_name(node.func) == "len":
            return node
    return None


@register_check
class ShapeDriftCheck(Check):
    name = "shape-drift"
    description = ("device arrays shaped by a Python len()/container "
                   "size inside a loop — every growth step recompiles "
                   "(bucketize via pow2_round_up)")

    def run(self, project: Project) -> Iterable[Finding]:
        dfa = analysis_for(project)
        findings: List[Finding] = []
        for mod in project.modules:
            table = dfa.imports.get(mod.path)
            aliases = (table.jnp_aliases() | table.np_aliases()) \
                if table else {"jnp"}
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                head, _, tail = name.partition(".")
                if head not in aliases or tail not in SHAPE_CTORS:
                    continue
                if not self._in_loop(mod, node):
                    continue
                shape_args: List[ast.AST] = list(node.args[:1])
                shape_args += [kw.value for kw in node.keywords
                               if kw.arg == "shape"]
                for arg in shape_args:
                    ln = _contains_len(arg)
                    if ln is not None:
                        findings.append(mod.finding(
                            self.name, "loop-grown-shape", node,
                            f"{name}(...) inside a loop takes its shape "
                            f"from len(...) — each growth step is a new "
                            f"compile key (the lazy-table mid-window "
                            f"recompile); bucketize with pow2_round_up "
                            f"or hoist the allocation"))
                        break
        return findings

    @staticmethod
    def _in_loop(mod: ModuleInfo, node: ast.AST) -> bool:
        for a in mod.ancestors(node):
            if isinstance(a, (ast.For, ast.While)):
                return True
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return False


# --- blocking-in-cycle -------------------------------------------------------

# roots: the hot scheduling cycle (the DEEP pipeline lives inside it)
CYCLE_ROOTS: Tuple[Tuple[str, str], ...] = (
    ("scheduler.py", "TPUScheduler.schedule_cycle"),
    ("scheduler.py", "TPUScheduler.run_until_idle"),
)


@register_check
class BlockingInCycleCheck(Check):
    name = "blocking-in-cycle"
    description = ("synchronous device fetches reachable from the "
                   "scheduling cycle outside the packed decision-fetch "
                   "boundaries")

    def run(self, project: Project) -> Iterable[Finding]:
        dfa = analysis_for(project)
        roots = []
        for suffix, qual in CYCLE_ROOTS:
            key = dfa.find_function(suffix, qual)
            if key is not None:
                roots.append(key)
        if not roots:
            return []
        # ONE boundary-matching rule for both checks: host-sync's skip set
        # and this check's traversal stops must never drift apart
        stop: Set[Tuple[str, str]] = set()
        for mod in project.modules:
            stop |= {(mod.path, q) for q in _boundary_quals(mod)}
        reach = dfa.reachable_from(roots, stop=stop)
        traced_by_path: Dict[str, Set[str]] = {}
        findings: List[Finding] = []
        for key in sorted(reach - stop):
            fn = dfa.functions.get(key)
            if fn is None:
                continue
            traced = traced_by_path.get(fn.path)
            if traced is None:  # per MODULE, not per reached function
                traced = traced_by_path[fn.path] = _traced_quals(fn.mod)
            if fn.qual in traced:
                continue  # traced code can't host-block; trace-safety's turf
            findings.extend(self._scan(dfa, fn))
        return findings

    def _scan(self, dfa: DataflowAnalysis,
              fn: FunctionNode) -> Iterable[Finding]:
        mod, qual = fn.mod, fn.qual
        table = dfa.imports.get(mod.path)
        np_aliases = table.np_aliases() if table else set()
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call) or \
                    mod.scope_of(node) != qual:
                continue
            name = dotted_name(node.func)
            head = name.split(".")[0] if name else ""
            blocking = None
            if name.endswith("block_until_ready"):
                blocking = "jax.block_until_ready"
            elif name == "jax.device_get":
                blocking = "jax.device_get"
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in SYNC_METHODS and \
                    dfa.expr_device(fn, node.func.value):
                blocking = f".{node.func.attr}()"
            elif head in np_aliases and \
                    name.rsplit(".", 1)[-1] in ("asarray", "array") and \
                    node.args and dfa.expr_device(fn, node.args[0]):
                blocking = f"{name}(device value)"
            if blocking:
                yield mod.finding(
                    self.name, "sync-fetch", node,
                    f"`{qual}` is reachable from the scheduling cycle "
                    f"and performs a synchronous fetch ({blocking}) "
                    f"outside the packed decision-fetch boundaries — "
                    f"route it through _bg_fetch/_complete or move it "
                    f"off the cycle path")
