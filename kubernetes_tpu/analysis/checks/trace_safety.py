"""trace-safety: host syncs / impurity reachable inside jit-traced code.

The jit boundary (scheduler.py _build_jitted, state/encoding.py
_scatter_rows) is the hot path: a ``.item()``, ``np.asarray`` or
``time.time()`` inside a traced function either forces a device→host sync
per call (~100ms on the tunnel-attached TPU) or silently bakes a
trace-time constant into the compiled program.  Roots are found three
ways: ``@jax.jit`` decorators, ``jax.jit(fn)`` wraps resolved to
same-module function defs, and a seed list of known traced entry points
(the framework/plugin tensor surface, which is jitted from
scheduler.py:596-609 across module boundaries).  Reachability closes over
same-module calls.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from ..core import Finding, ModuleInfo, Project, dotted_name
from ..registry import Check, register_check

# (path suffix, qualname prefix) pairs marking functions traced from another
# module's jit boundary.  "" prefix = every function in the module.
TRACED_SEEDS: Tuple[Tuple[str, str], ...] = (
    ("ops/segment.py", ""),
    ("state/encoding.py", "apply_scatter"),
    ("framework/runtime.py", "initial_dynamic_state"),
    ("framework/runtime.py", "BatchedFramework.prepare"),
    ("framework/runtime.py", "BatchedFramework.chain_prev"),
    ("framework/runtime.py", "BatchedFramework.compute_static"),
    ("framework/runtime.py", "BatchedFramework.compute_row"),
    ("framework/runtime.py", "BatchedFramework.compute_packed"),
    ("framework/runtime.py", "BatchedFramework.apply_commits"),
    ("framework/runtime.py", "BatchedFramework.greedy_assign"),
    ("framework/runtime.py", "BatchedFramework.batch_assign"),
    ("framework/runtime.py", "BatchedFramework.diagnose_bits"),
    ("framework/runtime.py", "BatchedFramework.select_host"),
    ("plugins/helpers.py", ""),
)
# every method with one of these names on any class under plugins/ runs
# inside the fused programs (the Plugin protocol's traced surface)
TRACED_PLUGIN_METHODS = {"filter", "score", "prepare", "chain_prev"}

# numpy attributes that are trace-safe (static shape/dtype reads, constants)
NP_BENIGN = {"shape", "ndim", "dtype", "int8", "int16", "int32", "int64",
             "uint8", "uint32", "float16", "float32", "float64", "bool_",
             "inf", "nan", "newaxis", "pi"}
# time.* and random.* are impure: they execute ONCE at trace time and bake
# that value into the compiled program forever
IMPURE_MODULES = {"time", "random"}
SYNC_METHODS = {"item", "tolist", "block_until_ready"}


def _numpy_aliases(mod: ModuleInfo) -> Set[str]:
    out = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
    return out


def _jit_roots(mod: ModuleInfo) -> Set[str]:
    """Qualnames of functions jit-wrapped within this module."""
    roots: Set[str] = set()
    # decorator form: @jax.jit / @jit / @partial(jax.jit, ...)
    for q, fn in mod.functions.items():
        for dec in getattr(fn, "decorator_list", ()):
            target = dec.func if isinstance(dec, ast.Call) else dec
            names = {dotted_name(target)}
            if isinstance(dec, ast.Call):
                names |= {dotted_name(a) for a in dec.args}
            if names & {"jax.jit", "jit"}:
                roots.add(q)
    # wrap form: jax.jit(fn) where fn names a def anywhere in the module
    # (the scheduler's _build_jitted table wraps nested defs this way)
    by_bare: Dict[str, List[str]] = {}
    for q in mod.functions:
        by_bare.setdefault(q.rsplit(".", 1)[-1], []).append(q)
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and dotted_name(node.func) in ("jax.jit", "jit")
                and node.args):
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                roots.update(by_bare.get(arg.id, ()))
            elif isinstance(arg, ast.Lambda):
                roots.add(mod.scope_of(arg))  # scan the enclosing scope
    return roots


def _seeded(mod: ModuleInfo) -> Set[str]:
    roots: Set[str] = set()
    for suffix, prefix in TRACED_SEEDS:
        if not mod.path.endswith(suffix):
            continue
        for q in mod.functions:
            if not prefix or q == prefix or q.startswith(prefix + "."):
                roots.add(q)
    if "/plugins/" in mod.path:
        for q in mod.functions:
            bare = q.rsplit(".", 1)[-1]
            if bare in TRACED_PLUGIN_METHODS and "." in q:
                roots.add(q)
    return roots


def _close_over_calls(mod: ModuleInfo, roots: Set[str]) -> Set[str]:
    """Add same-module functions called (by bare name or self.X) from roots."""
    by_bare: Dict[str, List[str]] = {}
    for q in mod.functions:
        by_bare.setdefault(q.rsplit(".", 1)[-1], []).append(q)
    work = list(roots)
    seen = set(roots)
    while work:
        q = work.pop()
        fn = mod.functions.get(q)
        if fn is None:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = ""
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id == "self"):
                callee = node.func.attr
            for cq in by_bare.get(callee, ()):
                if cq not in seen:
                    seen.add(cq)
                    work.append(cq)
    return seen


@register_check
class TraceSafetyCheck(Check):
    name = "trace-safety"
    description = ("host syncs, numpy ops, side effects, and wall-clock / "
                   "PRNG impurity inside jit-traced functions")

    def run(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for mod in project.modules:
            roots = _jit_roots(mod) | _seeded(mod)
            if not roots:
                continue
            traced = _close_over_calls(mod, roots)
            np_aliases = _numpy_aliases(mod)
            for q in sorted(traced):
                fn = mod.functions.get(q)
                if fn is None:
                    continue
                findings.extend(self._scan(mod, q, fn, np_aliases))
        return findings

    def _scan(self, mod: ModuleInfo, qual: str, fn: ast.AST,
              np_aliases: Set[str]) -> Iterable[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            # skip calls that belong to a NESTED function with its own
            # qualname (it is scanned under its own root if reachable)
            if mod.scope_of(node) != qual:
                continue
            name = dotted_name(node.func)
            head, _, tail = name.partition(".")
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in SYNC_METHODS:
                yield mod.finding(
                    self.name, "host-sync", node,
                    f".{node.func.attr}() in traced `{qual}` forces a "
                    f"device->host sync per call")
            elif head in np_aliases and tail not in NP_BENIGN:
                yield mod.finding(
                    self.name, "numpy-op", node,
                    f"{name}(...) in traced `{qual}` runs on host at trace "
                    f"time (constant-folded) or forces a transfer — use jnp")
            elif head in IMPURE_MODULES:
                yield mod.finding(
                    self.name, "impure", node,
                    f"{name}() in traced `{qual}` executes once at trace "
                    f"time; the compiled program reuses that value forever")
            elif name in ("print",) or head in ("klog", "logging"):
                yield mod.finding(
                    self.name, "side-effect", node,
                    f"{name}(...) in traced `{qual}` only runs at trace "
                    f"time — it will not fire per call")
            elif name in ("float", "int", "bool") and node.args:
                arg = node.args[0]
                if self._may_be_traced(arg):
                    yield mod.finding(
                        self.name, "concretize", node,
                        f"{name}(...) in traced `{qual}` concretizes its "
                        f"argument — a traced array here raises or syncs")

    @staticmethod
    def _may_be_traced(arg: ast.AST) -> bool:
        if isinstance(arg, ast.Constant):
            return False
        # ALL_CAPS names are module constants by convention
        # (MAX_NODE_SCORE et al.) — float()/int() of one is trace-safe
        if isinstance(arg, (ast.Name, ast.Attribute)):
            bare = dotted_name(arg).rsplit(".", 1)[-1]
            if bare and bare == bare.upper() and any(
                    c.isalpha() for c in bare):
                return False
        # len(...) and *.shape[...] are static under trace
        if isinstance(arg, ast.Call) and dotted_name(arg.func) == "len":
            return False
        if isinstance(arg, ast.Subscript) and \
                isinstance(arg.value, ast.Attribute) and \
                arg.value.attr == "shape":
            return False
        if isinstance(arg, ast.Attribute) and arg.attr in ("shape", "ndim",
                                                           "size"):
            return False
        return True
