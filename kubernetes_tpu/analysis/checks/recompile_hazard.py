"""recompile-hazard: jax.jit wraps that defeat the compile cache.

The round-2 profile (utils/compilemon.py docstring) showed recompilation
was 90% of bench wall time; the contract is O(1) compiles per cluster
tier.  Hazards flagged:

  jit-in-loop        jax.jit(...) inside a for/while body — a fresh
                     callable (and cache entry) per iteration
  jit-immediate      jax.jit(f)(args) — wrap-and-call compiles per call
  jit-lambda         jax.jit(lambda ...) inside a function — the lambda's
                     identity changes per enclosing call, so the jit cache
                     keys never hit
  uncached-builder   a function that builds jax.jit programs whose result
                     is not stored in an init-time cache (self attribute /
                     module-level binding) at some call site
  unhashable-static  a list/dict/set literal passed in a position declared
                     static_argnums on the jitted callable
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Finding, ModuleInfo, Project, dotted_name
from ..registry import Check, register_check


def _is_jit_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) in ("jax.jit", "jit"))


def _in_loop(mod: ModuleInfo, node: ast.AST) -> bool:
    for a in mod.ancestors(node):
        if isinstance(a, (ast.For, ast.While)):
            return True
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
    return False


def _static_argnums(call: ast.Call) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
    return ()


@register_check
class RecompileHazardCheck(Check):
    name = "recompile-hazard"
    description = ("per-call jax.jit wrapping, jit-of-lambda, uncached "
                   "program builders, unhashable static args")

    def run(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for mod in project.modules:
            findings.extend(self._scan_module(mod))
        return findings

    def _scan_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        # functions whose body wraps jit and returns/yields the result:
        # candidate "builders" whose call sites must cache
        builder_quals: Set[str] = set()
        jitted_names: Dict[str, ast.Call] = {}  # local name -> jit call
        for node in ast.walk(mod.tree):
            if not _is_jit_call(node):
                continue
            parent = mod.parents.get(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                yield mod.finding(
                    self.name, "jit-immediate", node,
                    "jax.jit(f)(...) wraps AND calls in one expression — "
                    "the compiled program is rebuilt every execution; "
                    "cache the jitted callable at init")
                continue
            if _in_loop(mod, node):
                yield mod.finding(
                    self.name, "jit-in-loop", node,
                    "jax.jit(...) inside a loop body creates a fresh "
                    "callable (and compile-cache entry) per iteration — "
                    "hoist the wrap out of the loop")
            if node.args and isinstance(node.args[0], ast.Lambda) and \
                    mod.enclosing_function(node) is not None:
                yield mod.finding(
                    self.name, "jit-lambda", node,
                    "jax.jit(lambda ...) inside a function: the lambda's "
                    "identity changes per call, so the jit cache never "
                    "hits across calls — name it and wrap once at init")
            fn = mod.enclosing_function(node)
            if fn is not None and self._escapes_via_return(mod, node, fn):
                builder_quals.add(mod.scope_of(node))
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                    and isinstance(parent.targets[0], ast.Name):
                jitted_names[parent.targets[0].id] = node

        yield from self._check_builders(mod, builder_quals)
        yield from self._check_static_args(mod, jitted_names)

    @staticmethod
    def _escapes_via_return(mod: ModuleInfo, jit_call: ast.Call,
                            fn: ast.AST) -> bool:
        """jit result returned directly, or via a local that is returned
        (incl. as a dict/tuple element — the scheduler's program table)."""
        for a in mod.ancestors(jit_call):
            if isinstance(a, ast.Return):
                return True
            if a is fn:
                break
        # assigned to a local that appears in some return expression
        parent = mod.parents.get(jit_call)
        if isinstance(parent, ast.Assign) and \
                isinstance(parent.targets[0], ast.Name):
            local = parent.targets[0].id
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    for n in ast.walk(node.value):
                        if isinstance(n, ast.Name) and n.id == local:
                            return True
        return False

    def _check_builders(self, mod: ModuleInfo,
                        builder_quals: Set[str]) -> Iterable[Finding]:
        """Every call site of a jit-program builder must store the result
        into an init-time cache: a self attribute/subscript, or a
        module-level binding outside any loop."""
        bare_builders = {q.rsplit(".", 1)[-1]: q for q in builder_quals}
        if not bare_builders:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = ""
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id == "self"):
                callee = node.func.attr
            qual = bare_builders.get(callee)
            if qual is None or mod.scope_of(node).startswith(qual):
                continue  # not a builder call / recursive self-reference
            if not self._cached_at_init(mod, node):
                yield mod.finding(
                    self.name, "uncached-builder", node,
                    f"result of `{callee}()` (which builds jax.jit "
                    f"programs) is not stored in an init-time cache — "
                    f"each call here compiles fresh executables")

    @staticmethod
    def _cached_at_init(mod: ModuleInfo, call: ast.Call) -> bool:
        # walk up through container displays / comprehensions to the
        # nearest Assign ({v: make(v) for v in ...} at module scope IS an
        # init-time cache); stop at function or statement boundaries
        parent = mod.parents.get(call)
        while isinstance(parent, (ast.Dict, ast.List, ast.Tuple, ast.Set,
                                  ast.DictComp, ast.ListComp, ast.SetComp,
                                  ast.comprehension)):
            parent = mod.parents.get(parent)
        if not isinstance(parent, ast.Assign):
            return False
        if _in_loop(mod, call):
            return False
        tgt = parent.targets[0]
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        if isinstance(tgt, ast.Attribute):  # self._jitted_by[...] = ...
            return True
        if isinstance(tgt, ast.Name):
            # module-level binding (one-time script/init cost) or an
            # __init__-scope local is treated as cached
            scope = mod.scope_of(call)
            return scope == "" or scope.endswith("__init__")
        return False

    def _check_static_args(self, mod: ModuleInfo,
                           jitted: Dict[str, ast.Call]) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in jitted):
                continue
            statics = _static_argnums(jitted[node.func.id])
            for idx in statics:
                if idx < len(node.args) and isinstance(
                        node.args[idx], (ast.List, ast.Dict, ast.Set)):
                    yield mod.finding(
                        self.name, "unhashable-static", node.args[idx],
                        f"arg {idx} of `{node.func.id}` is declared "
                        f"static_argnums but receives an unhashable "
                        f"literal — jit will raise (or thrash) at call "
                        f"time; pass a tuple/frozen value")
