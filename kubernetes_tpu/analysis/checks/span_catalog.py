"""span-catalog: every emitted span name is cataloged, every catalog entry
is emitted.

Guards the span-observability contract the same way metrics-registration
guards series names: ``tracer.span("naem")`` with a typo'd or ad-hoc name
would silently fork the span namespace — dashboards, the `ktpu trace`
renderer, and the harness's attempt-record aggregation all key on the
documented names.  The catalog is the ``SPAN_CATALOG`` frozenset literal in
component_base/trace.py (mirrored into COMPONENTS.md §Observability; the
doc sync is pinned by tests/test_trace.py).

Rules:
  unknown-span   ``X.span("name")`` whose literal name is not in
                 SPAN_CATALOG
  unused-span    a SPAN_CATALOG entry no scanned code ever emits (dead
                 catalog entry, or the emit site was lost in a refactor)
  dynamic-span   ``X.span(expr)`` with a non-literal first argument — span
                 names must be static so the catalog stays checkable
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from ..core import Finding, ModuleInfo, Project
from ..registry import Check, register_check

TRACE_MODULE_SUFFIX = "component_base/trace.py"


def _catalog_names(mod: ModuleInfo) -> Optional[Set[str]]:
    """String literals of the module-level SPAN_CATALOG assignment."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "SPAN_CATALOG":
            names = {
                c.value for c in ast.walk(node.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            }
            return names
    return None


def _span_calls(mod: ModuleInfo):
    """(node, literal-or-None) for every ``<expr>.span(...)`` call.  The
    receiver is unconstrained on purpose — the tracer travels under many
    names (self.tracer, api.tracer, a closure capture) and no other API in
    the scanned tree spells ``.span(``."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "span":
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                yield node, node.args[0].value
            else:
                yield node, None


@register_check
class SpanCatalogCheck(Check):
    name = "span-catalog"
    description = ("emitted tracer.span() names are static literals in "
                   "SPAN_CATALOG; catalog entries are all emitted")

    def run(self, project: Project) -> Iterable[Finding]:
        trace_mod = project.find(TRACE_MODULE_SUFFIX)
        if trace_mod is None:
            return []
        catalog = _catalog_names(trace_mod)
        if catalog is None:
            return []
        findings: List[Finding] = []
        used: Set[str] = set()
        for mod in project.modules:
            if mod is trace_mod:
                continue  # the tracer's own plumbing defines, not emits
            for node, name in _span_calls(mod):
                if name is None:
                    findings.append(mod.finding(
                        self.name, "dynamic-span", node,
                        "span name is not a string literal — the catalog "
                        "(and every consumer keyed on span names) cannot "
                        "check a dynamic name"))
                    continue
                used.add(name)
                if name not in catalog:
                    findings.append(mod.finding(
                        self.name, "unknown-span", node,
                        f"span `{name}` is not in SPAN_CATALOG "
                        f"(component_base/trace.py) — add it there AND to "
                        f"the COMPONENTS.md span catalog, or fix the typo"))
        for name in sorted(catalog - used):
            for node in trace_mod.tree.body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.targets[0], ast.Name) and \
                        node.targets[0].id == "SPAN_CATALOG":
                    findings.append(trace_mod.finding(
                        self.name, "unused-span", node,
                        f"span `{name}` is cataloged but no scanned code "
                        f"emits it — dead catalog entry or a lost emit "
                        f"site"))
                    break
        return findings
