"""exception-hygiene: broad excepts that swallow errors silently.

An ``except Exception`` (or bare ``except``) whose handler neither
re-raises, logs (klog/logging/print), nor records a metric hides real
failures — the class of bug PR 1's chaos harness exists to surface.  The
fix is one of: narrow the exception type to what the code actually
tolerates, add a klog line, or let it propagate.  Sites that are genuinely
best-effort get grandfathered in the baseline (shrink it, never grow it).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Finding, ModuleInfo, Project, dotted_name
from ..registry import Check, register_check

BROAD = {"Exception", "BaseException"}
# a call whose dotted name starts with one of these prefixes, or whose last
# segment is one of these names, makes the handler non-silent
LOGGING_PREFIXES = ("klog.", "logging.", "m.", "metrics.", "self.log",
                    "log.", "logger.", "_logger.", "warnings.")
LOGGING_TAILS = {"info_s", "error_s", "info", "error", "warning", "warn",
                 "debug", "exception", "print", "log", "inc", "observe",
                 "add_note"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, (ast.Name, ast.Attribute)):
        return dotted_name(t).rsplit(".", 1)[-1] in BROAD
    if isinstance(t, ast.Tuple):
        return any(dotted_name(e).rsplit(".", 1)[-1] in BROAD
                   for e in t.elts)
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if not name:
                continue
            if name.startswith(LOGGING_PREFIXES):
                return False
            if name.rsplit(".", 1)[-1] in LOGGING_TAILS:
                return False
    return True


@register_check
class ExceptionHygieneCheck(Check):
    name = "exception-hygiene"
    description = ("`except Exception` handlers that swallow without "
                   "re-raise, log, or metric")

    def run(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ExceptHandler) and \
                        _is_broad(node) and _is_silent(node):
                    scope = mod.scope_of(node) or "<module>"
                    findings.append(mod.finding(
                        self.name, "silent-swallow", node,
                        f"broad except in `{scope}` swallows the error "
                        f"with no re-raise, log, or metric — narrow the "
                        f"type or surface the failure"))
        return findings
