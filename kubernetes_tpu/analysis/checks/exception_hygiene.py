"""exception-hygiene: broad excepts that swallow errors silently.

An ``except Exception`` (or bare ``except``) whose handler neither
re-raises, logs (klog/logging/print), records a metric, nor hands the
CAUGHT EXCEPTION to a same-module function that (transitively) logs or
records one — the interprocedural upgrade that recognizes
``schedule_cycle``'s ``self._handle_cycle_failure(infos, e)`` while
still flagging a bare ``self.helper()`` whose helper merely bumps a
success metric — hides real failures, the class of bug PR 1's chaos
harness exists to surface.  The fix is one of:
narrow the exception type to what the code actually tolerates, add a
klog line/metric, or let it propagate.  Sites that are genuinely
best-effort carry a ``ktpu-analysis: ignore`` suppression with a
justification (core.py lints the justification itself).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from ..core import Finding, ModuleInfo, Project, dotted_name
from ..registry import Check, register_check

BROAD = {"Exception", "BaseException"}
# a call whose dotted name starts with one of these prefixes, or whose last
# segment is one of these names, makes the handler non-silent
LOGGING_PREFIXES = ("klog.", "logging.", "m.", "metrics.", "self.log",
                    "log.", "logger.", "_logger.", "warnings.")
LOGGING_TAILS = {"info_s", "error_s", "info", "error", "warning", "warn",
                 "debug", "exception", "print", "log", "inc", "observe",
                 "add_note"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, (ast.Name, ast.Attribute)):
        return dotted_name(t).rsplit(".", 1)[-1] in BROAD
    if isinstance(t, ast.Tuple):
        return any(dotted_name(e).rsplit(".", 1)[-1] in BROAD
                   for e in t.elts)
    return False


def _logs_directly(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = dotted_name(n.func)
            if not name:
                continue
            if name.startswith(LOGGING_PREFIXES):
                return True
            if name.rsplit(".", 1)[-1] in LOGGING_TAILS:
                return True
    return False


def _surfacing_functions(mod: ModuleInfo) -> Set[str]:
    """Qualnames that log or record a metric, directly or via same-module
    calls (transitive closure over bare-name and ``self.X`` edges)."""
    surfaces: Set[str] = set()
    calls: Dict[str, Set[str]] = {}
    by_bare: Dict[str, List[str]] = {}
    for q in mod.functions:
        by_bare.setdefault(q.rsplit(".", 1)[-1], []).append(q)
    for q, fn in mod.functions.items():
        body_logs = False
        callees: Set[str] = set()
        for n in ast.walk(fn):
            if mod.scope_of(n) != q:
                continue
            # note: a bare `raise` elsewhere in a helper does NOT make it
            # surfacing — only an actual log/metric does; almost any
            # function can raise on some branch
            if isinstance(n, ast.Call):
                name = dotted_name(n.func)
                if name.startswith(LOGGING_PREFIXES) or (
                        name and name.rsplit(".", 1)[-1] in LOGGING_TAILS):
                    body_logs = True
                callees.update(_callee_quals(mod, q, n, by_bare))
        if body_logs:
            surfaces.add(q)
        calls[q] = callees
    changed = True
    while changed:
        changed = False
        for q, callees in calls.items():
            if q not in surfaces and callees & surfaces:
                surfaces.add(q)
                changed = True
    return surfaces


def _callee_quals(mod: ModuleInfo, caller_qual: str, call: ast.Call,
                  by_bare: Dict[str, List[str]]) -> List[str]:
    """Resolve one call to candidate qualnames.  ``self.X`` binds to the
    CALLER'S OWN class when that class defines X — another class's
    same-named (surfacing) method must not exempt this one."""
    if isinstance(call.func, ast.Name):
        name = call.func.id
        if name in mod.functions:  # module-level def: exact
            return [name]
        return by_bare.get(name, [])
    if (isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "self"):
        meth = call.func.attr
        cls = caller_qual.split(".")[0] if "." in caller_qual else ""
        own = f"{cls}.{meth}"
        if own in mod.functions:
            return [own]
        return by_bare.get(meth, [])
    return []


def _is_silent(mod: ModuleInfo, handler: ast.ExceptHandler,
               caller_qual: str, surfaces: Set[str],
               by_bare: Dict[str, List[str]]) -> bool:
    exc_name = handler.name  # the `as e` binding, if any
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if not name:
                continue
            if name.startswith(LOGGING_PREFIXES):
                return False
            if name.rsplit(".", 1)[-1] in LOGGING_TAILS:
                return False
            # delegation: calling a same-module function that itself
            # surfaces (logs/metrics, transitively) counts ONLY when the
            # caught exception object is actually handed to it — a bare
            # `self.helper()` whose helper increments a success metric
            # must not exempt the swallow
            if exc_name is None or not any(
                    isinstance(n, ast.Name) and n.id == exc_name
                    for a in (list(node.args)
                              + [kw.value for kw in node.keywords])
                    for n in ast.walk(a)):
                continue
            if any(q in surfaces
                   for q in _callee_quals(mod, caller_qual, node, by_bare)):
                return False
    return True


@register_check
class ExceptionHygieneCheck(Check):
    name = "exception-hygiene"
    description = ("`except Exception` handlers that swallow without "
                   "re-raise, log, metric, or delegation to a function "
                   "that does")

    def run(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for mod in project.modules:
            surfaces = _surfacing_functions(mod)
            by_bare: Dict[str, List[str]] = {}
            for q in mod.functions:
                by_bare.setdefault(q.rsplit(".", 1)[-1], []).append(q)
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ExceptHandler) and \
                        _is_broad(node) and _is_silent(
                            mod, node, mod.scope_of(node), surfaces,
                            by_bare):
                    scope = mod.scope_of(node) or "<module>"
                    findings.append(mod.finding(
                        self.name, "silent-swallow", node,
                        f"broad except in `{scope}` swallows the error "
                        f"with no re-raise, log, or metric — narrow the "
                        f"type or surface the failure"))
        return findings
