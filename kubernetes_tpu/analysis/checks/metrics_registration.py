"""metrics-registration: every emitted metric exists, exactly once.

Guards the typo'd-counter class of bug: a misspelled attribute on the
scheduler_metrics module (``m.informer_relist.inc`` — note the missing
``s``) raises AttributeError only on the code path that emits it, which
under chaos is exactly the path nothing exercises until production.

Rules:
  unknown-attr       ``m.X`` where the scheduler_metrics module defines no
                     module-level ``X``
  unknown-name       ``default_registry.get("name")`` for a name no
                     registered metric carries
  duplicate-name     the same metric name string constructed more than once
  registered-unused  a registered series no scanned code ever references
                     (dead metric, or the emit site was lost in a refactor)
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from ..core import Finding, ModuleInfo, Project, dotted_name
from ..registry import Check, register_check

METRICS_MODULE_SUFFIX = "metrics/scheduler_metrics.py"
METRIC_CTORS = {"Counter", "Gauge", "Histogram"}


def _module_level_names(mod: ModuleInfo) -> Set[str]:
    names: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            names.update(t.id for t in node.targets
                         if isinstance(t, ast.Name))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            names.update(a.asname or a.name.split(".")[0]
                         for a in node.names)
    return names


def _metric_defs(mod: ModuleInfo) -> Dict[str, str]:
    """attr name -> registered metric name string (module level only)."""
    out: Dict[str, str] = {}
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        for call in ast.walk(node.value):
            if isinstance(call, ast.Call) and \
                    dotted_name(call.func).rsplit(".", 1)[-1] in METRIC_CTORS \
                    and call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                out[node.targets[0].id] = call.args[0].value
                break
    return out


def _aliases_of_metrics_module(mod: ModuleInfo) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "scheduler_metrics":
                    out.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("scheduler_metrics"):
                    out.add(a.asname or a.name.split(".")[0])
    return out


@register_check
class MetricsRegistrationCheck(Check):
    name = "metrics-registration"
    description = ("emitted metric attributes/names resolve to exactly one "
                   "registered series; registered series are emitted")

    def run(self, project: Project) -> Iterable[Finding]:
        metrics_mod = project.find(METRICS_MODULE_SUFFIX)
        if metrics_mod is None:
            return []
        defs = _metric_defs(metrics_mod)
        valid_attrs = _module_level_names(metrics_mod)
        registered_names = set(defs.values())
        findings: List[Finding] = []
        used_attrs: Set[str] = set()

        # duplicate-name: every Counter/Gauge/Histogram construction
        seen_ctor: Dict[str, Tuple[str, int]] = {}
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and \
                        dotted_name(node.func).rsplit(".", 1)[-1] in \
                        METRIC_CTORS and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    mname = node.args[0].value
                    if mname in seen_ctor:
                        first = seen_ctor[mname]
                        findings.append(mod.finding(
                            self.name, "duplicate-name", node,
                            f"metric `{mname}` is constructed more than "
                            f"once (first at {first[0]}:{first[1]}) — two "
                            f"series fight over one name"))
                    else:
                        seen_ctor[mname] = (mod.path, node.lineno)

        for mod in project.modules:
            aliases = _aliases_of_metrics_module(mod)
            for node in ast.walk(mod.tree):
                # unknown-attr: alias.X where X is not defined
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id in aliases:
                    used_attrs.add(node.attr)
                    if node.attr not in valid_attrs:
                        findings.append(mod.finding(
                            self.name, "unknown-attr", node,
                            f"`{node.value.id}.{node.attr}` does not exist "
                            f"in metrics/scheduler_metrics.py — typo'd "
                            f"metric raises AttributeError at emit time"))
                # unknown-name: registry.get("...") string lookups
                if isinstance(node, ast.Call) and \
                        dotted_name(node.func).endswith("registry.get") and \
                        node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    if node.args[0].value not in registered_names:
                        findings.append(mod.finding(
                            self.name, "unknown-name", node,
                            f"registry lookup of `{node.args[0].value}` "
                            f"matches no registered metric"))
                # any bare-name reference also counts as usage (re-exports)
                if isinstance(node, ast.Name) and node.id in defs and \
                        mod is not metrics_mod:
                    used_attrs.add(node.id)

        # registered-unused: defined series nothing references by attr OR
        # by name string (tests are out of scan scope on purpose — an
        # emit-path must exist in the code itself)
        looked_up: Set[str] = set()
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str) and \
                        node.value in registered_names and \
                        mod is not metrics_mod:
                    looked_up.add(node.value)
        for attr, mname in sorted(defs.items()):
            if attr not in used_attrs and mname not in looked_up:
                # anchor the finding at the registration site
                for node in metrics_mod.tree.body:
                    if isinstance(node, ast.Assign) and \
                            isinstance(node.targets[0], ast.Name) and \
                            node.targets[0].id == attr:
                        findings.append(metrics_mod.finding(
                            self.name, "registered-unused", node,
                            f"metric `{mname}` ({attr}) is registered but "
                            f"no scanned code emits or reads it"))
                        break
        return findings
