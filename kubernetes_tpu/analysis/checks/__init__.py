"""Check modules register themselves on import (plugins/__init__.py idiom)."""

from . import device_boundary  # noqa: F401
from . import exception_hygiene  # noqa: F401
from . import lock_discipline  # noqa: F401
from . import metrics_registration  # noqa: F401
from . import recompile_hazard  # noqa: F401
from . import span_catalog  # noqa: F401
from . import thread_ownership  # noqa: F401
from . import trace_safety  # noqa: F401
