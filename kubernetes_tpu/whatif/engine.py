"""The unified multi-fork counterfactual engine.

One evaluator for every fork-and-resolve consumer in the tree — the
descheduler's WhatIfPlanner, the cluster autoscaler's scale-up/scale-down
simulations, and (via whatif/dryrun.py) preemption's dry-run fan-out.
Upstream analogs: cluster-autoscaler's simulator (SchedulePod against a
cluster snapshot with template nodes) and the scheduler framework's
DryRunPreemption.

K candidate plans are evaluated as ONE ``[K, B, N]`` vmapped solve: each
fork (victim-mask / node-add / node-remove, whatif/fork.py) is applied to
the live DeviceSnapshot inside the program, and the scheduler's own
assignment semantics — same engine routing (conflict-partitioned batch
auction vs exact greedy scan), same gang all-or-nothing mask, same
deterministic tie-breaks — re-run per fork.  The vmapped K-fork solve is
bit-for-bit equal to K sequential single-fork solves (pinned in
tests/test_whatif.py), and a single victim-mask fork is bit-for-bit equal
to the scheduler's actual post-eviction bindings (the descheduler parity
contract, tests/test_descheduler.py).

Quiescence precondition (same as the pre-unification planner): an
in-flight pipelined batch holds placements the fork can't see —
``evaluate`` refuses rather than mispredict; controllers flush the
pipeline first.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import objects as v1
from ..metrics import scheduler_metrics as m
from ..state.encoding import NODE_ARRAYS as _NODE_ARRAYS
from ..state.units import pow2_round_up as _pow2
from .fork import ForkedEncoderView, ForkPayload, ForkSpec, apply_fork, stack_payloads


@dataclass
class Prediction:
    """One counterfactual solve's outcome."""

    placements: Dict[str, Optional[str]]  # pod uid → node name (None = no fit)
    pods: List[v1.Pod] = field(default_factory=list)  # solve order (= queue order)
    masked_victims: int = 0
    fork: Optional[ForkSpec] = None

    @property
    def placed(self) -> int:
        return sum(1 for n in self.placements.values() if n is not None)

    @property
    def unplaced(self) -> int:
        return sum(1 for n in self.placements.values() if n is None)


class _QueueShim:
    """Just enough QueuedPodInfo surface for the gang less-fn."""

    __slots__ = ("pod", "initial_attempt_timestamp")

    def __init__(self, pod: v1.Pod):
        self.pod = pod
        self.initial_attempt_timestamp = pod.metadata.creation_timestamp or 0.0


class WhatIfEngine:
    """Counterfactual solver bound to a live TPUScheduler (shares its
    cache/encoder/compiler; fork programs are its own, compiled once per
    (profile, engine) and reused across every consumer)."""

    def __init__(self, scheduler):
        self.sched = scheduler
        # (profile, mode) → (framework instance, jitted program); rebuilt
        # when the scheduler's framework for the profile is replaced
        # (domain growth clears TPUScheduler._fws)
        self._programs: Dict[Tuple[str, str], Tuple[object, object]] = {}

    # --- queue-order staging --------------------------------------------------

    def order_pending(self, pods: Sequence[v1.Pod]) -> List[v1.Pod]:
        """The queue's pop order (gang-cohesive priority sort) so the
        counterfactual batch matches what the real scheduler will pop."""
        less = self.sched.gangs.less
        shims = [_QueueShim(p) for p in pods]
        shims.sort(key=functools.cmp_to_key(
            lambda a, b: -1 if less(a, b) else (1 if less(b, a) else 0)))
        return [s.pod for s in shims]

    # --- the solve ------------------------------------------------------------

    def evaluate_one(self, pending: Sequence[v1.Pod],
                     fork: ForkSpec) -> Optional[Prediction]:
        out = self.evaluate(pending, [fork], vmapped=False)
        return out[0] if out else None

    def evaluate(self, pending: Sequence[v1.Pod],
                 forks: Sequence[ForkSpec],
                 vmapped: bool = True) -> Optional[List[Prediction]]:
        """Where would ``pending`` land under each of K candidate forks?

        Returns one Prediction per fork, or None when no solve can be
        trusted (empty/oversize batch, in-flight pipelined work) — callers
        must treat that as "no plan", never as "no fit".  ``vmapped=False``
        runs K sequential single-fork solves instead of the stacked vmap —
        the parity oracle (tests/test_whatif.py pins both paths equal
        bit-for-bit).
        """
        sched = self.sched
        if not pending or not forks or len(pending) > sched.batch_size:
            return None
        if getattr(sched, "_inflight_q", None):
            # quiescence precondition (module doc): refuse rather than
            # mispredict; controllers flush in-flight work first
            return None
        changed = sched.cache.update_snapshot(sched.snapshot)
        sched.encoder.sync(sched.snapshot, changed)
        enc = sched.encoder
        # compile BEFORE template-node encoding and the device upload (same
        # order as _dispatch_batch): first-seen topology keys register at
        # compile time and backfill node_topo rows both must carry
        pods = self.order_pending(pending)
        batch = sched.compiler.compile(pods, pad_to=sched.batch_size)
        payloads, views, added_names = self._build_forks(forks)
        # the framework is resolved AFTER fork building: scratch template
        # encodes may grow the topology domain, and _framework rebuilds the
        # plugin programs against the final domain_cap
        profile = sched._profile_of(pods[0])
        fw = sched._framework(profile)
        dsnap = enc.to_device()
        sched.gangs.stage_batch(pods)
        gang_seg = sched.gangs.gang_segments(pods, batch.size)
        host_auxes = [
            fw.host_prepare(batch, sched.snapshot, view,
                            namespace_labels=sched.namespace_labels)
            for view in views
        ]
        nom_rows, nom_req = sched._nominated_arrays({p.uid for p in pods})
        mode, coupling = self._route(batch)
        progs = self._programs_for(profile, fw, mode)
        order = np.arange(batch.size, dtype=np.int32)
        args = (nom_rows, nom_req, order, gang_seg)
        if vmapped and len(forks) > 1:
            stacked_aux = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *host_auxes)
            if getattr(sched, "mesh", None) is not None:
                # the [K, ..., N] stacked fork planes ride the same node-axis
                # shard spec as the snapshot — without this the vmapped solve
                # would silently replicate them onto every shard
                from ..parallel.mesh import shard_host_auxes

                stacked_aux = shard_host_auxes(stacked_aux, sched.mesh,
                                               enc._n)
            rows_k = np.asarray(progs["k"](
                batch, dsnap, stack_payloads(payloads), stacked_aux,
                coupling, sched.rng_key, *args))
        else:
            # dispatch ALL K programs before fetching ANY result: jax
            # dispatch is async, so fork k+1's device solve overlaps fork
            # k's fetch round instead of serializing K round-trips
            # (surfaced by the host-sync dataflow pass — the fetch sat
            # inside the dispatch loop)
            devs = [
                progs["one"](batch, dsnap, payload, aux, coupling,
                             sched.rng_key, *args)
                for payload, aux in zip(payloads, host_auxes)
            ]
            rows_k = np.stack([np.asarray(d) for d in devs])
        # the forked snapshots are NEVER committed back to the encoder —
        # the scheduler's real device state is untouched by the what-if
        m.whatif_forks.inc(by=len(forks))
        name_of = enc.row_to_name()
        out: List[Prediction] = []
        for k, (fork, payload) in enumerate(zip(forks, payloads)):
            rows = rows_k[k][: len(pods)]
            placements: Dict[str, Optional[str]] = {}
            for pod, row in zip(pods, rows):
                r = int(row)
                name = None
                if r >= 0:
                    name = added_names[k].get(r) or name_of.get(r)
                placements[pod.uid] = name
            out.append(Prediction(
                placements=placements, pods=pods,
                masked_victims=int((payload.vic_pod_rows >= 0).sum()),
                fork=fork))
        return out

    # --- fork payload construction -------------------------------------------

    def _build_forks(self, forks: Sequence[ForkSpec]):
        """Resolve each ForkSpec against the (just-synced) encoder into
        fixed-shape payloads, host views, and per-fork added-row→name maps.

        Template nodes are encoded into SCRATCH encoder rows (growing the
        tiers/dictionary exactly as the real scale-up will), their array
        rows captured, then rolled back — the mirrors uploaded to device
        carry the rows invalid, and each fork's payload re-activates only
        its own adds."""
        from ..state.node_info import NodeInfo

        enc = self.sched.encoder
        any_adds = any(f.add_nodes for f in forks)
        scratch: Dict[int, List[Tuple[int, str]]] = {}
        captured_vals: Dict[int, list] = {}
        captured_view: Dict[int, dict] = {}
        if any_adds:
            scratch_names: set = set()
            encode_order: List[Tuple[int, str]] = []
            try:
                for fi, f in enumerate(forks):
                    rows = []
                    for node in f.add_nodes:
                        name = node.metadata.name
                        if name in enc.node_rows and \
                                name not in scratch_names:
                            raise ValueError(
                                f"whatif node-add: node {name!r} "
                                f"already exists")
                        if name not in scratch_names:
                            scratch_names.add(name)
                            row = enc.encode_node(NodeInfo.of(node))
                            encode_order.append((row, name))
                        else:
                            row = enc.node_rows[name]
                        rows.append((row, name))
                    scratch[fi] = rows
            except Exception:
                # mid-build failure (name collision, encoding-capacity
                # overflow): already-encoded scratch rows MUST leave the
                # live encoder, or the scheduler's next cycle could place
                # real pods on phantom nodes
                for row, name in reversed(encode_order):
                    enc.remove_node(name)
                raise
            # capture AFTER all encodes: a later encode may grow the node
            # tier, reallocating the mirrors the capture must read
            for rows in scratch.values():
                for row, _name in rows:
                    if row in captured_vals:
                        continue
                    captured_vals[row] = [
                        np.copy(getattr(enc, name)[row])
                        for name in _NODE_ARRAYS
                    ]
                    captured_view[row] = {
                        "allocatable": np.copy(enc.allocatable[row]),
                        "requested": np.copy(enc.requested[row]),
                        "non_zero_requested":
                            np.copy(enc.non_zero_requested[row]),
                    }
            # roll back in REVERSE encode order: the encoder's free-row
            # list is a LIFO, so this leaves it positioned to hand the
            # SAME rows back to the same template names on an identical
            # rebuild — two evaluate() calls over one fork set then
            # tie-break identically (the vmapped==sequential parity
            # battery compares exactly that)
            for row, name in reversed(encode_order):
                enc.remove_node(name)

        dra = getattr(self.sched, "dra", None)
        per_fork: List[dict] = []
        for fi, f in enumerate(forks):
            vic: List[Tuple[int, int]] = []
            aff: List[Tuple[int, int]] = []
            chips: List[int] = []
            for v in f.victims:
                pr = enc.pod_rows.get(v.uid)
                nr = enc.node_rows.get(v.spec.node_name)
                if pr is None or nr is None:
                    continue  # not encoded (already gone / never bound): no-op
                vic.append((pr, nr))
                aff.extend(enc.aff.contributions(v.uid))
                # DRA: evicting a claim-holding victim releases its chips
                chips.append(dra.pod_chips(v) if dra is not None else 0)
            dels = [enc.node_rows[n] for n in f.remove_nodes
                    if n in enc.node_rows]
            adds = scratch.get(fi, [])
            per_fork.append({"vic": vic, "aff": aff, "del": dels,
                             "add": adds, "chips": chips})

        vcap = _pow2(max((len(p["vic"]) for p in per_fork), default=1), 8)
        acap = _pow2(max((len(p["aff"]) for p in per_fork), default=1), 8)
        dcap = _pow2(max((len(p["del"]) for p in per_fork), default=1), 8)
        mcap = (_pow2(max((len(p["add"]) for p in per_fork), default=1), 4)
                if any_adds else 0)

        # claim-chip release plane only when some victim actually holds
        # chips: a None field keeps the pre-DRA payload pytree, so existing
        # compiled variants (and claim-free runs) are untouched
        any_chips = any(any(p["chips"]) for p in per_fork)

        payloads: List[ForkPayload] = []
        views: List[ForkedEncoderView] = []
        added_names: List[Dict[int, str]] = []
        for p in per_fork:
            vic_p = np.full(vcap, -1, dtype=np.int32)
            vic_n = np.zeros(vcap, dtype=np.int32)
            vic_c = np.zeros(vcap, dtype=np.int32) if any_chips else None
            for i, (pr, nr) in enumerate(p["vic"]):
                vic_p[i], vic_n[i] = pr, nr
                if vic_c is not None:
                    vic_c[i] = p["chips"][i]
            aff_r = np.full(acap, -1, dtype=np.int32)
            aff_v = np.zeros(acap, dtype=np.int32)
            for i, (gr, dv) in enumerate(p["aff"]):
                aff_r[i], aff_v[i] = gr, dv
            del_r = np.full(dcap, -1, dtype=np.int32)
            for i, r in enumerate(p["del"]):
                del_r[i] = r
            add_rows = add_ok = add_vals = None
            if any_adds:
                add_rows = np.zeros(mcap, dtype=np.int32)
                add_ok = np.zeros(mcap, dtype=bool)
                for i, (row, _name) in enumerate(p["add"]):
                    add_rows[i], add_ok[i] = row, True
                add_vals = tuple(
                    np.stack([
                        (captured_vals[p["add"][i][0]][ai]
                         if i < len(p["add"])
                         else np.asarray(getattr(enc, name)[0]))
                        for i in range(mcap)
                    ])
                    for ai, name in enumerate(_NODE_ARRAYS)
                )
                # pad rows point at row 0 with ok=False — apply_fork
                # rewrites current values there (exact no-op)
            payloads.append(ForkPayload(
                vic_pod_rows=vic_p, vic_node_rows=vic_n,
                aff_rows=aff_r, aff_vals=aff_v, del_rows=del_r,
                add_rows=add_rows, add_ok=add_ok, add_vals=add_vals,
                vic_claim_chips=vic_c))
            views.append(ForkedEncoderView(
                enc, p["vic"], p["del"],
                [row for row, _ in p["add"]], captured_view,
                vic_claim_chips=p["chips"] if any_chips else None))
            added_names.append({row: name for row, name in p["add"]})
        return payloads, views, added_names

    # --- engine routing + compiled programs -----------------------------------

    def _route(self, batch):
        """Route through the scheduler's OWN engine-choice predicate — a
        fork's solve must provably route exactly like the real dispatch
        will (the parity contract depends on one implementation)."""
        mode, coupling, _info = self.sched.engine_choice(batch)
        return ("batch", coupling) if mode == "batch" else ("greedy", None)

    def _programs_for(self, profile: str, fw, mode: str):
        key = (profile, mode)
        cached = self._programs.get(key)
        if cached is not None and cached[0] is fw:
            return cached[1]
        from ..framework.runtime import initial_dynamic_state
        from ..gang import gang_all_or_nothing

        def reserve_nominated(dsnap, nom_rows, nom_req):
            dyn = initial_dynamic_state(dsnap)
            rows = jnp.clip(nom_rows, 0, dsnap.requested.shape[0] - 1)
            add = jnp.where((nom_rows >= 0)[:, None], nom_req, 0)
            return dyn._replace(
                requested=dyn.requested.at[rows].add(
                    add.astype(dyn.requested.dtype)))

        def body(batch, dsnap, payload, host_auxes, coupling, key,
                 nom_rows, nom_req, order, gang_seg):
            fsnap = apply_fork(dsnap, payload)
            dyn = reserve_nominated(fsnap, nom_rows, nom_req)
            auxes = fw.prepare(batch, fsnap, dyn, host_auxes)
            if mode == "batch":
                res = fw.batch_assign(batch, fsnap, dyn, auxes, order,
                                      coupling, key)
            else:
                res = fw.greedy_assign(batch, fsnap, dyn, auxes, order, key)
            return gang_all_or_nothing(res.node_row, gang_seg)

        def k_body(batch, dsnap, payloads, host_auxes, coupling, key,
                   nom_rows, nom_req, order, gang_seg):
            def one(payload, aux):
                return body(batch, dsnap, payload, aux, coupling, key,
                            nom_rows, nom_req, order, gang_seg)

            return jax.vmap(one)(payloads, host_auxes)

        progs = {"one": jax.jit(body), "k": jax.jit(k_body)}
        self._programs[key] = (fw, progs)
        return progs
