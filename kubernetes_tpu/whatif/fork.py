"""DeviceSnapshot forks: the counterfactual state a what-if solve runs on.

A fork is the device analog of upstream cluster-autoscaler's simulator
snapshot (simulator/clustersnapshot) and DryRunPreemption's cloned
NodeInfos: a COPY of cluster state with a hypothetical change applied,
never committed back.  Three capabilities compose freely in one fork:

  - victim-mask: scheduled pods invalidated, their request vectors
    subtracted from their hosts, AND their (anti)affinity term-count
    contributions subtracted from the incremental ``aff_*`` tables
    (state/affinity_index.py) — so affinity-carrying victims fork to
    exactly the state the encoder reaches after a real eviction, and no
    victim class is refused (the pre-whatif WhatIfPlanner's documented
    limitation);
  - node-add: template node rows (capacity/labels/taints, pre-encoded by
    the engine into scratch encoder rows) activated in the fork — the
    cluster-autoscaler "simulate against template nodes" primitive;
  - node-remove: host rows invalidated (callers pair this with a
    victim-mask of the host's pods for scale-down what-ifs).

``apply_fork`` is pure and traceable: the engine vmaps it (plus the whole
assignment program) over K stacked payloads for one ``[K, B, N]`` solve.
All payload groups are fixed-shape with -1 row padding so every fork of a
set shares one compiled program; pads are exact no-ops (masked adds,
scatter-max of False, ``.add`` of 0) and leave the result bit-identical
to a fork built without them.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..api import objects as v1
from ..state.encoding import NODE_ARRAYS as _NODE_ARRAYS


@dataclass
class ForkSpec:
    """One candidate plan, host-side: what to change before the solve."""

    victims: List[v1.Pod] = field(default_factory=list)
    add_nodes: List[v1.Node] = field(default_factory=list)
    remove_nodes: List[str] = field(default_factory=list)
    note: str = ""  # plan label for logs/metrics


class ForkPayload(NamedTuple):
    """Device-side fork arguments (one fork; the engine stacks K of these
    leaf-wise for the vmapped solve).  ``add_vals`` is aligned with
    ``state.encoding._NODE_ARRAYS``; the add group is None when no fork in
    the evaluated set adds nodes, so victim-only consumers (the
    descheduler) keep the cheaper compiled variant."""

    vic_pod_rows: np.ndarray  # i32[V] (-1 pad)
    vic_node_rows: np.ndarray  # i32[V]
    aff_rows: np.ndarray  # i32[A] (-1 pad) victim term-group rows
    aff_vals: np.ndarray  # i32[A] domain value per contribution
    del_rows: np.ndarray  # i32[D] (-1 pad) node rows to invalidate
    add_rows: object = None  # i32[M] | None — scratch rows to activate
    add_ok: object = None  # bool[M] | None
    add_vals: object = None  # tuple[np.ndarray[M, ...]] | None
    # DRA: chips each victim holds on its host (claim_allocated released on
    # eviction).  None (the default) keeps the pre-DRA pytree structure, so
    # claim-free consumers reuse their compiled variants unchanged.
    vic_claim_chips: object = None  # i32[V] | None


def apply_fork(dsnap, p: ForkPayload):
    """Apply one fork payload to a DeviceSnapshot (pure, traceable).

    The scatters are not donated: the live snapshot survives — a what-if
    is NEVER committed back (same contract the descheduler planner pinned
    in test_planner_does_not_disturb_live_state).
    """
    n = dsnap.requested.shape[0]
    pcap = dsnap.pod_valid.shape[0]
    # --- node-add: activate pre-encoded template rows -----------------------
    if p.add_rows is not None:
        rows = jnp.clip(p.add_rows, 0, n - 1)
        updates = {}
        for name, val in zip(_NODE_ARRAYS, p.add_vals):
            cur = getattr(dsnap, name)
            okb = p.add_ok.reshape((-1,) + (1,) * (val.ndim - 1))
            # pad rows (ok=False) rewrite their current values — exact no-op
            updates[name] = cur.at[rows].set(jnp.where(okb, val, cur[rows]))
        dsnap = dataclasses.replace(dsnap, **updates)
    # --- node-remove --------------------------------------------------------
    ok_d = p.del_rows >= 0
    drow = jnp.clip(p.del_rows, 0, n - 1)
    dead = jnp.zeros(n, dtype=bool).at[drow].max(ok_d)
    node_valid = dsnap.node_valid & ~dead
    # --- victim-mask (pods + host resources; duplicates/pads are safe:
    # the validity mask is a scatter-max and the resource deltas are
    # zero-weighted where the pod row is padding) ----------------------------
    ok_v = p.vic_pod_rows >= 0
    prow = jnp.clip(p.vic_pod_rows, 0, pcap - 1)
    nrow = jnp.clip(p.vic_node_rows, 0, n - 1)
    vic_mask = jnp.zeros(pcap, dtype=bool).at[prow].max(ok_v)
    pod_valid = dsnap.pod_valid & ~vic_mask
    okc = ok_v[:, None]
    requested = dsnap.requested.at[nrow].add(
        jnp.where(okc, -dsnap.pod_request[prow], 0))
    non_zero = dsnap.non_zero_requested.at[nrow].add(
        jnp.where(okc, -dsnap.pod_non_zero[prow], 0))
    # --- affinity-table mask: subtract each victim term contribution from
    # its (group row, domain value) count cell — exactly the delta
    # AffinityIndex.remove_pod applies on a real eviction, so the forked
    # tables equal the post-eviction rebuild bit-for-bit ---------------------
    ok_a = p.aff_rows >= 0
    g = dsnap.aff_counts.shape[0]
    d = dsnap.aff_counts.shape[1]
    arow = jnp.clip(p.aff_rows, 0, g - 1)
    aval = jnp.clip(p.aff_vals, 0, d - 1)
    aff_counts = dsnap.aff_counts.at[arow, aval].add(
        -ok_a.astype(dsnap.aff_counts.dtype))
    out = dict(node_valid=node_valid, pod_valid=pod_valid,
               requested=requested, non_zero_requested=non_zero,
               aff_counts=aff_counts)
    # --- DRA claim release: a victim's allocated chips return to its host
    # (pads carry chips=0, an exact no-op like the resource deltas) ----------
    if p.vic_claim_chips is not None:
        out["claim_allocated"] = dsnap.claim_allocated.at[nrow].add(
            jnp.where(ok_v, -p.vic_claim_chips, 0))
    return dataclasses.replace(dsnap, **out)


class ForkedEncoderView:
    """Read-only encoder facade with one fork applied to the HOST mirrors —
    handed to ``host_prepare`` so host-side plugin state (the Coscheduling
    anchor-slice plane's free-capacity scan, any host reader of
    ``requested``/``pod_valid``/``node_valid``) sees the same
    counterfactual the device fork encodes.  Everything else delegates to
    the live encoder.

    Fidelity note (node-add forks): added template nodes are visible in
    the mirrors here, but store-derived host state (the gang slice-domain
    plane reads Node objects from the store) cannot see nodes that do not
    exist yet — score-level preferences may therefore differ from the
    post-scale-up cluster.  Placeability (filters, resources) is exact;
    victim-mask and node-remove forks are bit-for-bit.
    """

    def __init__(self, encoder, vic_rows: Sequence[Tuple[int, int]],
                 del_rows: Sequence[int],
                 add_rows: Sequence[int],
                 add_captured: Optional[Dict[int, dict]] = None,
                 vic_claim_chips: Optional[Sequence[int]] = None):
        self._enc = encoder
        requested = encoder.requested.copy()
        non_zero = encoder.non_zero_requested.copy()
        pod_valid = encoder.pod_valid.copy()
        node_valid = encoder.node_valid.copy()
        allocatable = encoder.allocatable
        if add_rows:
            allocatable = allocatable.copy()
            for row in add_rows:
                cap = (add_captured or {}).get(row)
                node_valid[row] = True
                if cap is not None:
                    allocatable[row] = cap["allocatable"]
                    requested[row] = cap["requested"]
                    non_zero[row] = cap["non_zero_requested"]
        for pr, nr in vic_rows:
            requested[nr] -= encoder.pod_request[pr]
            non_zero[nr] -= encoder.pod_non_zero[pr]
            pod_valid[pr] = False
        for row in del_rows:
            node_valid[row] = False
        # DRA: victims release their allocated chips in the mirror too, so
        # host readers (the gang free-chip slice scan) match the device fork
        claim_allocated = encoder.claim_allocated
        if vic_claim_chips is not None and any(vic_claim_chips):
            claim_allocated = claim_allocated.copy()
            for (_pr, nr), chips in zip(vic_rows, vic_claim_chips):
                claim_allocated[nr] -= chips
        self.requested = requested
        self.non_zero_requested = non_zero
        self.pod_valid = pod_valid
        self.node_valid = node_valid
        self.allocatable = allocatable
        self.claim_allocated = claim_allocated

    def __getattr__(self, name):
        return getattr(self._enc, name)


def stack_payloads(payloads: Sequence[ForkPayload]) -> ForkPayload:
    """K same-shape payloads → one [K, ...]-leading payload for vmap."""
    first = payloads[0]
    if first.add_rows is None:
        add_rows = add_ok = add_vals = None
    else:
        add_rows = np.stack([p.add_rows for p in payloads])
        add_ok = np.stack([p.add_ok for p in payloads])
        add_vals = tuple(
            np.stack([p.add_vals[i] for p in payloads])
            for i in range(len(first.add_vals))
        )
    return ForkPayload(
        vic_pod_rows=np.stack([p.vic_pod_rows for p in payloads]),
        vic_node_rows=np.stack([p.vic_node_rows for p in payloads]),
        aff_rows=np.stack([p.aff_rows for p in payloads]),
        aff_vals=np.stack([p.aff_vals for p in payloads]),
        del_rows=np.stack([p.del_rows for p in payloads]),
        add_rows=add_rows, add_ok=add_ok, add_vals=add_vals,
        vic_claim_chips=(
            None if first.vic_claim_chips is None
            else np.stack([p.vic_claim_chips for p in payloads])),
    )
