"""Unified multi-fork counterfactual engine.

The ONE fork-and-resolve machine in the tree (ROADMAP item 2): K candidate
plans — victim masks, template-node adds, host removals — evaluated as a
single ``[K, B, N]`` vmapped solve over K forked DeviceSnapshots, with the
``aff_*`` affinity tables masked so no victim class is refused.

Layer map (COMPONENTS.md has the upstream-analogue table):
  fork.py   — ForkSpec/ForkPayload + the pure traceable ``apply_fork``
              (cluster-autoscaler simulator snapshot / DryRunPreemption
              NodeInfo clone analog)
  engine.py — WhatIfEngine: queue-order staging, fork payload build,
              scheduler-identical engine routing, the vmapped solve
  dryrun.py — preemption's batched dry-run primitives
              (candidate_mask_device, sweep_and_rank)

Consumers: descheduler/planner.py (WhatIfPlanner is a thin wrapper),
autoscaler/controller.py (scale-up/scale-down simulation), preemption.py
(dry-run fan-out).
"""

from .dryrun import PRIORITY_LEVEL_CAP, candidate_mask_device, sweep_and_rank
from .engine import Prediction, WhatIfEngine
from .fork import ForkPayload, ForkSpec, ForkedEncoderView, apply_fork

__all__ = [
    "PRIORITY_LEVEL_CAP",
    "candidate_mask_device",
    "sweep_and_rank",
    "Prediction",
    "WhatIfEngine",
    "ForkPayload",
    "ForkSpec",
    "ForkedEncoderView",
    "apply_fork",
]
