"""Preemption's dry-run fan-out — the victim-mask side of the unified
counterfactual engine.

Reference: pkg/scheduler/framework/preemption/preemption.go DryRunPreemption
(:546) fans one goroutine per candidate node, each cloning NodeInfos and
removing victims.  Here the same counterfactual is two batched primitives
shared by every fork-and-resolve consumer (preemption.py routes through
this module; descheduler/autoscaler forks ride whatif/fork.py's
DeviceSnapshot forks instead):

  - ``candidate_mask_device``: the FORK evaluated lazily for every
    (pod, node) pair at once — "would pod b fit node n with every
    lower-priority pod evicted" as one tensor program (the batched analog
    of the goroutine fan-out);
  - ``sweep_and_rank``: the RESOLVE step — the reprieve sweep +
    pickOneNodeForPreemption ranking over flat candidate arrays,
    dispatching to the native C++ single pass with the numpy parity
    oracle as fallback.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

#: level-table capacity for the segment-sum candidate mask; clusters with
#: more distinct scheduled-pod priorities fall back to the dense einsum
PRIORITY_LEVEL_CAP = 128


def candidate_mask_device(batch, snap, dyn, static_ok_mask, levels=None):
    """bool[B, N]: pod b would resource-fit on node n with every lower-priority
    pod evicted; static (unresolvable) filters must already pass.

    ``levels`` (i32[K], sorted unique scheduled-pod priorities padded with
    i32-max — see TPUScheduler._priority_levels) selects the segment-sum
    path: pods scatter-add their requests into a [K+1, N, R] per-priority-
    level table, an exclusive prefix over levels yields "resources freed by
    evicting everything below priority t", and each batch pod gathers its
    threshold row — O(P·R + K·N·R + B·N·R), ~50 MFLOP at 5k nodes/32k pods.
    Without levels the freed tensor is the dense einsum
    freed[b, n, :] = Σ_p request[p] · [pod on n, priority < b's], a
    B×P×N×R contraction (~275 TFLOP at the same shapes, ~1.4s of device
    time that serialized the pipelined device queue behind every
    speculative candidate dispatch — the dominant PreemptionBasic cost
    after round 4).  Both paths accumulate in f32; summation order may
    differ in the last ulp, never across a fit threshold in practice
    (requests are integer-valued unit counts).
    """
    n = snap.num_nodes
    req = batch.request[:, None, :].astype(jnp.float32)
    free_base = (
        snap.allocatable[None, :, :].astype(jnp.float32)
        - dyn.requested[None, :, :].astype(jnp.float32)
    )
    if levels is not None:
        k = levels.shape[0]
        valid = snap.pod_valid & (snap.pod_node >= 0)
        nrow = jnp.clip(snap.pod_node, 0, n - 1)
        bucket = jnp.searchsorted(levels, snap.pod_priority, side="left")
        bucket = jnp.where(valid, bucket, k)  # invalid → overflow bucket
        w = valid.astype(jnp.float32)
        contrib = snap.pod_request.astype(jnp.float32) * w[:, None]
        table = jnp.zeros((k + 1, n, contrib.shape[1]), jnp.float32)
        table = table.at[bucket, nrow].add(contrib)
        counts = jnp.zeros((k + 1, n), jnp.float32).at[bucket, nrow].add(w)
        # exclusive prefix: row t = totals over levels strictly below t
        prefix = jnp.concatenate(
            [jnp.zeros_like(table[:1]), jnp.cumsum(table[:k], axis=0)]
        )
        prefix_cnt = jnp.concatenate(
            [jnp.zeros_like(counts[:1]), jnp.cumsum(counts[:k], axis=0)]
        )
        tb = jnp.searchsorted(levels, batch.priority, side="left")  # [B]
        freed = prefix[tb]  # [B, N, R]
        has_victims = prefix_cnt[tb] > 0
    else:
        lower = (
            snap.pod_valid[None, :]
            & (snap.pod_priority[None, :] < batch.priority[:, None])
        )  # [B, P]
        prow = jnp.clip(snap.pod_node, 0, n - 1)
        onehot = (
            (prow[:, None] == jnp.arange(n)[None, :])
            & (snap.pod_node >= 0)[:, None]
        ).astype(jnp.float32)  # [P, N]
        # [B, P] × ([P, N] ⊗ [P, R]) → [B, N, R] via two einsums
        freed = jnp.einsum(
            "bp,pn,pr->bnr",
            lower.astype(jnp.float32), onehot,
            snap.pod_request.astype(jnp.float32),
        )
        has_victims = jnp.einsum(
            "bp,pn->bn", lower.astype(jnp.float32), onehot) > 0
    fits = jnp.all((req == 0) | (req <= free_base + freed), axis=-1)
    return fits & has_victims & static_ok_mask


def sweep_and_rank(base, alloc, vr, v_valid, v_viol, v_prio, v_ts, req_v):
    """The reprieve sweep + pickOneNodeForPreemption ranking over flat
    candidate arrays → (victim_mask, nviol, order, valid), or
    (..., None) when no candidate fits at all.

    OUTPUT CONTRACT — valid rows only: victim_mask/nviol/order carry
    meaningful values ONLY for rows where ``valid`` is True (and ``order``
    only up to the first invalid entry).  For infeasible candidates the
    native C++ pass zeroes victim_mask/nviol while the numpy oracle leaves
    real values there (all valid victims, actual violation counts) — the
    two backends intentionally diverge on rows no caller may read, and the
    parity test compares valid rows only.  Consumers of the full outputs
    must gate on ``valid`` or get backend-dependent garbage.

    Dispatches to the native C++ single pass (native/preempt_sweep.cpp)
    when available — the numpy path below is the parity oracle
    (tests/test_preemption.py pins native == numpy on randomized inputs)
    and the fallback without a toolchain or under KTPU_NO_NATIVE."""
    c, vmax = v_valid.shape
    lib = None
    if c and vmax:
        from ..native import load_preempt_sweep

        lib = load_preempt_sweep()
    if lib is not None:
        import ctypes

        i64 = np.ascontiguousarray
        base_c = i64(base, dtype=np.int64)
        alloc_c = i64(alloc, dtype=np.int64)
        vr_c = i64(vr, dtype=np.int64)
        valid_c = np.ascontiguousarray(v_valid, dtype=np.uint8)
        viol_c = np.ascontiguousarray(v_viol, dtype=np.uint8)
        prio_c = i64(v_prio, dtype=np.int64)
        ts_c = np.ascontiguousarray(v_ts, dtype=np.float64)
        req_c = i64(req_v, dtype=np.int64)
        victim_mask = np.zeros((c, vmax), dtype=np.uint8)
        order = np.zeros(c, dtype=np.int32)
        nviol = np.zeros(c, dtype=np.int32)
        valid = np.zeros(c, dtype=np.uint8)

        def p(a, t):
            return a.ctypes.data_as(ctypes.POINTER(t))

        n_valid = lib.ktpu_preempt_sweep(
            c, vmax, base_c.shape[1],
            p(base_c, ctypes.c_int64), p(alloc_c, ctypes.c_int64),
            p(vr_c, ctypes.c_int64), p(valid_c, ctypes.c_uint8),
            p(viol_c, ctypes.c_uint8), p(prio_c, ctypes.c_int64),
            p(ts_c, ctypes.c_double), p(req_c, ctypes.c_int64),
            p(victim_mask, ctypes.c_uint8), p(order, ctypes.c_int32),
            p(nviol, ctypes.c_int32), p(valid, ctypes.c_uint8),
        )
        if n_valid == 0:
            return victim_mask.astype(bool), nviol, order, None
        return victim_mask.astype(bool), nviol, order, valid.astype(bool)

    def fits(u):
        free = alloc - u
        return np.all((req_v == 0) | (req_v <= free), axis=1)

    feasible = fits(base)
    if not feasible.any():
        return None, None, None, None
    used = base.copy()
    reprieved = np.zeros_like(v_valid)
    for vi in range(v_valid.shape[1]):
        trial = used + vr[:, vi]
        ok = fits(trial) & v_valid[:, vi] & feasible
        used = np.where(ok[:, None], trial, used)
        reprieved[:, vi] = ok
    victim_mask = v_valid & ~reprieved
    count = victim_mask.sum(axis=1)
    valid = feasible & (count > 0)
    big = np.int64(1) << 60
    nviol = (victim_mask & v_viol).sum(axis=1)
    top_prio = np.where(victim_mask, v_prio, -big).max(axis=1)
    sum_key = np.where(victim_mask, v_prio + (1 << 31), 0).sum(axis=1)
    is_top = victim_mask & (v_prio == top_prio[:, None])
    earliest = np.where(is_top, v_ts, np.inf).min(axis=1)
    # pickOneNodeForPreemption's lexicographic chain; invalid rows rank
    # last, full ties resolve to the first candidate in window order
    # (np.lexsort is stable; last key is most significant)
    order = np.lexsort((
        -earliest, count, sum_key, top_prio,
        nviol, np.where(valid, 0, 1),
    ))
    return victim_mask, nviol, order, valid
