"""Retrying store transport: jittered exponential backoff honoring Retry-After.

Reference: client-go rest/request.go retries on 429/5xx reading Retry-After
(request.go:927 retryAfterSeconds) and util/retry.OnError for conflict
loops.  ``RetryingStore`` is the in-process analog — an ObjectStore-shaped
wrapper whose writes ride the same (list, watch, get) surface but absorb
TransientApiError and chaos-injected conflicts with bounded retries, so the
scheduler, hollow kubelets, and controllers run unchanged against a faulty
control plane.

Only SYNTHETIC conflicts (InjectedConflict) are resent blind: the store
object really is current, the 409 was injected ahead of it.  A genuine
StaleResourceVersion means the caller read a stale object and must re-read —
it propagates.
"""

from __future__ import annotations

import random
import time

from .faults import InjectedConflict, TransientApiError


def backoff_delay(attempt: int, initial: float, cap: float, rng,
                  floor: float = 0.0) -> float:
    """Jittered exponential backoff with an optional Retry-After floor —
    the ONE implementation of the wait every retrying path uses
    (RetryingStore, HTTPApiClient._request, Reflector's relist loop).
    Full jitter (client-go wait.Backoff Jitter) keeps a fault storm's
    retries from re-colliding in lockstep; ``floor`` carries the server's
    Retry-After hint, which always wins when longer."""
    backoff = min(cap, initial * (2 ** attempt))
    return max(floor, backoff * (0.5 + rng.random()))


class RetryingStore:
    """Wraps any ObjectStore-shaped store with write retries.

    Reads (get/list/watch/...) pass straight through — the sim injects
    faults on writes and watch streams, and read retry would add nothing to
    the paths under test.  ``sleep`` is injectable so fast tests can no-op
    the backoff while keeping the retry accounting real.
    """

    def __init__(self, store, max_retries: int = 6,
                 backoff_initial: float = 0.01, backoff_max: float = 0.5,
                 jitter_seed: int = 0, sleep=time.sleep):
        self._store = store
        self.max_retries = max_retries
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        self._rng = random.Random(jitter_seed)
        self._sleep = sleep
        self.retries = 0  # total resends across all ops (determinism probe)

    @property
    def CLUSTER_SCOPED(self):  # noqa: N802 — mirrors ObjectStore's attr
        return self._store.CLUSTER_SCOPED

    def _retry(self, fn):
        from ..metrics import scheduler_metrics as m

        attempt = 0
        while True:
            try:
                return fn()
            except TransientApiError as e:
                if attempt >= self.max_retries:
                    raise
                self.retries += 1
                m.client_request_retries.inc((str(e.code),))
                self._sleep(backoff_delay(attempt, self.backoff_initial,
                                          self.backoff_max, self._rng,
                                          floor=e.retry_after))
            except InjectedConflict:
                if attempt >= self.max_retries:
                    raise
                self.retries += 1
                m.client_request_retries.inc(("409",))
                self._sleep(backoff_delay(attempt, self.backoff_initial,
                                          self.backoff_max, self._rng))
            attempt += 1

    # --- retried writes ------------------------------------------------------

    def create(self, kind: str, obj) -> int:
        return self._retry(lambda: self._store.create(kind, obj))

    def update(self, kind: str, obj, expected_rv=None) -> int:
        return self._retry(
            lambda: self._store.update(kind, obj, expected_rv=expected_rv))

    def delete(self, kind: str, namespace: str, name: str):
        return self._retry(lambda: self._store.delete(kind, namespace, name))

    def bind_pod(self, namespace: str, name: str, node_name: str,
                 trace_parent=None) -> bool:
        # span-context handoff forwarded (sim/store.py bind_pod) — but the
        # scheduler probes THIS wrapper's signature, so forward only when
        # the wrapped store itself takes the kwarg (an HTTP facade does
        # not; blindly forwarding would TypeError every bind into the
        # transient-retry path forever)
        takes = getattr(self, "_bind_takes_trace", None)
        if takes is None:
            from ..utils import takes_kwarg

            takes = self._bind_takes_trace = takes_kwarg(
                self._store.bind_pod, "trace_parent")
        if takes:
            return self._retry(
                lambda: self._store.bind_pod(namespace, name, node_name,
                                             trace_parent=trace_parent))
        return self._retry(
            lambda: self._store.bind_pod(namespace, name, node_name))

    # --- passthrough reads / watch -------------------------------------------

    def __getattr__(self, attr):
        # get, list, list_namespaced, watch, current_rv, fault, _objects ...
        return getattr(self._store, attr)
