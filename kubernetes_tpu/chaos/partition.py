"""Network-partition chaos: kill lease renewals for node sets, whole zones,
and flapping subsets — deterministically, riding the seeded FaultSchedule —
plus the node-storm soak workload shared by tests/test_node_lifecycle.py and
tools/node_storm_soak.py.

The driver operates at the only layer a real partition touches: the node's
Lease renewals stop (HollowNode.fail), nothing else changes.  Detection,
zone aggregation, taints, tolerationSeconds countdowns, rate-limited
sweeps, and gang repair are all the NodeLifecycleController's job — the
soak asserts the ISSUE-13 contract end to end:

  - a whole zone going dark (FullDisruption) produces ZERO evictions while
    the outage holds, and healing cancels every pending countdown;
  - scattered failures drain at the zone's current token rate (secondary
    rate in PartialDisruption) — never a storm;
  - a gang losing one host is failed atomically and rebound EXACTLY once
    (store-history probe over (name, incarnation) bind transitions);
  - PDBs hold throughout (the shared gate refuses, never overrides);
  - the same seed replays the same kill sequence to the same final
    bindings.

Determinism contract: node subsets are chosen by blake2s rolls keyed on
(seed, tag, node name) — the smallest-roll k names — so thread timing,
dict order, and wall clock never enter a kill decision; all deadline math
runs on the injected clock.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..sim.hollow_node import HollowCluster, HollowNode
from .faults import FaultSchedule


class PartitionDriver:
    """Deterministic lease-renewal killer over a HollowCluster."""

    def __init__(self, cluster: HollowCluster,
                 schedule: Optional[FaultSchedule] = None, seed: int = 0,
                 clock=time.monotonic):
        self.cluster = cluster
        self.schedule = schedule or FaultSchedule(seed)
        self.clock = clock
        self._by_name: Dict[str, HollowNode] = {
            n.name: n for n in cluster.nodes}
        # (clock seconds, action, node name) in execution order — the
        # replay probe: same seed → identical sequence
        self.kill_log: List[Tuple[float, str, str]] = []
        # name → (down_seconds, up_seconds, epoch) flap cycle; phase
        # derives from the injected clock (per-name epoch: registering a
        # second flap set must not rephase earlier ones), so flapping is
        # pure state, not a thread
        self._flapping: Dict[str, Tuple[float, float, float]] = {}

    # --- deterministic selection ----------------------------------------------

    def _roll(self, tag: str, name: str) -> float:
        # the schedule's own blake2s primitive — ONE deterministic-roll
        # implementation per package, so same-seed replay symmetry can't
        # drift between fault classes
        return self.schedule._roll("partition", tag, name)

    def pick(self, names: List[str], k: int, tag: str = "pick") -> List[str]:
        """The k names with the smallest seeded rolls — a pure function of
        (seed, tag, name), independent of list order."""
        return sorted(sorted(names), key=lambda n: self._roll(tag, n))[:k]

    def zone_nodes(self, zone: str,
                   zone_label: str = "topology.kubernetes.io/zone") -> List[str]:
        return sorted(n.name for n in self.cluster.nodes
                      if n.labels.get(zone_label) == zone)

    # --- kill / heal ----------------------------------------------------------

    def _record(self, action: str, name: str) -> None:
        self.kill_log.append((self.clock(), action, name))
        with self.schedule._lock:
            self.schedule.injected[f"partition_{action}"] = (
                self.schedule.injected.get(f"partition_{action}", 0) + 1)

    def partition_nodes(self, names: List[str]) -> List[str]:
        for name in sorted(names):
            node = self._by_name[name]
            if node.alive:
                node.fail()
                self._record("kill", name)
        return sorted(names)

    def heal_nodes(self, names: List[str]) -> None:
        for name in sorted(names):
            node = self._by_name[name]
            self._flapping.pop(name, None)
            if not node.alive:
                node.recover()
                self._record("heal", name)

    def partition_zone(self, zone: str) -> List[str]:
        """Whole zone dark: every lease renewal in the zone stops."""
        return self.partition_nodes(self.zone_nodes(zone))

    def heal_zone(self, zone: str) -> None:
        self.heal_nodes(self.zone_nodes(zone))

    def scatter(self, fraction: float, zone: Optional[str] = None,
                tag: str = "scatter") -> List[str]:
        """Kill a deterministic ``fraction`` of the (zone's) nodes."""
        pool = (self.zone_nodes(zone) if zone is not None
                else sorted(self._by_name))
        k = max(1, int(round(len(pool) * fraction)))
        victims = self.pick(pool, k, tag=tag)
        return self.partition_nodes(victims)

    # --- flapping -------------------------------------------------------------

    def flap(self, names: List[str], down_seconds: float,
             up_seconds: float) -> None:
        """Register a down/up cycle for ``names``; ``step()`` applies the
        phase the injected clock implies.  Phase 0 starts DOWN (the node
        dies the moment flapping starts); each name's cycle anchors on its
        own registration time, so later flap sets never rephase earlier
        ones."""
        epoch = self.clock()
        for name in sorted(names):
            self._flapping[name] = (float(down_seconds), float(up_seconds),
                                    epoch)
        self.step()

    def step(self) -> None:
        """Apply flap phases for the current injected-clock time."""
        now = self.clock()
        for name, (down, up, epoch) in sorted(self._flapping.items()):
            node = self._by_name[name]
            t = (now - epoch) % (down + up)
            should_be_down = t < down
            if should_be_down and node.alive:
                node.fail()
                self._record("kill", name)
            elif not should_be_down and not node.alive:
                node.recover()
                self._record("heal", name)


# --- the node-storm soak ------------------------------------------------------


@dataclass
class StormResult:
    nodes: int
    pods: int
    # phase A: zone-wide outage
    outage_zone_mode: str = ""            # must hold FullDisruption
    outage_evictions: int = 0             # must be 0
    cancelled_on_heal: float = 0.0        # countdowns cancelled at heal > 0
    # phase B: scattered failures
    scattered_zone_mode: str = ""         # PartialDisruption
    scattered_swept: int = 0              # nodes drained during the window
    scattered_budget: int = 0             # token-math upper bound
    # phase C: gang repair (delta over phase C alone — scattered failures
    # in phase B may legitimately down a gang host too; every repair is
    # still exactly-once per outage via the bind probe)
    gang_repairs: float = 0.0             # must be 1
    gang_member_binds: Dict[Tuple[str, int], int] = field(default_factory=dict)
    # invariants across all phases
    pdb_floor_held: bool = True           # live protected pods ≥ minAvailable
    overridden_evictions: float = 0.0     # gate never overrode a PDB
    unbound: List[str] = field(default_factory=list)
    final_bindings: Dict[str, str] = field(default_factory=dict)
    kill_log: List[Tuple[float, str, str]] = field(default_factory=list)
    injected: Dict[str, int] = field(default_factory=dict)
    wall_seconds: float = 0.0

    @property
    def converged(self) -> bool:
        return (self.outage_evictions == 0
                and self.outage_zone_mode == "FullDisruption"
                and self.scattered_swept <= self.scattered_budget
                and self.gang_repairs == 1
                and all(c == 1 for c in self.gang_member_binds.values())
                and self.pdb_floor_held
                and self.overridden_evictions == 0
                and not self.unbound)

    def determinism_signature(self) -> Dict[str, object]:
        """The replay-stable view: kill sequence, fault counts, and the
        final binding map (pod → node)."""
        return {"kill_log": list(self.kill_log),
                "injected": dict(self.injected),
                "final_bindings": dict(self.final_bindings)}


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def run_node_storm(
    nodes_per_zone: int = 6,
    n_zones: int = 3,
    seed: int = 7,
    *,
    web_replicas: Optional[int] = None,
    gang_size: int = 3,
    grace: float = 40.0,
    secondary_qps: float = 0.01,
    large_zone_threshold: Optional[int] = None,
    toleration_seconds: int = 120,
) -> StormResult:
    """The ISSUE-13 acceptance scenario on a fake clock (fully
    deterministic): zone outage → heal → scattered partial disruption →
    gang-hosting node death → convergence.  Tier-1 runs the small shape;
    tools/node_storm_soak.py runs 3×100."""
    from ..api import objects as v1
    from ..controllers.disruption import DisruptionController
    from ..controllers.nodelifecycle import (
        ZONE_FULL, ZONE_PARTIAL, NodeLifecycleController)
    from ..gang import POD_GROUP_LABEL
    from ..metrics import scheduler_metrics as m
    from ..scheduler import TPUScheduler
    from ..sim.store import DELETED, MODIFIED, ObjectStore
    from ..testutil import make_pod

    t0 = time.monotonic()
    clock = _FakeClock()
    store = ObjectStore()
    n_nodes = nodes_per_zone * n_zones
    web_replicas = (2 * n_nodes if web_replicas is None else web_replicas)
    if large_zone_threshold is None:
        # make every zone "large" so PartialDisruption gets the secondary
        # rate instead of the small-cluster full stop
        large_zone_threshold = max(1, nodes_per_zone - 1)

    cancelled_before = sum(
        v for (labels, v) in m.node_lifecycle_evictions.items().items()
        if labels and labels[1] == "cancelled")

    sched = TPUScheduler(store, batch_size=32, clock=clock, batch_wait=0)
    sched.presize(n_nodes, web_replicas + gang_size + 64)
    cluster = HollowCluster(store, n_nodes, clock=clock, zones=n_zones)
    fault = FaultSchedule(seed)
    driver = PartitionDriver(cluster, fault, clock=clock)
    lifecycle = NodeLifecycleController(
        store, grace_period=grace, clock=clock,
        gang_directory=sched.gangs,
        secondary_eviction_qps=secondary_qps,
        large_zone_threshold=large_zone_threshold)
    disruption = DisruptionController(store)

    # --- workload: deterministic-name pods the harness itself re-creates
    # (a stand-in for the ReplicaSet controller whose generated names ride
    # a process-global counter — replay needs name-stable replacements).
    # Each name's Nth re-creation carries uid "<name>/rN".
    desired: Dict[str, dict] = {}
    generation: Dict[str, int] = {}

    def _spec(name: str, labels: Dict[str, str], cpu: str = "1",
              tol_seconds: Optional[int] = None):
        desired[name] = {"labels": labels, "cpu": cpu, "tol": tol_seconds}

    def _reconcile() -> int:
        created = 0
        for name, spec in desired.items():
            if store.get("Pod", "default", name) is not None:
                continue
            gen = generation.get(name, 0) + 1
            generation[name] = gen
            b = (make_pod().name(name).uid(f"{name}/r{gen}")
                 .namespace("default").req({"cpu": spec["cpu"]}))
            for k, val in spec["labels"].items():
                b = b.label(k, val)
            if spec["tol"] is not None:
                b = b.toleration(
                    key="node.kubernetes.io/unreachable",
                    operator=v1.TOLERATION_OP_EXISTS, effect="NoExecute",
                    toleration_seconds=spec["tol"])
            store.create("Pod", b.obj())
            created += 1
        return created

    for i in range(web_replicas):
        # half the web fleet carries a tolerationSeconds countdown — the
        # heal phase must cancel those instead of letting them fire
        _spec(f"web-{i:04d}", {"app": "web"},
              tol_seconds=(toleration_seconds if i % 2 == 0 else None))
    store.create("PodGroup", v1.PodGroup(
        metadata=v1.ObjectMeta(name="gang0", namespace="default"),
        min_member=gang_size, schedule_timeout_seconds=60))
    for i in range(gang_size):
        _spec(f"gang0-{i}", {POD_GROUP_LABEL: "gang0", "app": "gang"})
    pdb_floor = max(1, int(0.6 * web_replicas))
    store.create("PodDisruptionBudget", v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="web-pdb", namespace="default"),
        selector=v1.LabelSelector(match_labels={"app": "web"}),
        min_available=pdb_floor))

    result = StormResult(nodes=n_nodes, pods=web_replicas + gang_size)

    def _web_bound() -> int:
        # BOUND pods only: recreated-but-unbound replacements must not
        # satisfy the floor, or the probe could never fail
        return sum(1 for p in store.list("Pod")[0]
                   if p.metadata.labels.get("app") == "web"
                   and p.spec.node_name)

    probe_armed = False  # armed once the initial placement completes —
    # before anything was ever scheduled there is nothing to protect

    def settle(steps: int, dt: float) -> None:
        for _ in range(steps):
            clock.advance(dt)
            driver.step()
            cluster.heartbeat_all()
            disruption.sync_once()
            lifecycle.sync_once()
            # probe BEFORE replacements are recreated: the gate alone must
            # have kept ≥ minAvailable members standing (pods on dead but
            # unevicted nodes count — that is exactly the freeze contract)
            if probe_armed and _web_bound() < pdb_floor:
                result.pdb_floor_held = False
            _reconcile()
            sched.run_until_idle(max_cycles=20)
            cluster.sync_all()

    def deleted_pods() -> int:
        return sum(1 for ev in store._log
                   if ev.kind == "Pod" and ev.type == DELETED)

    # --- phase 0: schedule everything onto the healthy cluster
    _reconcile()
    settle(3, 1.0)
    probe_armed = True

    # --- phase A: whole zone dark → FullDisruption freeze, zero evictions
    driver.partition_zone("zone-0")
    before = deleted_pods()
    settle(6, grace / 2)  # well past grace, outage holds
    result.outage_zone_mode = lifecycle.zone_mode("zone-0")
    result.outage_evictions = deleted_pods() - before
    driver.heal_zone("zone-0")
    settle(2, 1.0)
    cancelled_now = sum(
        v for (labels, v) in m.node_lifecycle_evictions.items().items()
        if labels and labels[1] == "cancelled")
    result.cancelled_on_heal = cancelled_now - cancelled_before

    # --- phase B: scattered failures in zone-1 → PartialDisruption,
    # sweeps bounded by the secondary token rate.  The sweep count is the
    # controller's own draining set (a node enters it exactly when its
    # rate-limited pop ran); the budget is the token math over the whole
    # window (conservative: tokens only accrue once the zone is Partial)
    # plus the one banked burst token.
    victims = driver.scatter(0.6, zone="zone-1", tag="scatter-b")
    scatter_window = 4 * grace
    settle(20, scatter_window / 20)
    result.scattered_zone_mode = lifecycle.zone_mode("zone-1")
    result.scattered_swept = len(set(victims) & lifecycle.draining)
    result.scattered_budget = 1 + int(secondary_qps * scatter_window) + 1
    driver.heal_nodes(victims)
    settle(4, 5.0)

    # --- phase C: a gang-hosting node dies → atomic repair, rebound once
    gang_repairs_before = m.gang_repairs.value()
    gang_nodes = sorted({p.spec.node_name for p in store.list("Pod")[0]
                         if p.metadata.labels.get(POD_GROUP_LABEL)
                         and p.spec.node_name})
    if gang_nodes:
        driver.partition_nodes(gang_nodes[:1])
        settle(4, grace)  # detect + sweep + repair + requeue + rebind
        driver.heal_nodes(gang_nodes[:1])
        settle(4, 5.0)
    result.gang_repairs = m.gang_repairs.value() - gang_repairs_before

    # --- exactly-once probe: (name, incarnation) → bind transitions
    node_of: Dict[str, Optional[str]] = {}
    incarnation: Dict[str, int] = {}
    for ev in store._log:
        if ev.kind != "Pod":
            continue
        name = ev.obj.metadata.name
        if ev.type == DELETED:
            node_of.pop(name, None)
            incarnation[name] = incarnation.get(name, 0) + 1
            continue
        nn = ev.obj.spec.node_name or None
        if nn is not None and node_of.get(name) is None:
            if name.startswith("gang0-"):
                key = (name, incarnation.get(name, 0))
                result.gang_member_binds[key] = (
                    result.gang_member_binds.get(key, 0) + 1)
        node_of[name] = nn

    result.overridden_evictions = sum(
        v for (labels, v) in m.descheduler_evictions.items().items()
        if labels and labels[0] == "nodelifecycle"
        and labels[1] == "overridden")
    pods, _ = store.list("Pod")
    result.unbound = [p.metadata.name for p in pods if not p.spec.node_name]
    result.final_bindings = {p.metadata.name: p.spec.node_name for p in pods}
    result.kill_log = list(driver.kill_log)
    result.injected = fault.injected_counts()
    result.wall_seconds = time.monotonic() - t0
    sched.close(flush_events=False)
    return result
