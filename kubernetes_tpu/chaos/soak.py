"""Convergence-under-failure soak: a HollowCluster workload scheduled end to
end while a seeded FaultSchedule cuts watch streams, sheds writes with 429s,
and storms CAS conflicts — with one extender outage riding along.

Shared by tests/test_chaos.py (small fast battery + the full slow soak) and
tools/chaos_soak.py (the local full-size runner), so the acceptance workload
is one definition, not two drifting copies.

What converging means here (the honest-scale-claim prerequisite):
  - every pod bound EXACTLY once (one bind MODIFIED event per pod in the
    store's history — no duplicate or lost binds through the retry paths);
  - zero scheduler crashes (every fault routed through retry/requeue);
  - bounded retries: each injected write fault is absorbed by exactly one
    client resend (store_retries == injected write faults);
  - determinism: the same seed injects the same faults and costs the same
    retries across runs — fault decisions key on per-object operation
    sequences, not wall-clock interleavings (chaos/faults.py).
"""

from __future__ import annotations

import time
from collections import Counter
from dataclasses import dataclass
from typing import Dict, List

from ..client.informer import InformerFactory
from ..sim.hollow_node import HollowCluster
from ..sim.store import MODIFIED, ObjectStore
from .faults import FaultSchedule
from .retry import RetryingStore


@dataclass
class SoakResult:
    pods: int
    bound: int
    duplicate_binds: int
    unbound: List[str]
    injected: Dict[str, int]
    store_retries: int
    informer_relists: int
    informer_items: int
    circuit_state: int  # final extender circuit state (-1: no extender ran)
    wall_seconds: float

    @property
    def converged(self) -> bool:
        return (self.bound == self.pods and self.duplicate_binds == 0
                and not self.unbound)

    def determinism_signature(self) -> Dict[str, object]:
        """The replay-stable part of a run: injected fault counts + the
        retries they cost.  Wall time, cycle counts, and extender callout
        counts are wall-clock-shaped and excluded on purpose."""
        return {"injected": dict(self.injected),
                "store_retries": self.store_retries}


def run_soak(
    n_pods: int = 500,
    n_nodes: int = 50,
    seed: int = 7,
    batch_size: int = 64,
    *,
    watch_drop_rate: float = 0.10,
    write_429_rate: float = 0.05,
    write_500_rate: float = 0.02,
    conflict_rate: float = 0.03,
    extender_outage: bool = True,
    timeout_seconds: float = 300.0,
) -> SoakResult:
    """Drive ``n_pods`` through a faulty control plane until convergence.

    The default rates match the acceptance bar: ≥10% watch drops, 5% write
    429s (plus 500s and a conflict storm), one ignorable extender hard down
    (connection refused) so its circuit opens and the cycle degrades
    around it.
    """
    from ..extender import ExtenderConfig, HTTPExtender
    from ..scheduler import TPUScheduler
    from ..testutil import make_pod

    fault = FaultSchedule(
        seed,
        watch_drop_rate=watch_drop_rate,
        write_429_rate=write_429_rate,
        write_500_rate=write_500_rate,
        conflict_rate=conflict_rate,
        retry_after=0.01,
        slow_rate=0.0,
    )
    raw = ObjectStore(fault_injector=fault)
    store = RetryingStore(raw, jitter_seed=seed)

    # a relisting pod informer rides along: watch drops must cost it
    # relists, not correctness (its cache is checked at the end)
    factory = InformerFactory(store)
    pod_informer = factory.informer("Pod")
    factory.start()

    extenders = []
    if extender_outage:
        # hard-down ignorable extender: port 9 (discard) refuses instantly;
        # after failure_threshold trips the circuit opens and stays open
        # for the whole run (reset far beyond the soak) — pods keep
        # scheduling without it
        extenders = [HTTPExtender(ExtenderConfig(
            url_prefix="http://127.0.0.1:9", filter_verb="filter",
            ignorable=True, http_timeout=0.2,
            failure_threshold=3, circuit_reset_seconds=3600.0,
        ))]

    sched = TPUScheduler(
        store, batch_size=batch_size, extenders=extenders,
        pod_initial_backoff=0.05, pod_max_backoff=0.5, batch_wait=0.05,
    )
    sched.presize(n_nodes, n_pods)
    HollowCluster(store, n_nodes)

    t0 = time.monotonic()
    for i in range(n_pods):
        store.create(
            "Pod",
            make_pod().name(f"chaos-{i:05d}").uid(f"chaos-{i:05d}")
            .namespace("default").req({"cpu": "1"}).obj(),
        )

    deadline = t0 + timeout_seconds
    while time.monotonic() < deadline:
        sched.run_until_idle(max_cycles=50 * (n_pods // batch_size + 1))
        pods, _ = raw.list("Pod")
        unbound = [p for p in pods if not p.spec.node_name]
        if not unbound:
            break
        # stragglers parked in unschedulableQ (a requeue that missed the
        # event window would otherwise wait the 60s flush): activate and
        # re-drive — the failure handler's contract is retry, not loss
        sched.queue.activate(unbound)
    wall = time.monotonic() - t0

    pods, _ = raw.list("Pod")
    bound = sum(1 for p in pods if p.spec.node_name)
    unbound_names = [p.metadata.name for p in pods if not p.spec.node_name]
    # exactly-once binding, from the store's own event history: with no
    # hollow syncs or preemption in this workload, every Pod MODIFIED is a
    # bind — more than one per pod means a duplicate bind slipped through
    binds = Counter(
        ev.obj.metadata.name for ev in raw._log
        if ev.kind == "Pod" and ev.type == MODIFIED
    )
    duplicate_binds = sum(c - 1 for c in binds.values() if c > 1)

    circuit_state = extenders[0].breaker.state if extenders else -1
    result = SoakResult(
        pods=n_pods,
        bound=bound,
        duplicate_binds=duplicate_binds,
        unbound=unbound_names,
        injected=fault.injected_counts(),
        store_retries=store.retries,
        informer_relists=pod_informer.reflector.relists,
        informer_items=len(pod_informer.list()),
        circuit_state=circuit_state,
        wall_seconds=wall,
    )
    factory.stop()
    return result
