"""Chaos harness: seeded fault injection + the retry transports it exercises.

Layout:
  - faults.py — FaultSchedule (deterministic per-key fault decisions),
    TransientApiError / InjectedConflict / WatchDropped, steal_lease,
    and the deterministic crash-point framework (CRASH_POINTS catalog,
    ProcessCrash, maybe_crash hooks wired at the real call sites — the
    recovery layer's kill switch)
  - retry.py  — RetryingStore (Retry-After-honoring write retries)
  - soak.py   — the convergence-under-failure workload driver
    (tests/test_chaos.py battery + tools/chaos_soak.py share it)
  - partition.py — PartitionDriver (deterministic lease-renewal kills for
    node sets / whole zones / flapping subsets) + run_node_storm, the
    node-lifecycle storm soak (tests/test_node_lifecycle.py battery +
    tools/node_storm_soak.py share it)
  - replication.py — ShipFaults (deterministic ship-stream drops / torn
    batches / lag spikes) + run_replication_soak, the two-follower
    WAL-shipping failover soak (tests/test_replication.py battery +
    tools/replica_soak.py share it); scheduler-free, so it stays jax-free

soak and partition are imported lazily — they pull in the scheduler (and
jax); the fault primitives stay importable from stdlib-only contexts
(subprocess servers).
"""

from .faults import (  # noqa: F401
    CRASH_MID_CRD_REGISTER,
    CRASH_MID_ZONE_EVICT,
    CRASH_POINTS,
    CRASH_PRE_WAL_FSYNC,
    CRASH_TORN_WAL_WRITE,
    FaultSchedule,
    InjectedConflict,
    ProcessCrash,
    TransientApiError,
    WatchDropped,
    crash_schedule,
    install_crash_schedule,
    maybe_crash,
    maybe_torn_write,
    steal_lease,
)
from .replication import ShipFaults, run_replication_soak  # noqa: F401
from .retry import RetryingStore  # noqa: F401

__all__ = [
    "CRASH_MID_CRD_REGISTER",
    "CRASH_MID_ZONE_EVICT",
    "CRASH_POINTS",
    "CRASH_PRE_WAL_FSYNC",
    "CRASH_TORN_WAL_WRITE",
    "FaultSchedule",
    "InjectedConflict",
    "ProcessCrash",
    "TransientApiError",
    "WatchDropped",
    "RetryingStore",
    "ShipFaults",
    "run_replication_soak",
    "crash_schedule",
    "install_crash_schedule",
    "maybe_crash",
    "maybe_torn_write",
    "steal_lease",
]
