"""Chaos harness: seeded fault injection + the retry transports it exercises.

Layout:
  - faults.py — FaultSchedule (deterministic per-key fault decisions),
    TransientApiError / InjectedConflict / WatchDropped, steal_lease
  - retry.py  — RetryingStore (Retry-After-honoring write retries)
  - soak.py   — the convergence-under-failure workload driver
    (tests/test_chaos.py battery + tools/chaos_soak.py share it)

soak is imported lazily — it pulls in the scheduler (and jax); the fault
primitives stay importable from stdlib-only contexts (subprocess servers).
"""

from .faults import (  # noqa: F401
    FaultSchedule,
    InjectedConflict,
    TransientApiError,
    WatchDropped,
    steal_lease,
)
from .retry import RetryingStore  # noqa: F401

__all__ = [
    "FaultSchedule",
    "InjectedConflict",
    "TransientApiError",
    "WatchDropped",
    "RetryingStore",
    "steal_lease",
]
