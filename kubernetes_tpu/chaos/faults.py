"""Seeded, deterministic fault injection for the sim control plane.

The reference survives production because every layer assumes its neighbor
is flaky: the apiserver sheds load with 429 + Retry-After (API Priority and
Fairness, staging/src/k8s.io/apiserver/pkg/server/filters), etcd surfaces
conflicts that GuaranteedUpdate retries, watch streams drop and client-go
reflectors relist.  ``FaultSchedule`` reproduces those failure modes inside
the sim so the retry/degradation machinery (RetryingStore, the HTTP client's
Retry-After transport, the informer relist path, the extender circuit
breaker) can be exercised end to end.

Determinism contract
--------------------
Every decision is a pure function of ``(seed, tag, key, seq)`` where ``seq``
is a per-key counter: the Nth write to Pod ``p42`` sees the same fault in
every run with the same seed, REGARDLESS of thread interleavings or how the
scheduler groups its batches.  The per-key sequence (create, bind, ...) is
what must be deterministic for replay — wall-clock ordering across keys is
not.  Hashing is blake2s (process-independent — Python's tuple ``hash`` is
salted per process and would break replay — and with real avalanche: crc32
clusters sequential names like pod-0001/pod-0002 into near-identical rolls,
turning a 5% rate into all-or-nothing per name prefix).

Faults are injected BEFORE the guarded mutation applies (a rejected write
never half-happened), so a retry after TransientApiError/InjectedConflict is
always safe — the in-process analog of an apiserver 429 rejected at
admission, before storage.

Wiring: pass one schedule to EITHER ``ObjectStore(fault_injector=...)`` (in-
process actors) OR ``APIServer(fault_injector=...)`` (HTTP actors) — wiring
both layers of the same stack double-injects.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, Optional, Tuple

from ..sim.store import StaleResourceVersion


class TransientApiError(RuntimeError):
    """A retryable control-plane failure (429/500/503 analog).

    ``retry_after`` carries the server's Retry-After hint in seconds;
    retrying transports (chaos.retry.RetryingStore, HTTPApiClient) honor it.
    """

    def __init__(self, code: int, retry_after: float = 0.0, message: str = ""):
        super().__init__(message or f"transient API error {code}")
        self.code = code
        self.retry_after = retry_after


class InjectedConflict(StaleResourceVersion):
    """A chaos-injected 409 (CAS-conflict storm).

    Subclasses StaleResourceVersion so existing 409 handling (the apiserver's
    Conflict response, controller read-modify-write loops) applies unchanged;
    the distinct type lets RetryingStore know the conflict is synthetic —
    the stored object is actually current, so a plain resend is correct
    (a REAL stale rv must be re-read by the caller instead).
    """


class WatchDropped(ConnectionError):
    """Delivered to a watcher's on_error callback when its stream is cut."""


class FaultSchedule:
    """One seeded schedule of fault decisions across all fault classes.

    Rates are independent probabilities per operation; ``max_faults_per_key``
    bounds the injected failures any single (op, kind, name) can see so a
    bounded-retry client always converges (an unlucky key cannot 429
    forever).  ``exempt_kinds`` defaults to Event: best-effort event writes
    retrying through injected faults would add nondeterministic op sequences
    without exercising anything new.
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        watch_drop_rate: float = 0.0,
        write_429_rate: float = 0.0,
        write_500_rate: float = 0.0,
        write_503_rate: float = 0.0,
        conflict_rate: float = 0.0,
        slow_rate: float = 0.0,
        slow_seconds: float = 0.02,
        retry_after: float = 0.02,
        max_faults_per_key: int = 3,
        exempt_kinds=frozenset({"Event"}),
    ):
        self.seed = seed
        self.watch_drop_rate = watch_drop_rate
        self.write_429_rate = write_429_rate
        self.write_500_rate = write_500_rate
        self.write_503_rate = write_503_rate
        self.conflict_rate = conflict_rate
        self.slow_rate = slow_rate
        self.slow_seconds = slow_seconds
        self.retry_after = retry_after
        self.max_faults_per_key = max_faults_per_key
        self.exempt_kinds = frozenset(exempt_kinds)
        # RLock: the memoized watch-drop path holds it across _seq/_record
        self._lock = threading.RLock()
        self._counters: Dict[tuple, int] = {}
        self._key_faults: Dict[tuple, int] = {}
        # (kind, name, rv) → decision, so N concurrent watch streams of the
        # same store share ONE deterministic decision per event (see
        # should_drop_watch)
        self._drop_memo: Dict[tuple, bool] = {}
        # fault class → total injected; equal across same-seed runs whenever
        # each key's op sequence is deterministic (the soak's assertion)
        self.injected: Dict[str, int] = {}

    # --- deterministic primitives -------------------------------------------

    def _roll(self, *parts) -> float:
        digest = hashlib.blake2s(
            "|".join(map(str, (self.seed,) + parts)).encode(),
            digest_size=8,
        ).digest()
        return int.from_bytes(digest, "big") / 2.0**64

    def _seq(self, *key) -> int:
        with self._lock:
            n = self._counters.get(key, 0)
            self._counters[key] = n + 1
            return n

    def _record(self, fault: str, key: tuple):
        from ..metrics import scheduler_metrics as m

        with self._lock:
            self.injected[fault] = self.injected.get(fault, 0) + 1
            self._key_faults[key] = self._key_faults.get(key, 0) + 1
        m.chaos_faults_injected.inc((fault,))

    def _exhausted(self, key: tuple) -> bool:
        with self._lock:
            return self._key_faults.get(key, 0) >= self.max_faults_per_key

    def injected_counts(self) -> Dict[str, int]:
        """Snapshot of fault-class → injected count (the determinism probe)."""
        with self._lock:
            return dict(self.injected)

    # --- hooks consumed by sim/store.py -------------------------------------

    def write_fault(self, op: str, kind: str, name: str) -> None:
        """Raise the scheduled fault (if any) for this write attempt.

        Called by ObjectStore create/update/delete/bind BEFORE the mutation
        and before taking the store lock (injected delays must not stall
        unrelated writers).
        """
        if kind in self.exempt_kinds:
            return
        self.maybe_delay(op, kind, name)
        seq = self._seq("write", op, kind, name)
        key = (op, kind, name)
        if self._exhausted(key):
            return
        r = self._roll("write", op, kind, name, seq)
        edge = self.write_429_rate
        if r < edge:
            self._record("write_429", key)
            raise TransientApiError(429, self.retry_after,
                                    f"chaos: 429 on {op} {kind}/{name}")
        edge += self.write_500_rate
        if r < edge:
            self._record("write_500", key)
            raise TransientApiError(500, 0.0,
                                    f"chaos: 500 on {op} {kind}/{name}")
        edge += self.write_503_rate
        if r < edge:
            self._record("write_503", key)
            raise TransientApiError(503, self.retry_after,
                                    f"chaos: 503 on {op} {kind}/{name}")
        edge += self.conflict_rate
        if r < edge and op in ("update", "bind"):
            self._record("conflict", key)
            raise InjectedConflict(
                f"chaos: conflict storm on {op} {kind}/{name}")

    def should_drop_watch(self, kind: str, name: str, rv=None) -> bool:
        """Decide whether the watch stream carrying this event is cut.

        Keyed by the EVENT (kind, name, per-key event seq), not the watcher:
        the decision stays deterministic even when watcher subscription
        order varies between runs.  Callers that know the event's
        resourceVersion pass ``rv`` so N independent streams carrying the
        SAME event (each HTTP watch connection consults separately) share
        one memoized decision — without it each stream would consume its
        own sequence number and the injected count would depend on how many
        watchers happened to be connected (thread-interleaving-shaped,
        which the determinism contract forbids).
        """
        if self.watch_drop_rate <= 0 or kind in self.exempt_kinds:
            return False
        if rv is None:
            return self._decide_drop(kind, name)
        with self._lock:
            memo_key = (kind, name, rv)
            if memo_key not in self._drop_memo:
                self._drop_memo[memo_key] = self._decide_drop(kind, name)
            return self._drop_memo[memo_key]

    def _decide_drop(self, kind: str, name: str) -> bool:
        seq = self._seq("watch", kind, name)
        key = ("watch", kind, name)
        if self._exhausted(key):
            return False
        if self._roll("watch", kind, name, seq) < self.watch_drop_rate:
            self._record("watch_drop", key)
            return True
        return False

    def maybe_delay(self, op: str, kind: str, name: str) -> None:
        """Slow-response injection (sleeps; never raises)."""
        if self.slow_rate <= 0:
            return
        seq = self._seq("slow", op, kind, name)
        if self._roll("slow", op, kind, name, seq) < self.slow_rate:
            self._record("slow", ("slow", op, kind, name))
            time.sleep(self.slow_seconds)

    # --- hook consumed by apiserver/server.py -------------------------------

    def http_fault(self, method: str, kind: str,
                   name: str) -> Optional[Tuple[int, float]]:
        """(status code, retry_after_seconds) to shed this request with, or
        None to serve it.  The apiserver front end maps this to a Status
        response with a Retry-After header (the APF load-shedding surface);
        retry_after is 0 for 500s (no hint — clients fall back to their own
        backoff)."""
        if kind in self.exempt_kinds:
            return None
        self.maybe_delay(method, kind, name)
        seq = self._seq("http", method, kind, name)
        key = ("http", method, kind, name)
        if self._exhausted(key):
            return None
        r = self._roll("http", method, kind, name, seq)
        edge = self.write_429_rate
        if r < edge:
            self._record("http_429", key)
            return (429, self.retry_after)
        edge += self.write_500_rate
        if r < edge:
            self._record("http_500", key)
            return (500, 0.0)
        edge += self.write_503_rate
        if r < edge:
            self._record("http_503", key)
            return (503, self.retry_after)
        return None


def steal_lease(store, namespace: str, name: str,
                usurper: str = "chaos-usurper", clock=time.monotonic) -> bool:
    """Leader-election lease loss: hand the lease to ``usurper`` with a fresh
    renewTime, as a competing candidate (or an apiserver restart replaying a
    stale cache) would.  The current holder's next renewal sees a foreign
    holderIdentity and must release → reacquire (LeaderElector's
    renewal-failure path).  Returns False when no lease exists."""
    import copy

    lease = store.get("Lease", namespace, name)
    if lease is None:
        return False
    # mutate a private copy: in-process stores hand out the live object,
    # and a steal whose write is itself fault-rejected must not leave a
    # half-applied holder visible (the module's pre-mutation invariant)
    lease = copy.copy(lease)
    lease.metadata = copy.copy(lease.metadata)
    lease.holder_identity = usurper
    lease.renew_time = clock()
    store.update("Lease", lease)
    return True
