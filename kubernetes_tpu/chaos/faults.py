"""Seeded, deterministic fault injection for the sim control plane.

The reference survives production because every layer assumes its neighbor
is flaky: the apiserver sheds load with 429 + Retry-After (API Priority and
Fairness, staging/src/k8s.io/apiserver/pkg/server/filters), etcd surfaces
conflicts that GuaranteedUpdate retries, watch streams drop and client-go
reflectors relist.  ``FaultSchedule`` reproduces those failure modes inside
the sim so the retry/degradation machinery (RetryingStore, the HTTP client's
Retry-After transport, the informer relist path, the extender circuit
breaker) can be exercised end to end.

Determinism contract
--------------------
Every decision is a pure function of ``(seed, tag, key, seq)`` where ``seq``
is a per-key counter: the Nth write to Pod ``p42`` sees the same fault in
every run with the same seed, REGARDLESS of thread interleavings or how the
scheduler groups its batches.  The per-key sequence (create, bind, ...) is
what must be deterministic for replay — wall-clock ordering across keys is
not.  Hashing is blake2s (process-independent — Python's tuple ``hash`` is
salted per process and would break replay — and with real avalanche: crc32
clusters sequential names like pod-0001/pod-0002 into near-identical rolls,
turning a 5% rate into all-or-nothing per name prefix).

Faults are injected BEFORE the guarded mutation applies (a rejected write
never half-happened), so a retry after TransientApiError/InjectedConflict is
always safe — the in-process analog of an apiserver 429 rejected at
admission, before storage.

Wiring: pass one schedule to EITHER ``ObjectStore(fault_injector=...)`` (in-
process actors) OR ``APIServer(fault_injector=...)`` (HTTP actors) — wiring
both layers of the same stack double-injects.
"""

from __future__ import annotations

import hashlib
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional, Tuple

from ..sim.store import StaleResourceVersion

# --- deterministic crash points (kubernetes_tpu/recovery/) --------------------
#
# The registered kill-point catalog.  Each name is hard-wired at ONE real
# call site; maybe_crash(name) at that site raises ProcessCrash when an
# installed FaultSchedule armed the point — simulating process death at the
# exact state the site leaves behind (in-memory state discarded by the
# harness, store untouched).  Recovery (recovery/rebuild.cold_start) must
# converge from every one of these states; tests/test_recovery.py drives
# each point in turn.
CRASH_AFTER_ASSUME = "crash.after_assume"      # scheduler._complete: batch assumed, nothing bound
CRASH_MID_BIND = "crash.mid_bind"              # scheduler._finish_bind: store bind landed, bookkeeping lost
CRASH_PERMIT_HELD = "crash.permit_held"        # gang/directory.note_waiting: member holds its Permit
CRASH_MID_PLAN_APPLY = "crash.mid_plan_apply"  # descheduler/controller._apply: some victims evicted
CRASH_MID_SCALEUP = "crash.mid_scaleup"        # autoscaler/controller._scale_up: some nodes created
CRASH_POST_LEASE_RENEW = "crash.post_lease_renew"  # leaderelection._tick: lease renewed, holder dies
CRASH_PRE_WAL_FSYNC = "crash.pre_wal_fsync"    # sim/wal.append: record written, fsync never ran
CRASH_MID_ZONE_EVICT = "crash.mid_zone_evict"  # controllers/nodelifecycle: unreachable taint written, eviction sweep unrun
CRASH_MID_PROMOTE = "crash.mid_promote"        # sim/replication.promote: shipped tail durable, WAL not yet reattached
CRASH_MID_PROVISION = "crash.mid_provision"    # controllers/volumebinder.sync_once: PV claimRef written, PVC bind lost
CRASH_MID_CLAIM_COMMIT = "crash.mid_claim_commit"  # dra/plugin.pre_bind: some claims committed, pod not bound
CRASH_MID_CRD_REGISTER = "crash.mid_crd_register"  # apiextensions/registrar._install: CRD durable, kind not yet served
# Not in CRASH_POINTS (armed via arm_torn_write, not crash_points): the
# torn-write fault writes a PREFIX of the record before dying, so the point
# name only identifies the ProcessCrash it raises.
CRASH_TORN_WAL_WRITE = "crash.torn_wal_write"  # sim/wal.append: record half-written, then death

CRASH_POINTS = (
    CRASH_AFTER_ASSUME,
    CRASH_MID_BIND,
    CRASH_PERMIT_HELD,
    CRASH_MID_PLAN_APPLY,
    CRASH_MID_SCALEUP,
    CRASH_POST_LEASE_RENEW,
    CRASH_PRE_WAL_FSYNC,
    CRASH_MID_ZONE_EVICT,
    CRASH_MID_PROMOTE,
    CRASH_MID_PROVISION,
    CRASH_MID_CLAIM_COMMIT,
    CRASH_MID_CRD_REGISTER,
)


class ProcessCrash(BaseException):
    """Simulated process death at a registered crash point.

    BaseException ON PURPOSE: the resilience machinery this repo grew
    (cycle failure handlers, best-effort writes, eviction fail-stop) all
    catch ``Exception`` — a real SIGKILL is not catchable, so neither is
    this.  Only the crash harness (recovery/failover, test batteries)
    catches it, then discards the dead replica's in-memory state.
    """

    def __init__(self, point: str):
        super().__init__(f"simulated process death at {point}")
        self.point = point


# The installed schedule consulted by maybe_crash().  Module-level on
# purpose: the call sites (scheduler binding cycle, gang directory,
# controllers, leader election) have no shared config object, and a crash
# harness controls one process at a time.  None (the default) costs one
# global read per site.
_active_crash_schedule: Optional["FaultSchedule"] = None


def install_crash_schedule(schedule: Optional["FaultSchedule"]) -> None:
    global _active_crash_schedule
    _active_crash_schedule = schedule


@contextmanager
def crash_schedule(schedule: "FaultSchedule"):
    """Scoped install — the harness form, so a raising test can never leak
    an armed schedule into the next test's scheduler."""
    install_crash_schedule(schedule)
    try:
        yield schedule
    finally:
        install_crash_schedule(None)


def maybe_crash(point: str) -> None:
    """The call-site hook: raise ProcessCrash when the installed schedule
    armed this point for the current hit.  No-op (one global read) when no
    schedule is installed."""
    s = _active_crash_schedule
    if s is not None:
        s.crash_fault(point)


def maybe_torn_write(nbytes: int):
    """WAL torn-write hook (sim/wal.append): when the installed schedule
    armed a torn write for this append, returns the number of bytes of the
    ``nbytes``-long record to actually write (a strict prefix — the tail
    record the crash leaves behind fails its checksum, which is exactly
    what replay's truncation path must handle); None to write normally.
    The WAL raises ProcessCrash(CRASH_TORN_WAL_WRITE) after the partial
    write — a torn record only ever exists because the process died
    mid-append."""
    s = _active_crash_schedule
    if s is None:
        return None
    return s.torn_write_fault(nbytes)




class TransientApiError(RuntimeError):
    """A retryable control-plane failure (429/500/503 analog).

    ``retry_after`` carries the server's Retry-After hint in seconds;
    retrying transports (chaos.retry.RetryingStore, HTTPApiClient) honor it.
    """

    def __init__(self, code: int, retry_after: float = 0.0, message: str = ""):
        super().__init__(message or f"transient API error {code}")
        self.code = code
        self.retry_after = retry_after


class InjectedConflict(StaleResourceVersion):
    """A chaos-injected 409 (CAS-conflict storm).

    Subclasses StaleResourceVersion so existing 409 handling (the apiserver's
    Conflict response, controller read-modify-write loops) applies unchanged;
    the distinct type lets RetryingStore know the conflict is synthetic —
    the stored object is actually current, so a plain resend is correct
    (a REAL stale rv must be re-read by the caller instead).
    """


class WatchDropped(ConnectionError):
    """Delivered to a watcher's on_error callback when its stream is cut."""


class FaultSchedule:
    """One seeded schedule of fault decisions across all fault classes.

    Rates are independent probabilities per operation; ``max_faults_per_key``
    bounds the injected failures any single (op, kind, name) can see so a
    bounded-retry client always converges (an unlucky key cannot 429
    forever).  ``exempt_kinds`` defaults to Event: best-effort event writes
    retrying through injected faults would add nondeterministic op sequences
    without exercising anything new.
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        watch_drop_rate: float = 0.0,
        write_429_rate: float = 0.0,
        write_500_rate: float = 0.0,
        write_503_rate: float = 0.0,
        conflict_rate: float = 0.0,
        slow_rate: float = 0.0,
        slow_seconds: float = 0.02,
        retry_after: float = 0.02,
        max_faults_per_key: int = 3,
        exempt_kinds=frozenset({"Event"}),
        crash_points: Optional[Dict[str, int]] = None,
        wal_error_rate: float = 0.0,
    ):
        self.seed = seed
        self.watch_drop_rate = watch_drop_rate
        self.write_429_rate = write_429_rate
        self.write_500_rate = write_500_rate
        self.write_503_rate = write_503_rate
        self.conflict_rate = conflict_rate
        self.wal_error_rate = wal_error_rate
        self.slow_rate = slow_rate
        self.slow_seconds = slow_seconds
        self.retry_after = retry_after
        self.max_faults_per_key = max_faults_per_key
        self.exempt_kinds = frozenset(exempt_kinds)
        # RLock: the memoized watch-drop path holds it across _seq/_record
        self._lock = threading.RLock()
        self._counters: Dict[tuple, int] = {}
        self._key_faults: Dict[tuple, int] = {}
        # (kind, name, rv) → decision, so N concurrent watch streams of the
        # same store share ONE deterministic decision per event (see
        # should_drop_watch)
        self._drop_memo: Dict[tuple, bool] = {}
        # fault class → total injected; equal across same-seed runs whenever
        # each key's op sequence is deterministic (the soak's assertion)
        self.injected: Dict[str, int] = {}
        # point name → 1-based hit at which the point fires (ONCE; the
        # armed entry then moves to _crash_fired).  Hit counters ride the
        # same per-key _seq machinery as every other fault class, so a
        # crash at "the 3rd completed batch" replays at the 3rd completed
        # batch in every same-seed run — wall clock never enters it.
        self.crash_points: Dict[str, int] = dict(crash_points or {})
        self._crash_fired: Dict[str, int] = {}  # point → seq it fired at
        # 1-based WAL-append hit at which a torn write fires (once), and
        # the fraction of the record that survives; armed via
        # arm_torn_write, consumed by maybe_torn_write from sim/wal.append
        self._torn_write_at: Optional[int] = None
        self._torn_keep_fraction = 0.5

    # --- deterministic primitives -------------------------------------------

    def _roll(self, *parts) -> float:
        digest = hashlib.blake2s(
            "|".join(map(str, (self.seed,) + parts)).encode(),
            digest_size=8,
        ).digest()
        return int.from_bytes(digest, "big") / 2.0**64

    def _seq(self, *key) -> int:
        with self._lock:
            n = self._counters.get(key, 0)
            self._counters[key] = n + 1
            return n

    def _record(self, fault: str, key: tuple):
        from ..metrics import scheduler_metrics as m

        with self._lock:
            self.injected[fault] = self.injected.get(fault, 0) + 1
            self._key_faults[key] = self._key_faults.get(key, 0) + 1
        m.chaos_faults_injected.inc((fault,))

    def _exhausted(self, key: tuple) -> bool:
        with self._lock:
            return self._key_faults.get(key, 0) >= self.max_faults_per_key

    def injected_counts(self) -> Dict[str, int]:
        """Snapshot of fault-class → injected count (the determinism probe)."""
        with self._lock:
            return dict(self.injected)

    # --- deterministic crash points (consumed via maybe_crash) ---------------

    def arm_crash(self, point: str, at_hit: int = 1) -> None:
        """Arm ``point`` to fire at its ``at_hit``-th FUTURE hit (relative
        to hits already consumed), once.  The failover soak arms points one
        at a time — each epoch's kill is still a pure function of the
        per-point hit sequence, so same-seed replays kill at the same op."""
        if point not in CRASH_POINTS:
            raise ValueError(f"unknown crash point {point!r}; "
                             f"registered: {CRASH_POINTS}")
        with self._lock:
            seen = self._counters.get(("crashpoint", point), 0)
            self.crash_points[point] = seen + at_hit

    def crash_fault(self, point: str) -> None:
        """Raise ProcessCrash when ``point`` is armed for this hit.

        Counts the hit EVERY call (armed or not) so arming decisions made
        later still address a deterministic sequence position.  Fires
        once per armed point; the firing is recorded in ``injected`` under
        ``crash:<point>`` (part of the determinism signature)."""
        seq = self._seq("crashpoint", point)
        with self._lock:
            at = self.crash_points.get(point)
            if at is None or seq + 1 != at:
                return
            del self.crash_points[point]
            self._crash_fired[point] = seq
            self.injected[f"crash:{point}"] = (
                self.injected.get(f"crash:{point}", 0) + 1)
        from ..metrics import scheduler_metrics as m

        m.chaos_faults_injected.inc((f"crash:{point}",))
        raise ProcessCrash(point)

    def crashes_fired(self) -> Dict[str, int]:
        """point → hit seq it fired at (empty until points fire)."""
        with self._lock:
            return dict(self._crash_fired)

    # --- WAL fault shapes (consumed by sim/wal.py + sim/store.py) -------------

    def arm_torn_write(self, at_append: int = 1,
                       keep_fraction: float = 0.5) -> None:
        """Arm a torn WAL write at the ``at_append``-th FUTURE append
        (relative to appends already consumed), once: the record is cut to
        ``keep_fraction`` of its bytes and the process dies — the
        deterministic reproduction of power loss mid-append, so the replay
        path's checksum truncation is exercised on a known record."""
        if not (0.0 < keep_fraction < 1.0):
            raise ValueError("keep_fraction must leave a strict prefix")
        with self._lock:
            seen = self._counters.get(("walappend",), 0)
            self._torn_write_at = seen + at_append
            self._torn_keep_fraction = keep_fraction

    def torn_write_fault(self, nbytes: int) -> Optional[int]:
        """Bytes of this ``nbytes``-long record to write (a strict prefix)
        when the torn write is armed for this append; None to write whole.
        Counts every append (armed or not) so later arming still addresses
        a deterministic sequence position, mirroring crash_fault."""
        seq = self._seq("walappend")
        with self._lock:
            at = self._torn_write_at
            if at is None or seq + 1 != at:
                return None
            self._torn_write_at = None
            keep = max(1, min(nbytes - 1, int(nbytes
                                              * self._torn_keep_fraction)))
            self.injected["wal_torn_write"] = (
                self.injected.get("wal_torn_write", 0) + 1)
        from ..metrics import scheduler_metrics as m

        m.chaos_faults_injected.inc(("wal_torn_write",))
        return keep

    def wal_fault(self, op: str, kind: str, name: str) -> None:
        """Raise a retryable 500 when this write's durable-log commit is
        scheduled to fail (the apiserver's mapping of an etcd commit
        error).  Consulted by ObjectStore just before the WAL append, so
        the mutation never half-applies and a client resend is safe."""
        if self.wal_error_rate <= 0 or kind in self.exempt_kinds:
            return
        seq = self._seq("wal", op, kind, name)
        key = ("wal", op, kind, name)
        if self._exhausted(key):
            return
        if self._roll("wal", op, kind, name, seq) < self.wal_error_rate:
            self._record("wal_error", key)
            raise TransientApiError(
                500, 0.0, f"chaos: wal commit failed on {op} {kind}/{name}")

    # --- hooks consumed by sim/store.py -------------------------------------

    def write_fault(self, op: str, kind: str, name: str) -> None:
        """Raise the scheduled fault (if any) for this write attempt.

        Called by ObjectStore create/update/delete/bind BEFORE the mutation
        and before taking the store lock (injected delays must not stall
        unrelated writers).
        """
        if kind in self.exempt_kinds:
            return
        self.maybe_delay(op, kind, name)
        seq = self._seq("write", op, kind, name)
        key = (op, kind, name)
        if self._exhausted(key):
            return
        r = self._roll("write", op, kind, name, seq)
        edge = self.write_429_rate
        if r < edge:
            self._record("write_429", key)
            raise TransientApiError(429, self.retry_after,
                                    f"chaos: 429 on {op} {kind}/{name}")
        edge += self.write_500_rate
        if r < edge:
            self._record("write_500", key)
            raise TransientApiError(500, 0.0,
                                    f"chaos: 500 on {op} {kind}/{name}")
        edge += self.write_503_rate
        if r < edge:
            self._record("write_503", key)
            raise TransientApiError(503, self.retry_after,
                                    f"chaos: 503 on {op} {kind}/{name}")
        edge += self.conflict_rate
        if r < edge and op in ("update", "bind"):
            self._record("conflict", key)
            raise InjectedConflict(
                f"chaos: conflict storm on {op} {kind}/{name}")

    def should_drop_watch(self, kind: str, name: str, rv=None) -> bool:
        """Decide whether the watch stream carrying this event is cut.

        Keyed by the EVENT (kind, name, per-key event seq), not the watcher:
        the decision stays deterministic even when watcher subscription
        order varies between runs.  Callers that know the event's
        resourceVersion pass ``rv`` so N independent streams carrying the
        SAME event (each HTTP watch connection consults separately) share
        one memoized decision — without it each stream would consume its
        own sequence number and the injected count would depend on how many
        watchers happened to be connected (thread-interleaving-shaped,
        which the determinism contract forbids).
        """
        if self.watch_drop_rate <= 0 or kind in self.exempt_kinds:
            return False
        if rv is None:
            return self._decide_drop(kind, name)
        with self._lock:
            memo_key = (kind, name, rv)
            if memo_key not in self._drop_memo:
                self._drop_memo[memo_key] = self._decide_drop(kind, name)
            return self._drop_memo[memo_key]

    def _decide_drop(self, kind: str, name: str) -> bool:
        seq = self._seq("watch", kind, name)
        key = ("watch", kind, name)
        if self._exhausted(key):
            return False
        if self._roll("watch", kind, name, seq) < self.watch_drop_rate:
            self._record("watch_drop", key)
            return True
        return False

    def maybe_delay(self, op: str, kind: str, name: str) -> None:
        """Slow-response injection (sleeps; never raises)."""
        if self.slow_rate <= 0:
            return
        seq = self._seq("slow", op, kind, name)
        if self._roll("slow", op, kind, name, seq) < self.slow_rate:
            self._record("slow", ("slow", op, kind, name))
            time.sleep(self.slow_seconds)

    # --- hook consumed by apiserver/server.py -------------------------------

    def http_fault(self, method: str, kind: str,
                   name: str) -> Optional[Tuple[int, float]]:
        """(status code, retry_after_seconds) to shed this request with, or
        None to serve it.  The apiserver front end maps this to a Status
        response with a Retry-After header (the APF load-shedding surface);
        retry_after is 0 for 500s (no hint — clients fall back to their own
        backoff)."""
        if kind in self.exempt_kinds:
            return None
        self.maybe_delay(method, kind, name)
        seq = self._seq("http", method, kind, name)
        key = ("http", method, kind, name)
        if self._exhausted(key):
            return None
        r = self._roll("http", method, kind, name, seq)
        edge = self.write_429_rate
        if r < edge:
            self._record("http_429", key)
            return (429, self.retry_after)
        edge += self.write_500_rate
        if r < edge:
            self._record("http_500", key)
            return (500, 0.0)
        edge += self.write_503_rate
        if r < edge:
            self._record("http_503", key)
            return (503, self.retry_after)
        return None


def steal_lease(store, namespace: str, name: str,
                usurper: str = "chaos-usurper", clock=time.monotonic) -> bool:
    """Leader-election lease loss: hand the lease to ``usurper`` with a fresh
    renewTime, as a competing candidate (or an apiserver restart replaying a
    stale cache) would.  The current holder's next renewal sees a foreign
    holderIdentity and must release → reacquire (LeaderElector's
    renewal-failure path).  Returns False when no lease exists."""
    import copy

    lease = store.get("Lease", namespace, name)
    if lease is None:
        return False
    # mutate a private copy: in-process stores hand out the live object,
    # and a steal whose write is itself fault-rejected must not leave a
    # half-applied holder visible (the module's pre-mutation invariant)
    lease = copy.copy(lease)
    lease.metadata = copy.copy(lease.metadata)
    lease.holder_identity = usurper
    lease.renew_time = clock()
    # a holder change IS a lease transition: bumping it invalidates the
    # victim's fencing token (client/leaderelection.py check_fence), so a
    # stolen-from leader's in-flight binding cycles refuse at bind time
    # instead of racing the usurper's cycles
    lease.lease_transitions = getattr(lease, "lease_transitions", 0) + 1
    store.update("Lease", lease)
    return True
