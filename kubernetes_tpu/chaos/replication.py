"""Replication chaos: deterministic ship-stream faults + the two-follower
failover soak.

The WAL-shipping layer (sim/replication.py) claims exactly-once record
apply over an at-least-once wire, rv-consistent follower serving that
never overclaims a bookmark, and promotion that survives a leader death at
ANY shipped/unshipped boundary.  This module is the adversary for those
claims:

  - ``ShipFaults`` — seeded, per-(follower, batch-seq) deterministic
    decisions to DROP a ship batch on the wire, TEAR it mid-record (a
    strict byte prefix arrives), or LAG it (extra ship-delay ticks), in
    the FaultSchedule idiom (chaos/faults.py): same seed → same faults at
    the same sequence points, replay-stable across runs;
  - ``run_replication_soak`` — leader + two followers under churn with
    recording watchers on every replica, a mid-soak leader kill at a
    configurable shipped/unshipped/torn boundary, a PROMOTION RACE between
    the two followers (the election lease CAS picks exactly one winner —
    the loser's promote() raises PromotionFenced), the dead leader's
    unshipped suffix discarded exactly-once + divergence-probed, the old
    leader rejoined as a follower over its truncated file, and the
    discarded writes re-issued against the new leader (the client's retry
    of an un-acknowledged write).  Final accounting proves: zero
    lost/duplicated watch events on every replica across the incarnation
    boundary, zero overclaimed bookmarks, exactly-once binds per
    incarnation, bounded promotion time, and a replay-stable determinism
    signature.

Single-threaded, pump-driven, fake-clocked: ship lag, lease expiry, and
promotion timing all advance with the driver loop, never the wall clock —
the same seed replays the same run bit-for-bit.
"""

from __future__ import annotations

import hashlib
import os
import time
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..component_base import logging as klog

LEASE_NS, LEASE_NAME = "kube-system", "replication-leader"


class ShipFaults:
    """Deterministic ship-wire faults, keyed by (follower, batch seq).

    The LogShipper consults ``ship_fault`` once per delivery attempt and
    ``lag_spike`` once per batch cut; both decisions hash (seed, follower,
    sequence) — blake2s, the chaos-layer convention — so a same-seed rerun
    injects the identical fault sequence regardless of wall clock or
    thread interleaving.  ``max_faults_per_stream`` bounds each follower's
    total so a hostile rate cannot starve convergence forever (the same
    escape hatch FaultSchedule's max_faults_per_key provides)."""

    def __init__(self, seed: int, *, drop_rate: float = 0.0,
                 torn_rate: float = 0.0, lag_rate: float = 0.0,
                 lag_ticks: int = 3, max_faults_per_stream: int = 64):
        self.seed = seed
        self.drop_rate = drop_rate
        self.torn_rate = torn_rate
        self.lag_rate = lag_rate
        self.lag_ticks = lag_ticks
        self.max_faults_per_stream = max_faults_per_stream
        self._counters: Dict[tuple, int] = {}
        self._stream_faults: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}

    def _roll(self, *parts) -> float:
        digest = hashlib.blake2s(
            "|".join(map(str, (self.seed,) + parts)).encode(),
            digest_size=8,
        ).digest()
        return int.from_bytes(digest, "big") / 2.0**64

    def _seq(self, *key) -> int:
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return n

    def _record(self, fault: str, follower: str) -> None:
        from ..metrics import scheduler_metrics as m

        self.injected[fault] = self.injected.get(fault, 0) + 1
        self._stream_faults[follower] = \
            self._stream_faults.get(follower, 0) + 1
        m.chaos_faults_injected.inc((fault,))

    def _exhausted(self, follower: str) -> bool:
        return (self._stream_faults.get(follower, 0)
                >= self.max_faults_per_stream)

    def ship_fault(self, follower: str, seq: int,
                   nbytes: int) -> Optional[Tuple[str, int]]:
        """Decide one delivery's fate: None (clean), ("drop", 0) — the
        batch vanishes on the wire — or ("torn", keep) — a strict byte
        prefix arrives (cut mid-record unless the batch is one record
        long; the follower's crc walk rejects the fragment either way)."""
        if self._exhausted(follower):
            return None
        if self.drop_rate and \
                self._roll("ship_drop", follower, seq) < self.drop_rate:
            self._record("ship_drop", follower)
            return ("drop", 0)
        if self.torn_rate and \
                self._roll("ship_torn", follower, seq) < self.torn_rate:
            keep = max(1, min(nbytes - 1, int(
                nbytes * self._roll("ship_torn_keep", follower, seq))))
            self._record("ship_torn", follower)
            return ("torn", keep)
        return None

    def lag_spike(self, follower: str) -> int:
        """Extra ship-delay ticks for the batches cut this pump (a burst
        of replication lag; the rv-gated serving path rides it out)."""
        if not self.lag_rate or self._exhausted(follower):
            return 0
        n = self._seq("lag", follower)
        if self._roll("ship_lag", follower, n) < self.lag_rate:
            self._record("ship_lag", follower)
            return self.lag_ticks
        return 0

    def injected_counts(self) -> Dict[str, int]:
        return dict(self.injected)


class _Recorder:
    """One watch client on a replica's cache: records every delivered
    event and bookmark so the final accounting can prove zero lost/dup
    events and zero overclaimed bookmarks.  ``events`` holds
    (rv, type, kind, name); ``marks`` holds (position-in-stream, rv)."""

    def __init__(self, name: str):
        self.name = name
        self.events: List[Tuple[int, str, str, str]] = []
        self.marks: List[Tuple[int, int]] = []
        self._unwatch = None

    def attach(self, cache, since_rv: int = 0) -> None:
        self._unwatch = cache.watch(self._on_event, since_rv=since_rv,
                                    on_bookmark=self._on_bookmark)

    def detach(self) -> None:
        if self._unwatch is not None:
            self._unwatch()
            self._unwatch = None

    def _on_event(self, ev) -> None:
        self.events.append((ev.resource_version, ev.type, ev.kind,
                            getattr(ev.obj.metadata, "name", "")))

    def _on_bookmark(self, rv: int) -> None:
        self.marks.append((len(self.events), rv))

    def prune_above(self, rv: int) -> int:
        """Roll the recorded stream back to ≤ rv (a rebase discarded the
        replica's tail); returns events dropped."""
        keep = [e for e in self.events if e[0] <= rv]
        dropped = len(self.events) - len(keep)
        self.events = keep
        self.marks = [(min(p, len(keep)), brv) for p, brv in self.marks
                      if brv <= rv]
        return dropped

    def overclaims(self) -> int:
        """Bookmarks that promised an rv some LATER-delivered event undercut
        (the overclaim the watermark clamp forbids): a bookmark at rv B is
        a contract that every event ≤ B has already been delivered."""
        bad = 0
        for pos, brv in self.marks:
            if any(e[0] <= brv for e in self.events[pos:]):
                bad += 1
        return bad


@dataclass
class ReplicaSoakResult:
    pods: int
    bound: int
    events_lost: int            # expected-but-unrecorded, across replicas
    events_duplicated: int      # recorded more than once, across replicas
    bookmark_overclaims: int
    ship_errors: Dict[str, int]  # follower name → deliver-side anomalies
    promotion_ticks: int         # leader kill → winner promoted
    promoted: str                # winner replica name
    fenced_losers: int           # promote() attempts PromotionFenced
    discarded_records: int       # dead leader's unshipped suffix
    phantoms: List[str]          # divergence probe output (must be [])
    duplicate_binds: int         # beyond one per (pod, incarnation)
    rolled_back_events: int      # loser-rebase stream rollback size
    rejoined_rv: int             # old leader's rv after rejoin as follower
    final_rv: int                # new leader's rv at convergence
    injected: Dict[str, int]
    iterations: int
    wall_seconds: float

    @property
    def converged(self) -> bool:
        return (self.bound == self.pods and self.events_lost == 0
                and self.events_duplicated == 0
                and self.bookmark_overclaims == 0
                and not self.phantoms and self.duplicate_binds == 0
                and self.promoted != "")

    def determinism_signature(self) -> Dict[str, object]:
        """The replay-stable part of a run (wall time excluded)."""
        return {
            "injected": dict(self.injected),
            "promoted": self.promoted,
            "discarded": self.discarded_records,
            "final_rv": self.final_rv,
            "iterations": self.iterations,
        }


def run_replication_soak(
    seed: int = 11,
    n_pods: int = 40,
    n_nodes: int = 6,
    n_watchers: int = 2,
    *,
    workdir: str,
    kill_mode: str = "unshipped",   # "shipped" | "unshipped" | "torn"
    unshipped_writes: int = 5,
    drop_rate: float = 0.08,
    torn_rate: float = 0.05,
    lag_rate: float = 0.05,
    lag_ticks: int = 3,
    ship_delay: int = 1,
    batch_max_records: int = 8,
    lease_duration: float = 0.6,
    tick: float = 0.05,
    promotion_tick_cap: int = 200,
    bookmark_every: int = 3,
) -> ReplicaSoakResult:
    """The replication acceptance workload (fast shape by default;
    tests/test_replication.py's slow marker scales n_watchers to the
    1000-watcher acceptance shape).  Phases:

      1. churn the leader (creates/binds/updates/deletes) while pumping
         the faulty ship stream to two followers, bookmarking their
         caches on a fixed cadence;
      2. kill the leader at the configured boundary — fully shipped,
         with an unshipped suffix, or with a torn last record on top;
      3. race both followers' electors for the replica-set lease on a
         fake clock (seed-derived tick order); the winner promotes, the
         loser's promote() must fence;
      4. discard the dead leader's unshipped suffix exactly-once, probe
         for divergence, rejoin the old leader as a follower over its
         truncated file, rebase the loser if it ran ahead of the winner;
      5. re-issue the discarded writes against the new leader (the
         client retry of an un-acked write), churn more, drain, and
         account: zero lost/dup events per recorder, zero bookmark
         overclaims, exactly-once binds per incarnation, bounded
         promotion ticks, replay-stable signature.
    """
    from ..client.leaderelection import LeaderElector, LeaseLock
    from ..sim.replication import (
        FollowerReplica,
        LogShipper,
        PromotionFenced,
        discard_unshipped_suffix,
        divergence_probe,
        rebase_follower,
    )
    from ..sim.store import DELETED, ObjectStore
    from ..sim.wal import WriteAheadLog
    from ..testutil import make_node, make_pod

    t0 = time.monotonic()

    def rng(*parts) -> float:
        digest = hashlib.blake2s(
            "|".join(map(str, (seed,) + parts)).encode(),
            digest_size=8).digest()
        return int.from_bytes(digest, "big") / 2.0**64

    leader_path = os.path.join(workdir, "leader.wal")
    wal = WriteAheadLog(leader_path, fsync_every=0)
    leader = ObjectStore(wal=wal)
    faults = ShipFaults(seed, drop_rate=drop_rate, torn_rate=torn_rate,
                        lag_rate=lag_rate, lag_ticks=lag_ticks)
    shipper = LogShipper(leader_path, batch_max_records=batch_max_records,
                         ship_delay=ship_delay, faults=faults)
    followers = [
        FollowerReplica("f1", os.path.join(workdir, "f1.wal")),
        FollowerReplica("f2", os.path.join(workdir, "f2.wal")),
    ]
    for f in followers:
        shipper.attach(f)

    # recorders: n_watchers per follower, subscribed from rv 0 — their
    # streams must reproduce the authoritative history exactly
    recorders: Dict[str, List[_Recorder]] = {}
    for f in followers:
        recorders[f.name] = []
        for w in range(n_watchers):
            rec = _Recorder(f"{f.name}-w{w}")
            rec.attach(f.watch_cache)
            recorders[f.name].append(rec)

    # the election fabric: its own coordination store (the analog of the
    # identity-lease etcd), fake-clocked for deterministic expiry
    class _FakeClock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

        def advance(self, dt):
            self.t += dt

    clock = _FakeClock()
    election = ObjectStore()
    leader_elector = LeaderElector(
        LeaseLock(election, LEASE_NS, LEASE_NAME), identity="leader#0",
        lease_duration=lease_duration, clock=clock)
    electors = {
        f.name: LeaderElector(
            LeaseLock(election, LEASE_NS, LEASE_NAME), identity=f.name,
            lease_duration=lease_duration, clock=clock)
        for f in followers
    }
    assert leader_elector.try_acquire_or_renew()

    # --- phase 1: churn under a faulty ship stream ---------------------------
    iterations = 0
    bound_names: List[str] = []

    def churn_step(store, i: int) -> None:
        nonlocal iterations
        iterations += 1
        op = rng("op", i)
        if op < 0.55 or not bound_names:
            store.create("Pod", make_pod().name(f"p{i}").uid(f"p{i}")
                         .namespace("default").req({"cpu": "1"}).obj())
            node = f"n{i % n_nodes}"
            store.bind_pod("default", f"p{i}", node)
            bound_names.append(f"p{i}")
        elif op < 0.8:
            victim = bound_names[int(rng("upd", i) * len(bound_names))]
            pod = store.get("Pod", "default", victim)
            if pod is not None:
                pod.metadata.labels["touched"] = str(i)
                store.update("Pod", pod)
        else:
            victim = bound_names.pop(int(rng("del", i) * len(bound_names)))
            store.delete("Pod", "default", victim)

    for i in range(n_nodes):
        leader.create("Node", make_node().name(f"n{i}")
                      .capacity({"cpu": "64", "pods": "256"}).obj())
    half = n_pods // 2
    for i in range(half):
        churn_step(leader, i)
        shipper.pump()
        leader_elector.try_acquire_or_renew()
        clock.advance(tick / 10)  # renewals outpace expiry while alive
        if i % bookmark_every == 0:
            for f in followers:
                f.watch_cache.bookmark_now()

    # --- phase 2: kill the leader at the configured boundary -----------------
    if kill_mode == "shipped":
        shipper.pump_until_synced()
    else:
        shipper.pump_until_synced()
        for j in range(unshipped_writes):
            # acknowledged writes the ship stream will never carry: pods
            # created AND BOUND only on the dying leader (the phantom-bind
            # material the divergence probe hunts)
            name = f"unshipped{j}"
            leader.create("Pod", make_pod().name(name).uid(name)
                          .namespace("default").req({"cpu": "1"}).obj())
            leader.bind_pod("default", name, f"n{j % n_nodes}")
    wal.close()
    if kill_mode == "torn":
        # death mid-append: a strict prefix of the final record survives
        size = os.path.getsize(leader_path)
        with open(leader_path, "r+b") as fh:
            fh.truncate(size - 7)

    # --- phase 3: promotion race ---------------------------------------------
    promotion_ticks = 0
    winner: Optional[FollowerReplica] = None
    order = sorted(followers,
                   key=lambda f: rng("race", f.name, seed))
    while winner is None and promotion_ticks < promotion_tick_cap:
        promotion_ticks += 1
        clock.advance(tick)
        for f in order:
            if electors[f.name].try_acquire_or_renew():
                winner = f
                break
    if winner is None:
        raise AssertionError("promotion race: no winner within cap")
    loser = next(f for f in followers if f is not winner)
    fenced = 0
    try:
        loser.promote(elector=electors[loser.name])
    except PromotionFenced:
        fenced += 1
    winner.promote(elector=electors[winner.name])
    win_offset = winner.acked_offset()
    win_rv = winner.applied_rv()

    # --- phase 4: discard, probe, rejoin, rebase -----------------------------
    discard = discard_unshipped_suffix(leader_path, win_offset)
    again = discard_unshipped_suffix(leader_path, win_offset)
    assert not again.discarded and again.truncated_bytes == 0, \
        "unshipped-suffix discard ran twice"
    phantoms = divergence_probe(winner.store, discard.discarded, win_rv)

    new_shipper = LogShipper(winner.wal_path,
                             batch_max_records=batch_max_records,
                             ship_delay=ship_delay, faults=faults)
    rolled_back_events = 0
    if loser.acked_offset() > win_offset:
        # the loser out-raced the winner on the wire: its extra tail is
        # not in the new authoritative log — truncate + rebuild, and roll
        # the recorders back with it
        for rec in recorders[loser.name]:
            rec.detach()
        loser, rolled = rebase_follower(loser, win_offset)
        for rec in recorders[loser.name]:
            rolled_back_events += rec.prune_above(loser.applied_rv())
            rec.attach(loser.watch_cache, since_rv=loser.applied_rv())
    new_shipper.attach(loser)
    # the dead leader rejoins as a follower over its truncated file —
    # byte-offset compatible with the winner's log (common-prefix rule)
    rejoined = FollowerReplica("old-leader", leader_path)
    rej_recorder = _Recorder("old-leader-w0")
    rej_recorder.attach(rejoined.watch_cache,
                        since_rv=rejoined.applied_rv())
    rejoin_base_rv = rejoined.applied_rv()
    new_shipper.attach(rejoined)

    # --- phase 5: retry discarded writes, churn, drain, account --------------
    for rec_wal in discard.discarded:
        # the client's retry of an un-acked write: re-issued against the
        # new leader, assigned FRESH rvs — never replayed from the corpse
        if rec_wal.op == "create" and rec_wal.kind == "Pod":
            winner.store.create("Pod", make_pod()
                                .name(rec_wal.name).uid(rec_wal.name)
                                .namespace(rec_wal.namespace or "default")
                                .req({"cpu": "1"}).obj())
        elif rec_wal.op == "bind":
            winner.store.bind_pod(rec_wal.namespace or "default",
                                  rec_wal.name, rec_wal.node_name)
    if kill_mode != "shipped":
        # a TORN final record is not even in the discard list (it never
        # verified) — but its client still timed out and still retries;
        # the retry sweep covers every un-acked unshipped write the
        # harness issued, not just the ones the corpse's log can name
        for j in range(unshipped_writes):
            name = f"unshipped{j}"
            if winner.store.get("Pod", "default", name) is None:
                winner.store.create("Pod", make_pod().name(name).uid(name)
                                    .namespace("default")
                                    .req({"cpu": "1"}).obj())
            pod = winner.store.get("Pod", "default", name)
            if not getattr(pod.spec, "node_name", ""):
                winner.store.bind_pod("default", name, f"n{j % n_nodes}")
    for i in range(half, n_pods):
        churn_step(winner.store, i)
        new_shipper.pump()
        if i % bookmark_every == 0:
            winner.watch_cache.bookmark_now()
            loser.watch_cache.bookmark_now()
            rejoined.watch_cache.bookmark_now()
    new_shipper.pump_until_synced()
    for f in (loser, rejoined):
        f.watch_cache.bookmark_now()

    # --- accounting ----------------------------------------------------------
    expected = [(ev.resource_version, ev.type, ev.kind,
                 getattr(ev.obj.metadata, "name", ""))
                for ev in winner.store._log]
    expected_rvs = [e[0] for e in expected]

    def stream_errors(rec: _Recorder, since: int) -> Tuple[int, int]:
        want = [e for e in expected if e[0] > since]
        got = rec.events
        want_c, got_c = Counter(want), Counter(got)
        lost = sum((want_c - got_c).values())
        dup = sum((got_c - want_c).values())
        return lost, dup

    lost = dup = over = 0
    all_recs = ([(r, 0) for rs in recorders.values() for r in rs]
                + [(rej_recorder, rejoin_base_rv)])
    for rec, since in all_recs:
        n_lost, n_dup = stream_errors(rec, since)
        lost += n_lost
        dup += n_dup
        over += rec.overclaims()

    # exactly-once binds per (pod, incarnation) across the incarnation
    # boundary, from the authoritative history (failover.py's accounting):
    # a DELETE closes an incarnation; a re-bind or node change within one
    # is a duplicate.  The discarded-then-retried binds appear exactly
    # once — in the NEW leader's history only.
    node_of: Dict[str, Optional[str]] = {}
    incarnation: Counter = Counter()
    binds: Counter = Counter()
    duplicates = 0
    for ev in winner.store._log:
        if ev.kind != "Pod":
            continue
        name = ev.obj.metadata.name
        if ev.type == DELETED:
            node_of.pop(name, None)
            incarnation[name] += 1
            continue
        nn = getattr(ev.obj.spec, "node_name", "") or None
        prev = node_of.get(name)
        if nn is not None and prev is None:
            binds[(name, incarnation[name])] += 1
        elif nn is not None and prev is not None and nn != prev:
            duplicates += 1
        node_of[name] = nn
    duplicates += sum(c - 1 for c in binds.values() if c > 1)

    pods, _ = winner.store.list("Pod")
    n_bound = sum(1 for p in pods if getattr(p.spec, "node_name", ""))

    for rs in recorders.values():
        for rec in rs:
            rec.detach()
    rej_recorder.detach()
    rejoined.close()
    loser.close()
    winner.store.wal.close()
    winner.watch_cache.close()

    result = ReplicaSoakResult(
        pods=len(pods), bound=n_bound,
        events_lost=lost, events_duplicated=dup,
        bookmark_overclaims=over,
        ship_errors={f.name: f.ship_errors
                     for f in (winner, loser, rejoined)},
        promotion_ticks=promotion_ticks, promoted=winner.name,
        fenced_losers=fenced,
        discarded_records=len(discard.discarded), phantoms=phantoms,
        duplicate_binds=duplicates,
        rolled_back_events=rolled_back_events,
        rejoined_rv=rejoined.applied_rv(),
        final_rv=expected_rvs[-1] if expected_rvs else 0,
        injected=faults.injected_counts(),
        iterations=iterations,
        wall_seconds=time.monotonic() - t0,
    )
    klog.V(1).info_s(
        "Replication soak complete", pods=result.pods, bound=result.bound,
        promoted=result.promoted, promotion_ticks=result.promotion_ticks,
        discarded=result.discarded_records, lost=lost, dup=dup,
        overclaims=over, injected=result.injected)
    return result
