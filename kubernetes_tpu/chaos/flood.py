"""Flood + churn drivers: the workload side of the flow-control and
watch-cache batteries (tests/test_flowcontrol.py, tests/test_watchcache.py,
tools/watch_soak.py share these).

Two shapes:

  - ``run_reader_flood``: N greedy readers hammer an apiserver's LIST path
    concurrently with a mutating writer — the APF acceptance scenario:
    readonly seats exhaust, rejected readers back off per Retry-After (the
    HTTP transport's retry loop), every request eventually completes, and
    mutating throughput stays unaffected because the pools are split.
  - ``watch_churn_soak``: thousands of concurrent watchers on one watch
    cache under object churn, then a 10× object-count growth — asserting
    the two scale properties ROADMAP item 2 names: ZERO store-lock
    acquisitions on the list/watch-replay path, and flat resync cost as
    the world grows (a dropped watcher resumes by ring replay of its gap,
    never by an O(objects) relist).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List

from ..component_base import logging as klog


@dataclass
class FloodStats:
    requests: int = 0           # reader requests that completed (after retries)
    failures: int = 0           # reader requests that exhausted retries
    per_reader: Dict[str, int] = field(default_factory=dict)


def run_reader_flood(base_url: str, kind: str = "Pod", n_readers: int = 12,
                     duration: float = 1.5, max_retries: int = 50,
                     retry_backoff: float = 0.01) -> FloodStats:
    """Greedy readers list ``kind`` in a closed loop until ``duration``
    elapses; each reader is its own flow-control user (X-Remote-User), so
    the per-user fairness queues are actually exercised.  A request counts
    as failed only when the transport exhausted its retries — the flood
    acceptance requires zero of those (shed ≠ lost)."""
    from ..apiserver.client import HTTPApiClient

    stats = FloodStats()
    lock = threading.Lock()
    deadline = time.monotonic() + duration

    def reader(i: int):
        client = HTTPApiClient(base_url, user=f"flood-reader-{i}",
                               max_retries=max_retries,
                               retry_backoff=retry_backoff,
                               jitter_seed=i)
        ok = 0
        while time.monotonic() < deadline:
            try:
                client.list(kind)
                ok += 1
            except Exception as e:
                # a retries-exhausted request IS the flood test's failure
                # signal: counted (the battery asserts zero) and logged
                klog.V(2).info_s("flood reader request lost", reader=i,
                                 error=f"{type(e).__name__}: {e}")
                with lock:
                    stats.failures += 1
        with lock:
            stats.requests += ok
            stats.per_reader[f"flood-reader-{i}"] = ok

    threads = [threading.Thread(target=reader, args=(i,), daemon=True)
               for i in range(n_readers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration + 30)
    return stats


def timed_writes(base_url: str, namespace: str, names: List[str],
                 rounds: int = 3, user: str = "writer") -> float:
    """Wall seconds for ``rounds`` update sweeps over ``names`` (labels
    bumped through PATCH) — the mutating-throughput probe run once
    unloaded and once under a reader flood; the flood acceptance bound is
    loaded ≤ 2× unloaded."""
    from ..apiserver.client import HTTPApiClient

    client = HTTPApiClient(base_url, user=user)
    t0 = time.monotonic()
    for r in range(rounds):
        for n in names:
            client._request(
                "PATCH",
                client._url("Pod", namespace, n),
                {"metadata": {"labels": {"flood-round": str(r)}}})
    return time.monotonic() - t0


# --- watch-cache churn soak ----------------------------------------------------


def _churn(store, pods, rounds: int) -> None:
    """``rounds`` update sweeps over pre-fetched pod objects — no store
    reads (the zero-store-lock assertion brackets this)."""
    for _ in range(rounds):
        for p in pods:
            store.update("Pod", p)


def watch_churn_soak(n_watchers: int = 1000, n_objects: int = 100,
                     growth: int = 10, churn_rounds: int = 2,
                     resyncs: int = 30, resync_window: int = 64,
                     ring_size: int = 1 << 16) -> dict:
    """The thousand-watcher churn soak (ISSUE 11 acceptance): watchers
    ride one WatchCache while objects churn and the object count grows
    ``growth``×.  Returns the measurements; callers assert:

      - ``store_read_ops_delta`` == 0: every watch replay/resume and every
        paginated list during the soak was served by the cache;
      - ``resync_ratio`` stays ~flat (< 3): resuming a watcher from a
        bookmark-fresh rv costs ring replay of its GAP — the same wall
        time at 10× the objects — never an O(objects) relist;
      - every watcher saw every churn event (no fan-out loss);
      - ``encodes_per_event`` ~1 (round 19): every watcher pulls the
        event's serialized bytes, but the encode-once payload means the
        whole fan-out costs ONE json encode per event, not n_watchers.
    """
    from ..api import wire  # noqa: F401 — payload plumbing under test
    from ..metrics import scheduler_metrics as m
    from ..sim.store import ObjectStore
    from ..sim.watchcache import WatchCache
    from ..testutil import make_pod

    store = ObjectStore()
    cache = WatchCache(store, ring_size=ring_size)
    pods = []
    for i in range(n_objects):
        p = (make_pod().name(f"soak-{i}").uid(f"soak-{i}")
             .namespace("default").req({"cpu": "1"}).obj())
        store.create("Pod", p)
        pods.append(p)

    counts = [0] * n_watchers
    start_rv = cache.current_rv()

    def handler_for(i):
        def h(ev):
            counts[i] += 1
            if ev.payload is not None:
                ev.payload.json_bytes()  # serve bytes, as HTTP fan-out does
        return h

    unwatchers = [cache.watch(handler_for(i), since_rv=start_rv)
                  for i in range(n_watchers)]
    encodes0 = m.apiserver_wire_encode.value(("json", "false"))

    def measure_resync() -> float:
        """Median-free total: ``resyncs`` watcher resumes from an rv
        ``resync_window`` events back — the bookmark-resume shape (the
        gap is bounded by churn, not by object count)."""
        rv = cache.current_rv()
        t0 = time.monotonic()
        for _ in range(resyncs):
            got = []
            un = cache.watch(got.append, since_rv=rv - resync_window)
            un()
        return time.monotonic() - t0

    read0 = store.read_ops
    _churn(store, pods, churn_rounds)
    small_events = n_objects * churn_rounds
    small_resync = measure_resync()
    small_reads = store.read_ops - read0

    # grow the world 10×, churn the ORIGINAL cohort again (same event
    # volume), and re-measure: resync cost must not follow object count
    for i in range(n_objects, n_objects * growth):
        store.create("Pod", (make_pod().name(f"soak-{i}").uid(f"soak-{i}")
                             .namespace("default").req({"cpu": "1"}).obj()))
    read1 = store.read_ops
    _churn(store, pods, churn_rounds)
    big_resync = measure_resync()
    big_reads = store.read_ops - read1

    for un in unwatchers:
        un()
    cache.close()
    expected = small_events + n_objects * (growth - 1) + small_events
    return {
        "n_watchers": n_watchers,
        "objects_small": n_objects,
        "objects_big": n_objects * growth,
        "events_per_watcher": counts[0],
        "events_expected": expected,
        "watchers_complete": sum(1 for c in counts if c == expected),
        "resync_seconds_small": small_resync,
        "resync_seconds_big": big_resync,
        "resync_ratio": (big_resync / small_resync
                         if small_resync > 0 else 0.0),
        "store_read_ops_delta": small_reads + big_reads,
        "json_encodes_delta": m.apiserver_wire_encode.value(
            ("json", "false")) - encodes0,
        "encodes_per_event": (m.apiserver_wire_encode.value(
            ("json", "false")) - encodes0) / max(expected, 1),
    }
