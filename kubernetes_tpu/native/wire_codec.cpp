// CPython extension: the wire v1 codec's fast path (api/wire.py owns the
// format spec and the pure-Python reference implementation — byte parity
// between the two backends is pinned by tests/test_wire.py).
//
// Three layers, all emitting the identical byte stream:
//   encode_value / decode_value — generic manifest-dict <-> wire document
//   encode_pod / encode_node    — object -> wire document DIRECTLY (no
//       intermediate to_manifest dict); returns None ("bail") for any shape
//       outside the fast subset, and the caller falls back to the reference
//       path.  A bail is always safe: it defers to the reference encoder.
//   decode_object               — wire document -> typed Pod/Node via
//       __new__ + __dict__ fill, honoring every from_dict quirk (uid/now
//       factories, namespace "default", resourceVersion dropped, Node
//       allocatable copying capacity); returns None to bail to the
//       scheme.decode(wire_decode(...)) reference path.
//
// Built by native.load_wire_codec() with g++ against the interpreter's own
// headers; absent a toolchain (or under KTPU_NO_NATIVE) api/wire.py serves
// every call from the Python codec.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

#include <string>
#include <unordered_map>
#include <vector>

// ---- wire format constants (mirror api/wire.py; v1 is frozen) --------------

static const char WIRE_HEADER[4] = {'\xd7', 'K', 'W', '\x01'};

enum {
    T_NULL = 0x00, T_FALSE = 0x01, T_TRUE = 0x02,
    T_INT = 0x03, T_NINT = 0x04, T_FLOAT = 0x05,
    T_STR = 0x06, T_STRREF = 0x07, T_STRWK = 0x08,
    T_LIST = 0x09, T_MAP = 0x0a, T_BYTES = 0x0b,
};

static const int MAX_DEPTH = 200;

// ---- interned names: one table drives wire keys, getattr, and __dict__ -----

#define WIRE_NAMES(X) \
    /* wire keys + literals (camelCase / values) */ \
    X(kind, "kind") X(apiVersion, "apiVersion") X(metadata, "metadata") \
    X(name, "name") X(k_namespace, "namespace") X(uid, "uid") \
    X(labels, "labels") X(annotations, "annotations") \
    X(resourceVersion, "resourceVersion") \
    X(creationTimestamp, "creationTimestamp") \
    X(deletionTimestamp, "deletionTimestamp") \
    X(ownerReferences, "ownerReferences") \
    X(spec, "spec") X(status, "status") \
    X(containers, "containers") X(initContainers, "initContainers") \
    X(image, "image") X(resources, "resources") X(requests, "requests") \
    X(limits, "limits") X(ports, "ports") \
    X(containerPort, "containerPort") X(hostPort, "hostPort") \
    X(hostIP, "hostIP") X(protocol, "protocol") \
    X(nodeName, "nodeName") X(nodeSelector, "nodeSelector") \
    X(affinity, "affinity") X(tolerations, "tolerations") \
    X(priority, "priority") X(priorityClassName, "priorityClassName") \
    X(schedulerName, "schedulerName") \
    X(topologySpreadConstraints, "topologySpreadConstraints") \
    X(overhead, "overhead") X(volumes, "volumes") \
    X(hostNetwork, "hostNetwork") X(preemptionPolicy, "preemptionPolicy") \
    X(resourceClaims, "resourceClaims") \
    X(phase, "phase") X(nominatedNodeName, "nominatedNodeName") \
    X(conditions, "conditions") X(podIP, "podIP") \
    X(capacity, "capacity") X(allocatable, "allocatable") \
    X(images, "images") X(names, "names") X(sizeBytes, "sizeBytes") \
    X(volumesAttached, "volumesAttached") \
    X(unschedulable, "unschedulable") X(taints, "taints") \
    X(podCIDR, "podCIDR") X(key, "key") X(value, "value") \
    X(effect, "effect") X(timeAdded, "timeAdded") \
    X(v_Pod, "Pod") X(v_Node, "Node") X(v_v1, "v1") \
    X(v_default, "default") X(v_default_scheduler, "default-scheduler") \
    X(v_Pending, "Pending") X(v_PreemptLowerPriority, "PreemptLowerPriority") \
    X(v_TCP, "TCP") X(v_NoSchedule, "NoSchedule") \
    /* snake_case attribute names (getattr on encode, __dict__ on decode) */ \
    X(a_metadata, "metadata") X(a_spec, "spec") X(a_status, "status") \
    X(a_name, "name") X(a_namespace, "namespace") X(a_uid, "uid") \
    X(a_labels, "labels") X(a_annotations, "annotations") \
    X(a_resource_version, "resource_version") \
    X(a_creation_timestamp, "creation_timestamp") \
    X(a_deletion_timestamp, "deletion_timestamp") \
    X(a_owner_references, "owner_references") \
    X(a_containers, "containers") X(a_init_containers, "init_containers") \
    X(a_node_name, "node_name") X(a_node_selector, "node_selector") \
    X(a_affinity, "affinity") X(a_tolerations, "tolerations") \
    X(a_priority, "priority") \
    X(a_priority_class_name, "priority_class_name") \
    X(a_scheduler_name, "scheduler_name") \
    X(a_topology_spread_constraints, "topology_spread_constraints") \
    X(a_overhead, "overhead") X(a_volumes, "volumes") \
    X(a_host_network, "host_network") \
    X(a_preemption_policy, "preemption_policy") \
    X(a_resource_claims, "resource_claims") \
    X(a_phase, "phase") X(a_nominated_node_name, "nominated_node_name") \
    X(a_conditions, "conditions") X(a_pod_ip, "pod_ip") \
    X(a_image, "image") X(a_resources, "resources") X(a_ports, "ports") \
    X(a_requests, "requests") X(a_limits, "limits") \
    X(a_container_port, "container_port") X(a_host_port, "host_port") \
    X(a_host_ip, "host_ip") X(a_protocol, "protocol") \
    X(a_unschedulable, "unschedulable") X(a_taints, "taints") \
    X(a_pod_cidr, "pod_cidr") \
    X(a_capacity, "capacity") X(a_allocatable, "allocatable") \
    X(a_images, "images") X(a_volumes_attached, "volumes_attached") \
    X(a_names, "names") X(a_size_bytes, "size_bytes") \
    X(a_key, "key") X(a_value, "value") X(a_effect, "effect") \
    X(a_time_added, "time_added")

enum {
#define X(id, s) N_##id,
    WIRE_NAMES(X)
#undef X
    N_COUNT
};

static const char* const NAME_STRS[N_COUNT] = {
#define X(id, s) s,
    WIRE_NAMES(X)
#undef X
};

static PyObject* g_name_py[N_COUNT];
static int32_t g_name_wk[N_COUNT];

// ---- module state handed over by api/wire.py setup() ------------------------

static std::unordered_map<std::string, uint32_t>* g_wk = nullptr;
static std::vector<PyObject*>* g_wk_strs = nullptr;

static PyObject* g_WireError = nullptr;
static PyObject* g_object_new = nullptr;
static PyObject* g_new_uid = nullptr;
static PyObject* g_now = nullptr;
static PyObject* g_cls_Pod = nullptr;
static PyObject* g_cls_ObjectMeta = nullptr;
static PyObject* g_cls_PodSpec = nullptr;
static PyObject* g_cls_PodStatus = nullptr;
static PyObject* g_cls_Container = nullptr;
static PyObject* g_cls_RR = nullptr;
static PyObject* g_cls_ContainerPort = nullptr;
static PyObject* g_cls_Node = nullptr;
static PyObject* g_cls_NodeSpec = nullptr;
static PyObject* g_cls_NodeStatus = nullptr;
static PyObject* g_cls_Taint = nullptr;
static PyObject* g_cls_ContainerImage = nullptr;
static int g_ready = 0;

// ---- encode buffer ----------------------------------------------------------

struct Buf {
    std::string s;
    void u8(uint8_t b) { s.push_back((char)b); }
    void raw(const char* p, size_t n) { s.append(p, n); }
    void uvarint(uint64_t n) {
        while (true) {
            uint8_t b = n & 0x7f;
            n >>= 7;
            if (n) { s.push_back((char)(b | 0x80)); } else { s.push_back((char)b); return; }
        }
    }
};

typedef std::unordered_map<std::string, uint32_t> StrTable;

static void emit_str_raw(Buf& b, StrTable& t, const char* u, Py_ssize_t len) {
    std::string key(u, (size_t)len);
    auto wk = g_wk->find(key);
    if (wk != g_wk->end()) { b.u8(T_STRWK); b.uvarint(wk->second); return; }
    auto it = t.find(key);
    if (it != t.end()) { b.u8(T_STRREF); b.uvarint(it->second); return; }
    uint32_t slot = (uint32_t)t.size();
    t.emplace(std::move(key), slot);
    b.u8(T_STR); b.uvarint((uint64_t)len); b.raw(u, (size_t)len);
}

// well-known name emit: one byte-ish, no hashing (indices cached at setup)
static void emit_name(Buf& b, StrTable& t, int idx) {
    int32_t wk = g_name_wk[idx];
    if (wk >= 0) { b.u8(T_STRWK); b.uvarint((uint32_t)wk); return; }
    emit_str_raw(b, t, NAME_STRS[idx], (Py_ssize_t)strlen(NAME_STRS[idx]));
}

// ---- generic value encoder (parity with api/wire.py _encode_value) ----------

static int enc_value(PyObject* v, Buf& b, StrTable& t, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "wire value nests too deeply");
        return -1;
    }
    if (v == Py_None) { b.u8(T_NULL); return 0; }
    if (PyBool_Check(v)) { b.u8(v == Py_True ? T_TRUE : T_FALSE); return 0; }
    if (PyUnicode_Check(v)) {
        Py_ssize_t len;
        const char* u = PyUnicode_AsUTF8AndSize(v, &len);
        if (!u) return -1;
        emit_str_raw(b, t, u, len);
        return 0;
    }
    if (PyLong_Check(v)) {
        int overflow;
        long long llv = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (llv == -1 && !overflow && PyErr_Occurred()) return -1;
        if (!overflow) {
            if (llv >= 0) { b.u8(T_INT); b.uvarint((uint64_t)llv); }
            else { b.u8(T_NINT); b.uvarint(~(uint64_t)llv); }  // -1-x == ~x
            return 0;
        }
        if (overflow > 0) {
            unsigned long long ull = PyLong_AsUnsignedLongLong(v);
            if (ull == (unsigned long long)-1 && PyErr_Occurred()) return -1;
            b.u8(T_INT); b.uvarint(ull);
            return 0;
        }
        PyErr_SetString(PyExc_OverflowError,
                        "int exceeds wire v1's 64-bit range");
        return -1;
    }
    if (PyFloat_Check(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        uint64_t bits;
        memcpy(&bits, &d, 8);
        char be[8];
        for (int i = 0; i < 8; i++) be[i] = (char)(bits >> (56 - 8 * i));
        b.u8(T_FLOAT); b.raw(be, 8);
        return 0;
    }
    if (PyBytes_Check(v)) {
        b.u8(T_BYTES);
        b.uvarint((uint64_t)PyBytes_GET_SIZE(v));
        b.raw(PyBytes_AS_STRING(v), (size_t)PyBytes_GET_SIZE(v));
        return 0;
    }
    if (PyByteArray_Check(v)) {
        b.u8(T_BYTES);
        b.uvarint((uint64_t)PyByteArray_GET_SIZE(v));
        b.raw(PyByteArray_AS_STRING(v), (size_t)PyByteArray_GET_SIZE(v));
        return 0;
    }
    if (PyList_Check(v) || PyTuple_Check(v)) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(v);
        b.u8(T_LIST); b.uvarint((uint64_t)n);
        PyObject** items = PySequence_Fast_ITEMS(v);
        for (Py_ssize_t i = 0; i < n; i++)
            if (enc_value(items[i], b, t, depth + 1) < 0) return -1;
        return 0;
    }
    if (PyDict_Check(v)) {
        b.u8(T_MAP); b.uvarint((uint64_t)PyDict_GET_SIZE(v));
        PyObject *key, *val;
        Py_ssize_t pos = 0;
        while (PyDict_Next(v, &pos, &key, &val)) {
            if (!PyUnicode_Check(key)) {
                PyErr_Format(PyExc_ValueError,
                             "map keys must be strings, got %s",
                             Py_TYPE(key)->tp_name);
                return -1;
            }
            if (enc_value(key, b, t, depth + 1) < 0) return -1;
            if (enc_value(val, b, t, depth + 1) < 0) return -1;
        }
        return 0;
    }
    PyErr_Format(PyExc_TypeError, "unencodable type %s", Py_TYPE(v)->tp_name);
    return -1;
}

// ---- generic strict decoder (parity with api/wire.py _decode_value) ---------

struct Dec {
    const uint8_t* d;
    Py_ssize_t n;
    Py_ssize_t pos;
    std::vector<PyObject*> table;  // owned refs, released by dec_free
};

static void dec_free(Dec& c) {
    for (PyObject* s : c.table) Py_DECREF(s);
    c.table.clear();
}

static int rd_uvarint(Dec& c, uint64_t* out) {
    int shift = 0;
    uint64_t n = 0;
    while (true) {
        if (c.pos >= c.n) {
            PyErr_SetString(g_WireError, "truncated varint");
            return -1;
        }
        uint8_t b = c.d[c.pos++];
        n |= (uint64_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) { *out = n; return 0; }
        shift += 7;
        if (shift > 63) {
            PyErr_SetString(g_WireError, "varint exceeds 64 bits");
            return -1;
        }
    }
}

static PyObject* dec_value(Dec& c, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(g_WireError, "wire document nests too deeply");
        return NULL;
    }
    if (c.pos >= c.n) {
        PyErr_SetString(g_WireError, "truncated document");
        return NULL;
    }
    uint8_t tag = c.d[c.pos++];
    uint64_t u;
    switch (tag) {
    case T_NULL: Py_RETURN_NONE;
    case T_FALSE: Py_RETURN_FALSE;
    case T_TRUE: Py_RETURN_TRUE;
    case T_INT:
        if (rd_uvarint(c, &u) < 0) return NULL;
        return PyLong_FromUnsignedLongLong(u);
    case T_NINT: {
        if (rd_uvarint(c, &u) < 0) return NULL;
        if (u < (uint64_t)1 << 63)
            return PyLong_FromLongLong(-1 - (long long)u);
        PyObject* mag = PyLong_FromUnsignedLongLong(u);
        if (!mag) return NULL;
        PyObject* one = PyLong_FromLong(1);
        PyObject* tmp = PyNumber_Add(mag, one);
        Py_DECREF(mag); Py_DECREF(one);
        if (!tmp) return NULL;
        PyObject* out = PyNumber_Negative(tmp);
        Py_DECREF(tmp);
        return out;
    }
    case T_FLOAT: {
        if (c.pos + 8 > c.n) {
            PyErr_SetString(g_WireError, "truncated float");
            return NULL;
        }
        uint64_t bits = 0;
        for (int i = 0; i < 8; i++) bits = (bits << 8) | c.d[c.pos + i];
        c.pos += 8;
        double dv;
        memcpy(&dv, &bits, 8);
        return PyFloat_FromDouble(dv);
    }
    case T_STR: {
        if (rd_uvarint(c, &u) < 0) return NULL;
        if (c.pos + (Py_ssize_t)u > c.n || (Py_ssize_t)u < 0) {
            PyErr_SetString(g_WireError, "truncated string");
            return NULL;
        }
        PyObject* s = PyUnicode_DecodeUTF8(
            (const char*)c.d + c.pos, (Py_ssize_t)u, NULL);
        c.pos += (Py_ssize_t)u;
        if (!s) {
            PyObject *et, *ev, *tb;
            PyErr_Fetch(&et, &ev, &tb);
            PyErr_Format(g_WireError, "invalid utf-8 in string");
            Py_XDECREF(et); Py_XDECREF(ev); Py_XDECREF(tb);
            return NULL;
        }
        Py_INCREF(s);
        c.table.push_back(s);
        return s;
    }
    case T_STRREF: {
        if (rd_uvarint(c, &u) < 0) return NULL;
        if (u >= c.table.size()) {
            PyErr_Format(g_WireError,
                         "string back-ref %llu out of range",
                         (unsigned long long)u);
            return NULL;
        }
        PyObject* s = c.table[(size_t)u];
        Py_INCREF(s);
        return s;
    }
    case T_STRWK: {
        if (rd_uvarint(c, &u) < 0) return NULL;
        if (u >= g_wk_strs->size()) {
            PyErr_Format(g_WireError,
                         "well-known index %llu out of range",
                         (unsigned long long)u);
            return NULL;
        }
        PyObject* s = (*g_wk_strs)[(size_t)u];
        Py_INCREF(s);
        return s;
    }
    case T_BYTES: {
        if (rd_uvarint(c, &u) < 0) return NULL;
        if (c.pos + (Py_ssize_t)u > c.n || (Py_ssize_t)u < 0) {
            PyErr_SetString(g_WireError, "truncated bytes");
            return NULL;
        }
        PyObject* b = PyBytes_FromStringAndSize(
            (const char*)c.d + c.pos, (Py_ssize_t)u);
        c.pos += (Py_ssize_t)u;
        return b;
    }
    case T_LIST: {
        if (rd_uvarint(c, &u) < 0) return NULL;
        PyObject* out = PyList_New(0);
        if (!out) return NULL;
        for (uint64_t i = 0; i < u; i++) {
            PyObject* item = dec_value(c, depth + 1);
            if (!item || PyList_Append(out, item) < 0) {
                Py_XDECREF(item); Py_DECREF(out);
                return NULL;
            }
            Py_DECREF(item);
        }
        return out;
    }
    case T_MAP: {
        if (rd_uvarint(c, &u) < 0) return NULL;
        PyObject* out = PyDict_New();
        if (!out) return NULL;
        for (uint64_t i = 0; i < u; i++) {
            PyObject* k = dec_value(c, depth + 1);
            if (!k) { Py_DECREF(out); return NULL; }
            if (!PyUnicode_Check(k)) {
                PyErr_SetString(g_WireError, "map key is not a string");
                Py_DECREF(k); Py_DECREF(out);
                return NULL;
            }
            PyObject* v = dec_value(c, depth + 1);
            if (!v || PyDict_SetItem(out, k, v) < 0) {
                Py_DECREF(k); Py_XDECREF(v); Py_DECREF(out);
                return NULL;
            }
            Py_DECREF(k); Py_DECREF(v);
        }
        return out;
    }
    default:
        PyErr_Format(g_WireError, "unknown tag 0x%02x", tag);
        return NULL;
    }
}

static int check_ready() {
    if (!g_ready) {
        PyErr_SetString(PyExc_RuntimeError, "wire codec not set up");
        return -1;
    }
    return 0;
}

// ---- module functions: generic codec ---------------------------------------

static PyObject* py_encode_value(PyObject* self, PyObject* arg) {
    if (check_ready() < 0) return NULL;
    Buf b;
    b.raw(WIRE_HEADER, 4);
    StrTable t;
    if (enc_value(arg, b, t, 0) < 0) return NULL;
    return PyBytes_FromStringAndSize(b.s.data(), (Py_ssize_t)b.s.size());
}

static int dec_init(Dec& c, Py_buffer* view) {
    c.d = (const uint8_t*)view->buf;
    c.n = view->len;
    c.pos = 0;
    if (c.n < 4 || memcmp(c.d, WIRE_HEADER, 3) != 0) {
        PyErr_SetString(g_WireError, "not a wire document (bad magic)");
        return -1;
    }
    if (c.d[3] != (uint8_t)WIRE_HEADER[3]) {
        PyErr_Format(g_WireError, "unsupported wire version %d", c.d[3]);
        return -1;
    }
    c.pos = 4;
    return 0;
}

static PyObject* py_decode_value(PyObject* self, PyObject* arg) {
    if (check_ready() < 0) return NULL;
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    Dec c;
    PyObject* out = NULL;
    if (dec_init(c, &view) == 0) {
        out = dec_value(c, 0);
        if (out && c.pos != c.n) {
            PyErr_Format(g_WireError, "%zd trailing bytes after document",
                         c.n - c.pos);
            Py_CLEAR(out);
        }
    }
    dec_free(c);
    PyBuffer_Release(&view);
    return out;
}

// ---- object fast path: encode ----------------------------------------------
//
// Each emit_* mirrors api/serialize.py exactly (field order, skip-if-default
// rules, camelCase renames).  Any attribute whose type or value falls outside
// the fast subset sets *bail and the caller returns None to the reference
// encoder — bailing is always correct, never wrong bytes.

struct AttrVal {  // owned getattr with cleanup bookkeeping
    PyObject* o;
    AttrVal() : o(NULL) {}
    ~AttrVal() { Py_XDECREF(o); }
    bool get(PyObject* src, int name_idx) {
        // dataclass fields live in the instance dict — read it directly
        // and skip the type's MRO walk (the encode path does ~35 of these
        // per pod); fall back to the full protocol for anything exotic
        PyObject** dp = _PyObject_GetDictPtr(src);
        if (dp && *dp) {
            PyObject* v = PyDict_GetItem(*dp, g_name_py[name_idx]);
            if (v) {
                Py_INCREF(v);
                o = v;
                return true;
            }
        }
        o = PyObject_GetAttr(src, g_name_py[name_idx]);
        return o != NULL;
    }
};

static bool str_eq(PyObject* v, int name_idx) {
    return PyUnicode_Check(v) &&
           PyUnicode_Compare(v, g_name_py[name_idx]) == 0;
}

static bool str_empty(PyObject* v) {
    return PyUnicode_GET_LENGTH(v) == 0;
}

// truthiness matching Python `if value:`; -1 on error
static int truthy(PyObject* v) { return PyObject_IsTrue(v); }

static int emit_meta(PyObject* meta, Buf& b, StrTable& t, int* bail);
static int emit_pod_spec(PyObject* spec, Buf& b, StrTable& t, int* bail);
static int emit_pod_status(PyObject* st, Buf& b, StrTable& t, int* bail);
static int emit_node_spec(PyObject* spec, Buf& b, StrTable& t, int* bail);
static int emit_node_status(PyObject* st, Buf& b, StrTable& t, int* bail);

static PyObject* encode_obj_common(PyObject* obj, int kind_name,
                                   int (*spec_fn)(PyObject*, Buf&, StrTable&, int*),
                                   int (*status_fn)(PyObject*, Buf&, StrTable&, int*)) {
    if (check_ready() < 0) return NULL;
    Buf b;
    StrTable t;
    int bail = 0;
    b.raw(WIRE_HEADER, 4);
    b.u8(T_MAP); b.uvarint(5);
    emit_name(b, t, N_kind); emit_name(b, t, kind_name);
    emit_name(b, t, N_apiVersion); emit_name(b, t, N_v_v1);
    AttrVal meta, spec, status;
    if (!meta.get(obj, N_a_metadata) || !spec.get(obj, N_a_spec) ||
        !status.get(obj, N_a_status))
        return NULL;
    emit_name(b, t, N_metadata);
    if (emit_meta(meta.o, b, t, &bail) < 0) return NULL;
    if (bail) Py_RETURN_NONE;
    emit_name(b, t, N_spec);
    if (spec_fn(spec.o, b, t, &bail) < 0) return NULL;
    if (bail) Py_RETURN_NONE;
    emit_name(b, t, N_status);
    if (status_fn(status.o, b, t, &bail) < 0) return NULL;
    if (bail) Py_RETURN_NONE;
    return PyBytes_FromStringAndSize(b.s.data(), (Py_ssize_t)b.s.size());
}

static PyObject* py_encode_pod(PyObject* self, PyObject* pod) {
    return encode_obj_common(pod, N_v_Pod, emit_pod_spec, emit_pod_status);
}

static PyObject* py_encode_node(PyObject* self, PyObject* node) {
    return encode_obj_common(node, N_v_Node, emit_node_spec, emit_node_status);
}

// _meta(): name always; namespace/uid/labels/annotations/resourceVersion/
// creationTimestamp if truthy; deletionTimestamp if not None; ownerReferences
// present -> bail (outside the fast subset).
static int emit_meta(PyObject* meta, Buf& b, StrTable& t, int* bail) {
    AttrVal name, ns, uid, labels, ann, rv, ct, dt, owners;
    if (!name.get(meta, N_a_name) || !ns.get(meta, N_a_namespace) ||
        !uid.get(meta, N_a_uid) || !labels.get(meta, N_a_labels) ||
        !ann.get(meta, N_a_annotations) ||
        !rv.get(meta, N_a_resource_version) ||
        !ct.get(meta, N_a_creation_timestamp) ||
        !dt.get(meta, N_a_deletion_timestamp) ||
        !owners.get(meta, N_a_owner_references))
        return -1;
    int t_ns = truthy(ns.o), t_uid = truthy(uid.o), t_lab = truthy(labels.o);
    int t_ann = truthy(ann.o), t_rv = truthy(rv.o), t_ct = truthy(ct.o);
    int t_own = truthy(owners.o);
    if (t_ns < 0 || t_uid < 0 || t_lab < 0 || t_ann < 0 || t_rv < 0 ||
        t_ct < 0 || t_own < 0)
        return -1;
    if (t_own) { *bail = 1; return 0; }
    if (t_rv && !PyLong_Check(rv.o)) { *bail = 1; return 0; }
    int count = 1 + t_ns + t_uid + t_lab + t_ann + t_rv + t_ct +
                (dt.o != Py_None ? 1 : 0);
    b.u8(T_MAP); b.uvarint((uint64_t)count);
    emit_name(b, t, N_name);
    if (enc_value(name.o, b, t, 1) < 0) return -1;
    if (t_ns) {
        emit_name(b, t, N_k_namespace);
        if (enc_value(ns.o, b, t, 1) < 0) return -1;
    }
    if (t_uid) {
        emit_name(b, t, N_uid);
        if (enc_value(uid.o, b, t, 1) < 0) return -1;
    }
    if (t_lab) {
        emit_name(b, t, N_labels);
        if (enc_value(labels.o, b, t, 1) < 0) return -1;
    }
    if (t_ann) {
        emit_name(b, t, N_annotations);
        if (enc_value(ann.o, b, t, 1) < 0) return -1;
    }
    if (t_rv) {
        emit_name(b, t, N_resourceVersion);
        PyObject* s = PyObject_Str(rv.o);  // str(resource_version)
        if (!s) return -1;
        Py_ssize_t len;
        const char* u = PyUnicode_AsUTF8AndSize(s, &len);
        if (!u) { Py_DECREF(s); return -1; }
        emit_str_raw(b, t, u, len);
        Py_DECREF(s);
    }
    if (t_ct) {
        emit_name(b, t, N_creationTimestamp);
        if (enc_value(ct.o, b, t, 1) < 0) return -1;
    }
    if (dt.o != Py_None) {
        emit_name(b, t, N_deletionTimestamp);
        if (enc_value(dt.o, b, t, 1) < 0) return -1;
    }
    return 0;
}

// helpers for the skip-if-default rules -------------------------------------

// list attr: returns 0 and sets *skip when empty, bails on non-list or
// (when support_nonempty is false) on any elements
static int list_gate(PyObject* v, int* bail, int* nonempty,
                     int support_nonempty) {
    if (!PyList_Check(v)) { *bail = 1; return 0; }
    *nonempty = PyList_GET_SIZE(v) > 0;
    if (*nonempty && !support_nonempty) *bail = 1;
    return 0;
}

// str attr skipped when == default literal; bail on non-str
static int str_field(PyObject* v, int dflt_idx, int* bail, int* emit) {
    if (!PyUnicode_Check(v)) { *bail = 1; *emit = 0; return 0; }
    *emit = dflt_idx < 0 ? !str_empty(v) : !str_eq(v, dflt_idx);
    return 0;
}

static int emit_container(PyObject* c, Buf& b, StrTable& t, int* bail);

static int emit_pod_spec(PyObject* spec, Buf& b, StrTable& t, int* bail) {
    AttrVal cont, init, nn, nsel, aff, tol, prio, pcn, sched, tsc, over,
        vols, hn, pp, claims;
    if (!cont.get(spec, N_a_containers) ||
        !init.get(spec, N_a_init_containers) ||
        !nn.get(spec, N_a_node_name) || !nsel.get(spec, N_a_node_selector) ||
        !aff.get(spec, N_a_affinity) || !tol.get(spec, N_a_tolerations) ||
        !prio.get(spec, N_a_priority) ||
        !pcn.get(spec, N_a_priority_class_name) ||
        !sched.get(spec, N_a_scheduler_name) ||
        !tsc.get(spec, N_a_topology_spread_constraints) ||
        !over.get(spec, N_a_overhead) || !vols.get(spec, N_a_volumes) ||
        !hn.get(spec, N_a_host_network) ||
        !pp.get(spec, N_a_preemption_policy) ||
        !claims.get(spec, N_a_resource_claims))
        return -1;
    int e_cont = 0, e_init = 0, e_tol = 0, e_tsc = 0, e_vols = 0,
        e_claims = 0;
    list_gate(cont.o, bail, &e_cont, 1);
    list_gate(init.o, bail, &e_init, 0);
    list_gate(tol.o, bail, &e_tol, 0);
    list_gate(tsc.o, bail, &e_tsc, 0);
    list_gate(vols.o, bail, &e_vols, 0);
    list_gate(claims.o, bail, &e_claims, 0);
    if (aff.o != Py_None) *bail = 1;
    int e_nn, e_pcn, e_sched, e_pp;
    str_field(nn.o, -1, bail, &e_nn);
    str_field(pcn.o, -1, bail, &e_pcn);
    str_field(sched.o, N_v_default_scheduler, bail, &e_sched);
    str_field(pp.o, N_v_PreemptLowerPriority, bail, &e_pp);
    int e_nsel = 0;
    if (!PyDict_Check(nsel.o)) *bail = 1;
    else e_nsel = PyDict_GET_SIZE(nsel.o) > 0;
    if (!PyDict_Check(over.o) || PyDict_GET_SIZE(over.o) > 0) *bail = 1;
    int e_prio = 0;
    if (prio.o != Py_None) {  // None -> field skipped (val is None)
        if (PyBool_Check(prio.o) || !PyLong_Check(prio.o)) *bail = 1;
        else {
            long long p = PyLong_AsLongLong(prio.o);
            if (p == -1 && PyErr_Occurred()) return -1;
            e_prio = p != 0;
        }
    }
    int e_hn = 0;
    if (!PyBool_Check(hn.o)) *bail = 1;
    else e_hn = hn.o == Py_True;
    if (*bail) return 0;
    int count = e_cont + e_nn + e_nsel + e_prio + e_pcn + e_sched + e_hn +
                e_pp;
    b.u8(T_MAP); b.uvarint((uint64_t)count);
    if (e_cont) {
        emit_name(b, t, N_containers);
        Py_ssize_t n = PyList_GET_SIZE(cont.o);
        b.u8(T_LIST); b.uvarint((uint64_t)n);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (emit_container(PyList_GET_ITEM(cont.o, i), b, t, bail) < 0)
                return -1;
            if (*bail) return 0;
        }
    }
    if (e_nn) {
        emit_name(b, t, N_nodeName);
        if (enc_value(nn.o, b, t, 1) < 0) return -1;
    }
    if (e_nsel) {
        emit_name(b, t, N_nodeSelector);
        if (enc_value(nsel.o, b, t, 1) < 0) return -1;
    }
    if (e_prio) {
        emit_name(b, t, N_priority);
        if (enc_value(prio.o, b, t, 1) < 0) return -1;
    }
    if (e_pcn) {
        emit_name(b, t, N_priorityClassName);
        if (enc_value(pcn.o, b, t, 1) < 0) return -1;
    }
    if (e_sched) {
        emit_name(b, t, N_schedulerName);
        if (enc_value(sched.o, b, t, 1) < 0) return -1;
    }
    if (e_hn) {
        emit_name(b, t, N_hostNetwork);
        b.u8(T_TRUE);
    }
    if (e_pp) {
        emit_name(b, t, N_preemptionPolicy);
        if (enc_value(pp.o, b, t, 1) < 0) return -1;
    }
    return 0;
}

// int field skipped when 0; bail on bool/non-int
static int int_field(PyObject* v, int* bail, int* emit) {
    if (PyBool_Check(v) || !PyLong_Check(v)) { *bail = 1; *emit = 0; return 0; }
    long long x = PyLong_AsLongLong(v);
    if (x == -1 && PyErr_Occurred()) return -1;
    *emit = x != 0;
    return 0;
}

static int emit_port(PyObject* p, Buf& b, StrTable& t, int* bail) {
    if (!PyObject_TypeCheck(p, (PyTypeObject*)g_cls_ContainerPort)) {
        *bail = 1;
        return 0;
    }
    AttrVal cp, hp, hip, proto;
    if (!cp.get(p, N_a_container_port) || !hp.get(p, N_a_host_port) ||
        !hip.get(p, N_a_host_ip) || !proto.get(p, N_a_protocol))
        return -1;
    int e_cp, e_hp, e_hip, e_proto;
    if (int_field(cp.o, bail, &e_cp) < 0 || int_field(hp.o, bail, &e_hp) < 0)
        return -1;
    str_field(hip.o, -1, bail, &e_hip);
    str_field(proto.o, N_v_TCP, bail, &e_proto);
    if (*bail) return 0;
    b.u8(T_MAP); b.uvarint((uint64_t)(e_cp + e_hp + e_hip + e_proto));
    if (e_cp) {
        emit_name(b, t, N_containerPort);
        if (enc_value(cp.o, b, t, 1) < 0) return -1;
    }
    if (e_hp) {
        emit_name(b, t, N_hostPort);
        if (enc_value(hp.o, b, t, 1) < 0) return -1;
    }
    if (e_hip) {
        emit_name(b, t, N_hostIP);
        if (enc_value(hip.o, b, t, 1) < 0) return -1;
    }
    if (e_proto) {
        emit_name(b, t, N_protocol);
        if (enc_value(proto.o, b, t, 1) < 0) return -1;
    }
    return 0;
}

static int emit_container(PyObject* c, Buf& b, StrTable& t, int* bail) {
    if (!PyObject_TypeCheck(c, (PyTypeObject*)g_cls_Container)) {
        *bail = 1;
        return 0;
    }
    AttrVal name, image, res, ports;
    if (!name.get(c, N_a_name) || !image.get(c, N_a_image) ||
        !res.get(c, N_a_resources) || !ports.get(c, N_a_ports))
        return -1;
    int e_name, e_image;
    str_field(name.o, -1, bail, &e_name);
    str_field(image.o, -1, bail, &e_image);
    int e_ports = 0;
    list_gate(ports.o, bail, &e_ports, 1);
    if (!PyObject_TypeCheck(res.o, (PyTypeObject*)g_cls_RR)) *bail = 1;
    if (*bail) return 0;
    AttrVal req, lim;
    if (!req.get(res.o, N_a_requests) || !lim.get(res.o, N_a_limits))
        return -1;
    if (!PyDict_Check(req.o) || !PyDict_Check(lim.o)) { *bail = 1; return 0; }
    int e_req = PyDict_GET_SIZE(req.o) > 0, e_lim = PyDict_GET_SIZE(lim.o) > 0;
    int e_res = e_req || e_lim;  // resources == RR() -> skipped
    b.u8(T_MAP); b.uvarint((uint64_t)(e_name + e_image + e_res + e_ports));
    if (e_name) {
        emit_name(b, t, N_name);
        if (enc_value(name.o, b, t, 1) < 0) return -1;
    }
    if (e_image) {
        emit_name(b, t, N_image);
        if (enc_value(image.o, b, t, 1) < 0) return -1;
    }
    if (e_res) {
        emit_name(b, t, N_resources);
        b.u8(T_MAP); b.uvarint((uint64_t)(e_req + e_lim));
        if (e_req) {
            emit_name(b, t, N_requests);
            if (enc_value(req.o, b, t, 2) < 0) return -1;
        }
        if (e_lim) {
            emit_name(b, t, N_limits);
            if (enc_value(lim.o, b, t, 2) < 0) return -1;
        }
    }
    if (e_ports) {
        emit_name(b, t, N_ports);
        Py_ssize_t n = PyList_GET_SIZE(ports.o);
        b.u8(T_LIST); b.uvarint((uint64_t)n);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (emit_port(PyList_GET_ITEM(ports.o, i), b, t, bail) < 0)
                return -1;
            if (*bail) return 0;
        }
    }
    return 0;
}

static int emit_pod_status(PyObject* st, Buf& b, StrTable& t, int* bail) {
    if (!PyObject_TypeCheck(st, (PyTypeObject*)g_cls_PodStatus)) {
        *bail = 1;
        return 0;
    }
    AttrVal phase, nom, cond, ip;
    if (!phase.get(st, N_a_phase) ||
        !nom.get(st, N_a_nominated_node_name) ||
        !cond.get(st, N_a_conditions) || !ip.get(st, N_a_pod_ip))
        return -1;
    int e_phase, e_nom, e_ip, e_cond = 0;
    str_field(phase.o, N_v_Pending, bail, &e_phase);
    str_field(nom.o, -1, bail, &e_nom);
    str_field(ip.o, -1, bail, &e_ip);
    list_gate(cond.o, bail, &e_cond, 1);
    if (*bail) return 0;
    b.u8(T_MAP); b.uvarint((uint64_t)(e_phase + e_nom + e_cond + e_ip));
    if (e_phase) {
        emit_name(b, t, N_phase);
        if (enc_value(phase.o, b, t, 1) < 0) return -1;
    }
    if (e_nom) {
        emit_name(b, t, N_nominatedNodeName);
        if (enc_value(nom.o, b, t, 1) < 0) return -1;
    }
    if (e_cond) {
        emit_name(b, t, N_conditions);
        if (enc_value(cond.o, b, t, 1) < 0) return -1;
    }
    if (e_ip) {
        emit_name(b, t, N_podIP);
        if (enc_value(ip.o, b, t, 1) < 0) return -1;
    }
    return 0;
}

static int emit_taint(PyObject* taint, Buf& b, StrTable& t, int* bail) {
    if (!PyObject_TypeCheck(taint, (PyTypeObject*)g_cls_Taint)) {
        *bail = 1;
        return 0;
    }
    AttrVal key, val, eff, ta;
    if (!key.get(taint, N_a_key) || !val.get(taint, N_a_value) ||
        !eff.get(taint, N_a_effect) || !ta.get(taint, N_a_time_added))
        return -1;
    int e_key, e_val, e_eff;
    str_field(key.o, -1, bail, &e_key);
    str_field(val.o, -1, bail, &e_val);
    str_field(eff.o, N_v_NoSchedule, bail, &e_eff);
    if (*bail) return 0;
    int e_ta = ta.o != Py_None;
    b.u8(T_MAP); b.uvarint((uint64_t)(e_key + e_val + e_eff + e_ta));
    if (e_key) {
        emit_name(b, t, N_key);
        if (enc_value(key.o, b, t, 1) < 0) return -1;
    }
    if (e_val) {
        emit_name(b, t, N_value);
        if (enc_value(val.o, b, t, 1) < 0) return -1;
    }
    if (e_eff) {
        emit_name(b, t, N_effect);
        if (enc_value(eff.o, b, t, 1) < 0) return -1;
    }
    if (e_ta) {
        emit_name(b, t, N_timeAdded);
        if (enc_value(ta.o, b, t, 1) < 0) return -1;
    }
    return 0;
}

static int emit_node_spec(PyObject* spec, Buf& b, StrTable& t, int* bail) {
    if (!PyObject_TypeCheck(spec, (PyTypeObject*)g_cls_NodeSpec)) {
        *bail = 1;
        return 0;
    }
    AttrVal unsched, taints, cidr;
    if (!unsched.get(spec, N_a_unschedulable) ||
        !taints.get(spec, N_a_taints) || !cidr.get(spec, N_a_pod_cidr))
        return -1;
    int e_unsched = 0;
    if (!PyBool_Check(unsched.o)) *bail = 1;
    else e_unsched = unsched.o == Py_True;
    int e_taints = 0;
    list_gate(taints.o, bail, &e_taints, 1);
    int e_cidr;
    str_field(cidr.o, -1, bail, &e_cidr);
    if (*bail) return 0;
    b.u8(T_MAP); b.uvarint((uint64_t)(e_unsched + e_taints + e_cidr));
    if (e_unsched) {
        emit_name(b, t, N_unschedulable);
        b.u8(T_TRUE);
    }
    if (e_taints) {
        emit_name(b, t, N_taints);
        Py_ssize_t n = PyList_GET_SIZE(taints.o);
        b.u8(T_LIST); b.uvarint((uint64_t)n);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (emit_taint(PyList_GET_ITEM(taints.o, i), b, t, bail) < 0)
                return -1;
            if (*bail) return 0;
        }
    }
    if (e_cidr) {
        emit_name(b, t, N_podCIDR);
        if (enc_value(cidr.o, b, t, 1) < 0) return -1;
    }
    return 0;
}

static int emit_image(PyObject* img, Buf& b, StrTable& t, int* bail) {
    if (!PyObject_TypeCheck(img, (PyTypeObject*)g_cls_ContainerImage)) {
        *bail = 1;
        return 0;
    }
    AttrVal names, sz;
    if (!names.get(img, N_a_names) || !sz.get(img, N_a_size_bytes))
        return -1;
    int e_names = 0, e_sz;
    list_gate(names.o, bail, &e_names, 1);
    if (int_field(sz.o, bail, &e_sz) < 0) return -1;
    if (*bail) return 0;
    b.u8(T_MAP); b.uvarint((uint64_t)(e_names + e_sz));
    if (e_names) {
        emit_name(b, t, N_names);
        if (enc_value(names.o, b, t, 1) < 0) return -1;
    }
    if (e_sz) {
        emit_name(b, t, N_sizeBytes);
        if (enc_value(sz.o, b, t, 1) < 0) return -1;
    }
    return 0;
}

// node status: the serializer always emits all five keys (allocatable is
// kept alongside capacity because from_dict defaults it FROM capacity)
static int emit_node_status(PyObject* st, Buf& b, StrTable& t, int* bail) {
    if (!PyObject_TypeCheck(st, (PyTypeObject*)g_cls_NodeStatus)) {
        *bail = 1;
        return 0;
    }
    AttrVal cap, alloc, images, cond, va;
    if (!cap.get(st, N_a_capacity) || !alloc.get(st, N_a_allocatable) ||
        !images.get(st, N_a_images) || !cond.get(st, N_a_conditions) ||
        !va.get(st, N_a_volumes_attached))
        return -1;
    if (!PyDict_Check(cap.o) || !PyDict_Check(alloc.o) ||
        !PyList_Check(images.o) || !PyList_Check(cond.o) ||
        !PyList_Check(va.o)) {
        *bail = 1;
        return 0;
    }
    b.u8(T_MAP); b.uvarint(5);
    emit_name(b, t, N_capacity);
    if (enc_value(cap.o, b, t, 1) < 0) return -1;
    emit_name(b, t, N_allocatable);
    if (enc_value(alloc.o, b, t, 1) < 0) return -1;
    emit_name(b, t, N_images);
    Py_ssize_t n = PyList_GET_SIZE(images.o);
    b.u8(T_LIST); b.uvarint((uint64_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (emit_image(PyList_GET_ITEM(images.o, i), b, t, bail) < 0)
            return -1;
        if (*bail) return 0;
    }
    emit_name(b, t, N_conditions);
    if (enc_value(cond.o, b, t, 1) < 0) return -1;
    emit_name(b, t, N_volumesAttached);
    n = PyList_GET_SIZE(va.o);
    b.u8(T_LIST); b.uvarint((uint64_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
        b.u8(T_MAP); b.uvarint(1);
        emit_name(b, t, N_name);
        if (enc_value(PyList_GET_ITEM(va.o, i), b, t, 1) < 0) return -1;
    }
    return 0;
}

// ---- object fast path: decode ----------------------------------------------
//
// Structured walk over the document, building typed objects via
// object.__new__ + __dict__ fill.  Any structural surprise (unknown key,
// unexpected value type, non-Pod/Node kind) raises nothing — it sets *bail
// and decode_object returns None so api/wire.py runs the reference
// scheme.decode(wire_decode(data)) path, which handles every shape.
// Byte-level violations (bad magic, truncation) DO raise WireError —
// exactly what the reference path would raise.

struct FastDec {
    Dec c;
    int bail;
};

// decoded key == g_name_py[idx]?  Well-known-sourced keys are the interned
// g_wk_strs objects, so pointer equality answers first.
static bool key_is(PyObject* k, int idx) {
    if (k == g_name_py[idx]) return true;
    return PyUnicode_Compare(k, g_name_py[idx]) == 0;
}

// build an instance of cls with __dict__ = d (steals d on success).
// tp_alloc is exactly what object.__new__ does for these plain dataclass
// heap types (none overrides __new__ — setup() verifies), minus a Python
// call dispatch per object.
static PyObject* build(PyObject* cls, PyObject* d) {
    PyTypeObject* tp = (PyTypeObject*)cls;
    PyObject* inst = tp->tp_alloc(tp, 0);
    if (!inst) { Py_DECREF(d); return NULL; }
    PyObject** dictptr = _PyObject_GetDictPtr(inst);
    if (dictptr) {
        Py_XDECREF(*dictptr);
        *dictptr = d;  // stolen
        return inst;
    }
    int rc = PyObject_SetAttrString(inst, "__dict__", d);
    Py_DECREF(d);
    if (rc < 0) { Py_DECREF(inst); return NULL; }
    return inst;
}

static int dict_set(PyObject* d, int name_idx, PyObject* v_stolen) {
    if (!v_stolen) return -1;
    int rc = PyDict_SetItem(d, g_name_py[name_idx], v_stolen);
    Py_DECREF(v_stolen);
    return rc;
}

// expect and open a map; returns -1 error, 0 ok (count in *count)
static int open_map(FastDec& f, uint64_t* count) {
    if (f.c.pos >= f.c.n) {
        PyErr_SetString(g_WireError, "truncated document");
        return -1;
    }
    if (f.c.d[f.c.pos] != T_MAP) { f.bail = 1; return 0; }
    f.c.pos++;
    return rd_uvarint(f.c, count);
}

// read one map key (must be a string value); NULL on error/bail
static PyObject* read_key(FastDec& f) {
    PyObject* k = dec_value(f.c, 1);
    if (!k) return NULL;
    if (!PyUnicode_Check(k)) {
        Py_DECREF(k);
        PyErr_SetString(g_WireError, "map key is not a string");
        return NULL;
    }
    return k;
}

// skip-and-drop one value (consume for parity with from_dict's ignores)
static int drop_value(FastDec& f) {
    PyObject* v = dec_value(f.c, 1);
    if (!v) return -1;
    Py_DECREF(v);
    return 0;
}

// value coercions mirroring from_dict ---------------------------------------

// float(v) for int|float; bail otherwise (e.g. RFC3339 strings)
static PyObject* as_float(FastDec& f, PyObject* v) {
    if (PyFloat_Check(v)) return v;
    if (PyLong_Check(v) && !PyBool_Check(v)) {
        PyObject* out = PyNumber_Float(v);
        Py_DECREF(v);
        return out;
    }
    Py_DECREF(v);
    f.bail = 1;
    return NULL;
}

// int(v) — only exact ints pass (bool/float/str bail to the reference path)
static PyObject* as_int(FastDec& f, PyObject* v) {
    if (PyLong_Check(v) && !PyBool_Check(v)) return v;
    Py_DECREF(v);
    f.bail = 1;
    return NULL;
}

static PyObject* as_str(FastDec& f, PyObject* v) {
    if (PyUnicode_Check(v)) return v;
    Py_DECREF(v);
    f.bail = 1;
    return NULL;
}

static PyObject* as_bool(FastDec& f, PyObject* v) {
    if (PyBool_Check(v)) return v;
    Py_DECREF(v);
    f.bail = 1;
    return NULL;
}

static PyObject* as_dict(FastDec& f, PyObject* v) {
    if (PyDict_Check(v)) return v;
    Py_DECREF(v);
    f.bail = 1;
    return NULL;
}

static PyObject* as_list(FastDec& f, PyObject* v) {
    if (PyList_Check(v)) return v;
    Py_DECREF(v);
    f.bail = 1;
    return NULL;
}

// ObjectMeta.from_dict parity: namespace "default", uid falsy -> new_uid(),
// creationTimestamp absent -> now(), resourceVersion DROPPED (stays 0),
// ownerReferences/unknown keys -> bail.
static PyObject* dec_meta(FastDec& f) {
    uint64_t count;
    if (open_map(f, &count) < 0 || f.bail) return NULL;
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    int have_uid = 0, have_ct = 0;
    for (uint64_t i = 0; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) { Py_DECREF(d); return NULL; }
        PyObject* v = dec_value(f.c, 1);
        if (!v) { Py_DECREF(k); Py_DECREF(d); return NULL; }
        int rc = 0;
        if (key_is(k, N_name)) rc = dict_set(d, N_a_name, as_str(f, v));
        else if (key_is(k, N_k_namespace))
            rc = dict_set(d, N_a_namespace, as_str(f, v));
        else if (key_is(k, N_uid)) {
            v = as_str(f, v);
            if (v && PyUnicode_GET_LENGTH(v) > 0) {
                have_uid = 1;
                rc = dict_set(d, N_a_uid, v);
            } else
                Py_XDECREF(v);  // falsy uid -> factory below
        } else if (key_is(k, N_labels))
            rc = dict_set(d, N_a_labels, as_dict(f, v));
        else if (key_is(k, N_annotations))
            rc = dict_set(d, N_a_annotations, as_dict(f, v));
        else if (key_is(k, N_resourceVersion))
            Py_DECREF(v);  // from_dict drops resourceVersion on purpose
        else if (key_is(k, N_creationTimestamp)) {
            have_ct = 1;
            rc = dict_set(d, N_a_creation_timestamp, as_float(f, v));
        } else if (key_is(k, N_deletionTimestamp))
            rc = dict_set(d, N_a_deletion_timestamp, as_float(f, v));
        else {
            Py_DECREF(v);
            f.bail = 1;  // ownerReferences / unknown key
        }
        Py_DECREF(k);
        if (rc < 0 || f.bail) { Py_DECREF(d); return NULL; }
    }
    // defaults for absent keys
    if (!PyDict_GetItem(d, g_name_py[N_a_name]) &&
        dict_set(d, N_a_name, PyUnicode_FromString("")) < 0)
        { Py_DECREF(d); return NULL; }
    if (!PyDict_GetItem(d, g_name_py[N_a_namespace])) {
        Py_INCREF(g_name_py[N_v_default]);
        if (dict_set(d, N_a_namespace, g_name_py[N_v_default]) < 0)
            { Py_DECREF(d); return NULL; }
    }
    if (!have_uid &&
        dict_set(d, N_a_uid,
                 PyObject_CallFunctionObjArgs(g_new_uid, NULL)) < 0)
        { Py_DECREF(d); return NULL; }
    if (!PyDict_GetItem(d, g_name_py[N_a_labels]) &&
        dict_set(d, N_a_labels, PyDict_New()) < 0)
        { Py_DECREF(d); return NULL; }
    if (!PyDict_GetItem(d, g_name_py[N_a_annotations]) &&
        dict_set(d, N_a_annotations, PyDict_New()) < 0)
        { Py_DECREF(d); return NULL; }
    if (!have_ct &&
        dict_set(d, N_a_creation_timestamp,
                 PyObject_CallFunctionObjArgs(g_now, NULL)) < 0)
        { Py_DECREF(d); return NULL; }
    if (dict_set(d, N_a_resource_version, PyLong_FromLong(0)) < 0 ||
        dict_set(d, N_a_owner_references, PyList_New(0)) < 0)
        { Py_DECREF(d); return NULL; }
    if (!PyDict_GetItem(d, g_name_py[N_a_deletion_timestamp])) {
        Py_INCREF(Py_None);
        if (dict_set(d, N_a_deletion_timestamp, Py_None) < 0)
            { Py_DECREF(d); return NULL; }
    }
    return build(g_cls_ObjectMeta, d);
}

static PyObject* dec_meta_default() {
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    if (dict_set(d, N_a_name, PyUnicode_FromString("")) < 0)
        { Py_DECREF(d); return NULL; }
    Py_INCREF(g_name_py[N_v_default]);
    if (dict_set(d, N_a_namespace, g_name_py[N_v_default]) < 0 ||
        dict_set(d, N_a_uid,
                 PyObject_CallFunctionObjArgs(g_new_uid, NULL)) < 0 ||
        dict_set(d, N_a_labels, PyDict_New()) < 0 ||
        dict_set(d, N_a_annotations, PyDict_New()) < 0 ||
        dict_set(d, N_a_creation_timestamp,
                 PyObject_CallFunctionObjArgs(g_now, NULL)) < 0 ||
        dict_set(d, N_a_resource_version, PyLong_FromLong(0)) < 0 ||
        dict_set(d, N_a_owner_references, PyList_New(0)) < 0)
        { Py_DECREF(d); return NULL; }
    Py_INCREF(Py_None);
    if (dict_set(d, N_a_deletion_timestamp, Py_None) < 0)
        { Py_DECREF(d); return NULL; }
    return build(g_cls_ObjectMeta, d);
}

// absent-key defaults: set dflt (stolen) unless key already present
static int dflt(PyObject* d, int name_idx, PyObject* v_stolen) {
    if (!v_stolen) return -1;
    if (PyDict_GetItem(d, g_name_py[name_idx])) {
        Py_DECREF(v_stolen);
        return 0;
    }
    return dict_set(d, name_idx, v_stolen);
}

static PyObject* dflt_str(int lit_idx) {  // new ref to a literal
    Py_INCREF(g_name_py[lit_idx]);
    return g_name_py[lit_idx];
}

static PyObject* dec_rr(FastDec& f) {
    uint64_t count;
    if (open_map(f, &count) < 0 || f.bail) return NULL;
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    for (uint64_t i = 0; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) { Py_DECREF(d); return NULL; }
        PyObject* v = dec_value(f.c, 1);
        if (!v) { Py_DECREF(k); Py_DECREF(d); return NULL; }
        int rc = 0;
        if (key_is(k, N_requests)) {
            v = as_dict(f, v);
            if (v && PyDict_GET_SIZE(v) > 0) rc = dict_set(d, N_a_requests, v);
            else Py_XDECREF(v);  // `dict(d.get("requests") or {})` -> fresh {}
        } else if (key_is(k, N_limits)) {
            v = as_dict(f, v);
            if (v && PyDict_GET_SIZE(v) > 0) rc = dict_set(d, N_a_limits, v);
            else Py_XDECREF(v);
        } else
            Py_DECREF(v);  // RR.from_dict ignores unknown keys
        Py_DECREF(k);
        if (rc < 0 || f.bail) { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_requests, PyDict_New()) < 0 ||
        dflt(d, N_a_limits, PyDict_New()) < 0)
        { Py_DECREF(d); return NULL; }
    return build(g_cls_RR, d);
}

static PyObject* dec_port(FastDec& f) {
    uint64_t count;
    if (open_map(f, &count) < 0 || f.bail) return NULL;
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    for (uint64_t i = 0; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) { Py_DECREF(d); return NULL; }
        PyObject* v = dec_value(f.c, 1);
        if (!v) { Py_DECREF(k); Py_DECREF(d); return NULL; }
        int rc = 0;
        if (key_is(k, N_containerPort))
            rc = dict_set(d, N_a_container_port, as_int(f, v));
        else if (key_is(k, N_hostPort))
            rc = dict_set(d, N_a_host_port, as_int(f, v));
        else if (key_is(k, N_hostIP))
            rc = dict_set(d, N_a_host_ip, as_str(f, v));
        else if (key_is(k, N_protocol))
            rc = dict_set(d, N_a_protocol, as_str(f, v));
        else { Py_DECREF(v); f.bail = 1; }
        Py_DECREF(k);
        if (rc < 0 || f.bail) { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_container_port, PyLong_FromLong(0)) < 0 ||
        dflt(d, N_a_host_port, PyLong_FromLong(0)) < 0 ||
        dflt(d, N_a_host_ip, PyUnicode_FromString("")) < 0 ||
        dflt(d, N_a_protocol, dflt_str(N_v_TCP)) < 0)
        { Py_DECREF(d); return NULL; }
    return build(g_cls_ContainerPort, d);
}

static PyObject* dec_container(FastDec& f) {
    uint64_t count;
    if (open_map(f, &count) < 0 || f.bail) return NULL;
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    for (uint64_t i = 0; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) { Py_DECREF(d); return NULL; }
        int rc = 0;
        if (key_is(k, N_name)) {
            rc = dict_set(d, N_a_name, as_str(f, dec_value(f.c, 1)));
        } else if (key_is(k, N_image)) {
            rc = dict_set(d, N_a_image, as_str(f, dec_value(f.c, 1)));
        } else if (key_is(k, N_resources)) {
            rc = dict_set(d, N_a_resources, dec_rr(f));
        } else if (key_is(k, N_ports)) {
            PyObject* out = PyList_New(0);
            uint64_t n;
            if (!out) rc = -1;
            else if (f.c.pos >= f.c.n || f.c.d[f.c.pos] != T_LIST)
                { f.bail = 1; Py_DECREF(out); }
            else {
                f.c.pos++;
                if (rd_uvarint(f.c, &n) < 0) rc = -1;
                else
                    for (uint64_t j = 0; j < n; j++) {
                        PyObject* p = dec_port(f);
                        if (!p || PyList_Append(out, p) < 0) {
                            Py_XDECREF(p); rc = -1; break;
                        }
                        Py_DECREF(p);
                    }
                if (rc < 0 || f.bail) Py_DECREF(out);
                else rc = dict_set(d, N_a_ports, out);
            }
        } else
            f.bail = 1;
        Py_DECREF(k);
        if (rc < 0 || f.bail) { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_name, PyUnicode_FromString("")) < 0 ||
        dflt(d, N_a_image, PyUnicode_FromString("")) < 0)
        { Py_DECREF(d); return NULL; }
    if (!PyDict_GetItem(d, g_name_py[N_a_resources])) {
        PyObject* rd = PyDict_New();
        PyObject* rr = NULL;
        if (rd && dict_set(rd, N_a_requests, PyDict_New()) == 0 &&
            dict_set(rd, N_a_limits, PyDict_New()) == 0)
            rr = build(g_cls_RR, rd);
        else
            Py_XDECREF(rd);
        if (dflt(d, N_a_resources, rr) < 0) { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_ports, PyList_New(0)) < 0)
        { Py_DECREF(d); return NULL; }
    return build(g_cls_Container, d);
}

// read `[ ... ]` of element decoder fn
typedef PyObject* (*dec_fn)(FastDec&);
static PyObject* dec_typed_list(FastDec& f, dec_fn fn) {
    if (f.c.pos >= f.c.n || f.c.d[f.c.pos] != T_LIST) {
        f.bail = 1;
        return NULL;
    }
    f.c.pos++;
    uint64_t n;
    if (rd_uvarint(f.c, &n) < 0) return NULL;
    PyObject* out = PyList_New(0);
    if (!out) return NULL;
    for (uint64_t i = 0; i < n; i++) {
        PyObject* item = fn(f);
        if (!item || PyList_Append(out, item) < 0) {
            Py_XDECREF(item); Py_DECREF(out);
            return NULL;
        }
        Py_DECREF(item);
    }
    return out;
}

static PyObject* dec_pod_spec(FastDec& f) {
    uint64_t count;
    if (open_map(f, &count) < 0 || f.bail) return NULL;
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    for (uint64_t i = 0; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) { Py_DECREF(d); return NULL; }
        int rc = 0;
        if (key_is(k, N_containers))
            rc = dict_set(d, N_a_containers, dec_typed_list(f, dec_container));
        else if (key_is(k, N_nodeName))
            rc = dict_set(d, N_a_node_name, as_str(f, dec_value(f.c, 1)));
        else if (key_is(k, N_nodeSelector)) {
            PyObject* m = as_dict(f, dec_value(f.c, 1));
            if (m) {
                // from_dict str()-coerces values; pass through only all-str
                PyObject *mk, *mv;
                Py_ssize_t mpos = 0;
                while (PyDict_Next(m, &mpos, &mk, &mv))
                    if (!PyUnicode_Check(mv)) { f.bail = 1; break; }
                if (f.bail) Py_DECREF(m);
                else rc = dict_set(d, N_a_node_selector, m);
            }
        } else if (key_is(k, N_priority))
            rc = dict_set(d, N_a_priority, as_int(f, dec_value(f.c, 1)));
        else if (key_is(k, N_priorityClassName))
            rc = dict_set(d, N_a_priority_class_name,
                          as_str(f, dec_value(f.c, 1)));
        else if (key_is(k, N_schedulerName))
            rc = dict_set(d, N_a_scheduler_name,
                          as_str(f, dec_value(f.c, 1)));
        else if (key_is(k, N_hostNetwork))
            rc = dict_set(d, N_a_host_network, as_bool(f, dec_value(f.c, 1)));
        else if (key_is(k, N_preemptionPolicy))
            rc = dict_set(d, N_a_preemption_policy,
                          as_str(f, dec_value(f.c, 1)));
        else
            f.bail = 1;  // affinity/tolerations/volumes/... -> reference path
        Py_DECREF(k);
        if (rc < 0 || f.bail) { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_containers, PyList_New(0)) < 0 ||
        dict_set(d, N_a_init_containers, PyList_New(0)) < 0 ||
        dflt(d, N_a_node_name, PyUnicode_FromString("")) < 0 ||
        dflt(d, N_a_node_selector, PyDict_New()) < 0)
        { Py_DECREF(d); return NULL; }
    Py_INCREF(Py_None);
    if (dict_set(d, N_a_affinity, Py_None) < 0 ||
        dict_set(d, N_a_tolerations, PyList_New(0)) < 0 ||
        dflt(d, N_a_priority, PyLong_FromLong(0)) < 0 ||
        dflt(d, N_a_priority_class_name, PyUnicode_FromString("")) < 0 ||
        dflt(d, N_a_scheduler_name, dflt_str(N_v_default_scheduler)) < 0 ||
        dict_set(d, N_a_topology_spread_constraints, PyList_New(0)) < 0 ||
        dict_set(d, N_a_overhead, PyDict_New()) < 0 ||
        dict_set(d, N_a_volumes, PyList_New(0)) < 0)
        { Py_DECREF(d); return NULL; }
    if (!PyDict_GetItem(d, g_name_py[N_a_host_network])) {
        Py_INCREF(Py_False);
        if (dict_set(d, N_a_host_network, Py_False) < 0)
            { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_preemption_policy,
             dflt_str(N_v_PreemptLowerPriority)) < 0 ||
        dict_set(d, N_a_resource_claims, PyList_New(0)) < 0)
        { Py_DECREF(d); return NULL; }
    return build(g_cls_PodSpec, d);
}

static PyObject* dec_pod_status(FastDec& f) {
    uint64_t count;
    if (open_map(f, &count) < 0 || f.bail) return NULL;
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    for (uint64_t i = 0; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) { Py_DECREF(d); return NULL; }
        int rc = 0;
        if (key_is(k, N_phase))
            rc = dict_set(d, N_a_phase, as_str(f, dec_value(f.c, 1)));
        else if (key_is(k, N_nominatedNodeName))
            rc = dict_set(d, N_a_nominated_node_name,
                          as_str(f, dec_value(f.c, 1)));
        else if (key_is(k, N_conditions))
            rc = dict_set(d, N_a_conditions, as_list(f, dec_value(f.c, 1)));
        else if (key_is(k, N_podIP))
            rc = dict_set(d, N_a_pod_ip, as_str(f, dec_value(f.c, 1)));
        else
            f.bail = 1;
        Py_DECREF(k);
        if (rc < 0 || f.bail) { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_phase, dflt_str(N_v_Pending)) < 0 ||
        dflt(d, N_a_nominated_node_name, PyUnicode_FromString("")) < 0 ||
        dflt(d, N_a_conditions, PyList_New(0)) < 0 ||
        dflt(d, N_a_pod_ip, PyUnicode_FromString("")) < 0)
        { Py_DECREF(d); return NULL; }
    return build(g_cls_PodStatus, d);
}

static PyObject* dec_taint(FastDec& f) {
    uint64_t count;
    if (open_map(f, &count) < 0 || f.bail) return NULL;
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    for (uint64_t i = 0; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) { Py_DECREF(d); return NULL; }
        int rc = 0;
        if (key_is(k, N_key))
            rc = dict_set(d, N_a_key, as_str(f, dec_value(f.c, 1)));
        else if (key_is(k, N_value))
            rc = dict_set(d, N_a_value, as_str(f, dec_value(f.c, 1)));
        else if (key_is(k, N_effect))
            rc = dict_set(d, N_a_effect, as_str(f, dec_value(f.c, 1)));
        else if (key_is(k, N_timeAdded))
            rc = dict_set(d, N_a_time_added, as_float(f, dec_value(f.c, 1)));
        else
            f.bail = 1;
        Py_DECREF(k);
        if (rc < 0 || f.bail) { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_key, PyUnicode_FromString("")) < 0 ||
        dflt(d, N_a_value, PyUnicode_FromString("")) < 0 ||
        dflt(d, N_a_effect, dflt_str(N_v_NoSchedule)) < 0)
        { Py_DECREF(d); return NULL; }
    if (!PyDict_GetItem(d, g_name_py[N_a_time_added])) {
        Py_INCREF(Py_None);
        if (dict_set(d, N_a_time_added, Py_None) < 0)
            { Py_DECREF(d); return NULL; }
    }
    return build(g_cls_Taint, d);
}

static PyObject* dec_node_spec(FastDec& f) {
    uint64_t count;
    if (open_map(f, &count) < 0 || f.bail) return NULL;
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    for (uint64_t i = 0; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) { Py_DECREF(d); return NULL; }
        int rc = 0;
        if (key_is(k, N_unschedulable))
            rc = dict_set(d, N_a_unschedulable, as_bool(f, dec_value(f.c, 1)));
        else if (key_is(k, N_taints))
            rc = dict_set(d, N_a_taints, dec_typed_list(f, dec_taint));
        else if (key_is(k, N_podCIDR))
            rc = dict_set(d, N_a_pod_cidr, as_str(f, dec_value(f.c, 1)));
        else
            f.bail = 1;
        Py_DECREF(k);
        if (rc < 0 || f.bail) { Py_DECREF(d); return NULL; }
    }
    if (!PyDict_GetItem(d, g_name_py[N_a_unschedulable])) {
        Py_INCREF(Py_False);
        if (dict_set(d, N_a_unschedulable, Py_False) < 0)
            { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_taints, PyList_New(0)) < 0 ||
        dflt(d, N_a_pod_cidr, PyUnicode_FromString("")) < 0)
        { Py_DECREF(d); return NULL; }
    return build(g_cls_NodeSpec, d);
}

static PyObject* dec_image(FastDec& f) {
    uint64_t count;
    if (open_map(f, &count) < 0 || f.bail) return NULL;
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    for (uint64_t i = 0; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) { Py_DECREF(d); return NULL; }
        int rc = 0;
        if (key_is(k, N_names)) {
            PyObject* lst = as_list(f, dec_value(f.c, 1));
            if (lst) {
                for (Py_ssize_t j = 0; j < PyList_GET_SIZE(lst); j++)
                    if (!PyUnicode_Check(PyList_GET_ITEM(lst, j)))
                        { f.bail = 1; break; }  // str(n) coercion
                if (f.bail) Py_DECREF(lst);
                else rc = dict_set(d, N_a_names, lst);
            }
        } else if (key_is(k, N_sizeBytes))
            rc = dict_set(d, N_a_size_bytes, as_int(f, dec_value(f.c, 1)));
        else
            f.bail = 1;
        Py_DECREF(k);
        if (rc < 0 || f.bail) { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_names, PyList_New(0)) < 0 ||
        dflt(d, N_a_size_bytes, PyLong_FromLong(0)) < 0)
        { Py_DECREF(d); return NULL; }
    return build(g_cls_ContainerImage, d);
}

static PyObject* dec_node_status(FastDec& f) {
    uint64_t count;
    if (open_map(f, &count) < 0 || f.bail) return NULL;
    PyObject* d = PyDict_New();
    if (!d) return NULL;
    int have_alloc_nonempty = 0;
    for (uint64_t i = 0; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) { Py_DECREF(d); return NULL; }
        int rc = 0;
        if (key_is(k, N_capacity))
            rc = dict_set(d, N_a_capacity, as_dict(f, dec_value(f.c, 1)));
        else if (key_is(k, N_allocatable)) {
            PyObject* m = as_dict(f, dec_value(f.c, 1));
            if (m) {
                // `dict(d.get("allocatable") or cap)`: an EMPTY allocatable
                // is falsy and from_dict copies capacity instead
                if (PyDict_GET_SIZE(m) > 0) {
                    have_alloc_nonempty = 1;
                    rc = dict_set(d, N_a_allocatable, m);
                } else
                    Py_DECREF(m);
            }
        } else if (key_is(k, N_images))
            rc = dict_set(d, N_a_images, dec_typed_list(f, dec_image));
        else if (key_is(k, N_conditions))
            rc = dict_set(d, N_a_conditions, as_list(f, dec_value(f.c, 1)));
        else if (key_is(k, N_volumesAttached)) {
            PyObject* lst = as_list(f, dec_value(f.c, 1));
            if (lst) {
                PyObject* out = PyList_New(PyList_GET_SIZE(lst));
                if (!out) { Py_DECREF(lst); rc = -1; }
                else {
                    for (Py_ssize_t j = 0; j < PyList_GET_SIZE(lst); j++) {
                        PyObject* el = PyList_GET_ITEM(lst, j);
                        PyObject* nm;
                        if (PyDict_Check(el)) {
                            nm = PyDict_GetItem(el, g_name_py[N_name]);
                            if (!nm) nm = Py_None;  // v.get("name") -> None
                        } else if (PyUnicode_Check(el))
                            nm = el;  // str(v) of a str is itself
                        else { f.bail = 1; break; }
                        Py_INCREF(nm);
                        PyList_SET_ITEM(out, j, nm);
                    }
                    Py_DECREF(lst);
                    if (f.bail) Py_DECREF(out);
                    else rc = dict_set(d, N_a_volumes_attached, out);
                }
            }
        } else
            f.bail = 1;
        Py_DECREF(k);
        if (rc < 0 || f.bail) { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_capacity, PyDict_New()) < 0)
        { Py_DECREF(d); return NULL; }
    if (!have_alloc_nonempty) {
        PyObject* cap = PyDict_GetItem(d, g_name_py[N_a_capacity]);
        if (dict_set(d, N_a_allocatable, PyDict_Copy(cap)) < 0)
            { Py_DECREF(d); return NULL; }
    }
    if (dflt(d, N_a_images, PyList_New(0)) < 0 ||
        dflt(d, N_a_conditions, PyList_New(0)) < 0 ||
        dflt(d, N_a_volumes_attached, PyList_New(0)) < 0)
        { Py_DECREF(d); return NULL; }
    return build(g_cls_NodeStatus, d);
}

// empty-manifest sub-objects for absent spec/status keys
static PyObject* dec_from_empty(dec_fn fn) {
    static const uint8_t empty_map[] = {T_MAP, 0};
    FastDec f;
    f.c.d = empty_map;
    f.c.n = 2;
    f.c.pos = 0;
    f.bail = 0;
    PyObject* out = fn(f);
    dec_free(f.c);
    return out;
}

static PyObject* py_decode_object(PyObject* self, PyObject* arg) {
    if (check_ready() < 0) return NULL;
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    FastDec f;
    f.bail = 0;
    PyObject *meta = NULL, *spec = NULL, *status = NULL, *out = NULL;
    int is_pod = 0;
    uint64_t count = 0;
    if (dec_init(f.c, &view) < 0) goto done;
    if (open_map(f, &count) < 0 || f.bail) goto done;
    if (count < 1 || count > 5) { f.bail = 1; goto done; }
    {
        // first key must be "kind" so the right sub-decoders drive the rest
        PyObject* k = read_key(f);
        if (!k) goto done;
        int ok = key_is(k, N_kind);
        Py_DECREF(k);
        if (!ok) { f.bail = 1; goto done; }
        PyObject* v = dec_value(f.c, 1);
        if (!v) goto done;
        if (key_is(v, N_v_Pod)) is_pod = 1;
        else if (key_is(v, N_v_Node)) is_pod = 0;
        else { Py_DECREF(v); f.bail = 1; goto done; }
        Py_DECREF(v);
    }
    for (uint64_t i = 1; i < count; i++) {
        PyObject* k = read_key(f);
        if (!k) goto done;
        int rc = 0;
        if (key_is(k, N_apiVersion)) {
            PyObject* v = as_str(f, dec_value(f.c, 1));
            // fast path serves the default registration only ("", "v1");
            // anything else goes through scheme.decode's validation
            if (v && !key_is(v, N_v_v1)) f.bail = 1;
            Py_XDECREF(v);
        } else if (key_is(k, N_metadata)) {
            meta = dec_meta(f);
            if (!meta) rc = -1;
        } else if (key_is(k, N_spec)) {
            spec = is_pod ? dec_pod_spec(f) : dec_node_spec(f);
            if (!spec) rc = -1;
        } else if (key_is(k, N_status)) {
            status = is_pod ? dec_pod_status(f) : dec_node_status(f);
            if (!status) rc = -1;
        } else
            f.bail = 1;
        Py_DECREF(k);
        if (rc < 0 || f.bail) goto done;
    }
    if (f.c.pos != f.c.n) {
        PyErr_Format(g_WireError, "%zd trailing bytes after document",
                     f.c.n - f.c.pos);
        goto done;
    }
    if (!meta) meta = dec_meta_default();
    if (!spec) spec = dec_from_empty(is_pod ? dec_pod_spec : dec_node_spec);
    if (!status)
        status = dec_from_empty(is_pod ? dec_pod_status : dec_node_status);
    if (meta && spec && status) {
        PyObject* d = PyDict_New();
        if (d) {
            Py_INCREF(meta); Py_INCREF(spec); Py_INCREF(status);
            if (dict_set(d, N_a_metadata, meta) == 0 &&
                dict_set(d, N_a_spec, spec) == 0 &&
                dict_set(d, N_a_status, status) == 0)
                out = build(is_pod ? g_cls_Pod : g_cls_Node, d);
            else
                Py_DECREF(d);
        }
    }
done:
    Py_XDECREF(meta);
    Py_XDECREF(spec);
    Py_XDECREF(status);
    dec_free(f.c);
    PyBuffer_Release(&view);
    if (!out) {
        if (PyErr_Occurred()) return NULL;  // hard error (e.g. WireError)
        Py_RETURN_NONE;  // structural bail -> reference path
    }
    return out;
}

// ---- setup ------------------------------------------------------------------

static PyObject* ref_get(PyObject* refs, const char* name) {
    PyObject* v = PyDict_GetItemString(refs, name);
    if (!v) {
        PyErr_Format(PyExc_KeyError, "wire codec setup missing ref %s", name);
        return NULL;
    }
    Py_INCREF(v);
    return v;
}

static PyObject* py_setup(PyObject* self, PyObject* args) {
    PyObject *wk_list, *refs;
    if (!PyArg_ParseTuple(args, "OO", &wk_list, &refs)) return NULL;
    if (!PyList_Check(wk_list) || !PyDict_Check(refs)) {
        PyErr_SetString(PyExc_TypeError, "setup(wk_list, refs_dict)");
        return NULL;
    }
    if (g_ready) Py_RETURN_NONE;  // one configuration per process
    g_wk = new std::unordered_map<std::string, uint32_t>();
    g_wk_strs = new std::vector<PyObject*>();
    for (Py_ssize_t i = 0; i < PyList_GET_SIZE(wk_list); i++) {
        PyObject* s = PyList_GET_ITEM(wk_list, i);
        if (!PyUnicode_Check(s)) {
            PyErr_SetString(PyExc_TypeError, "well-known entries must be str");
            return NULL;
        }
        Py_INCREF(s);
        PyUnicode_InternInPlace(&s);
        g_wk_strs->push_back(s);
        Py_ssize_t len;
        const char* u = PyUnicode_AsUTF8AndSize(s, &len);
        if (!u) return NULL;
        g_wk->emplace(std::string(u, (size_t)len), (uint32_t)i);
    }
    for (int i = 0; i < N_COUNT; i++) {
        g_name_py[i] = PyUnicode_InternFromString(NAME_STRS[i]);
        if (!g_name_py[i]) return NULL;
        auto it = g_wk->find(NAME_STRS[i]);
        g_name_wk[i] = it == g_wk->end() ? -1 : (int32_t)it->second;
    }
    if (!(g_WireError = ref_get(refs, "WireError")) ||
        !(g_new_uid = ref_get(refs, "new_uid")) ||
        !(g_now = ref_get(refs, "now")) ||
        !(g_cls_Pod = ref_get(refs, "Pod")) ||
        !(g_cls_ObjectMeta = ref_get(refs, "ObjectMeta")) ||
        !(g_cls_PodSpec = ref_get(refs, "PodSpec")) ||
        !(g_cls_PodStatus = ref_get(refs, "PodStatus")) ||
        !(g_cls_Container = ref_get(refs, "Container")) ||
        !(g_cls_RR = ref_get(refs, "ResourceRequirements")) ||
        !(g_cls_ContainerPort = ref_get(refs, "ContainerPort")) ||
        !(g_cls_Node = ref_get(refs, "Node")) ||
        !(g_cls_NodeSpec = ref_get(refs, "NodeSpec")) ||
        !(g_cls_NodeStatus = ref_get(refs, "NodeStatus")) ||
        !(g_cls_Taint = ref_get(refs, "Taint")) ||
        !(g_cls_ContainerImage = ref_get(refs, "ContainerImage")))
        return NULL;
    g_object_new = PyObject_GetAttrString((PyObject*)&PyBaseObject_Type,
                                          "__new__");
    if (!g_object_new) return NULL;
    // build() allocates with tp_alloc, which is only object.__new__'s
    // behavior while no class overrides __new__ — verify that holds
    PyObject* built[] = {g_cls_Pod, g_cls_ObjectMeta, g_cls_PodSpec,
                         g_cls_PodStatus, g_cls_Container, g_cls_RR,
                         g_cls_ContainerPort, g_cls_Node, g_cls_NodeSpec,
                         g_cls_NodeStatus, g_cls_Taint, g_cls_ContainerImage};
    for (PyObject* cls : built) {
        if (!PyType_Check(cls) ||
            ((PyTypeObject*)cls)->tp_new != PyBaseObject_Type.tp_new) {
            PyErr_SetString(PyExc_TypeError,
                            "wire fast path requires plain __new__ classes");
            return NULL;
        }
    }
    g_ready = 1;
    Py_RETURN_NONE;
}

// ---- module -----------------------------------------------------------------

static PyMethodDef wire_methods[] = {
    {"setup", py_setup, METH_VARARGS,
     "setup(well_known_list, refs_dict) — configure the codec once"},
    {"encode_value", py_encode_value, METH_O,
     "manifest value -> wire v1 document bytes"},
    {"decode_value", py_decode_value, METH_O,
     "wire v1 document bytes -> manifest value (strict)"},
    {"encode_pod", py_encode_pod, METH_O,
     "Pod -> wire document, or None when outside the fast subset"},
    {"encode_node", py_encode_node, METH_O,
     "Node -> wire document, or None when outside the fast subset"},
    {"decode_object", py_decode_object, METH_O,
     "wire document -> typed Pod/Node, or None to use the reference path"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef wire_module = {
    PyModuleDef_HEAD_INIT, "ktpu_wire_codec",
    "wire v1 codec fast path (see api/wire.py for the format spec)",
    -1, wire_methods,
};

PyMODINIT_FUNC PyInit_ktpu_wire_codec(void) {
    return PyModule_Create(&wire_module);
}
