"""Native (C++) host hot paths, loaded via ctypes with a Python fallback.

Each kernel compiles its .cpp with g++ on first use (cached .so next to the
source) through one shared loader; callers fall back to pure Python when no
toolchain is available or KTPU_NO_NATIVE is set (both backends stay tested —
the Python paths are the parity oracles).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional

_HERE = os.path.dirname(__file__)


class _NativeLib:
    """Shared compile-and-cache scaffold: lock, one attempt, mtime-gated
    g++ rebuild, CDLL load + prototype configuration, exception → None,
    KTPU_NO_NATIVE opt-out — applied uniformly to every kernel."""

    def __init__(self, src: str, so: str,
                 configure: Callable[[ctypes.CDLL], None]):
        self._src = os.path.join(_HERE, src)
        self._so = os.path.join(_HERE, so)
        self._configure = configure
        self._lock = threading.Lock()
        self._lib: Optional[ctypes.CDLL] = None
        self._tried = False

    def load(self) -> Optional[ctypes.CDLL]:
        with self._lock:
            if self._tried:
                return self._lib
            self._tried = True
            if os.environ.get("KTPU_NO_NATIVE"):
                return None
            try:
                if not os.path.exists(self._so) or (
                    os.path.getmtime(self._so) < os.path.getmtime(self._src)
                ):
                    subprocess.run(
                        ["g++", "-O2", "-shared", "-fPIC", "-o",
                         self._so, self._src],
                        check=True, capture_output=True, timeout=120,
                    )
                lib = ctypes.CDLL(self._so)
                self._configure(lib)
                self._lib = lib
            # ktpu-analysis: ignore[exception-hygiene] -- best-effort capability probe: no compiler/toolchain is a SUPPORTED configuration (callers fall back to the pure-python interner on _lib None); failing loudly would break every toolchain-less install
            except Exception:
                self._lib = None
            return self._lib


def _configure_interner(lib: ctypes.CDLL) -> None:
    lib.ktpu_interner_new.restype = ctypes.c_void_p
    lib.ktpu_interner_free.argtypes = [ctypes.c_void_p]
    lib.ktpu_interner_size.argtypes = [ctypes.c_void_p]
    lib.ktpu_interner_size.restype = ctypes.c_int64
    lib.ktpu_intern.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.ktpu_intern.restype = ctypes.c_int32
    lib.ktpu_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.ktpu_lookup.restype = ctypes.c_int32
    lib.ktpu_intern_many.argtypes = [
    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int32),
    ]
    lib.ktpu_intern_many.restype = ctypes.c_int64
    lib.ktpu_numeric_table.argtypes = [
    ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
    ]
    lib.ktpu_string.argtypes = [
    ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.ktpu_string.restype = ctypes.c_int64


_interner = _NativeLib("interner.cpp", "_interner.so", _configure_interner)


def load_interner() -> Optional[ctypes.CDLL]:
    return _interner.load()


class NativeInterner:
    """Drop-in for state.dictionary.Dictionary backed by the C++ interner."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._h = ctypes.c_void_p(lib.ktpu_interner_new())

    def __del__(self):
        try:
            self._lib.ktpu_interner_free(self._h)
        # ktpu-analysis: ignore[exception-hygiene] -- __del__ during interpreter teardown: ctypes globals may already be torn down and raising in __del__ prints unraisable-exception noise; there is nothing to surface to
        except Exception:
            pass

    def __len__(self) -> int:
        return int(self._lib.ktpu_interner_size(self._h))

    def intern(self, s: str) -> int:
        b = s.encode()
        return int(self._lib.ktpu_intern(self._h, b, len(b)))

    def lookup(self, s: str) -> int:
        b = s.encode()
        return int(self._lib.ktpu_lookup(self._h, b, len(b)))

    def intern_many(self, strings) -> "list[int]":
        import numpy as np

        n = len(strings)
        if n == 0:
            return []
        # single join+encode: marshalling cost would otherwise dominate the
        # C++ win (strings are k8s names/labels — never contain NUL)
        flat = ("\0".join(strings) + "\0").encode()
        out = np.empty(n, dtype=np.int32)
        self._lib.ktpu_intern_many(
            self._h, flat, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )
        return out.tolist()

    def string(self, i: int) -> str:
        buf = ctypes.create_string_buffer(256)
        full = self._lib.ktpu_string(self._h, i, buf, 256)
        if full < 0:
            raise IndexError(i)
        if full < 256:
            return buf.value.decode()
        big = ctypes.create_string_buffer(int(full) + 1)
        self._lib.ktpu_string(self._h, i, big, full + 1)
        return big.value.decode()

    def numeric_table(self, min_size: int = 1):
        import numpy as np

        n = max(len(self), min_size)
        out = np.empty(n, dtype=np.float32)
        self._lib.ktpu_numeric_table(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n
        )
        return out



_wire_lock = threading.Lock()
# one-shot cell guarded by _wire_lock; a dict (mutated, never rebound) so
# the first caller may arrive on any thread
_wire_state = {"tried": False, "mod": None}


def load_wire_codec():
    """CPython-extension wire codec (api/wire.py's fast path, a full
    extension module rather than a ctypes kernel — it builds Python objects
    directly).  Compiled with the interpreter's own headers on first use,
    cached next to the source; returns the raw module (api/wire.py calls
    its setup()).  None without a toolchain or under KTPU_NO_NATIVE —
    api/wire.py's pure-Python codec is the parity oracle and serves every
    call byte-identically."""
    with _wire_lock:
        if _wire_state["tried"]:
            return _wire_state["mod"]
        _wire_state["tried"] = True
        if os.environ.get("KTPU_NO_NATIVE"):
            return None
        try:
            import sysconfig

            src = os.path.join(_HERE, "wire_codec.cpp")
            so = os.path.join(_HERE, "_wire_codec.so")
            if not os.path.exists(so) or (
                os.path.getmtime(so) < os.path.getmtime(src)
            ):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC",
                     f"-I{sysconfig.get_paths()['include']}",
                     "-o", so, src],
                    check=True, capture_output=True, timeout=180,
                )
            from importlib.machinery import ExtensionFileLoader
            from importlib.util import module_from_spec, spec_from_file_location

            loader = ExtensionFileLoader("ktpu_wire_codec", so)
            spec = spec_from_file_location("ktpu_wire_codec", so,
                                           loader=loader)
            mod = module_from_spec(spec)
            loader.exec_module(mod)
            _wire_state["mod"] = mod
        # ktpu-analysis: ignore[exception-hygiene] -- best-effort capability probe: no compiler/headers is a SUPPORTED configuration; api/wire.py falls back to the pure-python codec, which stays the parity oracle
        except Exception:
            _wire_state["mod"] = None
        return _wire_state["mod"]


def _configure_preempt_sweep(lib: ctypes.CDLL) -> None:
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ktpu_preempt_sweep.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        i64p, i64p, i64p, u8p, u8p, i64p,
        ctypes.POINTER(ctypes.c_double), i64p,
        u8p, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), u8p,
    ]
    lib.ktpu_preempt_sweep.restype = ctypes.c_int64


_preempt_sweep = _NativeLib("preempt_sweep.cpp", "_preempt_sweep.so",
                            _configure_preempt_sweep)


def load_preempt_sweep() -> Optional[ctypes.CDLL]:
    """C++ reprieve sweep + candidate ranking (preemption.py preempt_plain's
    hot loop); None without a toolchain or under KTPU_NO_NATIVE — callers
    fall back to the numpy path, which stays the parity oracle."""
    return _preempt_sweep.load()
