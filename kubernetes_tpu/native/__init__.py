"""Native (C++) host hot paths, loaded via ctypes with a Python fallback.

``load_interner()`` compiles interner.cpp with g++ on first use (cached .so next
to the source) and returns the ctypes handle module, or None when no toolchain
is available — callers (state/dictionary.py) fall back to pure Python.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_SRC = os.path.join(os.path.dirname(__file__), "interner.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_interner.so")


def load_interner() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
                    check=True, capture_output=True, timeout=120,
                )
            lib = ctypes.CDLL(_SO)
            lib.ktpu_interner_new.restype = ctypes.c_void_p
            lib.ktpu_interner_free.argtypes = [ctypes.c_void_p]
            lib.ktpu_interner_size.argtypes = [ctypes.c_void_p]
            lib.ktpu_interner_size.restype = ctypes.c_int64
            lib.ktpu_intern.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
            lib.ktpu_intern.restype = ctypes.c_int32
            lib.ktpu_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
            lib.ktpu_lookup.restype = ctypes.c_int32
            lib.ktpu_intern_many.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.ktpu_intern_many.restype = ctypes.c_int64
            lib.ktpu_numeric_table.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ]
            lib.ktpu_string.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64,
            ]
            lib.ktpu_string.restype = ctypes.c_int64
            _lib = lib
        except Exception:
            _lib = None
        return _lib


class NativeInterner:
    """Drop-in for state.dictionary.Dictionary backed by the C++ interner."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._h = ctypes.c_void_p(lib.ktpu_interner_new())

    def __del__(self):
        try:
            self._lib.ktpu_interner_free(self._h)
        except Exception:
            pass

    def __len__(self) -> int:
        return int(self._lib.ktpu_interner_size(self._h))

    def intern(self, s: str) -> int:
        b = s.encode()
        return int(self._lib.ktpu_intern(self._h, b, len(b)))

    def lookup(self, s: str) -> int:
        b = s.encode()
        return int(self._lib.ktpu_lookup(self._h, b, len(b)))

    def intern_many(self, strings) -> "list[int]":
        import numpy as np

        n = len(strings)
        if n == 0:
            return []
        # single join+encode: marshalling cost would otherwise dominate the
        # C++ win (strings are k8s names/labels — never contain NUL)
        flat = ("\0".join(strings) + "\0").encode()
        out = np.empty(n, dtype=np.int32)
        self._lib.ktpu_intern_many(
            self._h, flat, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )
        return out.tolist()

    def string(self, i: int) -> str:
        buf = ctypes.create_string_buffer(256)
        full = self._lib.ktpu_string(self._h, i, buf, 256)
        if full < 0:
            raise IndexError(i)
        if full < 256:
            return buf.value.decode()
        big = ctypes.create_string_buffer(int(full) + 1)
        self._lib.ktpu_string(self._h, i, big, full + 1)
        return big.value.decode()

    def numeric_table(self, min_size: int = 1):
        import numpy as np

        n = max(len(self), min_size)
        out = np.empty(n, dtype=np.float32)
        self._lib.ktpu_numeric_table(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n
        )
        return out


# --- native preemption victim sweep ------------------------------------------

_ps_lock = threading.Lock()
_ps_lib: Optional[ctypes.CDLL] = None
_ps_tried = False

_PS_SRC = os.path.join(os.path.dirname(__file__), "preempt_sweep.cpp")
_PS_SO = os.path.join(os.path.dirname(__file__), "_preempt_sweep.so")


def load_preempt_sweep() -> Optional[ctypes.CDLL]:
    """C++ reprieve sweep + candidate ranking (preemption.py preempt_plain's
    hot loop); compiled on first use, None without a toolchain — callers
    fall back to the numpy path, which stays the parity oracle."""
    global _ps_lib, _ps_tried
    with _ps_lock:
        if _ps_tried:
            return _ps_lib
        _ps_tried = True
        if os.environ.get("KTPU_NO_NATIVE"):
            _ps_lib = None
            return None
        try:
            if not os.path.exists(_PS_SO) or (
                os.path.getmtime(_PS_SO) < os.path.getmtime(_PS_SRC)
            ):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", _PS_SO, _PS_SRC],
                    check=True, capture_output=True, timeout=120,
                )
            lib = ctypes.CDLL(_PS_SO)
            i64p = ctypes.POINTER(ctypes.c_int64)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.ktpu_preempt_sweep.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                i64p, i64p, i64p, u8p, u8p, i64p,
                ctypes.POINTER(ctypes.c_double), i64p,
                u8p, ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32), u8p,
            ]
            lib.ktpu_preempt_sweep.restype = ctypes.c_int64
            _ps_lib = lib
        except Exception:
            _ps_lib = None
        return _ps_lib
