// Native preemption victim sweep: the reprieve loop + 6-criteria candidate
// ranking of preempt_plain (kubernetes_tpu/preemption.py) over flat arrays.
//
// Reference semantics: framework/preemption/preemption.go DryRunPreemption
// (:546) victim minimization — victims ordered violating-first then by
// descending importance, each reprieved if the preemptor still fits with it
// restored — and pickOneNodeForPreemption (:397) lexicographic ranking:
// fewest PDB violations, lowest top victim priority, lowest priority sum,
// fewest victims, latest earliest-start among top-priority victims; full
// ties resolve to window order.  The numpy implementation stays as the
// parity oracle (tests/test_preemption.py native-parity case); this C path
// is a single pass instead of ~20 numpy dispatches per preemptor (measured
// ~1ms/pod at C=500 — the per-preemptor host cost of a preemption wave).
//
// Build: g++ -O2 -shared -fPIC (native/__init__.py load_preempt_sweep).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <vector>

extern "C" {

// Inputs (row-major):
//   base[C][R]   used-minus-all-victims per candidate
//   alloc[C][R]  allocatable
//   vr[C][V][R]  per-victim request vectors (violating-first, importance-desc)
//   v_valid[C][V] (uint8), v_viol[C][V] (uint8)
//   v_prio[C][V] (int64), v_ts[C][V] (double)
//   req[R]       preemptor request
// Outputs:
//   victim_mask[C][V] (uint8)  final victims (valid & !reprieved)
//   order[C] (int32)           candidate indices, best-ranked first
//   nviol[C] (int32)           PDB violations among final victims
//   valid_out[C] (uint8)       candidate feasible with >0 victims
// Returns the number of valid candidates.
int64_t ktpu_preempt_sweep(
    int64_t C, int64_t V, int64_t R,
    const int64_t* base, const int64_t* alloc, const int64_t* vr,
    const uint8_t* v_valid, const uint8_t* v_viol,
    const int64_t* v_prio, const double* v_ts,
    const int64_t* req,
    uint8_t* victim_mask, int32_t* order, int32_t* nviol,
    uint8_t* valid_out)
{
    std::vector<int64_t> used(R);
    // per-candidate rank keys
    std::vector<int64_t> k_top(C), k_sum(C), k_cnt(C);
    std::vector<double> k_early(C);

    for (int64_t c = 0; c < C; ++c) {
        const int64_t* b = base + c * R;
        const int64_t* a = alloc + c * R;
        bool feasible = true;
        for (int64_t r = 0; r < R; ++r) {
            if (req[r] != 0 && req[r] > a[r] - b[r]) { feasible = false; break; }
        }
        int32_t count = 0, viol = 0;
        int64_t top = INT64_MIN, sum = 0;
        double early = 1e300;
        std::memcpy(used.data(), b, R * sizeof(int64_t));
        for (int64_t v = 0; v < V; ++v) {
            uint8_t vm = 0;
            if (feasible && v_valid[c * V + v]) {
                // reprieve: restore this victim if the preemptor still fits
                const int64_t* w = vr + (c * V + v) * R;
                bool fits = true;
                for (int64_t r = 0; r < R; ++r) {
                    if (req[r] != 0 && req[r] > a[r] - (used[r] + w[r])) {
                        fits = false; break;
                    }
                }
                if (fits) {
                    for (int64_t r = 0; r < R; ++r) used[r] += w[r];
                } else {
                    vm = 1;
                    ++count;
                    int64_t p = v_prio[c * V + v];
                    if (v_viol[c * V + v]) ++viol;
                    sum += p + (int64_t(1) << 31);
                    if (p > top) { top = p; early = v_ts[c * V + v]; }
                    else if (p == top && v_ts[c * V + v] < early)
                        early = v_ts[c * V + v];
                }
            }
            victim_mask[c * V + v] = vm;
        }
        bool ok = feasible && count > 0;
        valid_out[c] = ok ? 1 : 0;
        nviol[c] = viol;
        k_top[c] = ok ? top : INT64_MAX;
        k_sum[c] = ok ? sum : INT64_MAX;
        k_cnt[c] = ok ? count : INT32_MAX;
        k_early[c] = ok ? early : -1e300;  // ranking prefers LATEST earliest
    }

    int64_t n_valid = 0;
    for (int64_t c = 0; c < C; ++c) { order[c] = (int32_t)c; if (valid_out[c]) ++n_valid; }
    std::stable_sort(order, order + C, [&](int32_t x, int32_t y) {
        if (valid_out[x] != valid_out[y]) return valid_out[x] > valid_out[y];
        if (nviol[x] != nviol[y]) return nviol[x] < nviol[y];
        if (k_top[x] != k_top[y]) return k_top[x] < k_top[y];
        if (k_sum[x] != k_sum[y]) return k_sum[x] < k_sum[y];
        if (k_cnt[x] != k_cnt[y]) return k_cnt[x] < k_cnt[y];
        if (k_early[x] != k_early[y]) return k_early[x] > k_early[y];
        return false;  // stable: window order breaks full ties
    });
    return n_valid;
}

}  // extern "C"
