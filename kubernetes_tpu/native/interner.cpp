// Native host hot path: string interning + numeric side-table.
//
// Role (SURVEY.md §2.4): the reference's Go hot paths around snapshotting
// (cache.go UpdateSnapshot) become, in this framework, the per-event host work
// of dictionary-encoding every label/taint/name string into int32 ids before
// device upload (state/dictionary.py).  That interning is the innermost host
// loop — this C++ implementation replaces the Python dict path, exposed
// through a minimal C ABI consumed via ctypes (no pybind11 in this image).
//
// Build: g++ -O2 -shared -fPIC -o _interner.so interner.cpp
//
// Concurrency: single-writer like the Python Dictionary (the scheduler's
// event-ingest thread) — no locking on the hot path.

#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <string>
#include <unordered_map>
#include <vector>

using std::nanf;

namespace {

struct Interner {
    std::unordered_map<std::string, int32_t> to_id;
    std::vector<std::string> to_str;
    std::vector<float> numeric;  // NaN when the string is not an integer

    int32_t intern(const char* s, int64_t len) {
        std::string key(s, static_cast<size_t>(len));
        auto it = to_id.find(key);
        if (it != to_id.end()) return it->second;
        int32_t id = static_cast<int32_t>(to_str.size());
        to_id.emplace(key, id);
        to_str.push_back(key);
        numeric.push_back(parse_numeric(key));
        return id;
    }

    // Mirrors state/dictionary.py _parse_numeric (Go strconv.Atoi shape):
    // optional sign + ASCII digits only, int64 range. strtoll alone would
    // also accept leading whitespace, which Python's regex rejects.
    static float parse_numeric(const std::string& s) {
        if (s.empty()) return nanf("");
        size_t i = (s[0] == '+' || s[0] == '-') ? 1 : 0;
        if (i == s.size()) return nanf("");
        for (size_t j = i; j < s.size(); ++j)
            if (s[j] < '0' || s[j] > '9') return nanf("");
        errno = 0;
        char* end = nullptr;
        long long v = strtoll(s.c_str(), &end, 10);
        if (errno != 0 || end != s.c_str() + s.size()) return nanf("");
        return static_cast<float>(v);
    }
};

}  // namespace

extern "C" {

void* ktpu_interner_new() { return new Interner(); }

void ktpu_interner_free(void* h) { delete static_cast<Interner*>(h); }

int64_t ktpu_interner_size(void* h) {
    return static_cast<int64_t>(static_cast<Interner*>(h)->to_str.size());
}

int32_t ktpu_intern(void* h, const char* s, int64_t len) {
    return static_cast<Interner*>(h)->intern(s, len);
}

// Read-only lookup: -1 when never interned.
int32_t ktpu_lookup(void* h, const char* s, int64_t len) {
    auto* in = static_cast<Interner*>(h);
    auto it = in->to_id.find(std::string(s, static_cast<size_t>(len)));
    return it == in->to_id.end() ? -1 : it->second;
}

// Batch interning: `flat` holds n zero-terminated strings back to back;
// ids are written to out[n]. Returns n (convenience).
int64_t ktpu_intern_many(void* h, const char* flat, int64_t n, int32_t* out) {
    auto* in = static_cast<Interner*>(h);
    const char* p = flat;
    for (int64_t i = 0; i < n; ++i) {
        int64_t len = static_cast<int64_t>(strlen(p));
        out[i] = in->intern(p, len);
        p += len + 1;
    }
    return n;
}

// Copy the numeric side-table (float32) into out[cap]; pads with NaN.
void ktpu_numeric_table(void* h, float* out, int64_t cap) {
    auto* in = static_cast<Interner*>(h);
    int64_t n = static_cast<int64_t>(in->numeric.size());
    int64_t m = n < cap ? n : cap;
    memcpy(out, in->numeric.data(), static_cast<size_t>(m) * sizeof(float));
    for (int64_t i = m; i < cap; ++i) out[i] = nanf("");
}

// String of an id into out (truncated to cap-1, NUL-terminated);
// returns full length or -1 for a bad id.
int64_t ktpu_string(void* h, int32_t id, char* out, int64_t cap) {
    auto* in = static_cast<Interner*>(h);
    if (id < 0 || static_cast<size_t>(id) >= in->to_str.size()) return -1;
    const std::string& s = in->to_str[static_cast<size_t>(id)];
    int64_t m = static_cast<int64_t>(s.size()) < cap - 1
                    ? static_cast<int64_t>(s.size()) : cap - 1;
    memcpy(out, s.data(), static_cast<size_t>(m));
    out[m] = '\0';
    return static_cast<int64_t>(s.size());
}

}  // extern "C"
