"""Three-queue PriorityQueue with event-driven requeue.

Reference: pkg/scheduler/internal/queue/scheduling_queue.go —
  PriorityQueue :129-170 (activeQ heap by queue-sort less-fn, podBackoffQ heap by
  backoff expiry, unschedulableQ map), Pop :478, AddUnschedulableIfNotPresent
  :387, MoveAllToActiveOrBackoffQueue :608, podMatchesEvent :963,
  flushBackoffQCompleted :426, flushUnschedulableQLeftover :457,
  backoff 1s→10s :54-64, unschedulableQ max stay 60s, Activate :318.

Differences from the reference: batched Pop (``pop_batch``) drains up to K ready
pods in one call — the unit the device path schedules per cycle; no goroutines —
callers drive ``flush()`` from their loop (tests inject a fake clock).
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..api import objects as v1
from ..framework.events import ClusterEvent
from ..metrics import scheduler_metrics as m

DEFAULT_POD_INITIAL_BACKOFF = 1.0  # :54-64
DEFAULT_POD_MAX_BACKOFF = 10.0
DEFAULT_UNSCHEDULABLE_TIME_LIMIT = 60.0  # flushUnschedulableQLeftover


@dataclass
class QueuedPodInfo:
    """Reference framework.QueuedPodInfo."""

    pod: v1.Pod
    timestamp: float = 0.0  # when added to the queue
    initial_attempt_timestamp: float = 0.0
    attempts: int = 0
    unschedulable_plugins: Set[str] = field(default_factory=set)
    # when the pod last entered the ACTIVE queue (vs. timestamp, which is
    # this attempt's overall queue entry incl. backoff/unschedulable time):
    # the attempt span tree's queue_wait splits backoff wait from
    # poppable-but-not-yet-popped wait with these two stamps
    last_activation: float = 0.0


def default_less(a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
    """PrioritySort (queuesort/priority_sort.go): priority desc, then older first."""
    pa, pb = a.pod.spec.priority, b.pod.spec.priority
    if pa != pb:
        return pa > pb
    return a.initial_attempt_timestamp < b.initial_attempt_timestamp


class PriorityQueue:
    def __init__(
        self,
        less: Callable[[QueuedPodInfo, QueuedPodInfo], bool] = default_less,
        clock: Callable[[], float] = time.monotonic,
        pod_initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
        pod_max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
        unschedulable_time_limit: float = DEFAULT_UNSCHEDULABLE_TIME_LIMIT,
        cluster_event_map: Optional[Dict[ClusterEvent, Set[str]]] = None,
        group_key: Optional[Callable[[QueuedPodInfo], Optional[str]]] = None,
    ):
        self._less = less
        self._clock = clock
        # gang cohesion (kubernetes_tpu/gang/): pods sharing a non-None
        # group key move out of backoff/unschedulableQ TOGETHER — one
        # member trickling back alone just burns a Permit-timeout round
        # per member (the thrash the coscheduling subsystem exists to stop)
        self._group_key = group_key
        self._initial_backoff = pod_initial_backoff
        self._max_backoff = pod_max_backoff
        self._unschedulable_limit = unschedulable_time_limit
        # ClusterEvent → plugin names that registered it (scheduler.go:347-362)
        self._cluster_event_map = cluster_event_map or {}
        self._seq = itertools.count()
        self._active: List[Tuple[object, int, QueuedPodInfo]] = []  # heap
        self._backoff: List[Tuple[float, int, QueuedPodInfo]] = []  # heap by expiry
        self._unschedulable: Dict[str, QueuedPodInfo] = {}  # uid → info
        self._in_active: Set[str] = set()
        self._in_backoff: Set[str] = set()
        self._moves: int = 0  # moveRequestCycle analog
        # Debounce: move_all_to_active_or_backoff only records the event; the
        # O(unschedulable) match scan runs once per flush() over the deduped
        # pending set.  A 128-pod bind burst otherwise triggers 128 full scans
        # (each bind's watch event calls move_all — eventhandlers.go analog).
        self._pending_events: List[ClusterEvent] = []

    # --- sort key ------------------------------------------------------------

    class _Key:
        __slots__ = ("info", "less")

        def __init__(self, info, less):
            self.info, self.less = info, less

        def __lt__(self, other):
            return self.less(self.info, other.info)

    def _push_active(self, info: QueuedPodInfo, event: Optional[str] = None):
        """``event`` labels queue_incoming_pods (metrics.go's per-event
        inflow accounting); None = internal churn (pop_batch put-back),
        not a queue entry."""
        uid = info.pod.uid
        if uid in self._in_active:
            return
        info.last_activation = self._clock()
        heapq.heappush(
            self._active, (self._Key(info, self._less), next(self._seq), info)
        )
        self._in_active.add(uid)
        if event is not None:
            m.queue_incoming_pods.inc(("active", event))

    # --- public API ----------------------------------------------------------

    def add(self, pod: v1.Pod) -> None:
        now = self._clock()
        info = QueuedPodInfo(
            pod=pod, timestamp=now, initial_attempt_timestamp=now
        )
        self._push_active(info, "PodAdd")

    def __len__(self) -> int:
        self.flush()
        return len(self._active)

    def unschedulable_pods(self) -> List[v1.Pod]:
        """Pods parked in unschedulableQ — the cluster-autoscaler's demand
        signal (upstream reads the same queue via the scheduler's
        nominator/listers).  Pending event moves apply first (like
        pending_count): a pod a recorded cluster event — e.g. NODE_ADD
        from the autoscaler's own scale-up — has already queued back to
        active must not still read as parked demand."""
        self._apply_pending_moves()
        return [info.pod for info in self._unschedulable.values()]

    def pending_count(self) -> Tuple[int, int, int]:
        self._apply_pending_moves()
        return len(self._active), len(self._backoff), len(self._unschedulable)

    def pop(self) -> Optional[QueuedPodInfo]:
        self.flush()
        while self._active:
            _, _, info = heapq.heappop(self._active)
            uid = info.pod.uid
            if uid in self._in_active:
                self._in_active.discard(uid)
                info.attempts += 1
                return info
        return None

    def pop_batch(self, max_size: int, group_key=None) -> List[QueuedPodInfo]:
        """Drain up to max_size ready pods — the device batch unit.

        ``group_key(info)``: when given, the batch holds only pods sharing
        the HEAD pod's key (e.g. schedulerName — one framework per dispatch,
        profile/profile.go:45); non-matching pods are pushed back untouched."""
        out = []
        put_back = []
        key = None
        while len(out) < max_size and len(put_back) < max_size:
            # the put_back bound keeps the scan O(batch) even when another
            # profile dominates the queue (no full-heap drain per cycle)
            info = self.pop()
            if info is None:
                break
            if group_key is not None:
                k = group_key(info)
                if key is None:
                    key = k
                elif k != key:
                    put_back.append(info)
                    continue
            out.append(info)
        # through put_back(): attempts un-counted AND last_activation
        # preserved — a pod repeatedly riding profile-mismatch put-backs
        # must not have its active-wait attribution restamped every cycle
        self.put_back(put_back)
        return out

    def put_back(self, infos: Sequence[QueuedPodInfo]) -> None:
        """Return pods popped this cycle to the active queue untouched — the
        scheduler's micro-bucket split dispatches only the head of a popped
        batch and hands the tail straight back.  pop() counted an attempt
        for each; undo it (the pod was never dispatched).  ``timestamp``
        AND ``last_activation`` are deliberately preserved: the pod's
        queue-wait accounting (including the active-wait split the
        queue_wait span reports) must keep covering the time it spent
        riding put-back tails — _push_active would otherwise restamp
        activation every cycle."""
        for info in infos:
            info.attempts -= 1
            la = info.last_activation
            self._push_active(info)
            info.last_activation = la

    def add_unschedulable(self, info: QueuedPodInfo, pod_scheduling_cycle: Optional[int] = None) -> None:
        """AddUnschedulableIfNotPresent (:387): a move since the cycle started
        sends the pod to backoff instead of unschedulableQ."""
        uid = info.pod.uid
        if uid in self._in_active or uid in self._in_backoff or uid in self._unschedulable:
            return
        info.timestamp = self._clock()
        if pod_scheduling_cycle is not None and self._moves > pod_scheduling_cycle:
            self._push_backoff(info, "ScheduleAttemptFailure")
        else:
            self._unschedulable[uid] = info
            m.queue_incoming_pods.inc(
                ("unschedulable", "ScheduleAttemptFailure"))

    def requeue_after_error(self, info: QueuedPodInfo) -> None:
        """Transient-error requeue: straight to the backoff heap.

        An INTERNAL error (store outage mid-cycle, bind transport fault) is
        retriable on a timer — no cluster event will ever arrive to move the
        pod out of unschedulableQ, so parking it there strands it for the
        60s leftover flush.  The reference routes framework errors the same
        way (handleSchedulingFailure → podBackoffQ)."""
        uid = info.pod.uid
        if uid in self._in_active or uid in self._in_backoff \
                or uid in self._unschedulable:
            return
        info.timestamp = self._clock()
        self._push_backoff(info, "SchedulingError")

    def scheduling_cycle(self) -> int:
        return self._moves

    def _backoff_time(self, info: QueuedPodInfo) -> float:
        d = self._initial_backoff * (2 ** max(info.attempts - 1, 0))
        return info.timestamp + min(d, self._max_backoff)

    def _push_backoff(self, info: QueuedPodInfo, event: Optional[str] = None):
        uid = info.pod.uid
        if uid in self._in_backoff:
            return
        heapq.heappush(
            self._backoff, (self._backoff_time(info), next(self._seq), info)
        )
        self._in_backoff.add(uid)
        if event is not None:
            m.queue_incoming_pods.inc(("backoff", event))

    def activate(self, pods: Sequence[v1.Pod]) -> None:
        """Activate (:318): force named pods from backoff/unschedulable to
        active — expanded to every queued member of the named pods' groups
        (group_key), so a gang re-enters the active queue as ONE unit."""
        uids = {p.uid for p in pods}
        uids |= self._group_sibling_uids(
            self._groups_of_pods(pods) if self._group_key else set())
        self._remove_from_backoff(uids, to_active=True)
        for uid in list(self._unschedulable):
            if uid in uids:
                self._push_active(self._unschedulable.pop(uid),
                                  "ForceActivate")

    def _groups_of_pods(self, pods: Sequence[v1.Pod]) -> Set[str]:
        # group_key reads info.pod only; a transient wrapper is enough
        return {
            k for k in (self._group_key(QueuedPodInfo(pod=p)) for p in pods)
            if k is not None
        }

    def _group_sibling_uids(self, groups: Set[str]) -> Set[str]:
        """uids of every backoff/unschedulableQ member of ``groups``."""
        if not groups:
            return set()
        out: Set[str] = set()
        for info in self._unschedulable.values():
            if self._group_key(info) in groups:
                out.add(info.pod.uid)
        for _, _, info in self._backoff:
            if info.pod.uid in self._in_backoff \
                    and self._group_key(info) in groups:
                out.add(info.pod.uid)
        return out

    def _remove_from_backoff(self, uids: Set[str], to_active: bool):
        kept = []
        for expiry, seq, info in self._backoff:
            if info.pod.uid in uids and info.pod.uid in self._in_backoff:
                self._in_backoff.discard(info.pod.uid)
                if to_active:
                    self._push_active(info, "ForceActivate")
            else:
                kept.append((expiry, seq, info))
        heapq.heapify(kept)
        self._backoff = kept

    def move_all_to_active_or_backoff(self, event: ClusterEvent) -> None:
        """MoveAllToActiveOrBackoffQueue (:608) + podMatchesEvent (:963).

        The move counter bumps immediately (AddUnschedulableIfNotPresent's
        backoff-vs-unschedulable decision depends on it) but the scan is
        deferred to flush(), which every pop() runs first — observable
        behavior is unchanged, repeated events within one burst cost one scan."""
        self._moves += 1
        self._pending_events.append(event)

    def _apply_pending_moves(self) -> None:
        if not self._pending_events:
            return
        events, self._pending_events = self._pending_events, []
        seen = set()
        deduped = []
        for ev in events:
            k = (ev.resource, ev.action_type)
            if k not in seen:
                seen.add(k)
                deduped.append(ev)
        moved = []
        for uid, info in self._unschedulable.items():
            ev = next((ev for ev in deduped
                       if self._pod_matches_event(info, ev)), None)
            if ev is not None:
                moved.append((uid, ev.label or "ClusterEvent"))
        # Gang cohesion: an event that moves ANY member moves the WHOLE
        # group, and the group bypasses the per-pod backoff gate — members
        # re-dispatch together or the stragglers burn the released members'
        # Permit wait one timeout at a time.
        moved_groups: Set[str] = set()
        if self._group_key is not None and moved:
            for uid, _ in moved:
                g = self._group_key(self._unschedulable[uid])
                if g is not None:
                    moved_groups.add(g)
            if moved_groups:
                moved_uids = {u for u, _ in moved}
                label_of = {
                    self._group_key(self._unschedulable[u]): lbl
                    for u, lbl in moved
                }
                for uid, info in self._unschedulable.items():
                    g = self._group_key(info)
                    if g in moved_groups and uid not in moved_uids:
                        moved.append((uid, label_of[g]))
                backoff_sibs = self._group_sibling_uids(moved_groups) \
                    - {u for u, _ in moved}
                if backoff_sibs:
                    self._remove_from_backoff(backoff_sibs, to_active=True)
        for uid, label in moved:
            info = self._unschedulable.pop(uid)
            if self._group_key is not None \
                    and self._group_key(info) in moved_groups:
                self._push_active(info, label)
            elif self._clock() < self._backoff_time(info):
                self._push_backoff(info, label)
            else:
                self._push_active(info, label)

    def _pod_matches_event(self, info: QueuedPodInfo, event: ClusterEvent) -> bool:
        if event.is_wildcard():
            return True
        if not info.unschedulable_plugins:
            return True  # no diagnosis recorded — be permissive
        for registered, plugins in self._cluster_event_map.items():
            if registered.match(event) and (plugins & info.unschedulable_plugins):
                return True
        return False

    def update(self, old: v1.Pod, new: v1.Pod) -> None:
        """Pod spec update may make it schedulable: move out of unschedulableQ."""
        info = self._unschedulable.pop(new.uid, None)
        if info is not None:
            info.pod = new
            if self._clock() < self._backoff_time(info):
                self._push_backoff(info, "PodUpdate")
            else:
                self._push_active(info, "PodUpdate")

    def delete(self, pod: v1.Pod) -> None:
        self._in_active.discard(pod.uid)
        self._in_backoff.discard(pod.uid)
        self._unschedulable.pop(pod.uid, None)

    # --- flush loops (reference: goroutines at 1s / 30s) ----------------------

    def next_backoff_expiry(self) -> Optional[float]:
        """Expiry time of the soonest still-backed-off pod, or None.  Flushes
        first, so already-expired pods are in the active queue, not here —
        the scheduler's batch-formation hysteresis peeks at this."""
        self.flush()
        return self._backoff[0][0] if self._backoff else None

    def flush(self) -> None:
        self._apply_pending_moves()
        now = self._clock()
        while self._backoff:
            expiry, _, info = self._backoff[0]
            if expiry > now:
                break
            heapq.heappop(self._backoff)
            if info.pod.uid in self._in_backoff:
                self._in_backoff.discard(info.pod.uid)
                self._push_active(info, "BackoffComplete")
        for uid, info in list(self._unschedulable.items()):
            if now - info.timestamp > self._unschedulable_limit:
                del self._unschedulable[uid]
                if now < self._backoff_time(info):
                    self._push_backoff(info, "UnschedulableTimeout")
                else:
                    self._push_active(info, "UnschedulableTimeout")
