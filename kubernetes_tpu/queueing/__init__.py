"""Scheduling queue (reference: pkg/scheduler/internal/queue)."""

from .priority_queue import PriorityQueue, QueuedPodInfo  # noqa: F401
