"""Device-side gang pass: in-batch all-or-nothing over segment sums.

Runs INSIDE the fused cycle program (scheduler._build_jitted), after the
assignment engine produced ``node_row`` — a separate device program would
pay its own ~100ms tunnel pacing round per cycle.  Pure jnp; the segment
reductions ride the one-hot einsum kernels in ops/segment.py (minor-axis
gathers/scatters lower to serial loops on TPU).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops.segment import domain_gather, domain_scatter_add


def gang_all_or_nothing(node_row, gang_seg):
    """Mask every member of a gang with ANY unplaced member to -1.

    node_row: i32[B] assigned node row per pod (-1 = unschedulable).
    gang_seg: i32[B] per-pod gang segment id in [0, B), -1 for pods that
        are not gang members (including padding rows).

    Either every member of a gang present in this batch got a feasible row
    or the whole gang is withdrawn — a partially placed gang must never
    reach the binding cycle (members split across batches are instead held
    at Permit by the Coscheduling plugin).  An all(-1) gang_seg batch is a
    no-op, so gang-free and gang-bearing cycles share one compiled program.
    """
    b = node_row.shape[0]
    member = gang_seg >= 0
    # solos/padding land in an overflow bucket that never feeds back
    seg = jnp.where(member, gang_seg, b)
    missed = (member & (node_row < 0)).astype(jnp.float32)
    miss_per_gang = domain_scatter_add(missed, seg, b + 1)  # f32[B+1]
    incomplete = domain_gather(miss_per_gang, seg) > 0.5  # bool[B]
    return jnp.where(member & incomplete, -1, node_row)
