"""Gang scheduling subsystem: PodGroup-driven all-or-nothing placement.

Reference: sigs.k8s.io/scheduler-plugins pkg/coscheduling (PodGroup CRD +
the Coscheduling plugin's QueueSort/PreFilter/Permit/PostBind/Unreserve
chain).  Layers here:

  - L0 object model: ``api.objects.PodGroup`` (minMember,
    scheduleTimeoutSeconds, status.phase), registered in the scheme under
    scheduling.x-k8s.io/v1alpha1; pods join via the POD_GROUP_LABEL label.
  - ``GangDirectory`` (directory.py): the shared host-side runtime — group
    membership from store watch events, quorum PreFilter, Permit
    all-or-nothing release/timeout, phase writes, metrics.
  - ``CoschedulingPlugin`` (coscheduling.py): the framework plugin shell
    (QueueSort less, host Permit/Reserve/Unreserve/PostBind hooks, a
    device score plane preferring the gang's anchor slice).
  - ``gang_all_or_nothing`` (device.py): the in-batch solver mask — a
    segment-sum pass over gang ids that zeroes every member of a gang with
    any unplaced member, so partial placements never reach binding.
"""

from .device import gang_all_or_nothing
from .directory import (
    DEFAULT_GANG_TIMEOUT_SECONDS,
    POD_GROUP_LABEL,
    SLICE_LABEL,
    GangDirectory,
)
from .coscheduling import CoschedulingPlugin

__all__ = [
    "CoschedulingPlugin",
    "DEFAULT_GANG_TIMEOUT_SECONDS",
    "GangDirectory",
    "POD_GROUP_LABEL",
    "SLICE_LABEL",
    "gang_all_or_nothing",
]
