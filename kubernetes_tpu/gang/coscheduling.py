"""Coscheduling plugin: the framework-facing shell over GangDirectory.

Reference: sigs.k8s.io/scheduler-plugins pkg/coscheduling/coscheduling.go —
QueueSort (group cohesion), PreFilter (quorum), Permit (all-or-nothing
Wait/Allow), PostBind (phase), Unreserve (group reject).  Host hooks
delegate to the scheduler-owned GangDirectory (attached via
``attach_gang_directory``); the device side contributes one score plane
preferring nodes in the gang's anchor slice (see GangDirectory.host_aux).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import events as fwk_events
from ..framework.events import ActionType, ClusterEvent, EventResource
from ..framework.interface import Code, Plugin, Status
from .directory import GangDirectory


class CoschedulingPlugin(Plugin):
    name = "Coscheduling"
    # Permit Wait from this plugin HOLDS the binding cycle across scheduling
    # cycles (assume + reserve kept, bind deferred) instead of failing it —
    # see TPUScheduler._run_reserve_and_bind / _flush_waiting_binds.
    holds_on_wait = True

    def __init__(self):
        self._dir: GangDirectory = None

    def attach_gang_directory(self, directory: GangDirectory) -> None:
        self._dir = directory

    def events_to_register(self):
        # a quorum-rejected member becomes schedulable when a sibling pod
        # appears or the PodGroup changes; capacity frees on pod delete /
        # node add
        return [
            fwk_events.POD_GROUP_CHANGE,
            ClusterEvent(EventResource.POD, ActionType.ADD | ActionType.DELETE),
            fwk_events.NODE_ADD,
        ]

    # --- host extension points -----------------------------------------------

    def less(self, a, b) -> bool:
        if self._dir is None:
            from ..queueing.priority_queue import default_less

            return default_less(a, b)
        return self._dir.less(a, b)

    def pre_filter(self, state, pod):
        if self._dir is None:
            return None
        return self._dir.prefilter(pod)

    def reserve(self, state, pod, node_name) -> Status:
        # membership in the reserve chain is what routes rollbacks through
        # unreserve (the group-failure hook); admission itself is Permit's
        return Status.success()

    def unreserve(self, state, pod, node_name) -> None:
        if self._dir is not None:
            self._dir.on_unreserve(pod)

    def permit(self, state, pod, node_name):
        if self._dir is None:
            return Status.success(), 0.0
        decision, timeout = self._dir.on_permit(pod)
        if decision == "wait":
            return Status(code=Code.WAIT), timeout
        return Status.success(), 0.0

    def post_bind(self, state, pod, node_name) -> None:
        if self._dir is not None:
            self._dir.on_bound(pod, node_name)

    # --- device score: prefer the gang's anchor slice -------------------------

    def host_prepare(self, batch, snapshot, encoder, namespace_labels=None):
        b = int(batch.valid.shape[0])
        if self._dir is None:
            n = int(np.shape(encoder.node_valid)[0])
            return (np.full(n, -1, dtype=np.int32),
                    np.full(b, -2, dtype=np.int32))
        return self._dir.host_aux(b, encoder)

    def prepare(self, batch, snap, dyn, host_aux):
        return host_aux

    def host_aux_take(self, aux, rows):
        """Row-gather the pod-indexed half of the host aux (identity-class
        dedup builds a class-representative view; the slice-domain plane is
        node-indexed and shared)."""
        slice_dom, anchor = aux
        return (slice_dom, anchor[rows])

    def score(self, batch, snap, dyn, aux, mask=None):
        slice_dom, anchor = aux
        match = (anchor[:, None] == slice_dom[None, :]) & (anchor[:, None] >= 0)
        return match.astype(jnp.float32)

    def normalize(self, scores, mask):
        from ..plugins.helpers import default_normalize

        return default_normalize(scores, mask)
