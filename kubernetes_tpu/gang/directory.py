"""GangDirectory: the shared host-side gang-scheduling runtime.

Reference: sigs.k8s.io/scheduler-plugins pkg/coscheduling/core (the
PodGroupManager every extension point consults).  One directory is owned
by the scheduler and wired into every profile's ``CoschedulingPlugin``
instance; it tracks group membership from the store's watch stream, makes
the quorum (PreFilter), all-or-nothing release (Permit) and group-failure
(Unreserve) decisions, writes PodGroup ``status.phase``, and emits the
gang metric series.

All deadline math runs on the INJECTED clock (the scheduler's own), never
raw ``time.monotonic()`` — gang-timeout tests drive a fake clock and the
WaitingPodsMap deadlines must agree with it exactly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..api import objects as v1
from ..component_base import logging as klog
from ..framework.interface import Status
from ..metrics import scheduler_metrics as m

# Pods join a group via this label; the value is the PodGroup's name in the
# pod's own namespace (the upstream coscheduling label, shortened).
POD_GROUP_LABEL = "pod-group.scheduling/name"
# Node label naming the TPU slice a node belongs to; the gang score plane
# prefers nodes sharing the gang's anchor slice.
SLICE_LABEL = "tpu.kubernetes.io/slice"
DEFAULT_GANG_TIMEOUT_SECONDS = 60.0
PLUGIN_NAME = "Coscheduling"


@dataclass
class _GroupState:
    """Disjoint membership sets: pending (unbound, not held at Permit),
    waiting (assumed + held at Permit, uid → node), bound (uid → node)."""

    pg: Optional[v1.PodGroup] = None
    pending: Set[str] = field(default_factory=set)
    waiting: Dict[str, str] = field(default_factory=dict)
    bound: Dict[str, str] = field(default_factory=dict)
    first_wait_ts: Optional[float] = None
    quorum_rejected: bool = False  # metric edge-trigger
    failing: bool = False  # _fail_group reentrancy guard
    last_reject_reason: str = ""
    checked_gen: int = -1  # negative PodGroup-lookup cache generation
    # edge-trigger for the release side effects (metric + phase): a group
    # with MORE pods than minMember sees on_permit cross the threshold once
    # per member past the quorum — waiters are re-allowed every time
    # (idempotent), the attempt metric and phase write fire only once per
    # scheduling round
    released: bool = False


class GangDirectory:
    def __init__(self, store, clock=time.monotonic,
                 default_timeout: float = DEFAULT_GANG_TIMEOUT_SECONDS,
                 slice_label: str = SLICE_LABEL):
        self._store = store
        self._clock = clock
        self._default_timeout = default_timeout
        self._slice_label = slice_label
        self._groups: Dict[str, _GroupState] = {}
        self._pg_gen = 0  # bumped on PodGroup watch events (negative cache)
        self._waiting_pods = None  # WaitingPodsMap, bound by the scheduler
        self._staged: List[v1.Pod] = []
        # slice-domain cache: rebuilt when nodes change (invalidate_nodes)
        self._slice_ids: Dict[str, int] = {}
        self._node_gen = 0
        self._slice_cache: Optional[np.ndarray] = None
        self._slice_cache_gen = -1
        self._noop_seg_cache: Dict[int, np.ndarray] = {}
        # pod → pending chip demand (the scheduler wires its
        # DraIndex.pod_claim_demand); None = claim-blind anchor pick
        self._claim_demand = None

    def bind_runtime(self, waiting_pods) -> None:
        """Wire the scheduler-owned WaitingPodsMap (release/reject target)."""
        self._waiting_pods = waiting_pods

    def attach_claim_resolver(self, fn) -> None:
        """Make the anchor-slice pick consume DRA claim demand: a fresh
        gang anchors to a slice whose free CHIPS cover the gang's pending
        claims, so its members' claims co-allocate into one slice instead
        of scattering across slices that can each host only part of it."""
        self._claim_demand = fn

    # --- membership ----------------------------------------------------------

    @property
    def active(self) -> bool:
        return bool(self._groups)

    def group_key_of(self, pod: v1.Pod) -> Optional[str]:
        name = pod.metadata.labels.get(POD_GROUP_LABEL)
        if not name:
            return None
        return f"{pod.metadata.namespace}/{name}"

    def is_member(self, pod: v1.Pod) -> bool:
        return POD_GROUP_LABEL in pod.metadata.labels

    def _state(self, key: str) -> _GroupState:
        g = self._groups.get(key)
        if g is None:
            g = _GroupState()
            self._groups[key] = g
        if g.pg is None and g.checked_gen != self._pg_gen:
            # lazy store lookup with a negative cache: less() runs on every
            # queue heap compare and must not hit the store per compare for
            # a group that simply doesn't exist (yet)
            ns, _, name = key.partition("/")
            g.pg = self._store.get("PodGroup", ns, name)
            g.checked_gen = self._pg_gen
        return g

    # --- watch hooks (driven by the scheduler's store watch) ------------------

    def on_pod_event(self, ev_type: str, pod: v1.Pod, assigned: bool) -> None:
        key = self.group_key_of(pod)
        if key is None:
            return
        from ..sim.store import DELETED

        g = self._state(key)
        uid = pod.uid
        if ev_type == DELETED:
            g.pending.discard(uid)
            g.waiting.pop(uid, None)
            g.bound.pop(uid, None)
            if g.pg is not None and len(g.bound) < g.pg.min_member:
                g.released = False  # a re-formed gang releases anew
                known = len(g.pending) + len(g.waiting) + len(g.bound)
                if g.waiting and known < g.pg.min_member and not g.failing:
                    # the group can no longer reach quorum: fail the
                    # remaining waiters NOW instead of timing them out
                    g.failing = True
                    try:
                        self._fail_group(key, g,
                                         "rejected (member deleted below "
                                         "quorum)")
                    finally:
                        g.failing = False
            self._maybe_evict(key, g)
        elif assigned:
            self.on_bound(pod, pod.spec.node_name)
        elif uid not in g.bound and uid not in g.waiting:
            g.pending.add(uid)

    def on_group_event(self, ev_type: str, pg: v1.PodGroup) -> None:
        from ..sim.store import DELETED

        self._pg_gen += 1
        g = self._state(pg.key())
        g.pg = None if ev_type == DELETED else pg
        g.checked_gen = self._pg_gen
        if ev_type == DELETED:
            self._maybe_evict(pg.key(), g)

    def _maybe_evict(self, key: str, g: _GroupState) -> None:
        """Drop fully-drained dead group state: no PodGroup object and no
        members left means nothing can reference it again (a later pod
        lazily recreates it) — a long-lived scheduler churning through
        thousands of transient slice jobs must not grow _groups forever.
        (_slice_ids is different: it grows with DISTINCT slice-label
        values, bounded by node-label cardinality, and its ids are
        embedded in cached planes — left alone.)"""
        if g.pg is None and not g.pending and not g.waiting and not g.bound:
            self._groups.pop(key, None)

    def invalidate_nodes(self) -> None:
        """Node add/delete/label change: the slice-domain plane is stale."""
        self._node_gen += 1

    # --- QueueSort (the Coscheduling less-fn) ---------------------------------

    def sort_anchor(self, info) -> Tuple[float, str]:
        """Group cohesion key: members of one group share (group creation
        ts, group key) so the queue-sort heap keeps them ADJACENT — the
        batch pop then drains a gang contiguously.  Non-members anchor on
        their own pod creation timestamp (same wall-clock scale)."""
        key = self.group_key_of(info.pod)
        if key is None:
            return (info.pod.metadata.creation_timestamp, "")
        g = self._state(key)
        ts = (g.pg.metadata.creation_timestamp if g.pg is not None
              else info.pod.metadata.creation_timestamp)
        return (ts, key)

    def less(self, a, b) -> bool:
        """PrioritySort with gang cohesion (coscheduling queue_sort.go:
        priority desc, then group anchor, then per-pod arrival)."""
        pa, pb = a.pod.spec.priority, b.pod.spec.priority
        if pa != pb:
            return pa > pb
        ka, kb = self.sort_anchor(a), self.sort_anchor(b)
        if ka != kb:
            return ka < kb
        return a.initial_attempt_timestamp < b.initial_attempt_timestamp

    def queue_group_key(self, info) -> Optional[str]:
        """PriorityQueue group-cohesion key (group-aware activate/moves)."""
        return self.group_key_of(info.pod)

    # --- PreFilter quorum -----------------------------------------------------

    def prefilter(self, pod: v1.Pod) -> Optional[Status]:
        """None = pass; a Status rejects BEFORE any solver work.  Fewer
        than minMember known members can never form the gang, so the
        rejection is UnschedulableAndUnresolvable (a sibling-pod ADD or
        PodGroup change requeues via the registered cluster events)."""
        key = self.group_key_of(pod)
        if key is None:
            return None
        g = self._state(key)
        if g.pg is None:
            return Status.unschedulable(
                f"PodGroup {key} not found", plugin=PLUGIN_NAME,
                resolvable=False)
        known = len(g.pending) + len(g.waiting) + len(g.bound)
        if known < g.pg.min_member:
            if not g.quorum_rejected:
                g.quorum_rejected = True
                m.gang_scheduling_attempts.inc(("quorum_reject",))
            return Status.unschedulable(
                f"gang {key} has {known}/{g.pg.min_member} members",
                plugin=PLUGIN_NAME, resolvable=False)
        g.quorum_rejected = False
        return None

    # --- Permit: all-or-nothing release --------------------------------------

    def on_permit(self, pod: v1.Pod) -> Tuple[str, float]:
        """→ ("allow", 0) when this member completes the gang (all waiting
        siblings are released), else ("wait", timeout)."""
        key = self.group_key_of(pod)
        if key is None:
            return ("allow", 0.0)
        g = self._state(key)
        if g.pg is None:
            return ("wait", self._default_timeout)
        have = len(g.bound) + len(g.waiting) + 1  # + this pod
        if have >= g.pg.min_member:
            self._release(key, g)
            return ("allow", 0.0)
        timeout = (float(g.pg.schedule_timeout_seconds)
                   if g.pg.schedule_timeout_seconds is not None
                   else self._default_timeout)
        return ("wait", timeout)

    def note_waiting(self, pod: v1.Pod, node_name: str) -> None:
        """A member entered the Permit hold (assumed, reserve kept)."""
        key = self.group_key_of(pod)
        if key is None:
            return
        g = self._state(key)
        g.pending.discard(pod.uid)
        g.waiting[pod.uid] = node_name
        if g.first_wait_ts is None:
            g.first_wait_ts = self._clock()
        self._set_phase(g, v1.POD_GROUP_SCHEDULING)
        # kill-point: a gang member holds its Permit (assumed + reserved,
        # NOTHING bound in the store) — process death here must expire the
        # held permits into an atomic gang requeue on the successor, never
        # a half-bound gang (no store bind has happened for any waiter)
        from ..chaos.faults import maybe_crash

        maybe_crash("crash.permit_held")

    def note_wait_rejected(self, pod: v1.Pod, reason: str) -> None:
        """Flush-path context for the unreserve that follows: was this a
        Permit deadline expiry (gang timeout) or an ordinary rejection."""
        key = self.group_key_of(pod)
        if key is not None:
            self._state(key).last_reject_reason = reason

    def _release(self, key: str, g: _GroupState) -> None:
        # allowing waiters is idempotent and must run on EVERY threshold
        # crossing (a later member may find fresh waiters); the metric and
        # phase write are edge-triggered via g.released
        if self._waiting_pods is not None:
            for uid in list(g.waiting):
                wp = self._waiting_pods.get(uid)
                if wp is not None:
                    wp.allow(PLUGIN_NAME)
        if g.released:
            return
        g.released = True
        if g.first_wait_ts is not None:
            m.gang_wait_duration.observe(
                max(self._clock() - g.first_wait_ts, 0.0))
            g.first_wait_ts = None
        m.gang_scheduling_attempts.inc(("scheduled",))
        self._set_phase(g, v1.POD_GROUP_SCHEDULING)

    # --- Unreserve: group failure ---------------------------------------------

    def on_unreserve(self, pod: v1.Pod) -> None:
        """A member's binding cycle rolled back.  If it was holding the
        Permit wait, the gang cannot complete this round: reject every
        still-waiting sibling (their flush rollback requeues them) and
        mark the group — the coscheduling Unreserve contract."""
        key = self.group_key_of(pod)
        if key is None:
            return
        g = self._state(key)
        was_waiting = pod.uid in g.waiting
        g.waiting.pop(pod.uid, None)
        if pod.uid not in g.bound:
            g.pending.add(pod.uid)
        if was_waiting and not g.failing:
            g.failing = True
            try:
                self._fail_group(key, g, g.last_reject_reason or "rejected")
            finally:
                g.failing = False
                g.last_reject_reason = ""

    def _fail_group(self, key: str, g: _GroupState, reason: str) -> None:
        if self._waiting_pods is not None:
            for uid in list(g.waiting):
                wp = self._waiting_pods.get(uid)
                if wp is not None:
                    wp.reject(PLUGIN_NAME, f"gang {key} {reason}")
        g.pending.update(g.waiting)
        g.waiting.clear()
        g.released = False  # the next full round releases (and counts) anew
        if g.first_wait_ts is not None:
            m.gang_wait_duration.observe(
                max(self._clock() - g.first_wait_ts, 0.0))
            g.first_wait_ts = None
        if "timed out" in reason:
            m.gang_timeouts.inc()
            m.gang_scheduling_attempts.inc(("timeout",))
        else:
            m.gang_scheduling_attempts.inc(("rejected",))
        klog.V(2).info_s("Gang failed; members requeue together",
                         group=key, reason=reason)
        self._set_phase(g, v1.POD_GROUP_UNSCHEDULABLE)

    # --- node-lifecycle gang repair -------------------------------------------

    def repair(self, key: str, reason: str) -> None:
        """Lifecycle-controller hook (controllers/nodelifecycle.py): every
        bound member of ``key`` was just evicted atomically because a host
        died.  Reject still-waiting members NOW — their flush rollback
        requeues them alongside the deleted members' replacements — instead
        of waiting for the watch stream to deliver the deletes, and re-arm
        the release edge-trigger so the re-formed gang counts one fresh
        release.  Membership itself is corrected by the DELETED watch
        events (the store is the source of truth, exactly once)."""
        g = self._groups.get(key)
        if g is None:
            return
        g.released = False
        if g.waiting and not g.failing:
            g.failing = True
            try:
                self._fail_group(key, g, reason or "rejected (gang repair)")
            finally:
                g.failing = False

    # --- PostBind -------------------------------------------------------------

    def on_bound(self, pod: v1.Pod, node_name: str) -> None:
        key = self.group_key_of(pod)
        if key is None:
            return
        g = self._state(key)
        g.pending.discard(pod.uid)
        g.waiting.pop(pod.uid, None)
        g.bound[pod.uid] = node_name
        if g.pg is not None and len(g.bound) >= g.pg.min_member:
            self._set_phase(g, v1.POD_GROUP_SCHEDULED)

    def _set_phase(self, g: _GroupState, phase: str) -> None:
        pg = g.pg
        if pg is None or pg.phase == phase:
            return
        pg.phase = phase
        try:
            self._store.update("PodGroup", pg)
        except Exception as e:
            # best-effort status write: a store fault must never take the
            # binding cycle down with it — the phase repairs on the next
            # transition (the reference patches PodGroup status the same
            # lossy way)
            klog.V(1).info_s("PodGroup phase update failed",
                             group=pg.key(), phase=phase,
                             error=f"{type(e).__name__}: {e}")

    # --- preemption guard -----------------------------------------------------

    def allows_preemption(self, pod: v1.Pod) -> bool:
        """Never evict victims for a gang that cannot fully place: only
        the LAST missing member (everyone else bound or holding Permit)
        may run the preemption dry-run — an earlier member's evictions
        would free capacity for a gang that may still time out."""
        key = self.group_key_of(pod)
        if key is None:
            return True
        g = self._state(key)
        if g.pg is None:
            return False
        return len(g.bound) + len(g.waiting) >= g.pg.min_member - 1

    # --- solver integration ---------------------------------------------------

    def gang_segments(self, pods: List[v1.Pod], size: int) -> np.ndarray:
        """i32[size] per-pod gang segment id (-1 solo/padding) for the
        device all-or-nothing mask; gang-free batches reuse a cached
        all(-1) array so steady suites allocate nothing per cycle."""
        seg = None
        ids: Dict[str, int] = {}
        for i, pod in enumerate(pods):
            key = self.group_key_of(pod)
            if key is None:
                continue
            if seg is None:
                seg = np.full(size, -1, dtype=np.int32)
            seg[i] = ids.setdefault(key, len(ids))
        if seg is not None:
            return seg
        cached = self._noop_seg_cache.get(size)
        if cached is None:
            cached = np.full(size, -1, dtype=np.int32)
            self._noop_seg_cache[size] = cached
        return cached

    def stage_batch(self, pods: List[v1.Pod]) -> None:
        """Pods of the batch about to dispatch — host_aux reads them (the
        compiled PodBatch carries no pod objects)."""
        self._staged = list(pods)

    def host_aux(self, batch_size: int, encoder) -> Tuple[np.ndarray, np.ndarray]:
        """(slice_dom i32[N], anchor i32[B]) for the Coscheduling score
        plane: anchor[b] is the slice-domain id pod b's gang prefers —
        the slice already hosting bound/waiting members, else the slice
        with the most free CPU (pack a fresh gang into ONE slice) — and
        -2 for non-members (zero plane, shared compiled program)."""
        slice_dom = self._slice_dom(encoder)
        anchor = np.full(batch_size, -2, dtype=np.int32)
        # per-gang pending chip demand over this batch's staged members —
        # the slice the gang anchors to must have room for ALL of them
        demands: Dict[str, int] = {}
        if self._claim_demand is not None:
            for pod in self._staged[:batch_size]:
                key = self.group_key_of(pod)
                if key is not None:
                    demands[key] = demands.get(key, 0) + int(
                        self._claim_demand(pod))
        memo: Dict[str, int] = {}
        best = None  # lazily computed once per call (claim-free gangs)
        for i, pod in enumerate(self._staged[:batch_size]):
            key = self.group_key_of(pod)
            if key is None:
                continue
            a = memo.get(key)
            if a is None:
                g = self._groups.get(key)
                a = -2
                if g is not None:
                    for node in list(g.bound.values()) + list(g.waiting.values()):
                        row = encoder.node_rows.get(node)
                        if row is not None and 0 <= row < slice_dom.shape[0] \
                                and slice_dom[row] >= 0:
                            a = int(slice_dom[row])
                            break
                if a == -2:
                    need = demands.get(key, 0)
                    if need > 0:
                        a = self._best_free_slice(slice_dom, encoder, need)
                    else:
                        if best is None:
                            best = self._best_free_slice(slice_dom, encoder)
                        a = best
                memo[key] = a
            anchor[i] = a
        return slice_dom, anchor

    def _slice_dom(self, encoder) -> np.ndarray:
        n = int(np.shape(encoder.node_valid)[0])
        if (self._slice_cache is not None
                and self._slice_cache_gen == self._node_gen
                and self._slice_cache.shape[0] == n):
            return self._slice_cache
        dom = np.full(n, -1, dtype=np.int32)
        nodes, _ = self._store.list("Node")
        for node in nodes:
            val = node.metadata.labels.get(self._slice_label)
            if val is None:
                continue
            row = encoder.node_rows.get(node.metadata.name)
            if row is None or row >= n:
                continue
            dom[row] = self._slice_ids.setdefault(val, len(self._slice_ids))
        self._slice_cache, self._slice_cache_gen = dom, self._node_gen
        return dom

    def _best_free_slice(self, slice_dom: np.ndarray, encoder,
                         claim_demand: int = 0) -> int:
        valid = np.asarray(encoder.node_valid)
        member = (slice_dom >= 0) & valid
        if not member.any():
            return -2
        free = (np.asarray(encoder.allocatable)[:, 0].astype(np.int64)
                - np.asarray(encoder.requested)[:, 0])
        totals = np.zeros(int(slice_dom.max()) + 1, dtype=np.int64)
        np.add.at(totals, slice_dom[member], free[member])
        if claim_demand > 0:
            # claim-aware pick: among slices whose free CHIPS (the encoder
            # claim planes the DraIndex projects) cover the gang's pending
            # demand, take the most free CPU; if none can, take the most
            # free chips — members still filter per-node, and the Permit
            # timeout fails a truly starved gang atomically
            chips = (np.asarray(encoder.claim_capacity).astype(np.int64)
                     - np.asarray(encoder.claim_allocated))
            chip_tot = np.zeros_like(totals)
            np.add.at(chip_tot, slice_dom[member], chips[member])
            fits = chip_tot >= claim_demand
            if fits.any():
                return int(np.argmax(np.where(fits, totals, -1)))
            return int(np.argmax(chip_tot))
        return int(np.argmax(totals))
