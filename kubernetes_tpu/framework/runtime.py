"""Batched framework runtime: plugin composition + greedy-scan assignment.

Reference: pkg/scheduler/framework/runtime/framework.go —
  RunFilterPlugins (goroutine fan-out per node, scheduler.go:983-1023) → here ONE
  fused program producing the whole ``bool[B, N]`` mask;
  RunScorePlugins :874-946 (parallel per node, NormalizeScore :907, weight apply
  :925) → stacked score planes + one weighted contraction;
  scheduleOne's sequential assume loop (scheduler.go:496,571) → a ``lax.scan``
  over the pod batch whose carry holds the dynamic cluster arrays, so a whole
  pending batch is scheduled in ONE device program with exact greedy-sequential
  semantics.

select_host parity: the reference reservoir-samples among max-score ties
(scheduler.go:827-848); here ties break by lowest node row (deterministic) or by
a caller-provided PRNG key.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .interface import DynamicState, Plugin, PluginWithWeight
from ..state import units


class AssignResult(NamedTuple):
    node_row: jnp.ndarray  # i32[B] assigned node row, -1 = unschedulable
    feasible_count: jnp.ndarray  # i32[B] number of feasible nodes seen
    dyn: DynamicState  # final dynamic state after all assignments


class BatchedFramework:
    """Drives a fixed plugin list as fused tensor programs.

    The public entry points are pure functions of (batch, snap, dyn, auxes) and
    are safe to wrap in jax.jit (callers own the jit boundary so they can attach
    donate/sharding policies).
    """

    def __init__(self, plugins: Sequence[PluginWithWeight]):
        self.plugins = list(plugins)
        self.filter_plugins = [p for p in self.plugins if hasattr(p.plugin, "filter")]
        self.score_plugins = [p for p in self.plugins if hasattr(p.plugin, "score")]

    # --- host-side precompute (eager, before jit) ----------------------------

    def host_prepare(self, batch, snapshot, encoder, namespace_labels=None) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for pw in self.plugins:
            fn = getattr(pw.plugin, "host_prepare", None)
            if fn is not None:
                out[pw.plugin.name] = fn(
                    batch, snapshot, encoder, namespace_labels=namespace_labels
                )
        return out

    # --- device-side prepare (traceable) -------------------------------------

    def prepare(self, batch, snap, dyn, host_auxes: Optional[Dict[str, Any]] = None):
        host_auxes = host_auxes or {}
        auxes = []
        for pw in self.plugins:
            fn = getattr(pw.plugin, "prepare", None)
            if fn is None:
                auxes.append(None)
            else:
                auxes.append(fn(batch, snap, dyn, host_auxes.get(pw.plugin.name)))
        return tuple(auxes)

    # --- filter + score ------------------------------------------------------

    def run_filters(self, batch, snap, dyn, auxes):
        mask = snap.node_valid[None, :] & batch.valid[:, None]
        for pw, aux in zip(self.plugins, auxes):
            if hasattr(pw.plugin, "filter"):
                mask = mask & pw.plugin.filter(batch, snap, dyn, aux)
        return mask

    def run_scores(self, batch, snap, dyn, auxes, mask):
        """Weighted sum of normalized per-plugin planes
        (runtime/framework.go:874-946 as one contraction)."""
        total = jnp.zeros(mask.shape, jnp.float32)
        for pw, aux in zip(self.plugins, auxes):
            if not hasattr(pw.plugin, "score"):
                continue
            raw = pw.plugin.score(batch, snap, dyn, aux, mask=mask)
            norm = pw.plugin.normalize(raw, mask)
            # reference converts each plugin score to int64 (truncation) before
            # applying the weight — floor keeps integer parity for ≥0 scores
            total = total + pw.weight * jnp.floor(norm)
        return jnp.where(mask, total, -jnp.inf)

    def compute(self, batch, snap, dyn, auxes):
        mask = self.run_filters(batch, snap, dyn, auxes)
        scores = self.run_scores(batch, snap, dyn, auxes, mask)
        return mask, scores

    # --- host selection (parity with scheduler.go:827-848) -------------------

    @staticmethod
    def select_host(row_scores, row_mask, key=None):
        """Argmax with tie handling: deterministic lowest-row, or uniform among
        ties when a PRNG key is given (reservoir-sampling parity)."""
        masked = jnp.where(row_mask, row_scores, -jnp.inf)
        best = jnp.max(masked)
        ties = masked == best
        if key is None:
            return jnp.argmax(masked)
        noise = jax.random.uniform(key, masked.shape)
        return jnp.argmax(jnp.where(ties, noise, -1.0))

    # --- greedy batch assignment (lax.scan) ----------------------------------

    def apply_assignment(self, dyn: DynamicState, auxes, i, node_row, batch, snap):
        """assume: consume resources + run plugin in-scan updates."""
        req = batch.request[i]
        requested = dyn.requested.at[node_row].add(req)
        nz = dyn.non_zero.at[node_row].add(batch.non_zero[i])
        new_dyn = DynamicState(requested=requested, non_zero=nz)
        new_auxes = []
        for pw, aux in zip(self.plugins, auxes):
            fn = getattr(pw.plugin, "update", None)
            if fn is None or aux is None:
                new_auxes.append(aux)
            else:
                new_auxes.append(fn(aux, i, node_row, batch, snap))
        return new_dyn, tuple(new_auxes)

    def greedy_assign(self, batch, snap, dyn, auxes, order, key=None) -> AssignResult:
        """Schedule the batch pod-by-pod in ``order`` inside one lax.scan.

        Exact greedy-sequential semantics with a ROW-SLICED fast path: the
        static plugin planes (selector matches, taints, image locality, volume
        masks, …) are computed ONCE for the whole ``[B, N]`` batch before the
        scan; each scan step computes only pod i's ``[N]`` row for the four
        dynamic plugins (Fit, BalancedAllocation, PodTopologySpread,
        InterPodAffinity) against the carried state — O(N) per step instead of
        O(B·N).  Normalization is row-local in the reference too, so results
        are bit-identical to the dense recompute (test_fast_scan_parity).
        """
        b = batch.valid.shape[0]
        # device-ify all leaves so traced indexing works in eager calls too
        batch, auxes, dyn = jax.tree_util.tree_map(jnp.asarray, (batch, auxes, dyn))

        # --- static precompute (outside the scan) ----------------------------
        static_mask = snap.node_valid[None, :] & batch.valid[:, None]
        static_raw: List = []  # (pw, raw_plane or None)
        for pw, aux in zip(self.plugins, auxes):
            p = pw.plugin
            if not p.dynamic and hasattr(p, "filter"):
                static_mask = static_mask & p.filter(batch, snap, dyn, aux)
            if hasattr(p, "score") and not p.dynamic:
                static_raw.append((pw, p.score(batch, snap, dyn, aux)))

        dyn_plugins = [
            (pw, idx) for idx, pw in enumerate(self.plugins) if pw.plugin.dynamic
        ]
        dyn_auxes = tuple(auxes[idx] for _, idx in dyn_plugins)

        def step(carry, inp):
            dyn, dauxes = carry
            i = inp["i"]
            row_mask = static_mask[i]
            for (pw, _), aux in zip(dyn_plugins, dauxes):
                if hasattr(pw.plugin, "filter_row"):
                    row_mask = row_mask & pw.plugin.filter_row(batch, snap, dyn, aux, i)
            total = jnp.zeros(row_mask.shape, jnp.float32)
            for pw, plane in static_raw:
                norm = pw.plugin.normalize(plane[i][None, :], row_mask[None, :])[0]
                total = total + pw.weight * jnp.floor(norm)
            for (pw, _), aux in zip(dyn_plugins, dauxes):
                if not hasattr(pw.plugin, "score_row"):
                    continue
                raw = pw.plugin.score_row(batch, snap, dyn, aux, i, mask_row=row_mask)
                norm = pw.plugin.normalize(raw[None, :], row_mask[None, :])[0]
                total = total + pw.weight * jnp.floor(norm)
            row_scores = jnp.where(row_mask, total, -jnp.inf)

            feasible_n = jnp.sum(row_mask)
            feasible = feasible_n > 0
            node = self.select_host(row_scores, row_mask, inp.get("key"))
            # nominated-node fast path (scheduler.go:926-935)
            nom = batch.nominated_row[i]
            nom_ok = (nom >= 0) & row_mask[jnp.clip(nom, 0, row_mask.shape[0] - 1)]
            node = jnp.where(nom_ok, jnp.clip(nom, 0, row_mask.shape[0] - 1), node)
            node = jnp.where(feasible, node, 0)

            def do_assign(args):
                dyn, dauxes = args
                return self._apply_dynamic(dyn, dauxes, dyn_plugins, i, node, batch, snap)

            dyn, dauxes = jax.lax.cond(
                feasible & batch.valid[i], do_assign, lambda a: a, (dyn, dauxes)
            )
            out_node = jnp.where(feasible & batch.valid[i], node, -1)
            return (dyn, dauxes), {"i": i, "node": out_node, "feasible_n": feasible_n}

        inputs = {"i": order.astype(jnp.int32)}
        if key is not None:
            inputs["key"] = jax.random.split(key, b)
        (dyn, _), outs = jax.lax.scan(step, (dyn, dyn_auxes), inputs)
        node_row = jnp.full((b,), -1, jnp.int32).at[outs["i"]].set(outs["node"])
        feasible_count = jnp.zeros((b,), jnp.int32).at[outs["i"]].set(outs["feasible_n"])
        return AssignResult(node_row=node_row, feasible_count=feasible_count, dyn=dyn)

    def _apply_dynamic(self, dyn, dauxes, dyn_plugins, i, node_row, batch, snap):
        req = batch.request[i]
        requested = dyn.requested.at[node_row].add(req)
        nz = dyn.non_zero.at[node_row].add(batch.non_zero[i])
        new_dyn = DynamicState(requested=requested, non_zero=nz)
        new_auxes = []
        for (pw, _), aux in zip(dyn_plugins, dauxes):
            fn = getattr(pw.plugin, "update", None)
            if fn is None or aux is None:
                new_auxes.append(aux)
            else:
                new_auxes.append(fn(aux, i, node_row, batch, snap))
        return new_dyn, tuple(new_auxes)

    def greedy_assign_dense(self, batch, snap, dyn, auxes, order, key=None) -> AssignResult:
        """Reference implementation: full [B, N] recompute per step (used by the
        fast-path parity test)."""
        b = batch.valid.shape[0]
        batch, auxes, dyn = jax.tree_util.tree_map(jnp.asarray, (batch, auxes, dyn))

        def step(carry, inp):
            dyn, auxes = carry
            i = inp["i"]
            mask, scores = self.compute(batch, snap, dyn, auxes)
            row_mask = mask[i]
            row_scores = scores[i]
            feasible_n = jnp.sum(row_mask)
            feasible = feasible_n > 0
            node = self.select_host(row_scores, row_mask, inp.get("key"))
            nom = batch.nominated_row[i]
            nom_ok = (nom >= 0) & row_mask[jnp.clip(nom, 0, row_mask.shape[0] - 1)]
            node = jnp.where(nom_ok, jnp.clip(nom, 0, row_mask.shape[0] - 1), node)
            node = jnp.where(feasible, node, 0)

            def do_assign(args):
                dyn, auxes = args
                return self.apply_assignment(dyn, auxes, i, node, batch, snap)

            dyn, auxes = jax.lax.cond(
                feasible & batch.valid[i], do_assign, lambda a: a, (dyn, auxes)
            )
            out_node = jnp.where(feasible & batch.valid[i], node, -1)
            return (dyn, auxes), {"i": i, "node": out_node, "feasible_n": feasible_n}

        inputs = {"i": order.astype(jnp.int32)}
        if key is not None:
            inputs["key"] = jax.random.split(key, b)
        (dyn, auxes), outs = jax.lax.scan(step, (dyn, auxes), inputs)
        node_row = jnp.full((b,), -1, jnp.int32).at[outs["i"]].set(outs["node"])
        feasible_count = jnp.zeros((b,), jnp.int32).at[outs["i"]].set(outs["feasible_n"])
        return AssignResult(node_row=node_row, feasible_count=feasible_count, dyn=dyn)


def initial_dynamic_state(snap) -> DynamicState:
    return DynamicState(requested=snap.requested, non_zero=snap.non_zero_requested)
