"""Batched framework runtime: plugin composition + greedy-scan assignment.

Reference: pkg/scheduler/framework/runtime/framework.go —
  RunFilterPlugins (goroutine fan-out per node, scheduler.go:983-1023) → here ONE
  fused program producing the whole ``bool[B, N]`` mask;
  RunScorePlugins :874-946 (parallel per node, NormalizeScore :907, weight apply
  :925) → stacked score planes + one weighted contraction;
  scheduleOne's sequential assume loop (scheduler.go:496,571) → a ``lax.scan``
  over the pod batch whose carry holds the dynamic cluster arrays, so a whole
  pending batch is scheduled in ONE device program with exact greedy-sequential
  semantics.

select_host parity: the reference reservoir-samples among max-score ties
(scheduler.go:827-848); here ties break by lowest node row (deterministic) or by
a caller-provided PRNG key.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .interface import DynamicState, Plugin, PluginWithWeight
from ..state import units


class AssignResult(NamedTuple):
    node_row: jnp.ndarray  # i32[B] assigned node row, -1 = unschedulable
    feasible_count: jnp.ndarray  # i32[B] number of feasible nodes seen
    dyn: DynamicState  # final dynamic state after all assignments
    # engine rounds executed (scan steps for greedy_assign, auction rounds
    # for batch_assign) — feeds scheduler_assignment_rounds_total.  Plain-int
    # default (NOT a module-level device array: a concrete jax.Array captured
    # as a jit closure constant poisons host syncs — see plugins BIG note)
    rounds: object = 0


class PrevBatch(NamedTuple):
    """Deep-pipeline carry: the still-in-flight previous batch's identity +
    device-resident decisions, consumed by the next batch's fused program
    (apply_prev_delta for resources, plugin chain_prev hooks for tables).

    The four (anti)affinity term groups are carried ONLY when the
    dispatching batch itself has affinity content (so plain workloads never
    trace the affinity chain work; the pytree structure — groups present vs
    None — selects the compiled variant).  They let InterPodAffinity chain
    the prev batch's OWN terms (symmetric block/score effects) in addition
    to the label-side matches the arrays above already enable."""

    rows: jnp.ndarray  # i32[B0] node row per prev pod (-1 = none; device)
    req: jnp.ndarray  # i32[B0, R]
    nz: jnp.ndarray  # i32[B0, 2]
    valid: jnp.ndarray  # bool[B0]
    label_keys: jnp.ndarray  # i32[B0, PL]
    label_vals: jnp.ndarray  # i32[B0, PL]
    ns: jnp.ndarray  # i32[B0]
    req_affinity: object = None  # AffinityTermGroup | None (all four together)
    req_anti_affinity: object = None
    pref_affinity: object = None
    pref_anti_affinity: object = None


class CouplingFlags(NamedTuple):
    """Host-computed batch coupling for the parallel assignment engine.

    reads[b] — pod b's filter/score planes read cross-pod tables that other
        batch commits write (own topology-spread constraints or pod
        (anti)affinity terms): such a pod may only commit when it is its
        COMPONENT's first active pod, so it always sees exact greedy state
        relative to its component.
    solo[b]  — pod b has REQUIRED anti-affinity terms; its commit writes the
        existing-anti-affinity block plane its component-mates' filters read
        (interpodaffinity/filtering.go:44-55), so its commit closes its
        component for the rest of the round.
    comp[b]  — interaction-component id (framework/conflict.py): pods in
        different components provably never read each other's table writes,
        so they commit in the same parallel round.  None → conservative
        single-component fallback inside batch_assign.
    multi[b] — pod shares its component with ≥1 other batch pod.
    """

    reads: jnp.ndarray  # bool[B]
    solo: jnp.ndarray  # bool[B]
    comp: object = None  # i32[B] | None
    multi: object = None  # bool[B] | None


def coupling_flags(batch, namespace_labels=None, info=None) -> CouplingFlags:
    """Derive CouplingFlags from a compiled PodBatch (host-side, numpy),
    including the conflict partition over the batch's real pods.  Callers
    that already ran ``conflict_components`` (the scheduler times it as its
    own phase) pass the result via ``info``."""
    import numpy as np

    from .conflict import conflict_components

    reads = (
        batch.tsc_valid.any(axis=1)
        | batch.req_affinity.valid.any(axis=1)
        | batch.req_anti_affinity.valid.any(axis=1)
        | batch.pref_affinity.valid.any(axis=1)
        | batch.pref_anti_affinity.valid.any(axis=1)
    )
    solo = batch.req_anti_affinity.valid.any(axis=1)
    reads = np.asarray(reads, dtype=bool)
    solo = np.asarray(solo, dtype=bool)
    if info is None:
        pods = getattr(batch, "pods", None) or []
        if not pods and bool(reads.any() or solo.any()):
            # a coupled batch whose pod objects are unavailable (e.g. a
            # pytree round-trip dropped the skip=("pods",) aux) cannot be
            # partitioned — return the CONSERVATIVE comp=None form, which
            # batch_assign treats as one all-multi component, never the
            # unsound all-singleton no-coupling partition
            return CouplingFlags(reads=reads, solo=solo)
        info = conflict_components(
            pods, batch.size, namespace_labels=namespace_labels,
        )
    return CouplingFlags(reads=reads, solo=solo, comp=info.comp,
                         multi=info.multi)


def live_nodes(snap):
    """bool[N] schedulable universe: encoded (node_valid) AND Ready
    (node_ready — the node-lifecycle condition mask).  Every feasibility
    composition starts from this, so an in-flight cycle dispatched after
    the lifecycle controller marked a host NotReady can't bind onto it —
    the taint plane catches tolerating pods, this catches everything.
    ``getattr`` fallback keeps hand-built snapshot stand-ins (tests,
    stacked whatif forks) working without the plane."""
    ready = getattr(snap, "node_ready", None)
    return snap.node_valid if ready is None else snap.node_valid & ready


class BatchedFramework:
    """Drives a fixed plugin list as fused tensor programs.

    The public entry points are pure functions of (batch, snap, dyn, auxes) and
    are safe to wrap in jax.jit (callers own the jit boundary so they can attach
    donate/sharding policies).
    """

    def __init__(self, plugins: Sequence[PluginWithWeight]):
        self.plugins = list(plugins)
        self.filter_plugins = [p for p in self.plugins if hasattr(p.plugin, "filter")]
        self.score_plugins = [p for p in self.plugins if hasattr(p.plugin, "score")]
        # Host binding-cycle hook lists, precomputed once: the per-pod bind
        # segment must not walk 14 plugins × 4 hooks via getattr per pod
        # (RunReservePluginsReserve etc. iterate registered-extension-point
        # lists in the reference too, runtime/framework.go)
        self.reserve_plugins = [p for p in self.plugins if hasattr(p.plugin, "reserve")]
        self.permit_plugins = [p for p in self.plugins if hasattr(p.plugin, "permit")]
        self.pre_bind_plugins = [p for p in self.plugins if hasattr(p.plugin, "pre_bind")]
        self.post_bind_plugins = [p for p in self.plugins if hasattr(p.plugin, "post_bind")]

    # --- host-side precompute (eager, before jit) ----------------------------

    def host_prepare(self, batch, snapshot, encoder, namespace_labels=None) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for pw in self.plugins:
            fn = getattr(pw.plugin, "host_prepare", None)
            if fn is not None:
                out[pw.plugin.name] = fn(
                    batch, snapshot, encoder, namespace_labels=namespace_labels
                )
        return out

    # --- device-side prepare (traceable) -------------------------------------

    def prepare(self, batch, snap, dyn, host_auxes: Optional[Dict[str, Any]] = None):
        host_auxes = host_auxes or {}
        auxes = []
        for pw in self.plugins:
            fn = getattr(pw.plugin, "prepare", None)
            if fn is None:
                auxes.append(None)
            else:
                auxes.append(fn(batch, snap, dyn, host_auxes.get(pw.plugin.name)))
        return tuple(auxes)

    def chain_prev(self, batch, snap, auxes, prev: "PrevBatch"):
        """Fold a still-in-flight previous batch's placements into this
        batch's plugin aux tables (deep pipeline): dispatch to each plugin's
        ``chain_prev`` hook.  A no-op bundle (all rows -1) leaves every table
        unchanged, so shallow and deep cycles share one compiled program."""
        out = []
        for pw, aux in zip(self.plugins, auxes):
            fn = getattr(pw.plugin, "chain_prev", None)
            if fn is None or aux is None:
                out.append(aux)
            else:
                out.append(fn(aux, batch, snap, prev))
        return tuple(out)

    # --- filter + score ------------------------------------------------------

    def run_filters(self, batch, snap, dyn, auxes):
        mask = live_nodes(snap)[None, :] & batch.valid[:, None]
        for pw, aux in zip(self.plugins, auxes):
            if hasattr(pw.plugin, "filter"):
                mask = mask & pw.plugin.filter(batch, snap, dyn, aux)
        return mask

    def run_scores(self, batch, snap, dyn, auxes, mask):
        """Weighted sum of normalized per-plugin planes
        (runtime/framework.go:874-946 as one contraction)."""
        total = jnp.zeros(mask.shape, jnp.float32)
        for pw, aux in zip(self.plugins, auxes):
            if not hasattr(pw.plugin, "score"):
                continue
            raw = pw.plugin.score(batch, snap, dyn, aux, mask=mask)
            norm = pw.plugin.normalize(raw, mask)
            # reference converts each plugin score to int64 (truncation) before
            # applying the weight — floor keeps integer parity for ≥0 scores
            total = total + pw.weight * jnp.floor(norm)
        return jnp.where(mask, total, -jnp.inf)

    def compute(self, batch, snap, dyn, auxes):
        mask = self.run_filters(batch, snap, dyn, auxes)
        scores = self.run_scores(batch, snap, dyn, auxes, mask)
        return mask, scores

    def compute_packed(self, batch, snap, dyn, auxes):
        """compute() as ONE f32[B, N]: -inf marks infeasible nodes.  A single
        fetched array costs one device→host tunnel round; (mask, scores)
        separately cost two (the extender round path's per-round fetch)."""
        mask, scores = self.compute(batch, snap, dyn, auxes)
        return jnp.where(mask, scores.astype(jnp.float32), -jnp.inf)

    @property
    def filter_names(self):
        """Names of plugins with a Filter, in plugin order (Diagnosis keys)."""
        return [pw.plugin.name for pw in self.plugins if hasattr(pw.plugin, "filter")]

    def diagnose_bits(self, batch, snap, dyn, auxes):
        """bool[B, K]: does filter plugin k leave pod b ANY feasible node.

        Computed inside the fused cycle program (XLA CSEs the filter planes
        with the assignment engine's own), so diagnosing a failed batch costs
        zero extra device round-trips — the eager per-plugin fallback paid a
        ~100ms pacing round per plugin per batch (FitError.Diagnosis analog).
        """
        b = batch.valid.shape[0]
        bits = []
        for pw, aux in zip(self.plugins, auxes):
            if hasattr(pw.plugin, "filter"):
                mask = pw.plugin.filter(batch, snap, dyn, aux)
                # plugins may return a broadcastable [1, N] plane
                full = mask & live_nodes(snap)[None, :] & batch.valid[:, None]
                bits.append(jnp.any(full, axis=1))
        if not bits:
            return jnp.ones((b, 0), bool)
        return jnp.stack(bits, axis=1)

    # --- row-sliced compute (the extender path's per-pod unit) ---------------

    def compute_static(self, batch, snap, dyn, auxes):
        """Static (dyn-independent) feasibility mask and raw score planes,
        computed ONCE per batch: the extender path then evaluates each pod as
        an O(N) row (compute_row) instead of recomputing the full [B, N]
        planes per pod — O(B·N) total where it was O(B²·N)."""
        static_mask = live_nodes(snap)[None, :] & batch.valid[:, None]
        static_raw = []
        for pw, aux in zip(self.plugins, auxes):
            p = pw.plugin
            if not p.dynamic and hasattr(p, "filter"):
                static_mask = static_mask & p.filter(batch, snap, dyn, aux)
            if hasattr(p, "score") and not p.dynamic:
                static_raw.append(p.score(batch, snap, dyn, aux))
        return static_mask, tuple(static_raw)

    def compute_row(self, batch, snap, dyn, auxes, static_mask, static_raw, i):
        """Pod i's feasibility row and weighted total scores [N] against the
        current dynamic state (same math as greedy_assign's scan step)."""
        row_mask = static_mask[i]
        for pw, aux in zip(self.plugins, auxes):
            if pw.plugin.dynamic and hasattr(pw.plugin, "filter_row"):
                row_mask = row_mask & pw.plugin.filter_row(batch, snap, dyn, aux, i)
        total = jnp.zeros(row_mask.shape, jnp.float32)
        k = 0
        for pw, aux in zip(self.plugins, auxes):
            p = pw.plugin
            if hasattr(p, "score") and not p.dynamic:
                plane = static_raw[k]
                k += 1
                norm = p.normalize(plane[i][None, :], row_mask[None, :])[0]
                total = total + pw.weight * jnp.floor(norm)
            elif p.dynamic and hasattr(p, "score_row"):
                raw = p.score_row(batch, snap, dyn, aux, i, mask_row=row_mask)
                norm = p.normalize(raw[None, :], row_mask[None, :])[0]
                total = total + pw.weight * jnp.floor(norm)
        return row_mask, jnp.where(row_mask, total, -jnp.inf)

    # --- host selection (parity with scheduler.go:827-848) -------------------

    @staticmethod
    def select_host(row_scores, row_mask, key=None):
        """Argmax with tie handling: deterministic lowest-row, or uniform among
        ties when a PRNG key is given (reservoir-sampling parity)."""
        masked = jnp.where(row_mask, row_scores, -jnp.inf)
        best = jnp.max(masked)
        ties = masked == best
        if key is None:
            return jnp.argmax(masked)
        noise = jax.random.uniform(key, masked.shape)
        return jnp.argmax(jnp.where(ties, noise, -1.0))

    # --- greedy batch assignment (lax.scan) ----------------------------------

    def apply_assignment(self, dyn: DynamicState, auxes, i, node_row, batch, snap):
        """assume: consume resources + run plugin in-scan updates."""
        req = batch.request[i]
        requested = dyn.requested.at[node_row].add(req)
        nz = dyn.non_zero.at[node_row].add(batch.non_zero[i])
        new_dyn = DynamicState(requested=requested, non_zero=nz)
        new_auxes = []
        for pw, aux in zip(self.plugins, auxes):
            fn = getattr(pw.plugin, "update", None)
            if fn is None or aux is None:
                new_auxes.append(aux)
            else:
                new_auxes.append(fn(aux, i, node_row, batch, snap))
        return new_dyn, tuple(new_auxes)

    def greedy_assign(self, batch, snap, dyn, auxes, order, key=None) -> AssignResult:
        """Schedule the batch pod-by-pod in ``order`` inside one lax.scan.

        Exact greedy-sequential semantics with a ROW-SLICED fast path: the
        static plugin planes (selector matches, taints, image locality, volume
        masks, …) are computed ONCE for the whole ``[B, N]`` batch before the
        scan; each scan step computes only pod i's ``[N]`` row for the four
        dynamic plugins (Fit, BalancedAllocation, PodTopologySpread,
        InterPodAffinity) against the carried state — O(N) per step instead of
        O(B·N).  Normalization is row-local in the reference too, so results
        are bit-identical to the dense recompute (test_fast_scan_parity).
        """
        b = batch.valid.shape[0]
        # device-ify all leaves so traced indexing works in eager calls too
        batch, auxes, dyn = jax.tree_util.tree_map(jnp.asarray, (batch, auxes, dyn))

        # --- static precompute (outside the scan) ----------------------------
        static_mask = live_nodes(snap)[None, :] & batch.valid[:, None]
        static_raw: List = []  # (pw, raw_plane or None)
        for pw, aux in zip(self.plugins, auxes):
            p = pw.plugin
            if not p.dynamic and hasattr(p, "filter"):
                static_mask = static_mask & p.filter(batch, snap, dyn, aux)
            if hasattr(p, "score") and not p.dynamic:
                static_raw.append((pw, p.score(batch, snap, dyn, aux)))

        dyn_plugins = [
            (pw, idx) for idx, pw in enumerate(self.plugins) if pw.plugin.dynamic
        ]
        dyn_auxes = tuple(auxes[idx] for _, idx in dyn_plugins)

        def step(carry, inp):
            dyn, dauxes = carry
            i = inp["i"]
            row_mask = static_mask[i]
            for (pw, _), aux in zip(dyn_plugins, dauxes):
                if hasattr(pw.plugin, "filter_row"):
                    row_mask = row_mask & pw.plugin.filter_row(batch, snap, dyn, aux, i)
            total = jnp.zeros(row_mask.shape, jnp.float32)
            for pw, plane in static_raw:
                norm = pw.plugin.normalize(plane[i][None, :], row_mask[None, :])[0]
                total = total + pw.weight * jnp.floor(norm)
            for (pw, _), aux in zip(dyn_plugins, dauxes):
                if not hasattr(pw.plugin, "score_row"):
                    continue
                raw = pw.plugin.score_row(batch, snap, dyn, aux, i, mask_row=row_mask)
                norm = pw.plugin.normalize(raw[None, :], row_mask[None, :])[0]
                total = total + pw.weight * jnp.floor(norm)
            row_scores = jnp.where(row_mask, total, -jnp.inf)

            feasible_n = jnp.sum(row_mask)
            feasible = feasible_n > 0
            node = self.select_host(row_scores, row_mask, inp.get("key"))
            # nominated-node fast path (scheduler.go:926-935)
            nom = batch.nominated_row[i]
            nom_ok = (nom >= 0) & row_mask[jnp.clip(nom, 0, row_mask.shape[0] - 1)]
            node = jnp.where(nom_ok, jnp.clip(nom, 0, row_mask.shape[0] - 1), node)
            node = jnp.where(feasible, node, 0)

            def do_assign(args):
                dyn, dauxes = args
                return self._apply_dynamic(dyn, dauxes, dyn_plugins, i, node, batch, snap)

            dyn, dauxes = jax.lax.cond(
                feasible & batch.valid[i], do_assign, lambda a: a, (dyn, dauxes)
            )
            out_node = jnp.where(feasible & batch.valid[i], node, -1)
            return (dyn, dauxes), {"i": i, "node": out_node, "feasible_n": feasible_n}

        order_arr = order.astype(jnp.int32)
        keys = jax.random.split(key, b) if key is not None else None
        # while_loop with a DYNAMIC trip count instead of lax.scan over all b
        # padded positions — a 10-pod backoff-retry batch runs 10 steps, not
        # 128.  Padding pods were no-ops in the scan (valid gating) so results
        # are identical.  The bound is the last ORDER position naming a valid
        # pod (robust to any caller-supplied permutation, not just the
        # end-padded identity order pop_batch produces).
        n_valid = jnp.max(
            jnp.where(
                batch.valid[order_arr],
                jnp.arange(b, dtype=jnp.int32) + 1,
                0,
            )
        )
        node_row0 = jnp.full((b,), -1, jnp.int32)
        feasible0 = jnp.zeros((b,), jnp.int32)

        def cond(state):
            k, *_ = state
            return k < n_valid

        def body(state):
            k, dyn, dauxes, node_row, feasible_count = state
            inp = {"i": order_arr[k]}
            if keys is not None:
                inp["key"] = keys[k]
            (dyn, dauxes), out = step((dyn, dauxes), inp)
            node_row = node_row.at[out["i"]].set(out["node"])
            feasible_count = feasible_count.at[out["i"]].set(out["feasible_n"])
            return (k + 1, dyn, dauxes, node_row, feasible_count)

        k_final, dyn, _, node_row, feasible_count = jax.lax.while_loop(
            cond, body, (jnp.int32(0), dyn, dyn_auxes, node_row0, feasible0)
        )
        return AssignResult(node_row=node_row, feasible_count=feasible_count,
                            dyn=dyn, rounds=k_final)

    def _apply_dynamic(self, dyn, dauxes, dyn_plugins, i, node_row, batch, snap):
        req = batch.request[i]
        requested = dyn.requested.at[node_row].add(req)
        nz = dyn.non_zero.at[node_row].add(batch.non_zero[i])
        new_dyn = DynamicState(requested=requested, non_zero=nz)
        new_auxes = []
        for (pw, _), aux in zip(dyn_plugins, dauxes):
            fn = getattr(pw.plugin, "update", None)
            if fn is None or aux is None:
                new_auxes.append(aux)
            else:
                new_auxes.append(fn(aux, i, node_row, batch, snap))
        return new_dyn, tuple(new_auxes)

    # --- parallel batch assignment (round-based prefix commits) ---------------

    def batch_assign(
        self, batch, snap, dyn, auxes, order, coupling: CouplingFlags, key=None,
        classes=None,
    ) -> AssignResult:
        """Whole-batch parallel assignment replacing the serial scan.

        ``classes`` selects the identity-class DEDUP path (see
        ``_batch_assign_dedup``): ``(class_of i32[B], rep_batch PodBatch[C],
        rep_auxes)`` — the dense planes compute once per exact-content pod
        class at ``[C, N]`` instead of ``[B, N]``, bit-for-bit equal to the
        full computation (templated batches collapse to C≈2).  Callers gate
        it to batches with no cross-pod reads and no pod-indexed auxes
        (TPUScheduler's dedup gate).

        The serialized assume loop the reference runs one pod at a time
        (pkg/scheduler/scheduler.go:496,571) becomes rounds of ONE dense
        ``[B, N]`` filter+score program — the MXU-friendly shape — followed by
        a CONFLICT-PARTITIONED auction (components from
        framework/conflict.py via CouplingFlags.comp):

          round: ONE dense program computes every unresolved pod's
          feasibility mask and score plane under the committed state; then
          pods bid for their BEST STILL-UNUSED feasible node:
            (a) at most one pod per node per round — node-local filters
                (Fit, NodePorts, volumes…) checked against the round-start
                state stay valid under the final state; a pod whose feasible
                nodes are all taken skips and re-bids next round;
            (b) a READER (own cross-pod constraints, CouplingFlags.reads) in
                a multi-pod component commits only as its component's FIRST
                ACTIVE pod in order, with its true argmax — every earlier
                component member resolved in a previous round, so its plane
                is exact greedy state relative to its component.  Readers in
                SINGLETON components (nobody in the batch writes their
                tables) bid in parallel like plain pods — the partitioner's
                win over the old whole-round serialization;
            (c) a required-anti-affinity commit (CouplingFlags.solo) closes
                its COMPONENT for the round (its block-plane write is only
                read by component-mates), not the whole batch.

        Progress: the globally first active pod always commits or resolves
        each round, so at most B rounds run; serialization cost is bounded
        by the largest component, not the batch.

        Parity contract (SURVEY §7.6): on conflict-free batches (pairwise
        distinct argmaxes, no cross-pod reads) the result is identical to
        greedy_assign; a single component spanning the whole batch commits
        one pod per round against fresh dense planes — also identical to the
        scan.  Across components placements remain filter-valid under the
        final committed state, but score-derived choices may diverge from
        the serial order exactly as for plain contended pods — configure
        assign_mode="scan" for exact serial semantics.  Batches dominated by
        ONE giant component should use the scan (the TPUScheduler router
        compares the largest component against its threshold).
        """
        if classes is not None and key is None:
            # key=None only: per-(pod, node) tie noise is pod-distinct by
            # design, which the class-shared planes cannot carry — the
            # scheduler's dedup gate already requires a keyless instance
            return self._batch_assign_dedup(
                batch, snap, dyn, auxes, order, coupling, classes)
        b = batch.valid.shape[0]
        batch, auxes, dyn = jax.tree_util.tree_map(jnp.asarray, (batch, auxes, dyn))
        reads = jnp.asarray(coupling.reads)
        solo = jnp.asarray(coupling.solo)
        if coupling.comp is None:
            # conservative fallback: all pods share one component and count
            # as multi — every reader serializes, solo closes the round for
            # everyone (the pre-partitioner behavior)
            comp = jnp.zeros(b, jnp.int32)
            multi = jnp.ones(b, bool)
        else:
            comp = jnp.asarray(coupling.comp, jnp.int32)
            multi = jnp.asarray(coupling.multi, bool)
        reader = reads & multi
        order = order.astype(jnp.int32)

        # static planes once, as in greedy_assign's fast path
        static_mask = live_nodes(snap)[None, :] & batch.valid[:, None]
        static_raw: List = []
        for pw, aux in zip(self.plugins, auxes):
            p = pw.plugin
            if not p.dynamic and hasattr(p, "filter"):
                static_mask = static_mask & p.filter(batch, snap, dyn, aux)
            if hasattr(p, "score") and not p.dynamic:
                static_raw.append((pw, p.score(batch, snap, dyn, aux)))
        dyn_plugins = [
            (pw, idx) for idx, pw in enumerate(self.plugins) if pw.plugin.dynamic
        ]
        dyn_auxes = tuple(auxes[idx] for _, idx in dyn_plugins)

        # tie-break noise: uniform-among-ties like the reference's reservoir
        # sampling (scheduler.go:827-848).  Plugin totals are integer-valued
        # (each term is weight × floor), so sub-1 noise randomizes ties
        # without reordering distinct scores.  key=None → deterministic
        # first-max, the same rule select_host uses.
        n_nodes_cap = snap.node_valid.shape[0]
        tie_noise = None
        if key is not None:
            tie_noise = jax.random.uniform(key, (b, n_nodes_cap)) * 0.5

        def dense_rows(dyn, dauxes):
            mask = static_mask
            for (pw, _), aux in zip(dyn_plugins, dauxes):
                if hasattr(pw.plugin, "filter"):
                    mask = mask & pw.plugin.filter(batch, snap, dyn, aux)
            total = jnp.zeros(mask.shape, jnp.float32)
            for pw, plane in static_raw:
                total = total + pw.weight * jnp.floor(pw.plugin.normalize(plane, mask))
            for (pw, _), aux in zip(dyn_plugins, dauxes):
                if not hasattr(pw.plugin, "score"):
                    continue
                raw = pw.plugin.score(batch, snap, dyn, aux, mask=mask)
                total = total + pw.weight * jnp.floor(pw.plugin.normalize(raw, mask))
            return mask, jnp.where(mask, total, -jnp.inf)

        n_cap = snap.node_valid.shape[0]

        # pod → its position in `order` (the serial priority)
        pos_of = jnp.zeros(b, jnp.int32).at[order].set(jnp.arange(b, dtype=jnp.int32))

        def auction_commits(active, feasible, mask, scores):
            """Conflict-partitioned propose/resolve auction →
            (commit, choice, unsched).

            In every multi-pod component, the first ACTIVE pod in `order`
            (the component HEAD) is the only reader allowed to commit this
            round — an infeasible reader head resolves unschedulable, and a
            feasible SOLO head closes its component for the round.  Heads
            then bid in the SAME parallel loop as every other eligible pod
            (non-readers, singleton-component constraint carriers): each
            bids for its best still-unused feasible node, contested nodes go
            to the earliest pod in `order`, losers re-bid among unused
            nodes.  A head losing a node to ANOTHER component therefore
            diverts to its next-best unused node within the round — its
            within-component state is still exact (no component-mate
            committed this round); the diversion is the same accepted
            cross-component divergence plain contended pods have."""
            eff = jnp.where(mask, scores, -jnp.inf)
            if tie_noise is not None:
                eff = jnp.where(mask, eff + tie_noise, -jnp.inf)
            nom = jnp.clip(batch.nominated_row, 0, n_cap - 1)
            nom_ok = (batch.nominated_row >= 0) & mask[jnp.arange(b), nom]

            # --- component heads: the only slot a reader may commit in -------
            act_pos = jnp.where(active & multi, pos_of, b)
            # segment-min of active positions per component id (ids ∈ [0, B))
            comp_oh = comp[:, None] == jnp.arange(b)[None, :]  # [B, C]
            minpos_c = jnp.min(
                jnp.where(comp_oh, act_pos[:, None], b), axis=0
            )  # [C]
            is_head = active & multi & (pos_of == minpos_c[comp])
            head_reader = is_head & reader
            head_unsched = head_reader & ~feasible
            # rule (c), per component: a SOLO head that will commit this
            # round rewrites its component-mates' block planes, so the mates
            # sit the round out (the head itself still bids).  Pessimistic
            # when the head ends up not committing — that only defers the
            # mates one round, never invalidates a placement.
            closed_c = jnp.max(
                jnp.where(comp_oh, (head_reader & feasible & solo)[:, None],
                          False), axis=0
            )  # [C]
            comp_closed = multi & closed_c[comp] & ~is_head

            # --- parallel phase: all eligible bidders at once — non-readers
            # (incl. singleton-component constraint carriers) plus component
            # HEADS.  A head bids like everyone else and may divert to its
            # best UNUSED node when another component claims its argmax:
            # within its component the state is still exact (no mate
            # committed this round); cross-component diversion is the same
            # accepted divergence plain contended pods already have.
            unresolved0 = active & feasible & (~reader | is_head) & ~comp_closed
            commit0 = jnp.zeros(b, bool)
            choice0 = jnp.zeros(b, jnp.int32)
            used0 = jnp.zeros(n_cap, bool)

            def pcond(c):
                unresolved, _, _, _ = c
                return jnp.any(unresolved)

            def pbody(c):
                unresolved, used, commit, choice = c
                effm = jnp.where(used[None, :], -jnp.inf, eff)
                prop = jnp.argmax(effm, axis=1).astype(jnp.int32)
                take_nom = nom_ok & ~used[nom]
                prop = jnp.where(take_nom, nom, prop)
                has_bid = effm[jnp.arange(b), prop] > -jnp.inf
                bidder = unresolved & has_bid
                # winner per contested node by scatter-min of the bidders'
                # serial positions (exact-equivalent to the previous [B, N]
                # one-hot reduction, which materialized 33MB/iteration at
                # 131k nodes — the dominant term of the 100k auction's 613s
                # one-shot artifact)
                posb = jnp.where(bidder, pos_of, b)
                minpos_n = jnp.full(n_cap, b, pos_of.dtype).at[prop].min(posb)
                win = bidder & (minpos_n[prop] == posb)
                commit = commit | win
                choice = jnp.where(win, prop, choice)
                used = used.at[prop].max(win)
                # pods with no feasible unused node left drop out of the round
                return unresolved & ~win & has_bid, used, commit, choice

            _, _, commit, choice = jax.lax.while_loop(
                pcond, pbody, (unresolved0, used0, commit0, choice0)
            )
            # non-readers that are infeasible resolve as unschedulable any
            # round (their filters only shrink); readers only as component
            # heads with exact state
            unsched = (active & ~reader & ~feasible) | head_unsched
            return commit, choice, unsched

        def apply_commits(dyn, dauxes, commit, choice):
            """One batched state update for all of a round's commits.

            Commutative per-pod contributions (resource adds, domain-table
            bumps) sum over the committed set, so the whole round applies as
            a few einsums against the commit-weighted node one-hot `u` —
            no per-pod loop.  Plugins expose `update_batch`; any dynamic
            plugin without one falls back to its serial `update` under a
            fori_loop."""
            u = (
                (choice[:, None] == jnp.arange(n_cap)[None, :]) & commit[:, None]
            ).astype(jnp.float32)  # [B, N]
            req_add = jnp.einsum(
                "bn,br->nr", u, batch.request.astype(jnp.float32)
            )
            nz_add = jnp.einsum(
                "bn,br->nr", u, batch.non_zero.astype(jnp.float32)
            )
            new_dyn = DynamicState(
                requested=dyn.requested + req_add.astype(dyn.requested.dtype),
                non_zero=dyn.non_zero + nz_add.astype(dyn.non_zero.dtype),
            )

            new_auxes = []
            slow = []  # plugins needing the serial fallback
            for k, ((pw, _), aux) in enumerate(zip(dyn_plugins, dauxes)):
                bfn = getattr(pw.plugin, "update_batch", None)
                if bfn is not None and aux is not None:
                    new_auxes.append(bfn(aux, commit, choice, u, batch, snap))
                else:
                    new_auxes.append(aux)
                    if aux is not None and hasattr(pw.plugin, "update"):
                        slow.append(k)
            dauxes = tuple(new_auxes)
            if slow:
                def upd(j, dauxes):
                    i = order[j]

                    def app(dauxes):
                        out = list(dauxes)
                        for k in slow:
                            pw, _ = dyn_plugins[k]
                            out[k] = pw.plugin.update(
                                dauxes[k], i, choice[i], batch, snap
                            )
                        return tuple(out)

                    return jax.lax.cond(commit[i], app, lambda d: d, dauxes)

                dauxes = jax.lax.fori_loop(0, b, upd, dauxes)
            return new_dyn, dauxes

        def cond(state):
            _, _, _, active, _, _, rounds = state
            return jnp.any(active) & (rounds <= b)

        def body(state):
            dyn, dauxes, assigned, active, unsched, feas_n, rounds = state
            mask, scores = dense_rows(dyn, dauxes)
            feasible = jnp.any(mask, axis=1)
            commit, choice, new_unsched = auction_commits(
                active, feasible, mask, scores
            )
            dyn, dauxes = apply_commits(dyn, dauxes, commit, choice)
            resolved = commit | new_unsched
            feas_n = jnp.where(
                resolved & active, jnp.sum(mask, axis=1).astype(jnp.int32), feas_n
            )
            assigned = jnp.where(commit, choice, assigned)
            active = active & ~resolved
            unsched = unsched | new_unsched
            return dyn, dauxes, assigned, active, unsched, feas_n, rounds + 1

        init = (
            dyn,
            dyn_auxes,
            jnp.full(b, -1, jnp.int32),
            batch.valid,
            jnp.zeros(b, bool),
            jnp.zeros(b, jnp.int32),
            jnp.asarray(0, jnp.int32),
        )
        dyn, _, assigned, _, _, feas_n, rounds = jax.lax.while_loop(cond, body, init)
        return AssignResult(node_row=assigned, feasible_count=feas_n, dyn=dyn,
                            rounds=rounds)

    def _batch_assign_dedup(self, batch, snap, dyn, auxes, order,
                            coupling: CouplingFlags, classes) -> AssignResult:
        """batch_assign with identity-class-deduplicated dense planes.

        ``classes = (class_of i32[B], rep_batch PodBatch[C], rep_auxes)``
        (framework/podbatch.py identity_classes): pods of one class have
        byte-identical compiled rows, so their ``[N]`` filter/score rows are
        equal — each round computes the dense planes ONCE PER CLASS
        (``[C, N]``) and every pod proposes from its class's top-B candidate
        list instead of a full ``[B, N]`` argmax.

        Bit-for-bit exactness vs the full path:
          * plane rows: pure functions of (pod row content, snap, dyn, the
            carried aux state) — equal inputs, equal rows (the caller's
            gate excludes pod-indexed auxes, so no other state feeds them);
          * top-B candidate truncation: within one round at most B-1 OTHER
            pods commit (one node each), so a pod's best unused feasible
            node is always inside its class's top-B list — every node
            ranked above the list's best unused entry is used, and
            ``lax.top_k`` orders ties by ascending node row exactly like
            the full path's first-max argmax.  Affinity-carrying classes
            don't widen the bound: a rival's count/block/score effects land
            in the NEXT round's recomputed planes (apply-then-recompute,
            same as the full path), so within a round the only staleness is
            still the used-node set — one node per rival commit;
          * dynamic-plugin aux state: the full path's per-pod aux rows stay
            CLASS-UNIFORM under commits (every cross tensor is a pure
            function of the pending pod's class), so carrying the rep rows
            and updating them per round via the plugins'
            ``update_batch_classes`` hooks reproduces the full path's rows
            exactly.  A dynamic plugin with update hooks but no class hook
            fails loudly at trace time — the caller's gate should have
            routed that batch to the full path.

        Pinned by tests/test_batch_assign.py::test_dedup_* (deduped ==
        full-path bindings under contention, failure rows, nominated rows,
        and the randomized affinity-churn battery).
        """
        class_of, rep_batch, rep_auxes = classes
        b = batch.valid.shape[0]
        batch, dyn = jax.tree_util.tree_map(jnp.asarray, (batch, dyn))
        rep_batch, rep_auxes = jax.tree_util.tree_map(
            jnp.asarray, (rep_batch, rep_auxes))
        class_of = jnp.asarray(class_of, jnp.int32)
        for pw, aux in zip(self.plugins, rep_auxes):
            if pw.plugin.dynamic and aux is not None and (
                    getattr(pw.plugin, "update", None) is not None
                    or getattr(pw.plugin, "update_batch", None) is not None
            ) and getattr(pw.plugin, "update_batch_classes", None) is None:
                raise ValueError(
                    "identity-class dedup requires update-free dynamic "
                    f"auxes or an update_batch_classes hook; "
                    f"{pw.plugin.name} has neither — the caller's dedup "
                    "gate should have routed this batch to the full path")
        reads = jnp.asarray(coupling.reads)
        solo = jnp.asarray(coupling.solo)
        if coupling.comp is None:
            comp = jnp.zeros(b, jnp.int32)
            multi = jnp.ones(b, bool)
        else:
            comp = jnp.asarray(coupling.comp, jnp.int32)
            multi = jnp.asarray(coupling.multi, bool)
        reader = reads & multi
        order = order.astype(jnp.int32)
        n_cap = snap.node_valid.shape[0]
        kcand = min(b, n_cap)

        # static planes once, at CLASS granularity
        static_mask = live_nodes(snap)[None, :] & rep_batch.valid[:, None]
        static_raw: List = []
        for pw, aux in zip(self.plugins, rep_auxes):
            p = pw.plugin
            if not p.dynamic and hasattr(p, "filter"):
                static_mask = static_mask & p.filter(rep_batch, snap, dyn, aux)
            if hasattr(p, "score") and not p.dynamic:
                static_raw.append((pw, p.score(rep_batch, snap, dyn, aux)))
        dyn_plugins = [
            (pw, idx) for idx, pw in enumerate(self.plugins) if pw.plugin.dynamic
        ]
        dyn_rep_auxes = tuple(rep_auxes[idx] for _, idx in dyn_plugins)
        # affinity/spread-carrying classes: the rep aux rows must track the
        # round's commits exactly like the full path's pod rows (which stay
        # class-uniform — see the docstring).  update_batch_classes consumes
        # the CLASS-level placement one-hot u_c [Cp, N] (commits aggregated
        # by committer class), so a round's whole update is O(C·N), not
        # O(B·N) — the dedup win extends to the update half.
        needs_updates = any(
            aux is not None
            and getattr(pw.plugin, "update_batch_classes", None) is not None
            for (pw, _), aux in zip(dyn_plugins, dyn_rep_auxes))
        n_classes = rep_batch.valid.shape[0]

        def apply_aux_updates(dauxes, commit, choice):
            u_c = jnp.zeros((n_classes, n_cap), jnp.float32).at[
                class_of, jnp.clip(choice, 0, n_cap - 1)
            ].add(commit.astype(jnp.float32))
            out = []
            for (pw, _), aux in zip(dyn_plugins, dauxes):
                fn = getattr(pw.plugin, "update_batch_classes", None)
                if fn is None or aux is None:
                    out.append(aux)
                else:
                    out.append(fn(aux, u_c, batch, rep_batch, snap, class_of))
            return tuple(out)

        def dense_rep(dyn, dauxes):
            mask = static_mask
            for (pw, _), aux in zip(dyn_plugins, dauxes):
                if hasattr(pw.plugin, "filter"):
                    mask = mask & pw.plugin.filter(rep_batch, snap, dyn, aux)
            total = jnp.zeros(mask.shape, jnp.float32)
            for pw, plane in static_raw:
                total = total + pw.weight * jnp.floor(
                    pw.plugin.normalize(plane, mask))
            for (pw, _), aux in zip(dyn_plugins, dauxes):
                if not hasattr(pw.plugin, "score"):
                    continue
                raw = pw.plugin.score(rep_batch, snap, dyn, aux, mask=mask)
                total = total + pw.weight * jnp.floor(
                    pw.plugin.normalize(raw, mask))
            return mask, jnp.where(mask, total, -jnp.inf)

        pos_of = jnp.zeros(b, jnp.int32).at[order].set(
            jnp.arange(b, dtype=jnp.int32))
        nom = jnp.clip(batch.nominated_row, 0, n_cap - 1)

        def auction_commits(active, feasible, mask_r, scores_r):
            eff_r = jnp.where(mask_r, scores_r, -jnp.inf)  # [C, N]
            cand_val, cand_idx = jax.lax.top_k(eff_r, kcand)  # [C, K]
            cv = cand_val[class_of]  # [B, K] (a gather, not a recompute)
            ci = cand_idx[class_of].astype(jnp.int32)
            nom_ok = (batch.nominated_row >= 0) & mask_r[class_of, nom]

            # component heads — identical rules to the full path
            act_pos = jnp.where(active & multi, pos_of, b)
            comp_oh = comp[:, None] == jnp.arange(b)[None, :]  # [B, C]
            minpos_c = jnp.min(
                jnp.where(comp_oh, act_pos[:, None], b), axis=0)
            is_head = active & multi & (pos_of == minpos_c[comp])
            head_reader = is_head & reader
            head_unsched = head_reader & ~feasible
            closed_c = jnp.max(
                jnp.where(comp_oh, (head_reader & feasible & solo)[:, None],
                          False), axis=0)
            comp_closed = multi & closed_c[comp] & ~is_head

            unresolved0 = active & feasible & (~reader | is_head) & ~comp_closed
            commit0 = jnp.zeros(b, bool)
            choice0 = jnp.zeros(b, jnp.int32)
            used0 = jnp.zeros(n_cap, bool)

            def pcond(c):
                unresolved, _, _, _ = c
                return jnp.any(unresolved)

            def pbody(c):
                unresolved, used, commit, choice = c
                # best UNUSED candidate from the pod's class list: the
                # first (value-desc, row-asc) entry not yet claimed — the
                # full path's argmax-over-unused, at [B, K] cost
                ok = (cv > -jnp.inf) & ~used[ci]
                first = jnp.argmax(ok, axis=1)
                prop = ci[jnp.arange(b), first]
                has_cand = jnp.any(ok, axis=1)
                take_nom = nom_ok & ~used[nom]
                prop = jnp.where(take_nom, nom, prop)
                has_bid = jnp.where(take_nom, True, has_cand)
                bidder = unresolved & has_bid
                posb = jnp.where(bidder, pos_of, b)
                minpos_n = jnp.full(n_cap, b, pos_of.dtype).at[prop].min(posb)
                win = bidder & (minpos_n[prop] == posb)
                commit = commit | win
                choice = jnp.where(win, prop, choice)
                used = used.at[prop].max(win)
                return unresolved & ~win & has_bid, used, commit, choice

            _, _, commit, choice = jax.lax.while_loop(
                pcond, pbody, (unresolved0, used0, commit0, choice0)
            )
            unsched = (active & ~reader & ~feasible) | head_unsched
            return commit, choice, unsched

        def apply_dyn(dyn, commit, choice):
            # scatter-add instead of the full path's [B, N] one-hot einsum:
            # integer adds to distinct rows (one commit per node per round),
            # bit-identical and O(B·R) instead of O(B·N·R)
            rows = jnp.clip(choice, 0, n_cap - 1)
            addm = commit[:, None]
            req = dyn.requested.at[rows].add(
                jnp.where(addm, batch.request, 0).astype(dyn.requested.dtype))
            nz = dyn.non_zero.at[rows].add(
                jnp.where(addm, batch.non_zero, 0).astype(dyn.non_zero.dtype))
            return DynamicState(requested=req, non_zero=nz)

        def cond(state):
            _, _, _, active, _, _, rounds = state
            return jnp.any(active) & (rounds <= b)

        def body(state):
            dyn, dauxes, assigned, active, unsched, feas_n, rounds = state
            mask_r, scores_r = dense_rep(dyn, dauxes)
            feasible = jnp.any(mask_r, axis=1)[class_of]
            commit, choice, new_unsched = auction_commits(
                active, feasible, mask_r, scores_r
            )
            dyn = apply_dyn(dyn, commit, choice)
            if needs_updates:  # trace-time flag: plain batches skip entirely
                dauxes = apply_aux_updates(dauxes, commit, choice)
            resolved = commit | new_unsched
            feas_n = jnp.where(
                resolved & active,
                jnp.sum(mask_r, axis=1).astype(jnp.int32)[class_of], feas_n
            )
            assigned = jnp.where(commit, choice, assigned)
            active = active & ~resolved
            unsched = unsched | new_unsched
            return dyn, dauxes, assigned, active, unsched, feas_n, rounds + 1

        init = (
            dyn,
            dyn_rep_auxes,
            jnp.full(b, -1, jnp.int32),
            batch.valid,
            jnp.zeros(b, bool),
            jnp.zeros(b, jnp.int32),
            jnp.asarray(0, jnp.int32),
        )
        dyn, _, assigned, _, _, feas_n, rounds = jax.lax.while_loop(
            cond, body, init)
        return AssignResult(node_row=assigned, feasible_count=feas_n, dyn=dyn,
                            rounds=rounds)

    def apply_commits(self, batch, snap, dyn, auxes, commit, choice):
        """Apply a set of simultaneous placements (commit bool[B], choice
        i32[B]) to the dynamic state and every dynamic plugin's aux — the
        standalone jittable form of batch_assign's per-round state update,
        used by the round-based extender path.  Returns (dyn, auxes) with
        non-dynamic auxes unchanged."""
        n_cap = snap.node_valid.shape[0]
        u = (
            (choice[:, None] == jnp.arange(n_cap)[None, :]) & commit[:, None]
        ).astype(jnp.float32)  # [B, N]
        req_add = jnp.einsum("bn,br->nr", u, batch.request.astype(jnp.float32))
        nz_add = jnp.einsum("bn,br->nr", u, batch.non_zero.astype(jnp.float32))
        new_dyn = DynamicState(
            requested=dyn.requested + req_add.astype(dyn.requested.dtype),
            non_zero=dyn.non_zero + nz_add.astype(dyn.non_zero.dtype),
        )
        b = batch.valid.shape[0]
        new_auxes = list(auxes)
        slow = []
        for k, (pw, aux) in enumerate(zip(self.plugins, auxes)):
            if not pw.plugin.dynamic or aux is None:
                continue
            bfn = getattr(pw.plugin, "update_batch", None)
            if bfn is not None:
                new_auxes[k] = bfn(aux, commit, choice, u, batch, snap)
            elif hasattr(pw.plugin, "update"):
                slow.append(k)
        auxes = tuple(new_auxes)
        if slow:
            def upd(i, auxes):
                def app(auxes):
                    out = list(auxes)
                    for k in slow:
                        out[k] = self.plugins[k].plugin.update(
                            auxes[k], i, choice[i], batch, snap
                        )
                    return tuple(out)

                return jax.lax.cond(commit[i], app, lambda a: a, auxes)

            auxes = jax.lax.fori_loop(0, b, upd, auxes)
        return new_dyn, auxes

    def greedy_assign_dense(self, batch, snap, dyn, auxes, order, key=None) -> AssignResult:
        """Reference implementation: full [B, N] recompute per step (used by the
        fast-path parity test)."""
        b = batch.valid.shape[0]
        batch, auxes, dyn = jax.tree_util.tree_map(jnp.asarray, (batch, auxes, dyn))

        def step(carry, inp):
            dyn, auxes = carry
            i = inp["i"]
            mask, scores = self.compute(batch, snap, dyn, auxes)
            row_mask = mask[i]
            row_scores = scores[i]
            feasible_n = jnp.sum(row_mask)
            feasible = feasible_n > 0
            node = self.select_host(row_scores, row_mask, inp.get("key"))
            nom = batch.nominated_row[i]
            nom_ok = (nom >= 0) & row_mask[jnp.clip(nom, 0, row_mask.shape[0] - 1)]
            node = jnp.where(nom_ok, jnp.clip(nom, 0, row_mask.shape[0] - 1), node)
            node = jnp.where(feasible, node, 0)

            def do_assign(args):
                dyn, auxes = args
                return self.apply_assignment(dyn, auxes, i, node, batch, snap)

            dyn, auxes = jax.lax.cond(
                feasible & batch.valid[i], do_assign, lambda a: a, (dyn, auxes)
            )
            out_node = jnp.where(feasible & batch.valid[i], node, -1)
            return (dyn, auxes), {"i": i, "node": out_node, "feasible_n": feasible_n}

        inputs = {"i": order.astype(jnp.int32)}
        if key is not None:
            inputs["key"] = jax.random.split(key, b)
        (dyn, auxes), outs = jax.lax.scan(step, (dyn, auxes), inputs)
        node_row = jnp.full((b,), -1, jnp.int32).at[outs["i"]].set(outs["node"])
        feasible_count = jnp.zeros((b,), jnp.int32).at[outs["i"]].set(outs["feasible_n"])
        return AssignResult(node_row=node_row, feasible_count=feasible_count, dyn=dyn)


def initial_dynamic_state(snap) -> DynamicState:
    return DynamicState(requested=snap.requested, non_zero=snap.non_zero_requested)
