"""Cluster event taxonomy for event-driven requeue.

Reference: pkg/scheduler/framework/types.go:42-84 (ActionType bitmask, ClusterEvent)
and pkg/scheduler/internal/queue/events.go. A plugin registers the events that could
make a pod it rejected schedulable; MoveAllToActiveOrBackoffQueue only requeues pods
whose failing plugins registered the incoming event (scheduling_queue.go:963
podMatchesEvent).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class ActionType(enum.IntFlag):
    ADD = 1 << 0
    DELETE = 1 << 1
    UPDATE_NODE_ALLOCATABLE = 1 << 2
    UPDATE_NODE_LABEL = 1 << 3
    UPDATE_NODE_TAINT = 1 << 4
    UPDATE_NODE_CONDITION = 1 << 5
    UPDATE = (
        UPDATE_NODE_ALLOCATABLE
        | UPDATE_NODE_LABEL
        | UPDATE_NODE_TAINT
        | UPDATE_NODE_CONDITION
    )
    ALL = ADD | DELETE | UPDATE


class EventResource(str, enum.Enum):
    POD = "Pod"
    NODE = "Node"
    PVC = "PersistentVolumeClaim"
    PV = "PersistentVolume"
    STORAGE_CLASS = "StorageClass"
    CSI_NODE = "CSINode"
    SERVICE = "Service"
    POD_GROUP = "PodGroup"
    RESOURCE_CLAIM = "ResourceClaim"
    RESOURCE_SLICE = "ResourceSlice"
    DEVICE_CLASS = "DeviceClass"
    WILDCARD = "*"


@dataclass(frozen=True)
class ClusterEvent:
    resource: EventResource
    action_type: ActionType
    label: str = ""

    def is_wildcard(self) -> bool:
        return self.resource == EventResource.WILDCARD and self.action_type == ActionType.ALL

    def match(self, other: "ClusterEvent") -> bool:
        """Does a registered event (self) cover an incoming event (other)?"""
        if self.is_wildcard():
            return True
        return self.resource == other.resource and bool(
            self.action_type & other.action_type
        )


# Common event instances (internal/queue/events.go)
WILDCARD_EVENT = ClusterEvent(EventResource.WILDCARD, ActionType.ALL, "WildCardEvent")
NODE_ADD = ClusterEvent(EventResource.NODE, ActionType.ADD, "NodeAdd")
NODE_DELETE = ClusterEvent(EventResource.NODE, ActionType.DELETE, "NodeDelete")
POD_ADD = ClusterEvent(EventResource.POD, ActionType.ADD, "PodAdd")
POD_DELETE = ClusterEvent(EventResource.POD, ActionType.DELETE, "PodDelete")
POD_UPDATE = ClusterEvent(EventResource.POD, ActionType.UPDATE, "PodUpdate")
NODE_ALLOCATABLE_CHANGE = ClusterEvent(
    EventResource.NODE, ActionType.UPDATE_NODE_ALLOCATABLE, "NodeAllocatableChange"
)
NODE_LABEL_CHANGE = ClusterEvent(
    EventResource.NODE, ActionType.UPDATE_NODE_LABEL, "NodeLabelChange"
)
NODE_TAINT_CHANGE = ClusterEvent(
    EventResource.NODE, ActionType.UPDATE_NODE_TAINT, "NodeTaintChange"
)
NODE_CONDITION_CHANGE = ClusterEvent(
    EventResource.NODE, ActionType.UPDATE_NODE_CONDITION, "NodeConditionChange"
)
POD_GROUP_CHANGE = ClusterEvent(
    EventResource.POD_GROUP, ActionType.ADD | ActionType.UPDATE, "PodGroupChange"
)
PVC_ADD = ClusterEvent(EventResource.PVC, ActionType.ADD, "PvcAdd")
PV_ADD = ClusterEvent(EventResource.PV, ActionType.ADD, "PvAdd")
SERVICE_ADD = ClusterEvent(EventResource.SERVICE, ActionType.ADD, "ServiceAdd")
