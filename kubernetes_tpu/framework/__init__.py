"""Batched Scheduling Framework (reference: pkg/scheduler/framework).

The reference defines 11 extension points as Go interfaces evaluated per (pod, node)
by goroutine fan-out (framework/interface.go:305-495, runtime/framework.go). Here the
same extension points are *batched tensor programs*: a plugin's Filter produces a
``bool[B, N]`` feasibility mask and its Score a ``float32[B, N]`` plane for a whole
``PodBatch`` against a ``DeviceSnapshot`` in one fused XLA computation; the runtime's
per-plugin weight application (runtime/framework.go:925-940) becomes a single
contraction over the stacked ``[plugins, B, N]`` tensor.
"""

from .interface import (  # noqa: F401
    Code,
    Status,
    CycleState,
    Plugin,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
    MAX_TOTAL_SCORE,
)
from .events import ClusterEvent, ActionType, EventResource  # noqa: F401
from .podbatch import PodBatch, PodBatchCompiler  # noqa: F401
