"""Plugin API: extension points, Status codes, CycleState.

Reference: pkg/scheduler/framework/interface.go — QueueSortPlugin :305,
PreFilterPlugin :338, FilterPlugin :361, PostFilterPlugin :379, PreScorePlugin :398,
ScorePlugin :416, ReservePlugin :433, PermitPlugin :469, PreBindPlugin :449,
BindPlugin :482, PostBindPlugin :458; MaxNodeScore :101; Status codes :~150.

Design delta vs the reference: Filter/Score are *batched* — one call covers the whole
``[B pods, N nodes]`` plane as a pure jnp function, so they can be jit-fused into a
single device program.  Host-only extension points (queue sort less-fn, reserve,
permit, bind) keep per-pod Python signatures.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, NamedTuple, Optional

MAX_NODE_SCORE = 100  # framework/interface.go:101
MIN_NODE_SCORE = 0
MAX_TOTAL_SCORE = (1 << 63) - 1


class Code(enum.IntEnum):
    """Status codes (framework/interface.go Status)."""

    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5


@dataclass
class Status:
    code: Code = Code.SUCCESS
    reasons: tuple = ()
    plugin: str = ""

    @classmethod
    def success(cls) -> "Status":
        return cls()

    @classmethod
    def unschedulable(cls, *reasons: str, plugin: str = "", resolvable: bool = True) -> "Status":
        code = Code.UNSCHEDULABLE if resolvable else Code.UNSCHEDULABLE_AND_UNRESOLVABLE
        return cls(code=code, reasons=tuple(reasons), plugin=plugin)

    @classmethod
    def error(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(code=Code.ERROR, reasons=tuple(reasons), plugin=plugin)

    def is_success(self) -> bool:
        return self.code == Code.SUCCESS

    def is_rejected(self) -> bool:
        return self.code in (Code.UNSCHEDULABLE, Code.UNSCHEDULABLE_AND_UNRESOLVABLE)

    def message(self) -> str:
        return "; ".join(self.reasons)


class DynamicState(NamedTuple):
    """Cluster arrays that mutate *within* a batch as pods are greedily assigned
    (the device-side analog of the reference's ``assume``, scheduler.go:424,571).
    Plugins read these instead of the frozen DeviceSnapshot fields."""

    requested: Any  # i32[N, R]
    non_zero: Any  # i32[N, 2]


class CycleState:
    """Per-scheduling-cycle scratchpad (framework/cycle_state.go).

    In the batched design one CycleState covers one PodBatch cycle; plugins stash
    precomputed host/device data under their own keys (the analog of
    PreFilter writing plugin state read back by Filter/Score).
    """

    def __init__(self):
        self._data: Dict[str, Any] = {}
        self.skip_filter_plugins: set = set()
        self.skip_score_plugins: set = set()

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def read(self, key: str) -> Any:
        return self._data.get(key)

    def clone(self) -> "CycleState":
        c = CycleState()
        c._data = dict(self._data)
        c.skip_filter_plugins = set(self.skip_filter_plugins)
        c.skip_score_plugins = set(self.skip_score_plugins)
        return c


class Plugin:
    """Base for batched plugins.

    Subclasses override any subset (mirroring the Go interfaces):

      name: str  (class attr)
      events_to_register() -> list[ClusterEvent]       # EnqueueExtensions
      pre_filter(state, batch, snap) -> Optional[Status]
      filter(state, batch, snap) -> bool[B, N]          # pure jnp
      pre_score(state, batch, snap, mask) -> None
      score(state, batch, snap) -> f32[B, N]            # pure jnp, any scale
      normalize(scores: f32[B, N], mask) -> f32[B, N]   # → [0, MAX_NODE_SCORE]
      # host-side, per pod:
      less(pod_info_a, pod_info_b) -> bool              # QueueSort
      reserve(state, pod, node_name) -> Status
      unreserve(state, pod, node_name) -> None
      permit(state, pod, node_name) -> (Status, timeout_s)
      pre_bind(state, pod, node_name) -> Status
      bind(state, pod, node_name) -> Status
      post_bind(state, pod, node_name) -> None
      post_filter(state, batch_or_pod, snap, filtered) -> (result, Status)
    """

    name: str = "Plugin"
    # dynamic plugins read DynamicState / scan-updated aux; static plugins are
    # precomputed once per batch outside the assignment scan
    dynamic: bool = False

    # feature-detection helpers used by the runtime registry
    def has(self, method: str) -> bool:
        return type(self).__dict__.get(method) is not None or any(
            method in klass.__dict__ for klass in type(self).__mro__[1:-1]
            if klass is not Plugin
        )

    def events_to_register(self):
        return []


@dataclass
class PluginWithWeight:
    plugin: Plugin
    weight: int = 1
