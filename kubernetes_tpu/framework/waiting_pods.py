"""Permit "Wait" support: the waiting-pods map.

Reference: pkg/scheduler/framework/runtime/waiting_pods_map.go — a Permit plugin
may return Wait with a timeout; the binding cycle blocks in WaitOnPermit until
every waiting plugin allows (or any rejects / the timeout fires).

Clock contract: every deadline is computed AND checked against the single
injected ``clock`` (the scheduler's own) — no raw ``time.monotonic()`` or
``time.sleep`` anywhere in the deadline math, so gang-timeout behavior is
exactly reproducible under a fake clock (the wait is re-polled by the
scheduler's cycle loop, never slept on).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Set

from ..api import objects as v1


@dataclass
class WaitingPod:
    pod: v1.Pod
    pending_plugins: Dict[str, float] = field(default_factory=dict)  # plugin → deadline
    rejected: Optional[str] = None  # rejecting plugin message

    def allow(self, plugin: str) -> None:
        self.pending_plugins.pop(plugin, None)

    def reject(self, plugin: str, msg: str = "") -> None:
        self.rejected = f"{plugin}: {msg}"

    def is_allowed(self) -> bool:
        return not self.pending_plugins and self.rejected is None


class WaitingPodsMap:
    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._pods: Dict[str, WaitingPod] = {}

    def add(self, pod: v1.Pod, plugin: str, timeout: float) -> WaitingPod:
        wp = self._pods.get(pod.uid)
        if wp is None:
            wp = WaitingPod(pod=pod)
            self._pods[pod.uid] = wp
        wp.pending_plugins[plugin] = self._clock() + timeout
        return wp

    def get(self, uid: str) -> Optional[WaitingPod]:
        return self._pods.get(uid)

    def remove(self, uid: str) -> None:
        self._pods.pop(uid, None)

    def next_deadline(self) -> Optional[float]:
        """Earliest pending-plugin deadline across all waiting pods (on the
        injected clock's scale), or None — lets a driving loop know when a
        gang hold can next expire without polling blind."""
        deadlines = [
            dl for wp in self._pods.values()
            for dl in wp.pending_plugins.values()
        ]
        return min(deadlines) if deadlines else None

    def wait_on_permit(self, pod: v1.Pod) -> Optional[str]:
        """→ None (allowed) or a rejection reason. Expired waits reject
        (the reference's timeout behavior)."""
        wp = self._pods.get(pod.uid)
        if wp is None:
            return None
        now = self._clock()
        for plugin, deadline in list(wp.pending_plugins.items()):
            if now >= deadline:
                wp.reject(plugin, "timed out waiting on permit")
        result = wp.rejected if not wp.is_allowed() and wp.rejected else (
            None if wp.is_allowed() else
            f"still waiting on {sorted(wp.pending_plugins)}"
        )
        if wp.is_allowed() or wp.rejected:
            self.remove(pod.uid)
        return result
