"""PodBatch: a batch of pending pods compiled into padded device arrays.

The reference walks one pod's Go spec per cycle (scheduler.go:496 scheduleOne);
plugins re-parse it per node visit.  Here a whole batch of B pending pods is
compiled ONCE host-side into fixed-shape int32/float32 arrays, and every plugin's
Filter/Score reads only these arrays — so the full ``[B, N]`` feasibility/score
planes are pure jnp programs.

Compiled per pod (MISSING = -1 pads everywhere):
  requests        — i32[B, R] scaled units (fit.go:162-178 semantics, incl. overhead)
  tolerations     — key/val/op/effect/valid [B, TT] (Toleration.ToleratesTaint)
  node selector   — pod.spec.nodeSelector as a matchLabels-only selector (AND)
  node affinity   — requiredDuringScheduling terms (OR of ANDed reqs) + weighted
                    preferred terms (nodeaffinity/node_affinity.go)
  topology spread — per-constraint key/maxSkew/whenUnsatisfiable/minDomains +
                    compiled label selector (podtopologyspread/common.go);
                    topology keys become encoder topo slots (compact domain ids)
  pod (anti)affinity — 4 term groups, each: topology key, compiled selector,
                    resolved namespace id list (namespaces ∪ namespaceSelector
                    resolved host-side, mirroring PreFilter's namespace resolution)
  ports, labels, namespace, priority, nodeName
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from ..api import objects as v1
from ..api.labels import match_label_selector
from ..state.dictionary import MISSING, Dictionary
from ..state.encoding import (
    EFFECT_CODE,
    _PROTO_CODE,
    ClusterEncoder,
    EncodingCapacityError,
)
from ..state import selectors as sel
from ..state.selectors import (
    CompiledLabelSelectors,
    CompiledNodeSelectors,
    compile_label_selectors,
    compile_node_selectors,
)

TOL_OP_EQUAL = 0
TOL_OP_EXISTS = 1

WHEN_DO_NOT_SCHEDULE = 0
WHEN_SCHEDULE_ANYWAY = 1


from ..state.units import pow2_round_up as _pow2


# the four pod-(anti)affinity term groups, in PodBatch field order — the ONE
# source for the compiler loop, the group_present default, and
# InterPodAffinityPlugin._present
AFFINITY_GROUPS = ("req_affinity", "req_anti_affinity",
                   "pref_affinity", "pref_anti_affinity")


@dataclass
class AffinityTermGroup:
    """One group of pod-affinity terms for the whole batch ([B, T] padded).

    selectors are flattened row-major: term (i, t) -> flat index i*T + t.
    """

    valid: np.ndarray  # bool[B, T]
    topo_key: np.ndarray  # i32[B, T]
    weight: np.ndarray  # f32[B, T]  (1.0 for required terms)
    ns_ids: np.ndarray  # i32[B, T, NS]
    all_namespaces: np.ndarray  # bool[B, T]  (empty-but-non-nil namespaceSelector)
    selectors: CompiledLabelSelectors  # batch size B*T

    @property
    def terms_per_pod(self) -> int:
        return self.valid.shape[1]


@dataclass
class PodBatch:
    pods: List[v1.Pod]
    valid: np.ndarray  # bool[B]
    request: np.ndarray  # i32[B, R]
    non_zero: np.ndarray  # i32[B, 2]
    ns: np.ndarray  # i32[B]
    label_keys: np.ndarray  # i32[B, PL]
    label_vals: np.ndarray  # i32[B, PL]
    priority: np.ndarray  # i32[B]
    node_name_id: np.ndarray  # i32[B] (MISSING when spec.nodeName unset)
    nominated_row: np.ndarray  # i32[B] node row from status.nominatedNodeName (-1 none)
    ports: np.ndarray  # i32[B, PP]
    ports_ip: np.ndarray  # i32[B, PP] (hostIP dictionary id; ID_WILDCARD_IP = any)
    image_ids: np.ndarray  # i32[B, CI] (container images, for ImageLocality)
    # tolerations
    tol_valid: np.ndarray  # bool[B, TT]
    tol_key: np.ndarray  # i32[B, TT] (MISSING = empty key → any)
    tol_val: np.ndarray  # i32[B, TT]
    tol_op: np.ndarray  # i32[B, TT]
    tol_effect: np.ndarray  # i32[B, TT] (-1 = all effects)
    # node selection
    node_selector: CompiledLabelSelectors  # B (pod.spec.nodeSelector)
    node_affinity: CompiledNodeSelectors  # B (required terms)
    pref_valid: np.ndarray  # bool[B, PT] preferred node-affinity terms
    pref_weight: np.ndarray  # f32[B, PT]
    pref_req_key: np.ndarray  # i32[B, PT, S]
    pref_req_op: np.ndarray
    pref_req_vals: np.ndarray  # i32[B, PT, S, V]
    pref_req_num: np.ndarray  # f32[B, PT, S]
    # topology spread
    tsc_valid: np.ndarray  # bool[B, C]
    tsc_key: np.ndarray  # i32[B, C]
    tsc_max_skew: np.ndarray  # i32[B, C]
    tsc_when: np.ndarray  # i32[B, C]
    tsc_min_domains: np.ndarray  # i32[B, C] (0 = unset)
    tsc_selectors: CompiledLabelSelectors  # B*C
    # pod (anti)affinity term groups
    req_affinity: AffinityTermGroup
    req_anti_affinity: AffinityTermGroup
    pref_affinity: AffinityTermGroup
    pref_anti_affinity: AffinityTermGroup
    # STATIC (pytree aux) batch-content flags: trace-time constants that let
    # the runtime compile constraint-free batches WITHOUT the topology-spread
    # / inter-pod-affinity programs at all — their per-step domain ops are
    # O(N·D) and dominate the greedy scan at 5k nodes even when every
    # constraint row is invalid padding
    has_spread: bool = False
    has_affinity: bool = False
    # pow-2 bound on compact domain indices across the batch's USED spread
    # keys.  The encoder's global domain_cap covers EVERY registered topology
    # key — one hostname-keyed pod anywhere (5k domains at 5k nodes) would
    # make every zone-spread batch contract [C, N, 8192] one-hots when its
    # own key has 3 domains.  Static (trace-time constant) → one compiled
    # program variant per bucket.  None (the default for any batch built
    # without the compiler's sizing pass) falls back to the global
    # domain_cap in the plugin — a too-small bucket would silently merge
    # domains past it.
    tsc_domain_bucket: Optional[int] = None
    # same bound over the batch's pod-(anti)affinity term keys — drives both
    # the InterPodAffinity table width AND its planes-vs-tables choice
    # (zone-affinity batches get [B,T,9] tables instead of [B,T,N] planes)
    ipa_domain_bucket: Optional[int] = None
    # which of the four (anti)affinity term groups have ANY valid term in
    # this batch (static): InterPodAffinity compiles out the per-scan-step
    # update work of empty groups — an anti-only batch skips the three
    # other groups' [B,T,N] plane rewrites on every step
    group_present: tuple = AFFINITY_GROUPS

    def __len__(self) -> int:
        return len(self.pods)

    @property
    def size(self) -> int:
        return self.valid.shape[0]

    def has_pod_affinity(self) -> bool:
        return bool(
            self.req_affinity.valid.any()
            or self.req_anti_affinity.valid.any()
            or self.pref_affinity.valid.any()
            or self.pref_anti_affinity.valid.any()
        )

    def has_topology_spread(self) -> bool:
        return bool(self.tsc_valid.any())

    def take(self, rows) -> "PodBatch":
        """Row-gather along the pod axis: a PodBatch whose pod i is this
        batch's pod ``rows[i]`` (static pytree aux copied unchanged).

        Works on host numpy and inside traced programs (``rows`` may be a
        traced i32 vector) — the identity-class dedup path gathers the
        class REPRESENTATIVES' rows this way, so the dense filter/score
        planes compute at ``[C, N]`` instead of ``[B, N]``.  The compiled
        selector structs hold content-deduplicated unique rows plus a
        per-pod ``index`` map, so gathering a selector batch is just
        gathering ``index``; per-pod-flattened selector batches (B*T
        row-major) gather whole T-blocks."""
        import dataclasses

        b = self.valid.shape[0]

        def g(a):  # plain pod-dim array
            return a[rows]

        def sel_take(cs, per_pod: int):
            idx = cs.index.reshape(b, per_pod)[rows].reshape(-1)
            return dataclasses.replace(cs, index=idx)

        def group_take(grp: "AffinityTermGroup"):
            t = grp.valid.shape[1]
            return AffinityTermGroup(
                valid=g(grp.valid), topo_key=g(grp.topo_key),
                weight=g(grp.weight), ns_ids=g(grp.ns_ids),
                all_namespaces=g(grp.all_namespaces),
                selectors=sel_take(grp.selectors, t),
            )

        return dataclasses.replace(
            self,
            pods=[],  # host pod objects are not gatherable by traced rows
            valid=g(self.valid), request=g(self.request),
            non_zero=g(self.non_zero), ns=g(self.ns),
            label_keys=g(self.label_keys), label_vals=g(self.label_vals),
            priority=g(self.priority), node_name_id=g(self.node_name_id),
            nominated_row=g(self.nominated_row),
            ports=g(self.ports), ports_ip=g(self.ports_ip),
            image_ids=g(self.image_ids),
            tol_valid=g(self.tol_valid), tol_key=g(self.tol_key),
            tol_val=g(self.tol_val), tol_op=g(self.tol_op),
            tol_effect=g(self.tol_effect),
            node_selector=sel_take(self.node_selector, 1),
            node_affinity=sel_take(self.node_affinity, 1),
            pref_valid=g(self.pref_valid), pref_weight=g(self.pref_weight),
            pref_req_key=g(self.pref_req_key), pref_req_op=g(self.pref_req_op),
            pref_req_vals=g(self.pref_req_vals),
            pref_req_num=g(self.pref_req_num),
            tsc_valid=g(self.tsc_valid), tsc_key=g(self.tsc_key),
            tsc_max_skew=g(self.tsc_max_skew), tsc_when=g(self.tsc_when),
            tsc_min_domains=g(self.tsc_min_domains),
            tsc_selectors=sel_take(self.tsc_selectors,
                                   self.tsc_valid.shape[1]),
            req_affinity=group_take(self.req_affinity),
            req_anti_affinity=group_take(self.req_anti_affinity),
            pref_affinity=group_take(self.pref_affinity),
            pref_anti_affinity=group_take(self.pref_anti_affinity),
        )


from ..utils.pytrees import register_pytree_dataclass as _reg  # noqa: E402

_reg(AffinityTermGroup)
_reg(PodBatch, skip=("pods",),
     static=("has_spread", "has_affinity", "tsc_domain_bucket",
             "ipa_domain_bucket", "group_present"))


class PodBatchCompiler:
    """Compiles pods → PodBatch against a ClusterEncoder's dictionary/resource dims.

    namespace_labels: ns name → labels, used to resolve PodAffinityTerm
    namespaceSelector host-side (the reference resolves it in PreFilter via a
    namespace lister — interpodaffinity/plugin.go GetNamespaceLabelsSnapshot).
    """

    def __init__(
        self,
        encoder: ClusterEncoder,
        namespace_labels: Optional[Mapping[str, Mapping[str, str]]] = None,
    ):
        self.enc = encoder
        self.dic: Dictionary = encoder.dic
        self.namespace_labels = namespace_labels or {}
        # Sticky per-dimension caps: each inner dim (labels, tolerations,
        # spread constraints, affinity terms, …) is a pow-2 HIGH-WATER MARK
        # across all batches this compiler has seen, not the current batch's
        # max.  Otherwise batches alternating between pod kinds (e.g. plain ↔
        # anti-affinity in the mixed suites) flip shapes every cycle and each
        # flip recompiles the whole program suite.  Padding is semantically
        # inert (valid[] gates everything), so growing a cap never changes
        # results — test_podbatch_sticky_caps.
        self._caps: Dict[str, int] = {}

    def _cap(self, name: str, need: int, minimum: int) -> int:
        c = max(_pow2(need, minimum), self._caps.get(name, 0))
        self._caps[name] = c
        return c

    def _compile_ls(self, name: str, sel_list) -> CompiledLabelSelectors:
        """compile_label_selectors with sticky u/s/v caps (same rationale as _cap)."""
        cs = compile_label_selectors(
            sel_list, self.dic,
            min_s=self._caps.get(f"{name}_s", 4),
            min_v=self._caps.get(f"{name}_v", 4),
            min_u=self._caps.get(f"{name}_u", 4),
        )
        self._caps[f"{name}_s"] = cs.req_key.shape[-1]
        self._caps[f"{name}_v"] = cs.req_vals.shape[-1]
        self._caps[f"{name}_u"] = cs.req_key.shape[0]
        return cs

    def _compile_ns(self, name: str, sel_list) -> CompiledNodeSelectors:
        cs = compile_node_selectors(
            sel_list, self.dic,
            min_t=self._caps.get(f"{name}_t", 2),
            min_s=self._caps.get(f"{name}_s", 4),
            min_v=self._caps.get(f"{name}_v", 4),
            min_u=self._caps.get(f"{name}_u", 2),
        )
        self._caps[f"{name}_t"] = cs.req_key.shape[1]
        self._caps[f"{name}_s"] = cs.req_key.shape[2]
        self._caps[f"{name}_v"] = cs.req_vals.shape[-1]
        self._caps[f"{name}_u"] = cs.req_key.shape[0]
        return cs

    def compile(self, pods: Sequence[v1.Pod], pad_to: Optional[int] = None) -> PodBatch:
        b_real = len(pods)
        b = pad_to if pad_to is not None else _pow2(b_real, 1)
        if b < b_real:
            raise ValueError(f"pad_to {b} < batch size {b_real}")
        enc, dic = self.enc, self.dic
        cfg = enc.cfg
        r = cfg.num_resource_dims

        valid = np.zeros(b, dtype=bool)
        request = np.zeros((b, r), dtype=np.int32)
        non_zero = np.zeros((b, 2), dtype=np.int32)
        ns = np.full(b, MISSING, dtype=np.int32)
        priority = np.zeros(b, dtype=np.int32)
        node_name_id = np.full(b, MISSING, dtype=np.int32)
        nominated_row = np.full(b, -1, dtype=np.int32)

        pl_cap = self._cap("pl", max((len(p.metadata.labels) for p in pods), default=0), 4)
        label_keys = np.full((b, pl_cap), MISSING, dtype=np.int32)
        label_vals = np.full((b, pl_cap), MISSING, dtype=np.int32)

        port_lists = [sorted(
            {(_PROTO_CODE.get(proto, 0) * 65536 + port, dic.intern(ip))
             for (ip, proto, port) in _pod_host_ports(p)}
        ) for p in pods]
        pp_cap = self._cap("pp", max((len(pl) for pl in port_lists), default=0), 2)
        ports = np.full((b, pp_cap), MISSING, dtype=np.int32)
        ports_ip = np.full((b, pp_cap), MISSING, dtype=np.int32)

        ci_cap = self._cap("ci", max((len(p.spec.containers) for p in pods), default=0), 2)
        image_ids = np.full((b, ci_cap), MISSING, dtype=np.int32)

        tt_cap = self._cap("tt", max((len(p.spec.tolerations) for p in pods), default=0), 2)
        tol_valid = np.zeros((b, tt_cap), dtype=bool)
        tol_key = np.full((b, tt_cap), MISSING, dtype=np.int32)
        tol_val = np.full((b, tt_cap), MISSING, dtype=np.int32)
        tol_op = np.zeros((b, tt_cap), dtype=np.int32)
        tol_effect = np.full((b, tt_cap), -1, dtype=np.int32)

        node_selectors: List[Optional[v1.LabelSelector]] = []
        node_affinities: List[Optional[v1.NodeSelector]] = []
        pref_terms: List[List[v1.PreferredSchedulingTerm]] = []
        tsc_lists: List[List[v1.TopologySpreadConstraint]] = []

        for i, pod in enumerate(pods):
            valid[i] = True
            request[i] = enc.pod_request_units(pod)
            non_zero[i] = enc.pod_non_zero_units(pod)
            ns[i] = dic.intern(pod.namespace)
            priority[i] = pod.spec.priority
            if pod.spec.node_name:
                node_name_id[i] = dic.intern(pod.spec.node_name)
            if pod.status.nominated_node_name:
                nominated_row[i] = enc.node_rows.get(
                    pod.status.nominated_node_name, -1
                )
            for j, (k, val) in enumerate(pod.metadata.labels.items()):
                label_keys[i, j] = dic.intern(k)
                label_vals[i, j] = dic.intern(val)
            for j, (code, ip_id) in enumerate(port_lists[i]):
                ports[i, j] = code
                ports_ip[i, j] = ip_id
            for j, c in enumerate(pod.spec.containers):
                if c.image:
                    image_ids[i, j] = dic.intern(c.image)
            for j, t in enumerate(pod.spec.tolerations):
                tol_valid[i, j] = True
                tol_key[i, j] = dic.intern(t.key) if t.key else MISSING
                tol_val[i, j] = dic.intern(t.value)
                tol_op[i, j] = (
                    TOL_OP_EXISTS if t.operator == v1.TOLERATION_OP_EXISTS else TOL_OP_EQUAL
                )
                tol_effect[i, j] = EFFECT_CODE.get(t.effect, -1) if t.effect else -1

            # nodeSelector: empty selector matches everything (matchLabels AND)
            node_selectors.append(
                v1.LabelSelector(match_labels=dict(pod.spec.node_selector))
            )
            aff = pod.spec.affinity
            na = aff.node_affinity if aff else None
            node_affinities.append(na.required if na else None)
            pref_terms.append(list(na.preferred) if na else [])
            tsc_lists.append(list(pod.spec.topology_spread_constraints))

        # pad rows: invalid pods get empty node selector (matches everything) so
        # padded rows never constrain anything; valid[] gates all results anyway.
        node_selectors += [v1.LabelSelector()] * (b - b_real)
        node_affinities += [None] * (b - b_real)
        pref_terms += [[]] * (b - b_real)
        tsc_lists += [[]] * (b - b_real)

        compiled_ns = self._compile_ls("nodesel", node_selectors)
        compiled_na = self._compile_ns("nodeaff", node_affinities)

        # preferred node-affinity terms
        pt_cap = self._cap("pt", max((len(t) for t in pref_terms), default=0), 1)
        s_cap = self._cap(
            "pt_s",
            max(
                (len(t.preference.match_expressions) + len(t.preference.match_fields)
                 for terms in pref_terms for t in terms),
                default=0,
            ),
            2,
        )
        v_cap = self._cap(
            "pt_v",
            max(
                (len(e.values)
                 for terms in pref_terms for t in terms
                 for e in list(t.preference.match_expressions) + list(t.preference.match_fields)),
                default=0,
            ),
            2,
        )
        pref_valid = np.zeros((b, pt_cap), dtype=bool)
        pref_weight = np.zeros((b, pt_cap), dtype=np.float32)
        pref_req_key = np.full((b, pt_cap, s_cap), MISSING, dtype=np.int32)
        pref_req_op = np.full((b, pt_cap, s_cap), sel.OP_PAD, dtype=np.int32)
        pref_req_vals = np.full((b, pt_cap, s_cap, v_cap), MISSING, dtype=np.int32)
        pref_req_num = np.full((b, pt_cap, s_cap), np.nan, dtype=np.float32)
        for i, terms in enumerate(pref_terms):
            for ti, term in enumerate(terms):
                reqs = list(term.preference.match_expressions)
                fields = [
                    v1.NodeSelectorRequirement(
                        key="metadata.name" if e.key in ("metadata.name", "name") else e.key,
                        operator=e.operator,
                        values=list(e.values),
                    )
                    for e in term.preference.match_fields
                ]
                reqs = reqs + fields
                # a preferred term with no requirements matches nothing (reference:
                # empty NodeSelectorTerm matches no objects)
                pref_valid[i, ti] = len(reqs) > 0
                pref_weight[i, ti] = float(term.weight)
                for j, e in enumerate(reqs):
                    pref_req_key[i, ti, j] = dic.intern(e.key)
                    pref_req_op[i, ti, j] = sel._OP_CODE[e.operator]
                    for k, val in enumerate(e.values):
                        pref_req_vals[i, ti, j, k] = dic.intern(val)
                    if e.values:
                        try:
                            pref_req_num[i, ti, j] = float(int(e.values[0]))
                        except ValueError:
                            pass

        # topology spread constraints
        c_cap = self._cap("tsc", max((len(t) for t in tsc_lists), default=0), 1)
        tsc_valid = np.zeros((b, c_cap), dtype=bool)
        tsc_key = np.full((b, c_cap), MISSING, dtype=np.int32)
        tsc_max_skew = np.ones((b, c_cap), dtype=np.int32)
        tsc_when = np.full((b, c_cap), -1, dtype=np.int32)
        tsc_min_domains = np.zeros((b, c_cap), dtype=np.int32)
        tsc_sel_list: List[Optional[v1.LabelSelector]] = [None] * (b * c_cap)
        for i, constraints in enumerate(tsc_lists):
            for ci, c in enumerate(constraints):
                tsc_valid[i, ci] = True
                tsc_key[i, ci] = self.enc.topo_slot(c.topology_key)
                tsc_max_skew[i, ci] = c.max_skew
                tsc_when[i, ci] = (
                    WHEN_DO_NOT_SCHEDULE
                    if c.when_unsatisfiable == v1.DO_NOT_SCHEDULE
                    else WHEN_SCHEDULE_ANYWAY
                )
                tsc_min_domains[i, ci] = c.min_domains or 0
                tsc_sel_list[i * c_cap + ci] = c.label_selector
        tsc_selectors = self._compile_ls("tsc_sel", tsc_sel_list)

        groups = {}
        for gname in AFFINITY_GROUPS:
            groups[gname] = self._compile_affinity_group(pods, b, gname)
        has_spread = bool(tsc_valid.any())
        group_present = tuple(
            name for name in AFFINITY_GROUPS if bool(groups[name].valid.any())
        )
        has_affinity = bool(group_present)  # derived: one source of truth
        # effective domain axis for THIS batch's spread keys (see the field
        # comment): pow2 of the largest used key's live domain count, with
        # headroom floor 8 so zone-churn (a 4th zone appearing) doesn't
        # recompile.  MISSING-keyed rows (padding) contribute nothing.
        tsc_domain_bucket = self._domain_bucket(tsc_key[tsc_valid])
        ipa_domain_bucket = self._domain_bucket(
            *(g.topo_key[g.valid] for g in groups.values())
        )

        return PodBatch(
            pods=list(pods),
            valid=valid, request=request, non_zero=non_zero, ns=ns,
            label_keys=label_keys, label_vals=label_vals, priority=priority,
            node_name_id=node_name_id, nominated_row=nominated_row,
            ports=ports, ports_ip=ports_ip, image_ids=image_ids,
            tol_valid=tol_valid, tol_key=tol_key, tol_val=tol_val,
            tol_op=tol_op, tol_effect=tol_effect,
            node_selector=compiled_ns, node_affinity=compiled_na,
            pref_valid=pref_valid, pref_weight=pref_weight,
            pref_req_key=pref_req_key, pref_req_op=pref_req_op,
            pref_req_vals=pref_req_vals, pref_req_num=pref_req_num,
            tsc_valid=tsc_valid, tsc_key=tsc_key, tsc_max_skew=tsc_max_skew,
            tsc_when=tsc_when, tsc_min_domains=tsc_min_domains,
            tsc_selectors=tsc_selectors,
            has_spread=has_spread, has_affinity=has_affinity,
            tsc_domain_bucket=tsc_domain_bucket,
            ipa_domain_bucket=ipa_domain_bucket,
            group_present=group_present,
            **groups,
        )

    # --- pod affinity ---------------------------------------------------------

    def _terms_of(self, pod: v1.Pod, group: str):
        aff = pod.spec.affinity
        if aff is None:
            return []
        pa = aff.pod_affinity if "anti" not in group else aff.pod_anti_affinity
        if pa is None:
            return []
        if group.startswith("req"):
            return [(t, 1.0) for t in pa.required]
        return [(wt.pod_affinity_term, float(wt.weight)) for wt in pa.preferred]

    def _resolve_namespaces(self, pod: v1.Pod, term: v1.PodAffinityTerm):
        """→ (ns_names, all_namespaces). Mirrors PreFilter namespace resolution:
        namespaces ∪ namespaceSelector matches; neither set → pod's own namespace;
        empty-but-set namespaceSelector selects every namespace."""
        names = set(term.namespaces)
        all_ns = False
        if term.namespace_selector is not None:
            if not term.namespace_selector.match_labels and not term.namespace_selector.match_expressions:
                all_ns = True
            else:
                for ns_name, labels in self.namespace_labels.items():
                    if match_label_selector(term.namespace_selector, labels):
                        names.add(ns_name)
        if not names and not all_ns:
            names = {pod.namespace}
        return sorted(names), all_ns

    def _domain_bucket(self, *slot_arrays) -> int:
        """pow2 bound on the live domain counts of the topo-key slots named
        by the given arrays, floor 8 (headroom so small-domain churn — a 4th
        zone appearing — doesn't recompile).  See PodBatch.tsc_domain_bucket."""
        d = 1
        for arr in slot_arrays:
            for slot in np.unique(arr):
                if 0 <= slot < len(self.enc.topo_value_maps):
                    d = max(d, len(self.enc.topo_value_maps[slot]))
        return _pow2(d, 8)

    def _compile_affinity_group(
        self, pods: Sequence[v1.Pod], b: int, group: str
    ) -> AffinityTermGroup:
        dic = self.dic
        term_lists = [self._terms_of(p, group) for p in pods]
        t_cap = self._cap(
            f"{group}_t", max((len(t) for t in term_lists), default=0), 1
        )
        resolved = [
            [self._resolve_namespaces(p, term) for (term, _w) in terms]
            for p, terms in zip(pods, term_lists)
        ]
        ns_cap = self._cap(
            f"{group}_ns",
            max((len(names) for rl in resolved for (names, _a) in rl), default=0), 1
        )
        valid = np.zeros((b, t_cap), dtype=bool)
        topo_key = np.full((b, t_cap), MISSING, dtype=np.int32)
        weight = np.zeros((b, t_cap), dtype=np.float32)
        ns_ids = np.full((b, t_cap, ns_cap), MISSING, dtype=np.int32)
        all_namespaces = np.zeros((b, t_cap), dtype=bool)
        sel_list: List[Optional[v1.LabelSelector]] = [None] * (b * t_cap)
        for i, terms in enumerate(term_lists):
            for ti, (term, w) in enumerate(terms):
                valid[i, ti] = True
                topo_key[i, ti] = self.enc.topo_slot(term.topology_key)
                weight[i, ti] = w
                names, all_ns = resolved[i][ti]
                all_namespaces[i, ti] = all_ns
                for k, name in enumerate(names):
                    ns_ids[i, ti, k] = dic.intern(name)
                sel_list[i * t_cap + ti] = term.label_selector
        return AffinityTermGroup(
            valid=valid, topo_key=topo_key, weight=weight, ns_ids=ns_ids,
            all_namespaces=all_namespaces,
            selectors=self._compile_ls(f"{group}_sel", sel_list),
        )


def identity_classes(batch: PodBatch):
    """Host-side exact-content pod classes over a compiled batch.

    Two pods share a class iff every compiled pod-row that feeds the
    filter/score planes is byte-identical — so their ``[N]`` plane rows are
    provably equal and the dense compute can run once per class
    (``batch_assign``'s dedup path) instead of once per pod.  The compiled
    selector structs are content-deduplicated at compile time, so comparing
    their per-pod ``index`` rows compares selector CONTENT.
    ``nominated_row`` is excluded on purpose: it steers host selection, not
    the planes.  Returns ``(class_of i32[B], rep_rows i32[C])`` with
    ``rep_rows[class_of[b]]`` the first batch row of b's class.

    Templated scheduler_perf workloads collapse to a handful of classes
    (measured C=2 at B=256 on the basic suites: one pod template plus the
    padding rows), which turns the ``[B, N]`` dense planes — 18s/batch at
    131k nodes on the 1-core CI host — into a ``[C, N]`` compute (0.26s).

    The result is memoized on the batch object: the router precheck
    (TPUScheduler.engine_choice), the dedup gate, and the extender callout
    dedup all consult it for the same compiled batch.
    """
    cached = getattr(batch, "_identity_classes_cache", None)
    if cached is not None:
        return cached
    b = batch.size

    def flat(a):
        return np.ascontiguousarray(np.asarray(a)).reshape(b, -1)

    cols = [
        flat(a) for a in (
            batch.valid, batch.request, batch.non_zero, batch.ns,
            batch.label_keys, batch.label_vals, batch.priority,
            batch.node_name_id, batch.ports, batch.ports_ip,
            batch.image_ids, batch.tol_valid, batch.tol_key, batch.tol_val,
            batch.tol_op, batch.tol_effect, batch.pref_valid,
            batch.pref_weight, batch.pref_req_key, batch.pref_req_op,
            batch.pref_req_vals, batch.pref_req_num, batch.tsc_valid,
            batch.tsc_key, batch.tsc_max_skew, batch.tsc_when,
            batch.tsc_min_domains,
            batch.node_selector.index, batch.node_affinity.index,
            batch.tsc_selectors.index,
        )
    ]
    for grp in (batch.req_affinity, batch.req_anti_affinity,
                batch.pref_affinity, batch.pref_anti_affinity):
        cols += [flat(grp.valid), flat(grp.topo_key), flat(grp.weight),
                 flat(grp.ns_ids), flat(grp.all_namespaces),
                 flat(grp.selectors.index)]
    blob = np.concatenate(cols, axis=1)
    seen: Dict[bytes, int] = {}
    class_of = np.zeros(b, dtype=np.int32)
    rep_rows: List[int] = []
    for i in range(b):
        key = blob[i].tobytes()
        c = seen.get(key)
        if c is None:
            c = seen[key] = len(rep_rows)
            rep_rows.append(i)
        class_of[i] = c
    out = (class_of, np.asarray(rep_rows, dtype=np.int32))
    try:
        batch._identity_classes_cache = out
    except (AttributeError, TypeError):
        pass  # frozen stand-ins just recompute
    return out


def _pod_host_ports(pod: v1.Pod):
    out = set()
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                out.add((p.host_ip or "0.0.0.0", p.protocol or "TCP", p.host_port))
    return out
