"""Host-side pod–pod conflict partitioner for the hybrid assignment engine.

The pre-round-6 dispatch heuristic was all-or-nothing: a batch whose
coupled-pod fraction exceeded ``coupled_fraction_threshold`` abandoned the
parallel batch engine for the greedy-sequential scan WHOLESALE — serializing
even the pods in that batch that interact with nothing.  This module builds
the actual interaction graph instead:

  * pod (anti)affinity: pod A interacts with pod B when any of A's four term
    groups matches B (``affinity_term_matches`` — selector + namespace
    resolution), in either direction (A's commit writes tables B's filter or
    score reads, or vice versa);
  * topology spread: A's constraint selector matches B in A's namespace
    (B's commit bumps A's count tables);
  * gang membership: same PodGroup (the all-or-nothing mask couples them).

Connected components of that graph are the true serialization units:
independent components and all uncoupled pods commit in parallel
batch_assign rounds; only genuinely coupled chains serialize — bounded by
COMPONENT size, not batch size (framework/runtime.py batch_assign).

Pods are deduplicated into identity CLASSES first (namespace + labels +
constraint signatures + gang): templated workloads collapse to a handful of
classes, so the pairwise matching is O(classes²) Python instead of O(B²).
A batch with more than ``class_cap`` distinct classes falls back to the
sound over-approximation (every coupled pod in one component — exactly the
old wholesale behavior after the dispatch router's threshold).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from ..api.labels import affinity_term_matches, match_label_selector
from ..state.affinity_index import _term_signature, _selector_signature


@dataclass
class ConflictInfo:
    """Per-pod component assignment over a compiled batch.

    comp  — i32[B]: component id (the smallest member pod index); every
            singleton (uncoupled or conflict-free) pod keeps its own index.
    multi — bool[B]: pod shares its component with ≥1 other pod — only these
            pods need any serialization in the engine.
    sizes — multi-component sizes (for the coupled_component_size histogram).
    exact — False when the class-cap fallback merged all coupled pods.
    single_class_reps — component root → representative pod, for multi
            components made of exactly ONE identity class with no gang
            membership.  TPUScheduler's parallel-safe relaxation inspects
            these reps against the live topology (engine_choice): a class
            whose only intra-class effects are used-node-mask-equivalent
            (required anti over singleton domains) or plane-uniform
            (affinity over a single live domain) commits in parallel
            auction rounds like plain pods.
    """

    comp: np.ndarray
    multi: np.ndarray
    sizes: List[int]
    exact: bool = True
    single_class_reps: Optional[dict] = None

    @property
    def max_multi(self) -> int:
        return max(self.sizes, default=0)


def _pod_terms(pod):
    """All four (anti)affinity term groups of a pod, flattened."""
    aff = pod.spec.affinity
    out = []
    if aff is not None:
        if aff.pod_affinity is not None:
            out += list(aff.pod_affinity.required)
            out += [wt.pod_affinity_term for wt in aff.pod_affinity.preferred]
        if aff.pod_anti_affinity is not None:
            out += list(aff.pod_anti_affinity.required)
            out += [wt.pod_affinity_term
                    for wt in aff.pod_anti_affinity.preferred]
    return out


def _class_key(pod, gang_id):
    terms = tuple(sorted(
        repr(_term_signature(t, pod.namespace)) for t in _pod_terms(pod)
    ))
    spreads = tuple(
        (c.topology_key, repr(_selector_signature(c.label_selector)))
        for c in pod.spec.topology_spread_constraints
    )
    return (
        pod.namespace,
        tuple(sorted(pod.metadata.labels.items())),
        terms,
        spreads,
        gang_id,
    )


class _UnionFind:
    def __init__(self, n: int):
        self.p = list(range(n))

    def find(self, x: int) -> int:
        while self.p[x] != x:
            self.p[x] = self.p[self.p[x]]
            x = self.p[x]
        return x

    def union(self, a: int, b: int):
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.p[max(ra, rb)] = min(ra, rb)


def _interacts(a, b, namespace_labels) -> bool:
    """Does placing a pod of class-rep ``a`` affect class-rep ``b``'s
    filter/score planes (or vice versa)?  Symmetric by construction of the
    caller (checked both ways)."""
    for term in _pod_terms(a):
        if affinity_term_matches(term, a, b, namespace_labels):
            return True
    for c in a.spec.topology_spread_constraints:
        if b.namespace == a.namespace and match_label_selector(
                c.label_selector, b.metadata.labels):
            return True
    return False


def conflict_components(pods, size: int, namespace_labels=None,
                        gang_of=None, class_cap: int = 64) -> ConflictInfo:
    """Partition a batch's pods into interaction components.

    ``pods`` — the batch's real pods (≤ size); padding rows get singleton
    components.  ``gang_of`` — optional pod → gang-id callable (defaults to
    the POD_GROUP_LABEL label).
    """
    comp = np.arange(size, dtype=np.int32)
    multi = np.zeros(size, dtype=bool)
    if not pods:
        return ConflictInfo(comp=comp, multi=multi, sizes=[])
    if gang_of is None:
        from ..gang import POD_GROUP_LABEL

        def gang_of(p):
            return p.metadata.labels.get(POD_GROUP_LABEL)

    keys = [_class_key(p, gang_of(p)) for p in pods]
    class_of: dict = {}
    members: List[List[int]] = []
    reps = []
    for i, k in enumerate(keys):
        c = class_of.get(k)
        if c is None:
            c = class_of[k] = len(members)
            members.append([])
            reps.append(pods[i])
        members[c].append(i)
    k_classes = len(members)

    coupled = [
        bool(_pod_terms(r) or r.spec.topology_spread_constraints
             or gang_of(r) is not None)
        for r in reps
    ]
    if k_classes > class_cap:
        # sound over-approximation: all coupled pods one component (the
        # router's threshold then sends the batch to the scan — the exact
        # pre-partitioner behavior)
        idxs = [i for c, m in zip(coupled, members) if c for i in m]
        if len(idxs) >= 2:
            root = min(idxs)
            for i in idxs:
                comp[i] = root
                multi[i] = True
        return ConflictInfo(comp=comp, multi=multi,
                            sizes=[len(idxs)] if len(idxs) >= 2 else [],
                            exact=False)

    uf = _UnionFind(k_classes)
    self_edge = [False] * k_classes
    for a in range(k_classes):
        if not coupled[a]:
            continue
        for b2 in range(k_classes):
            hit = (
                (gang_of(reps[a]) is not None
                 and gang_of(reps[a]) == gang_of(reps[b2]))
                or _interacts(reps[a], reps[b2], namespace_labels)
            )
            if not hit:
                continue
            if a == b2:
                self_edge[a] = True
            else:
                uf.union(a, b2)

    # class-component → pod indices (a class joins a multi component when it
    # is edge-connected to another class, or self-interacts with ≥2 pods)
    groups: dict = {}
    for c in range(k_classes):
        root = uf.find(c)
        groups.setdefault(root, []).append(c)
    sizes: List[int] = []
    single_class_reps: dict = {}
    for root, classes in groups.items():
        idxs = [i for c in classes for i in members[c]]
        linked = len(classes) > 1 or any(self_edge[c] for c in classes)
        if linked and len(idxs) >= 2:
            rep = min(idxs)
            for i in idxs:
                comp[i] = rep
                multi[i] = True
            sizes.append(len(idxs))
            if len(classes) == 1 and gang_of(reps[classes[0]]) is None:
                single_class_reps[rep] = reps[classes[0]]
    return ConflictInfo(comp=comp, multi=multi, sizes=sizes,
                        single_class_reps=single_class_reps)
