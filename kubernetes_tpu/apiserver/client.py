"""HTTP client adapter: the Reflector's (list, watch) contract over the wire.

Reference: client-go rest.Client + tools/cache ListerWatcher — LIST returns
(objects, resourceVersion), WATCH streams ordered events from that rv.  A
Reflector(HTTPApiClient(url), "Pod") therefore runs list+watch over real
HTTP exactly as it does over the in-process store.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.request
from typing import Callable, List, Optional, Tuple

from ..api import wire
from ..api.scheme import Scheme, default_scheme
from ..chaos.retry import backoff_delay
from ..metrics import scheduler_metrics as m
from ..sim.store import ERROR, WatchEvent
from .server import resource_of

# retryable statuses (client-go rest/request.go:927 retries on 429 +
# transient 5xx, reading Retry-After for the wait)
RETRYABLE_CODES = (429, 500, 503)


class HTTPApiClient:
    def __init__(self, base_url: str, scheme: Optional[Scheme] = None,
                 user: str = "", groups: tuple = (), max_retries: int = 4,
                 retry_backoff: float = 0.05, retry_backoff_max: float = 2.0,
                 jitter_seed: int = 0, codec: str = "wire"):
        self.base_url = base_url.rstrip("/")
        self.scheme = scheme or default_scheme()
        self.user = user
        self.groups = tuple(groups)
        # preferred wire codec, sent as the Accept header; the response's
        # Content-Type decides the actual decode (negotiation is the
        # server's call — an old server answering JSON still works, and
        # errors are always JSON Status bodies), so callers never see the
        # format: lists return objects, watches return WatchEvents either
        # way.  "json" opts out (legacy servers, debugging with curl).
        self.codec = codec if codec in ("wire", "json") else "wire"
        self._watch_threads: List[threading.Thread] = []
        self._stopped = False
        # retrying transport: 429/500/503 are resent after honoring the
        # server's Retry-After (floor) or jittered exponential backoff;
        # other statuses surface to the caller unchanged
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        self.retry_backoff_max = retry_backoff_max
        self._retry_rng = random.Random(jitter_seed)

    # --- url plumbing -------------------------------------------------------

    def _prefix(self, kind: str) -> str:
        gv = self.scheme.gv_of(self._type_of(kind))
        group, version = gv if gv else ("", "v1")
        return (f"/apis/{group}/{version}" if group else f"/api/{version}")

    def _type_of(self, kind: str):
        entry = self.scheme.kind_types().get(kind)
        if entry is None:
            raise KeyError(kind)
        return entry[2]

    def _url(self, kind: str, namespace: str = "", name: str = "",
             query: str = "") -> str:
        path = self._prefix(kind)
        if namespace:
            path += f"/namespaces/{namespace}"
        # CRD-minted types declare their REST plural (spec.names.plural);
        # built-ins derive it from the kind name
        resource = getattr(self._type_of(kind), "plural", "") \
            or resource_of(kind)
        path += f"/{resource}"
        if name:
            path += f"/{name}"
        return self.base_url + path + (f"?{query}" if query else "")

    def _request(self, method: str, url: str, body: Optional[dict] = None):
        if body is None:
            data = None
        elif self.codec == "wire":
            data = wire.wire_encode(body)
        else:
            data = json.dumps(body).encode()
        attempt = 0
        while True:
            req = urllib.request.Request(url, data=data, method=method)
            req.add_header("Content-Type",
                           wire.content_type_for(self.codec)
                           if data is not None else "application/json")
            req.add_header("Accept", wire.content_type_for(self.codec))
            if self.user:
                req.add_header("X-Remote-User", self.user)
            if self.groups:
                req.add_header("X-Remote-Group", ",".join(self.groups))
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    raw = resp.read() or b"{}"
                    # decode what the server actually sent: a wire doc
                    # decodes to the same manifest dict json would carry
                    # (the round-trip parity contract), so callers are
                    # codec-blind from here on
                    if wire.is_wire(raw):
                        return wire.wire_decode(raw)
                    return json.loads(raw)
            except urllib.error.HTTPError as e:  # type: ignore[attr-defined]
                if e.code not in RETRYABLE_CODES or attempt >= self.max_retries:
                    raise
                m.client_request_retries.inc((str(e.code),))
                # Retry-After is a FLOOR (the server's load-shedding hint,
                # APF filters); without one, jittered exponential backoff.
                # Safe to resend even non-idempotent verbs: a shed request
                # (429/503) or handler-refused write never reached storage.
                try:
                    retry_after = float(e.headers.get("Retry-After") or 0.0)
                except (TypeError, ValueError):
                    retry_after = 0.0
                time.sleep(backoff_delay(
                    attempt, self.retry_backoff, self.retry_backoff_max,
                    self._retry_rng, floor=retry_after))
                attempt += 1

    # --- the ListerWatcher contract ----------------------------------------

    def _decode_item(self, item):
        """One LIST item: a binary list embeds each object as its
        self-contained wire doc (bytes — decoded by the native fast path),
        a JSON list carries manifest dicts."""
        if isinstance(item, (bytes, bytearray)):
            return wire.decode_object(bytes(item), self.scheme)
        return self.scheme.decode(item)

    def list(self, kind: str) -> Tuple[List[object], int]:
        payload = self._request("GET", self._url(kind))
        rv = int(payload.get("metadata", {}).get("resourceVersion", "0"))
        objs = [self._decode_item(m) for m in payload.get("items", [])]
        return objs, rv

    def list_page(self, kind: str, limit: int = 0,
                  continue_: Optional[str] = None
                  ) -> Tuple[List[object], int, str]:
        """One rv-consistent page: (objects, rv, continue token; '' at the
        end).  The server pins every page of a walk to the first page's rv
        (watch-cache pagination); an expired token surfaces as HTTPError
        410 — the caller restarts its walk from a fresh LIST (the
        reflector's paged-relist retry loop does)."""
        query = f"limit={limit}" if limit else ""
        if continue_:
            query += f"&continue={continue_}" if query \
                else f"continue={continue_}"
        payload = self._request("GET", self._url(kind, query=query))
        meta = payload.get("metadata", {})
        rv = int(meta.get("resourceVersion", "0"))
        objs = [self._decode_item(m) for m in payload.get("items", [])]
        return objs, rv, meta.get("continue", "")

    def for_kind(self, kind: str) -> "_KindClient":
        """A (list, watch) view of ONE kind — the shape Reflector expects.
        In-process stores multiplex kinds on one watch; HTTP serves one
        resource per stream, so the per-kind view bridges the two."""
        return _KindClient(self, kind)

    def watch_kind(self, kind: str, handler: Callable[[WatchEvent], None],
                   since_rv: int = 0, timeout_seconds: float = 30,
                   on_bookmark: Optional[Callable[[int], None]] = None,
                   on_error: Optional[Callable[[Optional[Exception]], None]] = None):
        """Stream watch events to ``handler``.  Bookmarks are requested
        (allowWatchBookmarks, reflector.go's default) and consumed HERE:
        they carry no object, only a fresh resourceVersion, which is handed
        to ``on_bookmark`` (e.g. a Reflector advancing its restart point)
        rather than surfaced as a WatchEvent.

        ``on_error`` is the stream-lifecycle callback, invoked from the
        watch thread with the failure when the stream errors (transport
        exception, or WatchDropped for an in-band ERROR event — rv
        continuity broken, the consumer must RELIST) and with None when the
        stream simply ends at the server's timeoutSeconds (rv continuity
        intact — a cheap re-watch from last_rv suffices; reflector.go's
        ListAndWatch restart makes the same distinction).  Without it,
        transport errors raise in the watch thread (the pre-chaos
        behavior)."""
        stop = threading.Event()

        def run():
            url = self._url(
                kind,
                query=f"watch=true&resourceVersion={since_rv}"
                      f"&timeoutSeconds={timeout_seconds}"
                      f"&allowWatchBookmarks=true",
            )
            req = urllib.request.Request(url)
            req.add_header("Accept", wire.content_type_for(self.codec))
            if self.user:
                req.add_header("X-Remote-User", self.user)
            if self.groups:
                req.add_header("X-Remote-Group", ",".join(self.groups))

            def stream_error(message: str):
                # in-band stream failure (watch protocol ERROR, e.g. 410
                # Gone / chaos drop): rv continuity is broken — the
                # consumer must relist
                if on_error is not None and not stop.is_set():
                    from ..chaos.faults import WatchDropped

                    on_error(WatchDropped(message))

            try:
                with urllib.request.urlopen(req, timeout=timeout_seconds + 5) as resp:
                    ct = resp.headers.get("Content-Type") or ""
                    if wire.codec_of_content_type(ct) == "wire":
                        # binary framing: the rv rides the frame header and
                        # the object doc takes the native decoder
                        while not stop.is_set():
                            frame = wire.read_watch_frame(resp)
                            if frame is None:
                                break
                            ev_type, rv, doc = frame
                            if ev_type == ERROR:
                                stream_error(str(
                                    (wire.wire_decode(doc) or {})
                                    .get("message", "watch ERROR")))
                                return
                            if ev_type == "BOOKMARK":
                                if on_bookmark is not None:
                                    on_bookmark(rv)
                                continue
                            obj = wire.decode_object(doc, self.scheme)
                            handler(WatchEvent(ev_type, kind, obj, rv))
                    else:
                        for raw in resp:
                            if stop.is_set():
                                break
                            line = raw.strip()
                            if not line:
                                continue
                            ev = json.loads(line)
                            if ev["type"] == ERROR:
                                stream_error(str(
                                    (ev.get("object") or {})
                                    .get("message", "watch ERROR")))
                                return
                            rv = int((ev["object"].get("metadata") or {})
                                     .get("resourceVersion", "0"))
                            if ev["type"] == "BOOKMARK":
                                if on_bookmark is not None:
                                    on_bookmark(rv)
                                continue
                            obj = self.scheme.decode(ev["object"])
                            handler(WatchEvent(ev["type"], kind, obj, rv))
            except Exception as e:
                if not stop.is_set():
                    if on_error is not None:
                        on_error(e)
                        return
                    raise
                return
            # clean end of stream (server's timeoutSeconds elapsed): None
            # tells the reflector rv continuity held — re-watch from
            # last_rv, no relist needed
            if on_error is not None and not stop.is_set():
                on_error(None)
        t = threading.Thread(target=run, daemon=True)
        t.start()
        # prune finished threads while appending: a relisting reflector
        # re-invokes watch_kind on every stream cycle, and an unbounded
        # list of dead Thread objects would leak over a long chaos soak
        self._watch_threads = [
            w for w in self._watch_threads if w.is_alive()]
        self._watch_threads.append(t)

        def unwatch():
            stop.set()
        return unwatch

    # --- CRUD convenience ----------------------------------------------------

    def get(self, kind: str, namespace: str, name: str):
        try:
            return self.scheme.decode(
                self._request("GET", self._url(kind, namespace, name)))
        except urllib.error.HTTPError as e:  # type: ignore[attr-defined]
            if e.code == 404:
                return None
            raise

    def create(self, kind: str, obj) -> dict:
        from ..api.serialize import to_manifest

        ns = "" if kind in _CLUSTER_SCOPED else obj.metadata.namespace
        return self._request("POST", self._url(kind, ns),
                             to_manifest(obj, self.scheme))

    def update(self, kind: str, obj) -> dict:
        from ..api.serialize import to_manifest

        ns = "" if kind in _CLUSTER_SCOPED else obj.metadata.namespace
        return self._request("PUT", self._url(kind, ns, obj.metadata.name),
                             to_manifest(obj, self.scheme))

    def delete(self, kind: str, namespace: str, name: str) -> dict:
        return self._request("DELETE", self._url(kind, namespace, name))

    def bind_pod(self, namespace: str, name: str, node_name: str) -> dict:
        url = (self.base_url + f"/api/v1/namespaces/{namespace}"
               f"/pods/{name}/binding")
        return self._request("POST", url, {
            "apiVersion": "v1", "kind": "Binding",
            "metadata": {"name": name},
            "target": {"kind": "Node", "name": node_name},
        })

    def evict_pod(self, namespace: str, name: str) -> dict:
        """POST the eviction subresource — the SERVER-side gate decides
        (PDB check + budget drain under the server's own lock), so remote
        callers never race it with a client-local check-then-delete.
        Raises HTTPError 429 when the disruption budget refuses (after
        the transport's retries), 404 when the pod is already gone."""
        url = (self.base_url + f"/api/v1/namespaces/{namespace}"
               f"/pods/{name}/eviction")
        return self._request("POST", url, {
            "apiVersion": "policy/v1", "kind": "Eviction",
            "metadata": {"name": name, "namespace": namespace},
        })


class HTTPStoreFacade:
    """ObjectStore-shaped facade over HTTPApiClient — the CRUD subset
    kubectl and other store-driven callers use, so they run unchanged
    against the HTTP apiserver (kubectl --server)."""

    def __init__(self, client: HTTPApiClient):
        self._client = client

    @property
    def CLUSTER_SCOPED(self):  # noqa: N802 — mirrors ObjectStore's attr
        return _CLUSTER_SCOPED

    def list(self, kind: str):
        try:
            return self._client.list(kind)
        except KeyError:  # kind not served: the store returns empty, not 404
            return [], 0

    def get(self, kind: str, namespace: str, name: str):
        if kind in _CLUSTER_SCOPED:
            namespace = ""
        return self._client.get(kind, namespace, name)

    def create(self, kind: str, obj) -> int:
        reply = self._client.create(kind, obj)
        return int((reply.get("metadata") or {}).get("resourceVersion", "0"))

    def update(self, kind: str, obj, expected_rv=None) -> int:
        """``expected_rv`` is accepted for ObjectStore signature parity
        (LeaderElector's CAS renew passes it): over HTTP the CAS rides the
        PUT body's metadata.resourceVersion — the server 409s when it is
        stale — so the kwarg only needs to be stamped into the object."""
        if expected_rv is not None:
            obj.metadata.resource_version = expected_rv
        reply = self._client.update(kind, obj)
        return int((reply.get("metadata") or {}).get("resourceVersion", "0"))

    def delete(self, kind: str, namespace: str, name: str):
        if kind in _CLUSTER_SCOPED:
            namespace = ""
        try:
            # DELETE returns the deleted object's final state (one round
            # trip, no get-then-delete TOCTOU window)
            return self._client.scheme.decode(
                self._client.delete(kind, namespace, name))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def evict_pod(self, namespace: str, name: str) -> dict:
        """Server-side eviction gate (POST pods/{name}/eviction) — remote
        drains MUST use this instead of a client-local PDB check + delete,
        which would race the server's budget lock."""
        return self._client.evict_pod(namespace, name)

    def watch(self, handler, since_rv: int = 0):
        raise NotImplementedError(
            "HTTP watch is per-resource: use HTTPApiClient.watch_kind / "
            "for_kind (one stream per kind)")


class _KindClient:
    """Reflector-compatible (list, watch) facade over one HTTP resource."""

    CLUSTER_SCOPED = None  # filled below (Reflector reads the class attr)

    def __init__(self, client: HTTPApiClient, kind: str):
        self._client = client
        self._kind = kind

    def list(self, kind: str):
        return self._client.list(kind)

    def list_page(self, kind: str, limit: int = 0, continue_=None):
        return self._client.list_page(kind, limit=limit, continue_=continue_)

    def watch(self, handler, since_rv: int = 0, on_bookmark=None,
              on_error=None):
        return self._client.watch_kind(self._kind, handler, since_rv=since_rv,
                                       on_bookmark=on_bookmark,
                                       on_error=on_error)


import urllib.error  # noqa: E402  (used in get())

from ..sim.store import ObjectStore as _OS  # noqa: E402

_CLUSTER_SCOPED = _OS.CLUSTER_SCOPED
_KindClient.CLUSTER_SCOPED = _OS.CLUSTER_SCOPED
