"""HTTP API server over the object store.

Reference surface: staging/src/k8s.io/apiserver (handlers/rest.go GET/LIST/
POST/PUT/PATCH/DELETE + watch streaming) and pkg/registry/core/pod/rest
(the pods/{name}/binding subresource).  The storage behind it is the
resourceVersion'd ObjectStore (sim/store.py), so LIST+WATCH semantics —
consistent snapshot rv, ordered events after it — come from the same code
path the in-process clients use.

Served paths:
  /api/v1/{resource}[/{name}]                        (core, cluster-scoped)
  /api/v1/namespaces/{ns}/{resource}[/{name}]        (core, namespaced)
  /apis/{group}/{version}/...                        (named groups)
  /api/v1/namespaces/{ns}/pods/{name}/binding        (POST, binding)
  /healthz /readyz /api /apis                        (discovery + health)

Query params: ``watch=true`` + ``resourceVersion`` stream JSON-lines watch
events (chunked); ``labelSelector`` (equality terms) and ``fieldSelector``
(``spec.nodeName``/``metadata.name``) filter lists, mirroring the selectors
kubelets and controllers actually use.

Request chain (the reference generic server's handler chain shape,
staging/src/k8s.io/apiserver/pkg/server/config.go:816 — authn → authz →
admission → registry):

- Authentication: a pluggable ``authenticators`` list, each
  ``(headers) -> Optional[UserInfo]``; the first non-None wins, and when
  authenticators are configured an unidentified request gets 401.
  ``header_authenticator`` implements the reference's request-header authn
  (X-Remote-User / X-Remote-Group); ``token_authenticator`` the static
  token file (Authorization: Bearer).
- Authorization: a pluggable ``authorizer(user, verb, resource,
  namespace) -> bool``.  When the callable also accepts the keyword
  attributes ``name``/``api_group``/``groups`` (detected once by
  signature probe), the server passes them — ``auth.rbac.RBACAuthorizer``
  is the full policy evaluator over stored Role/ClusterRole objects; a
  legacy 4-positional lambda keeps working.
- Admission: ``mutating_admission`` then ``validating_admission`` hook
  lists run on every write after decode, before storage — each mutating
  hook is ``(operation, kind, obj, user) -> obj | None`` (None keeps the
  object), each validating hook returns an error string to deny (403
  AdmissionDenied) or None to admit.  The reference's webhook/plugin
  chain reduced to in-process hook points.
"""

from __future__ import annotations

import inspect
import json
import queue
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..api import wire
from ..api.scheme import Scheme, SchemeError, default_scheme
from ..api.serialize import to_manifest
from ..metrics import registry as metrics_registry
from ..metrics import scheduler_metrics as m
from ..sim.store import (
    ADDED,
    DELETED,
    ERROR,
    MODIFIED,
    ObjectStore,
    QuotaExceeded,
    StaleResourceVersion,
)
from ..sim.watchcache import TooOldResourceVersion, WatchCache
from .flowcontrol import FlowController, RequestRejected


class UserInfo:
    """Authenticated request identity (authentication/user.Info analog)."""

    __slots__ = ("name", "groups")

    def __init__(self, name: str, groups: Tuple[str, ...] = ()):
        self.name = name
        self.groups = tuple(groups)

    def __repr__(self):
        return f"UserInfo({self.name!r}, groups={self.groups!r})"


def header_authenticator(headers) -> Optional[UserInfo]:
    """Request-header authentication (the reference's front-proxy authn:
    --requestheader-username-headers): X-Remote-User (+ X-Remote-Group)."""
    user = headers.get("X-Remote-User")
    if not user:
        return None
    groups = tuple(
        g.strip() for g in (headers.get("X-Remote-Group") or "").split(",")
        if g.strip()
    )
    return UserInfo(user, groups)


def token_authenticator(tokens: Dict[str, str]):
    """Static bearer-token authentication (token-file authn): token →
    username map; returns an authenticator callable."""

    def authenticate(headers) -> Optional[UserInfo]:
        auth = headers.get("Authorization") or ""
        if not auth.startswith("Bearer "):
            return None
        user = tokens.get(auth[len("Bearer "):].strip())
        return UserInfo(user) if user else None

    return authenticate


def resource_of(kind: str) -> str:
    """Kind → REST resource name (lowercase plural, apimachinery style)."""
    low = kind.lower()
    if low.endswith("ss"):  # StorageClass → storageclasses
        return low + "es"
    if low.endswith("s"):  # Endpoints → endpoints
        return low
    return low + "s"


def _match_label_selector(param: str, obj) -> bool:
    labels = getattr(obj.metadata, "labels", {}) or {}
    for term in param.split(","):
        term = term.strip()
        if not term:
            continue
        if "!=" in term:
            k, v = term.split("!=", 1)
            if labels.get(k.strip()) == v.strip():
                return False
        elif "=" in term:
            k, v = term.split("=", 1)
            if labels.get(k.strip()) != v.strip():
                return False
        else:  # bare key: exists
            if term not in labels:
                return False
    return True


def _match_field_selector(param: str, obj) -> bool:
    for term in param.split(","):
        term = term.strip()
        if not term or "=" not in term:
            continue
        k, v = term.split("=", 1)
        k = k.strip().removeprefix("==")
        if k == "metadata.name":
            if obj.metadata.name != v:
                return False
        elif k == "metadata.namespace":
            if getattr(obj.metadata, "namespace", "") != v:
                return False
        elif k == "spec.nodeName":
            if getattr(obj.spec, "node_name", "") != v:
                return False
    return True


class APIServer:
    """Thread-per-connection HTTP front end for an ObjectStore."""

    def __init__(
        self,
        store: Optional[ObjectStore] = None,
        scheme: Optional[Scheme] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        authorizer: Optional[Callable[[str, str, str, str], bool]] = None,
        authenticators: Optional[list] = None,
        mutating_admission: Optional[list] = None,
        validating_admission: Optional[list] = None,
        fault_injector=None,
        readyz=None,
        watch_cache="auto",
        flow_control="auto",
        tracer=None,
        replica=None,
        follower_wait_seconds: float = 1.0,
    ):
        # replication follower front end (sim/replication.FollowerReplica):
        # when set, this server serves the replica's store + watch cache and
        # rv-gates every read against the replication watermark — a list or
        # watch at rv ≤ applied_rv serves locally, above it waits at most
        # ``follower_wait_seconds`` then 504s (the client retries or goes to
        # the leader), and writes answer 503 until promotion flips the role.
        self.replica = replica
        self.follower_wait_seconds = follower_wait_seconds
        if store is None:
            if replica is None:
                raise ValueError("APIServer needs a store or a replica")
            store = replica.store
        self.store = store
        # readiness source (component_base.healthz.Readyz or None): when
        # set, /readyz serves 503 + per-component rebuild progress while a
        # cold-start reconstruction is in flight — a recovering replica
        # never takes traffic mid-rebuild.  /healthz and /livez stay 200
        # (the process is alive either way).
        self.readyz = readyz
        self.scheme = scheme or default_scheme()
        self.authorizer = authorizer
        # chaos hook (chaos.faults.FaultSchedule-shaped, or None): write
        # verbs may be shed with 429/500/503 + Retry-After BEFORE reaching
        # the store (the APF load-shedding surface), and watch streams may
        # be cut with an in-band ERROR event.  Attach the schedule HERE for
        # HTTP actors (not also to the store — that would double-inject).
        self.fault = fault_injector
        # authn chain: first non-None UserInfo wins; configured-but-failed
        # authentication is 401 (no anonymous fallthrough)
        self.authenticators = list(authenticators or [])
        # admission hook points (mutating then validating), run on writes
        self.mutating_admission = list(mutating_admission or [])
        self.validating_admission = list(validating_admission or [])
        # resource name → kind, rebuilt whenever the scheme's generation
        # moves (the dynamic-kind registrar adds/removes CRD kinds at
        # runtime; a generation compare per route() is one int read, so
        # built-in traffic pays nothing for the dynamism)
        self.kinds_by_resource: Dict[str, str] = {}
        self._resource_by_kind: Dict[str, str] = {}
        self._group_by_kind: Dict[str, str] = {}
        self._kinds_generation = -1
        self._kinds_lock = threading.Lock()
        self._refresh_kinds()
        # authorizer capability probe (once, at wiring time): the RBAC
        # authorizer takes the richer (name, api_group, groups) keywords;
        # a legacy 4-positional callable still works unchanged
        self._authz_rich = False
        if authorizer is not None:
            try:
                params = inspect.signature(authorizer).parameters
                self._authz_rich = all(
                    k in params for k in ("name", "api_group", "groups"))
            except (TypeError, ValueError):
                self._authz_rich = False
        # the shared eviction gate behind POST pods/{name}/eviction
        # (pkg/registry/core/pod eviction REST analog): PDB-consulting,
        # 429 TooManyRequests when budget is exhausted
        from ..descheduler.evictions import EvictionAPI

        self.evictions = EvictionAPI(store)
        # versioned watch cache (sim/watchcache.py): lists, pagination, and
        # since_rv watch replays are served from it WITHOUT the store lock;
        # "auto" (default) builds one — pass False to read the store
        # directly (the pre-cache behavior), or a WatchCache to share one
        # across servers.
        if replica is not None and watch_cache == "auto":
            # the replica already feeds its own cache (bookmark_gate
            # clamped to the replication watermark) — never build a second
            self.watch_cache = replica.watch_cache
            self._owns_watch_cache = False
        elif watch_cache == "auto" or watch_cache is True:
            self.watch_cache: Optional[WatchCache] = WatchCache(
                store, scheme=self.scheme)
            self._owns_watch_cache = True
        else:
            self.watch_cache = watch_cache or None
            # a shared cache outlives this server: stop() must not close
            # it out from under the other servers reading it
            self._owns_watch_cache = False
        # APF-style flow control (apiserver/flowcontrol.py): split
        # mutating/readonly inflight pools + per-user fairness queues;
        # every resource request holds a seat for its duration (watches:
        # handshake only).  "auto" builds generous defaults; False
        # disables; a FlowController tunes the pools (flood tests do).
        if flow_control == "auto":
            # a follower's mutating pool shrinks to near-zero (every write
            # is a 503 until promotion) and its readonly pool widens — the
            # whole point of a read replica is read capacity
            self.flow: Optional[FlowController] = FlowController.for_role(
                "follower" if replica is not None else "leader")
        else:
            self.flow = flow_control or None
        if replica is not None:
            m.apiserver_role.set(1.0, (replica.name, replica.role))
        # span tracer (component_base/trace.py): one apiserver_request span
        # per resource request with an apf_wait child when the flow-control
        # queue actually held it.  Health/discovery/metrics probes are not
        # spanned (they are exempt from flow control for the same reason).
        # NOOP by default: a disabled tracer costs one attribute read.
        from ..component_base.trace import NOOP_TRACER

        self.tracer = tracer or NOOP_TRACER
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "APIServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)
        if self.watch_cache is not None and self._owns_watch_cache:
            self.watch_cache.close()

    # --- path handling ------------------------------------------------------

    def _refresh_kinds(self) -> None:
        """Rebuild the resource↔kind routing maps when the scheme's
        generation moved (a CRD installed or uninstalled a kind).  The
        common case is one int compare; the rebuild itself is a full
        replace under a small lock so a racing request never reads a
        half-built map.  CRD-minted types declare their REST plural
        (``plural`` class attr, from spec.names.plural); built-ins derive
        it from the kind name as before."""
        gen = self.scheme.generation
        if gen == self._kinds_generation:
            return
        with self._kinds_lock:
            if gen == self._kinds_generation:
                return
            by_resource: Dict[str, str] = {}
            by_kind: Dict[str, str] = {}
            group_of: Dict[str, str] = {}
            for kind, (group, _version, typ) in \
                    self.scheme.kind_types().items():
                res = getattr(typ, "plural", "") or resource_of(kind)
                by_resource[res] = kind
                by_kind[kind] = res
                group_of[kind] = group
            self.kinds_by_resource = by_resource
            self._resource_by_kind = by_kind
            self._group_by_kind = group_of
            self._kinds_generation = gen

    def serves_kind(self, kind: str) -> bool:
        """True while ``kind`` is a served (routable) kind.  Open watch
        streams poll this each loop so a CRD deletion terminates them
        in-band instead of leaving readers on a dead resource."""
        self._refresh_kinds()
        return kind in self._resource_by_kind

    def resource_for(self, kind: str) -> str:
        """Kind → its served REST resource name (authz attribute)."""
        return self._resource_by_kind.get(kind) or resource_of(kind)

    def group_for(self, kind: str) -> str:
        return self._group_by_kind.get(kind, "")

    def route(self, path: str) -> Optional[Tuple[str, str, str, str]]:
        """path → (kind, namespace, name, subresource); '' for absent parts.

        None for non-resource paths (health/discovery handled elsewhere)."""
        self._refresh_kinds()
        parts = [p for p in path.split("/") if p]
        if not parts:
            return None
        if parts[0] == "api":
            if len(parts) < 3 or parts[1] != "v1":
                return None
            rest = parts[2:]
        elif parts[0] == "apis":
            if len(parts) < 4:
                return None
            rest = parts[3:]
        else:
            return None
        ns = ""
        if rest[0] == "namespaces" and len(rest) >= 3:
            ns = rest[1]
            rest = rest[2:]
        elif rest[0] == "namespaces" and len(rest) == 2:
            # /api/v1/namespaces/{name} — the Namespace object itself
            return ("Namespace", "", rest[1], "")
        elif rest[0] == "namespaces":
            return ("Namespace", "", "", "")
        kind = self.kinds_by_resource.get(rest[0])
        if kind is None:
            return None
        name = rest[1] if len(rest) > 1 else ""
        sub = rest[2] if len(rest) > 2 else ""
        return (kind, ns, name, sub)


def _make_handler(api: APIServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "kubernetes-tpu-apiserver"

        def log_message(self, *a):  # quiet
            pass

        # --- plumbing -------------------------------------------------------

        def _send_bytes(self, code: int, body: bytes, content_type: str,
                        headers=()):
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, payload: dict, headers=()):
            self._send_bytes(code, json.dumps(payload).encode(),
                             "application/json", headers=headers)

        def _codec(self) -> str:
            """Negotiate the response codec from the Accept header (the
            protobuf-negotiation analog: runtime/negotiate.go) and count
            the request under it.  Call once per resource request."""
            codec = wire.negotiate_codec(self.headers.get("Accept"))
            m.apiserver_wire_requests.inc((codec,))
            return codec

        def _send_object(self, code: int, obj, codec: str, headers=()):
            """One object in the negotiated codec, served from its
            encode-once payload (api.wire.payload_for): the bytes a write
            response sends are the SAME bytes every watcher was fanned —
            encoded once per codec per write."""
            p = wire.payload_for(obj, api.scheme)
            self._send_bytes(code, p.bytes_for(codec),
                             wire.content_type_for(codec), headers=headers)

        def _status_err(self, code: int, reason: str, message: str,
                        headers=()):
            self._send_json(code, {
                "kind": "Status", "apiVersion": "v1", "status": "Failure",
                "reason": reason, "message": message, "code": code,
            }, headers=headers)

        def _shed(self, verb: str, kind: str, name: str) -> bool:
            """Chaos load shedding for write verbs: True when this request
            was answered with an injected 429/500/503 (Retry-After carries
            the server's wait hint, fractional seconds — the sim's clients
            parse floats; real Retry-After is integral).  Runs BEFORE
            admission/storage so a shed write never half-applied and any
            retry is safe."""
            if api.fault is None:
                return False
            hit = api.fault.http_fault(verb, kind, name)
            if hit is None:
                return False
            code, retry_after = hit
            reason = {429: "TooManyRequests", 503: "ServiceUnavailable"}.get(
                code, "InternalError")
            m.apiserver_rejected.inc(("chaos_shed",))
            self._status_err(
                code, reason, f"chaos: shed {verb} {kind}/{name}",
                headers=(("Retry-After", f"{retry_after:.3f}"),)
                if retry_after else (),
            )
            return True

        # --- flow control (apiserver/flowcontrol.py) ------------------------

        def _flow_admit(self, mutating: bool, span=None) -> bool:
            """Run authn, then acquire an inflight seat (the reference APF
            position: WithPriorityAndFairness sits after WithAuthentication
            precisely so fairness keys on the VERIFIED identity — keying on
            a raw header would let one tenant spoof another's queue and
            starve it).  False when the request was already answered (401
            from authn, or 429 + Retry-After from the queue).  The identity
            is stashed for ``_check``/admission so the chain authenticates
            once.  ``span`` is the enclosing apiserver_request span: a seat
            that actually queued gets a retroactive apf_wait child covering
            its fair-queue wait."""
            self._flow_seat = None
            ui = self._user()
            if ui is None:
                return False  # 401 already sent
            self._req_user = ui
            if api.flow is None:
                return True
            user = ui.name or "system:anonymous"
            try:
                self._flow_seat = api.flow.admit(user, mutating=mutating)
            except RequestRejected as e:
                if span is not None:
                    span.set(rejected=e.reason)
                self._status_err(
                    429, "TooManyRequests", str(e),
                    headers=(("Retry-After", f"{e.retry_after:.3f}"),))
                return False
            waited = self._flow_seat.waited
            if span is not None and waited > 0:
                now = api.tracer.clock()
                api.tracer.span("apf_wait", parent=span, start=now - waited,
                                user=user).finish(end=now)
            return True

        def _req_span(self, verb: str):
            """apiserver_request span for one resource request; None when
            the tracer is disabled (the constant-false guard)."""
            if not api.tracer.enabled:
                return None
            return api.tracer.span("apiserver_request", verb=verb,
                                   path=self.path)

        def _flow_release(self):
            seat = getattr(self, "_flow_seat", None)
            if seat is not None:
                seat.release()
                self._flow_seat = None

        def _body(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b"{}"
            raw = raw or b"{}"
            # wire-encoded request body: negotiated via Content-Type, with
            # a magic-byte sniff as backstop (the magic is not valid UTF-8,
            # so a JSON body can never be misread as wire)
            ct = self.headers.get("Content-Type") or ""
            if wire.WIRE_CONTENT_TYPE in ct or wire.is_wire(raw):
                return wire.wire_decode(raw)
            return json.loads(raw)

        def _user(self) -> Optional[UserInfo]:
            """Run the authn chain.  None means 401 was already sent.  With
            no chain configured, header identity is honored with an
            anonymous fallback (no 401s — the pre-authn surface)."""
            if not api.authenticators:
                return (header_authenticator(self.headers)
                        or UserInfo("system:anonymous"))
            for auth in api.authenticators:
                ui = auth(self.headers)
                if ui is not None:
                    return ui
            self._status_err(401, "Unauthorized",
                             "no authenticator identified the request")
            return None

        def _check(self, verb: str, kind: str, ns: str,
                   name: str = "") -> bool:
            """Authorize one request; sends the 401/403 on failure.  The
            identity was established by ``_flow_admit`` (authn runs once
            per request, before fairness queuing); the fallback `_user()`
            covers callers outside the seated path.  A rich authorizer
            (RBAC) additionally receives the object name, API group, and
            the identity's groups — resourceNames rules and group-shaped
            bindings need them."""
            user = getattr(self, "_req_user", None)
            if user is None:
                user = self._user()
                if user is None:
                    return False
                self._req_user = user
            if api.authorizer is not None:
                resource = api.resource_for(kind)
                if api._authz_rich:
                    allowed = api.authorizer(
                        user.name, verb, resource, ns, name=name,
                        api_group=api.group_for(kind),
                        groups=tuple(getattr(user, "groups", ()) or ()))
                else:
                    allowed = api.authorizer(user.name, verb, resource, ns)
                if not allowed:
                    self._status_err(403, "Forbidden",
                                     f"user {user.name} cannot {verb} "
                                     f"{resource}")
                    return False
            return True

        def _admit(self, operation: str, kind: str, obj):
            """Mutating then validating admission (config.go:816 chain
            position: after authz, before the registry write).  Returns the
            (possibly mutated) object, or None when a validating hook
            denied (403 already sent)."""
            user = getattr(self, "_req_user", None)
            for hook in api.mutating_admission:
                out = hook(operation, kind, obj, user)
                if out is not None:
                    obj = out
            for hook in api.validating_admission:
                err = hook(operation, kind, obj, user)
                if err:
                    self._status_err(
                        403, "AdmissionDenied",
                        f"admission webhook denied the request: {err}")
                    return None
            return obj

        # --- verbs ----------------------------------------------------------

        def do_GET(self):
            url = urlparse(self.path)
            # health/discovery/metrics are EXEMPT from flow control: the
            # probes and the observability that diagnose a flood must not
            # be shed by it (the reference exempts non-resource paths too)
            if url.path in ("/healthz", "/readyz", "/livez", "/api", "/apis",
                            "/metrics"):
                self._nonresource(url)
                return
            span = self._req_span("get")
            try:
                if not self._flow_admit(mutating=False, span=span):
                    return
                try:
                    self._get_resource(url)
                finally:
                    self._flow_release()
            finally:
                if span is not None:
                    span.finish()

        def _nonresource(self, url):
            if url.path in ("/healthz", "/readyz", "/livez"):
                code, body = 200, b"ok"
                if url.path == "/readyz" and api.readyz is not None:
                    # readiness is gated on the wired Readyz: NotReady
                    # (mid-reconstruction) is 503 with the per-component
                    # progress as the body, the reference's verbose
                    # /readyz failure rendering.  ONE render() call is the
                    # single snapshot — a separate ready check could
                    # disagree with the body it ships.
                    rendered = api.readyz.render()
                    if rendered != "ok":
                        code, body = 503, rendered.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if url.path == "/metrics":
                # text exposition of the process registry — what `ktpu
                # controlplane status --server` reads
                body = metrics_registry.render_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if url.path == "/api":
                self._send_json(200, {"kind": "APIVersions",
                                      "versions": ["v1"]})
                return
            groups = sorted({e.split(":")[0] for e in
                             api.scheme.recognized() if "/" in e})
            self._send_json(200, {"kind": "APIGroupList",
                                  "groups": [{"name": g.split("/")[0]}
                                             for g in groups]})

        def _follower_wait(self, rv: int) -> bool:
            """rv-gate a read against the replication watermark: True when
            the request may serve locally (not a follower, rv already
            applied, or the watermark caught up within the bounded wait);
            False after answering 504 — the client retries, relists at
            rv=0, or goes to another replica.  A 504 (not 410) because the
            rv is VALID, just not HERE YET — Expired would trigger a
            spurious relist."""
            rep = api.replica
            if rep is None or rv <= rep.applied_rv():
                return True
            if rep.wait_for_rv(rv, api.follower_wait_seconds):
                return True
            m.apiserver_rejected.inc(("follower_lag",))
            self._status_err(
                504, "Timeout",
                f"follower {rep.name} applied_rv {rep.applied_rv()} has "
                f"not reached requested resourceVersion {rv} "
                f"(lag {rep.lag_rv()})",
                headers=(("Retry-After", "1"),))
            return False

        def _get_resource(self, url):
            q = parse_qs(url.query)
            r = api.route(url.path)
            if r is None:
                self._status_err(404, "NotFound", url.path)
                return
            kind, ns, name, _sub = r
            if not self._check("watch" if "watch" in q else
                               ("get" if name else "list"), kind, ns,
                               name=name):
                return
            codec = self._codec()
            if name:
                obj = api.store.get(kind, ns, name)
                if obj is None:
                    self._status_err(404, "NotFound", f"{kind} {ns}/{name}")
                    return
                self._send_object(200, obj, codec)
                return
            if q.get("watch", ["false"])[0] == "true":
                self._watch(kind, ns, q, codec)
                return
            # LIST: served from the watch cache (zero store-lock reads),
            # with rv-consistent limit/continue pagination; a continue
            # token or resourceVersion older than the cache's ring answers
            # 410 Gone (reason Expired) — the client restarts its walk
            # from a fresh LIST, the reference pagination contract.
            limit = int(q.get("limit", ["0"])[0] or 0)
            cont = q.get("continue", [None])[0]
            # resourceVersion="0" (and "") means "serve current from cache"
            # in the reference LIST contract (client-go reflectors send it)
            # — NOT an exact rollback to the pre-history world
            rv_param = q.get("resourceVersion", [None])[0]
            exact_rv = int(rv_param) if rv_param and rv_param != "0" else None
            if exact_rv is not None and not self._follower_wait(exact_rv):
                return
            next_token = ""
            if api.watch_cache is not None:
                try:
                    objs, rv, next_token = api.watch_cache.list_page(
                        kind, limit=limit, continue_=cont,
                        resource_version=exact_rv)
                except TooOldResourceVersion as e:
                    m.apiserver_rejected.inc(("watch_expired",))
                    self._status_err(410, "Expired", str(e))
                    return
                except ValueError as e:  # malformed continue token / rv
                    self._status_err(400, "BadRequest", str(e))
                    return
            else:
                objs, rv = api.store.list(kind)
            sel = q.get("labelSelector", [None])[0]
            fsel = q.get("fieldSelector", [None])[0]
            items = []
            for o in objs:
                if ns and getattr(o.metadata, "namespace", "") != ns:
                    continue
                if sel and not _match_label_selector(sel, o):
                    continue
                if fsel and not _match_field_selector(fsel, o):
                    continue
                # encode-once: objects at the cache's current rv hit the
                # payload memo captured at apply time; only rolled-back
                # pagination snapshots pay a fresh encode
                items.append(wire.payload_for(o, api.scheme))
            meta = {"resourceVersion": str(rv)}
            if next_token:
                # like the reference: selectors filter WITHIN the page, so
                # a page may carry fewer than `limit` items while continue
                # is still set — clients walk until continue is empty
                meta["continue"] = next_token
            head = {"kind": f"{kind}List", "apiVersion": "v1",
                    "metadata": meta}
            if codec == "wire":
                # each item is embedded as a BYTES value holding the SAME
                # self-contained wire doc the GET/watch planes serve — the
                # envelope encode copies bytes, it never re-serializes
                doc = dict(head)
                doc["items"] = [p.wire_bytes() for p in items]
                self._send_bytes(200, wire.wire_encode(doc),
                                 wire.WIRE_CONTENT_TYPE)
                return
            # JSON: splice the cached item bytes verbatim into the
            # envelope — json.dumps never sees the items
            body = (json.dumps(head).encode()[:-1] + b', "items": ['
                    + b", ".join(p.json_bytes() for p in items) + b"]}")
            self._send_bytes(200, body, "application/json")

        def _watch(self, kind: str, ns: str, q: dict, codec: str = "json"):
            """Chunked watch stream from a resourceVersion — JSON lines or
            length-prefixed binary frames, per the negotiated codec.

            ``allowWatchBookmarks=true`` adds periodic BOOKMARK events — an
            otherwise-empty object carrying just the store's current
            resourceVersion (the watch cache's bookmark machinery,
            cacher.go:56,161-185) — so an idle watcher's restart point
            stays fresh and a relist after disconnect replays almost
            nothing."""
            since = int(q.get("resourceVersion", ["0"])[0] or 0)
            if since and not self._follower_wait(since):
                return
            timeout = float(q.get("timeoutSeconds", ["30"])[0])
            bookmarks = q.get("allowWatchBookmarks", ["false"])[0] == "true"
            events: "queue.Queue" = queue.Queue(maxsize=4096)
            lossy = [False]  # an overflowed stream must never bookmark

            def on_event(ev):
                if ev.kind != kind:
                    return
                if ns and getattr(ev.obj.metadata, "namespace", "") != ns:
                    return
                try:
                    events.put_nowait(ev)
                except queue.Full:
                    # client too slow: it relists on gap detection — and a
                    # bookmark after a drop could advance the client PAST
                    # the dropped event, so bookmarks stop for good
                    lossy[0] = True

            # subscribe through the watch cache when present: the ring
            # serves the since_rv replay without the store lock, and a
            # too-old rv answers 410 Gone (reason Expired) so the client
            # relists — the reference cacher contract.  Without a cache,
            # the store's full-history replay serves any rv (legacy path).
            source = api.watch_cache if api.watch_cache is not None \
                else api.store
            try:
                unwatch = source.watch(on_event, since_rv=since)
            except TooOldResourceVersion as e:
                m.apiserver_rejected.inc(("watch_expired",))
                self._status_err(410, "Expired", str(e))
                return
            # the watch handshake is over: release the flow-control seat
            # so a long-lived stream never pins the readonly pool (APF's
            # long-running-request exemption)
            self._flow_release()

            def write_raw(blob: bytes) -> bool:
                chunk = f"{len(blob):X}\r\n".encode() + blob + b"\r\n"
                try:
                    self.wfile.write(chunk)
                    self.wfile.flush()
                    return True
                except (BrokenPipeError, ConnectionResetError,
                        socket.timeout):
                    return False

            def event_bytes(ev_type: str, payload=None, obj_doc=None,
                            rv: int = 0) -> bytes:
                """One watch event in the negotiated codec.  ``payload``
                (api.wire.EncodedPayload) serves the cached bytes — THE
                encode-once fan-out: a thousand watchers write the same
                bytes object.  ``obj_doc`` is for synthetic objects
                (bookmarks, errors) that have no payload."""
                if codec == "wire":
                    body = (payload.wire_bytes() if payload is not None
                            else wire.wire_encode(obj_doc))
                    return wire.encode_watch_frame(ev_type, body, rv=rv)
                body = (payload.json_bytes() if payload is not None
                        else json.dumps(obj_doc).encode())
                return (b'{"type": "' + ev_type.encode()
                        + b'", "object": ' + body + b'}\n')

            try:
                self.send_response(200)
                self.send_header("Content-Type",
                                 wire.content_type_for(codec))
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                deadline = time.monotonic() + timeout
                # bookmark cadence: ~1s idle (the reference's cacher sends
                # them at bookmarkFrequency ~1/min; the sim's watches are
                # short-lived, so a faster tick keeps the behavior testable)
                next_bookmark = time.monotonic() + 1.0
                while True:
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        break
                    if not api.serves_kind(kind):
                        # the CRD defining this kind was deleted out from
                        # under the stream: flush the events already fanned
                        # (the cascade's ordered DELETED drain), then
                        # terminate in-band so the client stops (and
                        # relists into a 404) instead of idling on a
                        # resource that no longer exists
                        while True:
                            try:
                                ev = events.get_nowait()
                            except queue.Empty:
                                break
                            p = ev.payload or wire.payload_for(
                                ev.obj, api.scheme)
                            if not write_raw(event_bytes(
                                    ev.type, payload=p,
                                    rv=ev.resource_version)):
                                return
                        if write_raw(event_bytes(
                                ERROR,
                                obj_doc={"kind": "Status",
                                         "status": "Failure",
                                         "reason": "Expired",
                                         "message": "the server no longer "
                                                    f"serves {kind}"})):
                            try:
                                self.wfile.write(b"0\r\n\r\n")
                            except (BrokenPipeError, ConnectionResetError):
                                pass
                        return
                    if bookmarks and time.monotonic() >= next_bookmark:
                        next_bookmark = time.monotonic() + 1.0
                        # correctness order: read the fully-fanned-out rv
                        # FIRST (all events ≤ it have been emitted to this
                        # watcher's callback — the cache's fanned_rv
                        # watermark, or the store's under-lock rv), THEN
                        # require the queue drained — the bookmark then
                        # provably covers only events already written to
                        # the wire (cacher.go bookmarks cover progress
                        # sent to that watcher).  bookmark_rv additionally
                        # clamps to the replication watermark on a
                        # follower (the cross-process no-overclaim rule).
                        rv = (api.watch_cache.bookmark_rv()
                              if api.watch_cache is not None
                              else api.store.current_rv())
                        if not lossy[0] and events.empty():
                            if not write_raw(event_bytes(
                                    "BOOKMARK", rv=rv,
                                    obj_doc={"kind": kind, "metadata":
                                             {"resourceVersion": str(rv)}})):
                                return
                    try:
                        ev = events.get(timeout=min(remain, 0.25))
                    except queue.Empty:
                        continue
                    if api.fault is not None and api.fault.should_drop_watch(
                            ev.kind,
                            getattr(ev.obj.metadata, "name", ""),
                            rv=ev.resource_version):
                        # chaos stream cut: the in-band ERROR event (watch
                        # protocol stream-failure marker) REPLACES this
                        # event — the client must relist to recover it,
                        # exactly as after a real 410 Gone
                        if write_raw(event_bytes(
                                ERROR,
                                obj_doc={"kind": "Status",
                                         "status": "Failure",
                                         "reason": "Expired",
                                         "message": "chaos: watch dropped"})):
                            try:  # close the stream cleanly after ERROR
                                self.wfile.write(b"0\r\n\r\n")
                            except (BrokenPipeError, ConnectionResetError):
                                pass
                        return
                    # the cache stamped the payload at apply time; events
                    # from a cache-less store encode on demand (memoized)
                    p = ev.payload or wire.payload_for(ev.obj, api.scheme)
                    if not write_raw(event_bytes(ev.type, payload=p,
                                                 rv=ev.resource_version)):
                        return
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass
            finally:
                unwatch()

        def _mutating(self, verb: str, body_fn) -> None:
            """Shared wrapper for the write verbs: request span →
            flow-control admit → handler → release/finish.

            A replication FOLLOWER answers every write 503 before any of
            that — its store would raise FollowerReadOnly anyway (a local
            write forks the shipped history), but rejecting at the door
            gives the client the Retry-After + reason it needs to go to
            the leader.  The check reads the replica's LIVE role, so
            promotion opens writes with no server restart."""
            if api.replica is not None and api.replica.role != "leader":
                m.apiserver_rejected.inc(("follower_readonly",))
                self._status_err(
                    503, "ServiceUnavailable",
                    f"replica {api.replica.name} is a read-only follower; "
                    f"send writes to the leader",
                    headers=(("Retry-After", "1"),))
                return
            span = self._req_span(verb)
            try:
                if not self._flow_admit(mutating=True, span=span):
                    return
                try:
                    body_fn()
                finally:
                    self._flow_release()
            finally:
                if span is not None:
                    span.finish()

        def do_POST(self):
            self._mutating("post", self._post)

        def do_PUT(self):
            self._mutating("put", self._put)

        def do_PATCH(self):
            self._mutating("patch", self._patch)

        def do_DELETE(self):
            self._mutating("delete", self._delete)

        def _post(self):
            url = urlparse(self.path)
            r = api.route(url.path)
            if r is None:
                self._status_err(404, "NotFound", url.path)
                return
            kind, ns, name, sub = r
            if self._shed("POST", kind, name or ""):
                return
            if kind == "Pod" and name and sub == "binding":
                if not self._check("create", "Pod", ns, name=name):
                    return
                body = self._body()
                node = ((body.get("target") or {}).get("name")) or ""
                # admission covers the binding subresource too (the
                # reference runs its chain on every write, bindings
                # included) — hooks see the pod with the proposed nodeName
                pod = api.store.get("Pod", ns, name)
                if pod is not None:
                    import copy as _copy

                    proposed = _copy.copy(pod)
                    proposed.spec = _copy.copy(pod.spec)
                    proposed.spec.node_name = node
                    if self._admit("CONNECT", "Pod", proposed) is None:
                        return
                if api.store.bind_pod(ns, name, node):
                    self._send_json(201, {"kind": "Status",
                                          "status": "Success"})
                else:
                    self._status_err(404, "NotFound", f"pod {ns}/{name}")
                return
            if kind == "Pod" and name and sub == "eviction":
                # the Eviction subresource (policy/v1): the shared gate
                # decides; an exhausted PodDisruptionBudget answers 429
                # TooManyRequests exactly like the reference handler
                if not self._check("delete", "Pod", ns, name=name):
                    return
                body = self._body()
                if body:
                    try:
                        eviction = api.scheme.decode(body)
                    except (SchemeError, ValueError) as e:
                        self._status_err(400, "BadRequest", str(e))
                        return
                    if eviction.metadata.name and \
                            eviction.metadata.name != name:
                        self._status_err(
                            400, "BadRequest",
                            f"eviction names pod "
                            f"{eviction.metadata.name!r}, URL names "
                            f"{name!r}")
                        return
                    # deleteOptions.gracePeriodSeconds decodes but is
                    # ignored: sim pods terminate instantly (documented
                    # deviation on api.objects.Eviction)
                pod = api.store.get("Pod", ns, name)
                if pod is None:
                    self._status_err(404, "NotFound", f"pod {ns}/{name}")
                    return
                result = api.evictions.evict(pod, reason="api eviction",
                                             policy="api")
                if result.evicted:
                    self._send_json(201, {"kind": "Status",
                                          "status": "Success"})
                elif not result.allowed:
                    self._status_err(429, "TooManyRequests", result.reason)
                elif result.reason == "pod already gone":
                    # a concurrent eviction won the race: same 404 the
                    # sequential retry gets from the pre-check above
                    self._status_err(404, "NotFound", f"pod {ns}/{name}")
                else:
                    self._status_err(409, "Conflict",
                                     result.reason or "eviction failed")
                return
            if not self._check("create", kind, ns):
                return
            try:
                obj = api.scheme.decode(self._body())
            except (SchemeError, ValueError) as e:
                self._status_err(400, "BadRequest", str(e))
                return
            if ns:
                obj.metadata.namespace = ns
            obj = self._admit("CREATE", kind, obj)
            if obj is None:
                return
            try:
                api.store.create(kind, obj)
            except QuotaExceeded as e:
                self._status_err(403, "Forbidden", str(e))
                return
            except ValueError as e:
                self._status_err(409, "AlreadyExists", str(e))
                return
            # the store write already fanned the object through the watch
            # cache, which captured its payload — this response reuses it
            self._send_object(201, obj, self._codec())

        def _put(self):
            url = urlparse(self.path)
            r = api.route(url.path)
            if r is None or not r[2]:
                self._status_err(404, "NotFound", url.path)
                return
            kind, ns, name, _sub = r
            if self._shed("PUT", kind, name):
                return
            if not self._check("update", kind, ns, name=name):
                return
            if api.store.get(kind, ns, name) is None:
                self._status_err(404, "NotFound", f"{kind} {ns}/{name}")
                return
            try:
                body = self._body()
                obj = api.scheme.decode(body)
            except (SchemeError, ValueError) as e:
                self._status_err(400, "BadRequest", str(e))
                return
            obj.metadata.namespace = ns or obj.metadata.namespace
            obj.metadata.name = name
            obj = self._admit("UPDATE", kind, obj)
            if obj is None:
                return
            rv = ((body.get("metadata") or {}).get("resourceVersion"))
            if not self._store_update_rv(kind, obj,
                                         None if rv in (None, "") else rv):
                return
            self._send_object(200, obj, self._codec())

        def _store_update_rv(self, kind, obj, rv) -> bool:
            """Write through the store with ``rv`` (when not None) as an
            atomic CAS precondition — a submitted rv that is no longer
            current means the writer read a stale object: 409 Conflict, the
            contract controllers' read-modify-write loops rely on (apiserver
            Conflict; etcd3 store.go GuaranteedUpdate).  The check happens
            INSIDE the store lock so concurrent writers with the same rv
            cannot both pass."""
            try:
                api.store.update(kind, obj, expected_rv=rv)
            except StaleResourceVersion as e:
                self._status_err(
                    409, "Conflict",
                    f"operation cannot be fulfilled: the object has been "
                    f"modified ({e})",
                )
                return False
            except KeyError:
                self._status_err(404, "NotFound", f"{kind}")
                return False
            return True

        def _patch(self):
            url = urlparse(self.path)
            r = api.route(url.path)
            if r is None or not r[2]:
                self._status_err(404, "NotFound", url.path)
                return
            kind, ns, name, _sub = r
            if self._shed("PATCH", kind, name):
                return
            if not self._check("patch", kind, ns, name=name):
                return
            patch = self._body()
            client_rv = ((patch.get("metadata") or {}).get("resourceVersion"))
            # The write CASes on the rv the merge was computed against, so a
            # concurrent writer between read and write surfaces as a CAS
            # miss, never a lost update.  A client-supplied rv that is stale
            # → 409 (the client read a stale object); with no client rv the
            # server re-reads and re-applies the merge, the reference
            # apiserver's internal GuaranteedUpdate retry loop.
            for _ in range(5):
                cur = api.store.get(kind, ns, name)
                if cur is None:
                    self._status_err(404, "NotFound", f"{kind} {ns}/{name}")
                    return
                merged = _merge(to_manifest(cur, api.scheme), patch)
                try:
                    obj = api.scheme.decode(merged)
                except (SchemeError, ValueError) as e:
                    self._status_err(400, "BadRequest", str(e))
                    return
                obj.metadata.uid = cur.metadata.uid
                obj = self._admit("UPDATE", kind, obj)
                if obj is None:
                    return
                if client_rv not in (None, "") and \
                        str(client_rv) != str(cur.metadata.resource_version):
                    break  # stale client rv → Conflict below
                try:
                    api.store.update(kind, obj,
                                     expected_rv=cur.metadata.resource_version)
                except StaleResourceVersion:
                    if client_rv not in (None, ""):
                        break
                    continue  # benign race: re-merge against the new state
                except KeyError:
                    self._status_err(404, "NotFound", f"{kind} {ns}/{name}")
                    return
                self._send_object(200, obj, self._codec())
                return
            self._status_err(
                409, "Conflict",
                "operation cannot be fulfilled: the object has been modified",
            )

        def _delete(self):
            url = urlparse(self.path)
            r = api.route(url.path)
            if r is None or not r[2]:
                self._status_err(404, "NotFound", url.path)
                return
            kind, ns, name, _sub = r
            if self._shed("DELETE", kind, name):
                return
            if not self._check("delete", kind, ns, name=name):
                return
            cur = api.store.get(kind, ns, name)
            if cur is None:
                self._status_err(404, "NotFound", f"{kind} {ns}/{name}")
                return
            # admission gates DELETE as well (hooks see the current object)
            if self._admit("DELETE", kind, cur) is None:
                return
            obj = api.store.delete(kind, ns, name)
            if obj is None:
                self._status_err(404, "NotFound", f"{kind} {ns}/{name}")
                return
            # the deleted object's final state, as the reference apiserver
            # returns it (clients needing only confirmation ignore the body)
            self._send_object(200, obj, self._codec())

    return Handler


def _merge(base: dict, patch: dict) -> dict:
    """RFC 7386 JSON merge patch."""
    out = dict(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        elif isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out
