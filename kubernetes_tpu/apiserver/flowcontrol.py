"""APF-style request flow control: split max-inflight pools + per-user
fairness queues answering 429 + Retry-After.

Reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol
(apf_controller.go) and the older --max-requests-inflight /
--max-mutating-requests-inflight filters.  The properties kept:

  - MUTATING and READONLY requests draw from SEPARATE seat pools, so a
    flood of greedy readers can exhaust every readonly seat without
    delaying a single write — the "mutating never starves" contract the
    flood test pins;
  - when a pool is full, requests WAIT in bounded per-user queues and
    seats hand off round-robin ACROSS USERS (the fair-queuing half of
    APF): one user's thousand queued lists cannot starve another user's
    one;
  - a queue past its per-user bound, or a wait past the queue timeout,
    answers 429 + Retry-After — which the PR-1 retrying transports
    (HTTPApiClient, chaos.RetryingStore) already honor, so a shed request
    is retried-to-success, never lost.

WATCH requests occupy a readonly seat only through the handshake (routing,
authn/z, subscription): the apiserver releases the seat before entering the
stream loop, matching APF's treatment of long-running requests.

Observability: ``apiserver_inflight_requests{kind}`` tracks seats held per
pool; ``apiserver_rejected_requests_total{reason}`` counts sheds by
``{mutating,readonly}_{queue_full,timeout}``.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, Optional

from ..analysis import lockcheck
from ..metrics import scheduler_metrics as m


class RequestRejected(Exception):
    """This request was shed (429 TooManyRequests + Retry-After)."""

    def __init__(self, reason: str, retry_after: float, message: str = ""):
        super().__init__(message or f"request rejected: {reason}")
        self.reason = reason
        self.retry_after = retry_after


class _Waiter:
    __slots__ = ("event", "granted")

    def __init__(self):
        self.event = threading.Event()
        self.granted = False


class _Seat:
    """A held inflight seat; ``release`` is idempotent (the handler's
    finally always runs it, and the watch path releases early).
    ``waited`` is the fair-queue wait this seat paid before being granted
    (0.0 on the uncontended fast path) — the apiserver's ``apf_wait`` span
    and any queue-latency observability read it off the seat instead of
    re-timing the admit call."""

    __slots__ = ("_gate", "_released", "waited")

    def __init__(self, gate: "_InflightGate", waited: float = 0.0):
        self._gate = gate
        self._released = False
        self.waited = waited

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._gate._release()


class _InflightGate:
    """One seat pool (mutating OR readonly) with per-user fair queuing."""

    def __init__(self, kind: str, max_inflight: int, max_queue_per_user: int,
                 queue_timeout: float, retry_after: float,
                 max_queued_total: Optional[int] = None):
        self.kind = kind
        self.max_inflight = max_inflight
        self.max_queue_per_user = max_queue_per_user
        self.queue_timeout = queue_timeout
        self.retry_after = retry_after
        # TOTAL queued bound across all users: the per-user bound alone is
        # bypassable by rotating the fairness identity — a flooder minting
        # a fresh user per request would otherwise grow queues and handler
        # threads without ever seeing a 429 (APF bounds total seats+queues
        # the same way).  With authenticators configured the identity is
        # the AUTHENTICATED user (401 precedes admission, so rotation
        # requires minting real credentials); header-spoofing only works
        # on open servers, and this bound holds either way.  Default: 8
        # queued per seat.
        self.max_queued_total = (max_queued_total if max_queued_total
                                 is not None else max_inflight * 8)
        self._lock = lockcheck.maybe_wrap(
            threading.Lock(), f"FlowGate[{kind}]._lock")
        self._inflight = 0
        self._queues: Dict[str, Deque[_Waiter]] = {}
        self._queued_total = 0
        self._rr = 0  # round-robin cursor over users with waiters

    def acquire(self, user: str) -> _Seat:
        with self._lock:
            if self._inflight < self.max_inflight:
                self._inflight += 1
                m.apiserver_inflight.set(float(self._inflight), (self.kind,))
                return _Seat(self)
            q = self._queues.get(user)
            if (q is not None and len(q) >= self.max_queue_per_user) or \
                    self._queued_total >= self.max_queued_total:
                m.apiserver_rejected.inc((f"{self.kind}_queue_full",))
                raise RequestRejected(
                    f"{self.kind}_queue_full", self.retry_after,
                    f"too many queued {self.kind} requests for {user!r}")
            w = _Waiter()
            if q is None:
                q = self._queues[user] = deque()
            q.append(w)
            self._queued_total += 1
        import time as _time

        t_q = _time.monotonic()
        if w.event.wait(self.queue_timeout):
            # seat handed over by a releaser; carry the queue wait out
            return _Seat(self, waited=_time.monotonic() - t_q)
        with self._lock:
            if w.granted:
                # granted exactly at the deadline: the seat is ours
                return _Seat(self, waited=_time.monotonic() - t_q)
            q = self._queues.get(user)
            if q is not None:
                try:
                    q.remove(w)
                    self._queued_total -= 1
                except ValueError:
                    pass  # a concurrent grant raced the timeout path above
                if not q:
                    del self._queues[user]
        m.apiserver_rejected.inc((f"{self.kind}_timeout",))
        raise RequestRejected(
            f"{self.kind}_timeout", self.retry_after,
            f"{self.kind} request queued past "
            f"{self.queue_timeout:g}s for {user!r}")

    def _release(self) -> None:
        wake: Optional[_Waiter] = None
        with self._lock:
            # hand the seat to the next user's head waiter, round-robin
            # across users — the fair-queuing guarantee: seat handoffs
            # rotate over DISTINCT users, not FIFO over one user's flood
            users = [u for u, q in self._queues.items() if q]
            if users:
                u = users[self._rr % len(users)]
                self._rr += 1
                q = self._queues[u]
                wake = q.popleft()
                self._queued_total -= 1
                wake.granted = True
                if not q:
                    del self._queues[u]
                # seat transfers: _inflight unchanged
            else:
                self._inflight -= 1
                m.apiserver_inflight.set(float(self._inflight), (self.kind,))
        if wake is not None:
            wake.event.set()

    def queued(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def inflight(self) -> int:
        with self._lock:
            return self._inflight


class FlowController:
    """Split mutating/readonly gates behind one ``admit`` entry point.

    Defaults are deliberately generous (invisible to well-behaved
    in-process traffic); flood tests construct tighter ones.  The
    classification matches the reference filters: GET/LIST/WATCH are
    readonly, everything else mutating.
    """

    def __init__(self, max_mutating_inflight: int = 32,
                 max_readonly_inflight: int = 64,
                 max_queue_per_user: int = 64,
                 queue_timeout: float = 2.0,
                 retry_after: float = 0.1,
                 max_queued_total: Optional[int] = None):
        self.mutating = _InflightGate(
            "mutating", max_mutating_inflight, max_queue_per_user,
            queue_timeout, retry_after, max_queued_total=max_queued_total)
        self.readonly = _InflightGate(
            "readonly", max_readonly_inflight, max_queue_per_user,
            queue_timeout, retry_after, max_queued_total=max_queued_total)

    @classmethod
    def for_role(cls, role: str) -> "FlowController":
        """Pool shape per replication role (sim/replication.py).

        A FOLLOWER exists to absorb reads: its readonly pool doubles and
        its mutating pool shrinks to a sliver — every write it admits is
        answered 503 at the handler, so seats there only cover the cost of
        saying no (and of the write burst that arrives the instant
        promotion flips the role, before callers re-resolve endpoints).
        A LEADER keeps the defaults."""
        if role == "follower":
            return cls(max_mutating_inflight=4, max_readonly_inflight=128)
        return cls()

    def admit(self, user: str, mutating: bool) -> _Seat:
        """Acquire a seat (possibly after a fair-queued wait) or raise
        RequestRejected — the caller answers 429 + Retry-After.

        ``user`` is the AUTHENTICATED name when the server has
        authenticators (APIServer._flow_admit authenticates first, so
        fairness keys on a verified identity); on open servers it falls
        back to the self-reported header, and the total-queued bound
        absorbs identity-rotation floods either way."""
        gate = self.mutating if mutating else self.readonly
        return gate.acquire(user or "system:anonymous")
