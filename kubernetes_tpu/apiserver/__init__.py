from .server import APIServer, resource_of
from .client import HTTPApiClient

__all__ = ["APIServer", "HTTPApiClient", "resource_of"]
