from .server import (
    APIServer,
    UserInfo,
    header_authenticator,
    resource_of,
    token_authenticator,
)
from .client import HTTPApiClient

__all__ = [
    "APIServer",
    "HTTPApiClient",
    "UserInfo",
    "header_authenticator",
    "resource_of",
    "token_authenticator",
]
