"""Preemption engine (PostFilter).

Reference: pkg/scheduler/framework/preemption/preemption.go (Evaluator.Preempt
:138, findCandidates :198, DryRunPreemption :546, SelectCandidate :301,
pickOneNodeForPreemption :397) + defaultpreemption/default_preemption.go
(SelectVictimsOnNode :139, candidate count = max(10%·n, 100) :110-127).

Split of labor mirrors the reference's own two phases, device-first:
  - the *dry-run fit check* over all candidate nodes at once is a tensor
    program: freed-by-preemption resource vectors come from one
    pods×nodes matmul, so "would the pod fit if every lower-priority pod on
    this node were evicted" is evaluated for every node in parallel — the
    batched analog of DryRunPreemption's goroutine fan-out;
  - exact victim minimization + the 6-criteria candidate ranking run host-side
    with the oracle's reference-exact filters over the few surviving
    candidates (potential victims are per-node small).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .api import objects as v1
from .api.labels import match_label_selector
from .oracle import Oracle
from .state.cache import Snapshot
from .state.node_info import NodeInfo
from .state.node_info import _pod_host_ports as _node_info_host_ports

# The fork-and-resolve primitives live in the unified counterfactual
# engine (whatif/dryrun.py) — this module keeps the host-side candidate
# enumeration, reprieve orchestration and 6-criteria ranking, and
# re-exports the device fan-out under its historical names (scheduler.py
# and the test battery import them from here).
from .whatif.dryrun import (  # noqa: F401 — re-exported API
    PRIORITY_LEVEL_CAP,
    candidate_mask_device,
    sweep_and_rank as _sweep_and_rank,
)


@dataclass
class Candidate:
    node_name: str
    victims: List[v1.Pod]
    num_pdb_violations: int


@dataclass
class PlainTables:
    """Per-snapshot victim tables for PLAIN preemptors at one priority
    threshold — the preemptor-independent 80% of select_victims_vectorized
    (potential-victim enumeration, ordering, resource vectors), built ONCE
    per (snapshot generation, priority, PDB state) and shared by every
    preemptor in a burst.  At 5k nodes the per-preemptor rebuild was ~35ms
    × a 256-pod batch ≈ 9s/cycle — the dominant PreemptionBasic cost."""

    names: List[str]
    index: Dict[str, int]
    infos: List[NodeInfo]
    victims: List[List[v1.Pod]]       # violating-first, importance-descending
    base: np.ndarray                   # [C,4] used minus all potential victims
    alloc: np.ndarray                  # [C,4]
    vr_mat: np.ndarray                 # [C,Vmax,4]
    v_valid: np.ndarray                # [C,Vmax] bool
    v_viol: np.ndarray                 # [C,Vmax] bool  (PDB-violating victim)
    v_prio: np.ndarray                 # [C,Vmax] int64
    v_ts: np.ndarray                   # [C,Vmax] float64 creation timestamps


def pods_with_pdb_violation(
    victims: Sequence[v1.Pod], pdbs: Sequence[v1.PodDisruptionBudget]
) -> Tuple[List[v1.Pod], List[v1.Pod]]:
    """filterPodsWithPDBViolation: a victim violates when any matching PDB has
    no disruption budget left."""
    violating, ok = [], []
    for pod in victims:
        bad = False
        for pdb in pdbs:
            if pdb.metadata.namespace != pod.namespace:
                continue
            if not match_label_selector(pdb.selector, pod.metadata.labels):
                continue
            if pdb.disruptions_allowed <= 0:
                bad = True
                break
        (violating if bad else ok).append(pod)
    return violating, ok


def more_important(a: v1.Pod, b: v1.Pod) -> bool:
    """util.MoreImportantPod: higher priority, then earlier start."""
    if a.spec.priority != b.spec.priority:
        return a.spec.priority > b.spec.priority
    return (a.metadata.creation_timestamp or 0) < (b.metadata.creation_timestamp or 0)


class Evaluator:
    def __init__(self, oracle: Optional[Oracle] = None):
        self.oracle = oracle or Oracle()
        # rotating start offset into the candidate list (the reference draws
        # rand.Intn(len(potentialNodes)) per attempt, preemption.go
        # findCandidates/GetOffsetAndNumCandidates): without it every
        # preemptor in a burst dry-runs the SAME first-cap nodes, later ones
        # find them all claimed by earlier nominations, return no candidate,
        # and burn a full retry cycle
        self._offset = 0
        # (snapshot id, snapshot generation, priority, pdb fingerprint) →
        # PlainTables; one entry per threshold survives a whole batch
        self._tables: Dict[tuple, PlainTables] = {}
        # (priority, pdb fingerprint) → node name → cached per-node row,
        # keyed by NodeInfo.generation: across cycles only nodes whose pods
        # changed (evictions, binds) rebuild their victim row — the full
        # rebuild was ~0.9s/cycle at 5k nodes / 25k pods
        self._rows: Dict[tuple, Dict[str, tuple]] = {}

    def plain_tables(
        self,
        snapshot: Snapshot,
        priority: int,
        pdbs: Sequence[v1.PodDisruptionBudget] = (),
    ) -> PlainTables:
        """Build (or fetch) the preemptor-independent victim tables for every
        node holding at least one pod below ``priority``.  Static node
        predicates are NOT applied here — they depend on the preemptor and
        are verified on the ranked winner only (see preempt_plain)."""
        pdb_fp = tuple(
            (p.metadata.namespace, p.metadata.name, p.disruptions_allowed)
            for p in pdbs
        )
        key = (id(snapshot), snapshot.generation, priority, pdb_fp)
        hit = self._tables.get(key)
        if hit is not None:
            return hit
        # evict only STALE generations: a batch mixing preemptor priorities
        # keeps one live entry per threshold (a full clear would rebuild the
        # tables once per pod, not once per threshold)
        for k in [k for k in self._tables if k[:2] != key[:2]]:
            del self._tables[k]
        from .api.resource import compute_pod_resource_request

        if len(self._rows) > 8:  # many distinct thresholds: drop stale keys
            self._rows.clear()
        rows = self._rows.setdefault((priority, pdb_fp), {})

        names: List[str] = []
        infos: List[NodeInfo] = []
        victim_lists: List[List[v1.Pod]] = []
        row_data: List[tuple] = []
        seen = set()
        for info in snapshot.node_info_list:
            name = info.node_name
            seen.add(name)
            cached = rows.get(name)
            if cached is not None and cached[0] == info.generation:
                if cached[1] is None:  # no potential victims on this node
                    continue
                _, victims, vr, viol, prio, ts, base_u, alloc_u = cached
            else:
                potential = [
                    pi.pod for pi in info.pods
                    if pi.pod.spec.priority < priority
                ]
                if not potential:
                    rows[name] = (info.generation, None)
                    continue
                used = info.requested
                u = np.array(
                    [used.milli_cpu, used.memory, used.ephemeral_storage,
                     len(info.pods)], dtype=np.int64,
                )
                potential.sort(
                    key=lambda p: (-p.spec.priority,
                                   p.metadata.creation_timestamp or 0)
                )
                violating, non_violating = pods_with_pdb_violation(
                    potential, pdbs)
                victims = violating + non_violating
                nv = len(victims)
                vr = np.zeros((nv, 4), dtype=np.int64)
                prio = np.zeros(nv, dtype=np.int64)
                ts = np.zeros(nv, dtype=np.float64)
                for vi, victim in enumerate(victims):
                    r = compute_pod_resource_request(victim)
                    vr[vi] = (r.milli_cpu, r.memory, r.ephemeral_storage, 1)
                    prio[vi] = victim.spec.priority or 0
                    ts[vi] = victim.metadata.creation_timestamp or 0
                viol = np.zeros(nv, dtype=bool)
                viol[:len(violating)] = True
                base_u = u - vr.sum(axis=0)
                al = info.allocatable
                alloc_u = np.array(
                    [al.milli_cpu, al.memory, al.ephemeral_storage,
                     al.allowed_pod_number], dtype=np.int64,
                )
                rows[name] = (info.generation, victims, vr, viol, prio, ts,
                              base_u, alloc_u)
            names.append(name)
            infos.append(info)
            victim_lists.append(victims)
            row_data.append((vr, viol, prio, ts, base_u, alloc_u))
        if len(rows) > len(seen):  # nodes deleted since last cycle
            for name in list(rows):
                if name not in seen:
                    del rows[name]

        c = len(names)
        vmax = max((r[0].shape[0] for r in row_data), default=0)
        vr_mat = np.zeros((c, vmax, 4), dtype=np.int64)
        v_valid = np.zeros((c, vmax), dtype=bool)
        v_viol = np.zeros((c, vmax), dtype=bool)
        v_prio = np.zeros((c, vmax), dtype=np.int64)
        v_ts = np.zeros((c, vmax), dtype=np.float64)
        base = np.zeros((c, 4), dtype=np.int64)
        alloc = np.zeros((c, 4), dtype=np.int64)
        for ci, (vr, viol, prio, ts, base_u, alloc_u) in enumerate(row_data):
            nv = vr.shape[0]
            vr_mat[ci, :nv] = vr
            v_valid[ci, :nv] = True
            v_viol[ci, :nv] = viol
            v_prio[ci, :nv] = prio
            v_ts[ci, :nv] = ts
            base[ci] = base_u
            alloc[ci] = alloc_u
        tables = PlainTables(
            names=names, index={n: i for i, n in enumerate(names)},
            infos=infos, victims=victim_lists,
            base=base, alloc=alloc,
            vr_mat=vr_mat, v_valid=v_valid, v_viol=v_viol,
            v_prio=v_prio, v_ts=v_ts,
        )
        self._tables[key] = tables
        return tables

    def preempt_plain(
        self,
        pod: v1.Pod,
        tables: PlainTables,
        candidate_names: Sequence[str],
        nominated: Optional[Dict[str, List[v1.Pod]]] = None,
    ) -> Optional[Candidate]:
        """Fast preempt() body for plain preemptors: numpy reprieve sweep +
        vectorized 6-criteria ranking over the shared tables, materializing
        ONLY the winner's victim list.  Static node predicates are verified
        on the ranked winner (walking down on the rare failure) — the exact
        outcome the serial path reaches by pre-filtering every candidate."""
        from .api.resource import compute_pod_resource_request
        from .oracle import (
            node_affinity_fits,
            node_name_fits,
            node_schedulable,
            tolerates_all_hard_taints,
        )

        req = compute_pod_resource_request(pod)
        if req.scalar_resources:
            raise ValueError(
                "preempt_plain does not support preemptors with scalar "
                "(extended) resource requests; use select_victims_on_node"
            )
        rows = np.array(
            [tables.index[n] for n in candidate_names if n in tables.index],
            dtype=np.int64,
        )
        if rows.size == 0:
            return None
        req_v = np.array(
            [req.milli_cpu, req.memory, req.ephemeral_storage, 1],
            dtype=np.int64,
        )
        base = tables.base[rows].copy()
        # fold nominated reservations (equal-or-higher-priority nominees on a
        # candidate add their request before the fit check, matching
        # select_victims_on_node's AddNominatedPods analog)
        if nominated:
            my_prio = pod.spec.priority or 0
            for ri, row in enumerate(rows):
                noms = nominated.get(tables.names[row])
                if not noms:
                    continue
                for nom in noms:
                    if nom.uid != pod.uid and (nom.spec.priority or 0) >= my_prio:
                        nr = compute_pod_resource_request(nom)
                        base[ri] += (nr.milli_cpu, nr.memory,
                                     nr.ephemeral_storage, 1)
        alloc = tables.alloc[rows]
        vr = tables.vr_mat[rows]
        v_valid = tables.v_valid[rows]

        victim_mask, nviol, order, valid = _sweep_and_rank(
            base, alloc, vr, v_valid, tables.v_viol[rows],
            tables.v_prio[rows], tables.v_ts[rows], req_v,
        )
        if valid is None or not valid.any():
            return None
        for oi in order:
            if not valid[oi]:
                return None
            row = int(rows[oi])
            info = tables.infos[row]
            node = info.node
            if (node is None or not node_name_fits(pod, node)
                    or not node_schedulable(pod, node)
                    or not node_affinity_fits(pod, node)
                    or not tolerates_all_hard_taints(pod, node)):
                continue  # statics fail: winner drops, next-ranked wins
            victims = [
                p for vi, p in enumerate(tables.victims[row])
                if victim_mask[oi, vi]
            ]
            victims.sort(
                key=lambda p: (-p.spec.priority,
                               p.metadata.creation_timestamp or 0)
            )
            return Candidate(info.node_name, victims, int(nviol[oi]))
        return None

    def select_victims_on_node(
        self,
        pod: v1.Pod,
        info: NodeInfo,
        node_infos: List[NodeInfo],
        pdbs: Sequence[v1.PodDisruptionBudget] = (),
        cluster_has_req_anti_affinity: bool = True,
        nominated: Optional[Dict[str, List[v1.Pod]]] = None,
    ) -> Optional[Candidate]:
        """SelectVictimsOnNode (default_preemption.go:139): remove all lower-
        priority pods, verify fit, then reprieve greedily (PDB-violating pods
        reprieved first, both groups by descending importance).

        ``nominated`` maps node name → pods already nominated there; equal-or-
        higher-priority nominees are added to the simulated node before the fit
        check (the reference's AddNominatedPods inside
        RunFilterPluginsWithNominatedPods, runtime/framework.go:822-836) so a
        burst of same-priority preemptors spreads across nodes instead of all
        claiming the first viable one."""
        sim = info.clone()
        potential = [
            pi.pod for pi in info.pods if pi.pod.spec.priority < pod.spec.priority
        ]
        if not potential:
            return None
        for victim in potential:
            sim.remove_pod(victim)
        for nom in (nominated or {}).get(info.node_name, []):
            if nom.uid != pod.uid and nom.spec.priority >= pod.spec.priority:
                sim.add_pod(nom)

        # Cross-node context is only needed when the preemptor carries
        # global constraints (topology-spread min counts, pod-affinity
        # domain counts); plain resource/taint/selector feasibility is
        # node-local, and evaluating just the simulated node keeps each
        # dry run O(1) in cluster size (the reference likewise filters one
        # node against preFilter state, default_preemption.go:139).
        aff = pod.spec.affinity
        needs_global = bool(
            pod.spec.topology_spread_constraints
            or (aff and (aff.pod_affinity or aff.pod_anti_affinity))
            # existing pods' required anti-affinity can block the preemptor
            # through a multi-node topology domain
            or cluster_has_req_anti_affinity
        )
        others = (
            [ni for ni in node_infos if ni.node_name != info.node_name]
            if needs_global
            else []
        )
        plain = _is_plain_preemptor(pod, cluster_has_req_anti_affinity)

        # Resource-only fast path for the REPRIEVE loop: for a PLAIN
        # preemptor (no global constraints, no host ports, no volumes) the
        # only node predicates that change as reprieved victims come back are
        # the resource/pod-count fits.  The INITIAL per-candidate check below
        # always runs the full oracle against the current snapshot — static
        # predicates (taints, cordon, selectors) may have changed since the
        # device candidate mask was computed (pipelined dispatch), and direct
        # Evaluator.preempt callers pass arbitrary candidates.  At 5k nodes
        # the full-oracle fits() per REPRIEVE step was the dominant
        # preemption cost (cap = n/10 = 500 dry-runs per pod).
        def full_fits() -> bool:
            feas = self.oracle.feasible_nodes(pod, others + [sim])
            return any(ni is sim for ni in feas)

        def fits() -> bool:
            from .oracle import fits_resources

            if plain:
                return fits_resources(pod, sim)
            return full_fits()

        if not full_fits():
            return None
        victims: List[v1.Pod] = []
        num_violating = 0
        potential.sort(key=lambda p: (-p.spec.priority, p.metadata.creation_timestamp or 0))
        violating, non_violating = pods_with_pdb_violation(potential, pdbs)

        def reprieve(p: v1.Pod) -> bool:
            sim.add_pod(p)
            if fits():
                return True
            sim.remove_pod(p)
            return False

        for p in violating:
            if not reprieve(p):
                victims.append(p)
                num_violating += 1
        for p in non_violating:
            if not reprieve(p):
                victims.append(p)
        if not victims:
            return None
        victims.sort(key=lambda p: (-p.spec.priority, p.metadata.creation_timestamp or 0))
        return Candidate(info.node_name, victims, num_violating)

    def select_victims_vectorized(
        self,
        pod: v1.Pod,
        infos: List[NodeInfo],
        pdbs: Sequence[v1.PodDisruptionBudget] = (),
        nominated: Optional[Dict[str, List[v1.Pod]]] = None,
    ) -> List[Optional[Candidate]]:
        """select_victims_on_node over ALL candidates at once for PLAIN
        preemptors (no global constraints, host ports, volumes, or scalar
        resources): the reprieve loop is a ≤Vmax-step numpy sweep over
        [C, 4] resource vectors instead of per-candidate NodeInfo
        clone/remove/add churn (which profiled as ~80% of preempt()).

        Exactly the serial semantics: victims sorted violating-first then by
        descending importance; each reprieve re-checks the resource fit with
        that victim restored (test_preemption asserts equality vs the serial
        path).  Static node predicates are the caller's responsibility (the
        device candidate mask), matching the serial fast path's contract.
        """
        from .api.resource import compute_pod_resource_request
        from .oracle import (
            node_affinity_fits,
            node_name_fits,
            node_schedulable,
            tolerates_all_hard_taints,
        )

        req = compute_pod_resource_request(pod)
        if req.scalar_resources:
            # an all-None return would alias "every candidate infeasible";
            # callers must route scalar-resource preemptors to the serial path
            raise ValueError(
                "select_victims_vectorized does not support preemptors with "
                "scalar (extended) resource requests; use select_victims_on_node"
            )

        def statics_ok(info) -> bool:
            # the serial path's full-oracle initial check re-verifies static
            # predicates against the CURRENT snapshot (they may have changed
            # since the device candidate mask was computed under pipelined
            # dispatch); reproduce exactly that portion here — ports/volumes
            # are excluded by the plain gate, resources are the vector pass
            node = info.node
            return (
                node is not None
                and node_name_fits(pod, node)
                and node_schedulable(pod, node)
                and node_affinity_fits(pod, node)
                and tolerates_all_hard_taints(pod, node)
            )
        req_v = np.array(
            [req.milli_cpu, req.memory, req.ephemeral_storage, 1], dtype=np.int64
        )
        c = len(infos)
        per_cand_victims: List[List[v1.Pod]] = []
        per_cand_viol: List[List[bool]] = []
        base = np.zeros((c, 4), dtype=np.int64)
        alloc = np.zeros((c, 4), dtype=np.int64)
        viable = np.zeros(c, dtype=bool)
        for ci, info in enumerate(infos):
            potential = [
                pi.pod for pi in info.pods if pi.pod.spec.priority < pod.spec.priority
            ]
            if not potential or not statics_ok(info):
                per_cand_victims.append([])
                per_cand_viol.append([])
                continue
            viable[ci] = True
            used = info.requested
            u = np.array(
                [used.milli_cpu, used.memory, used.ephemeral_storage, len(info.pods)],
                dtype=np.int64,
            )
            for victim in potential:
                vr = compute_pod_resource_request(victim)
                u -= (vr.milli_cpu, vr.memory, vr.ephemeral_storage, 1)
            for nom in (nominated or {}).get(info.node_name, []):
                if nom.uid != pod.uid and nom.spec.priority >= pod.spec.priority:
                    nr = compute_pod_resource_request(nom)
                    u += (nr.milli_cpu, nr.memory, nr.ephemeral_storage, 1)
            base[ci] = u
            al = info.allocatable
            alloc[ci] = (al.milli_cpu, al.memory, al.ephemeral_storage,
                         al.allowed_pod_number)
            potential.sort(
                key=lambda p: (-p.spec.priority, p.metadata.creation_timestamp or 0)
            )
            violating, non_violating = pods_with_pdb_violation(potential, pdbs)
            ordered = violating + non_violating
            per_cand_victims.append(ordered)
            per_cand_viol.append(
                [True] * len(violating) + [False] * len(non_violating)
            )

        vmax = max((len(v) for v in per_cand_victims), default=0)
        vr_mat = np.zeros((c, vmax, 4), dtype=np.int64)
        v_valid = np.zeros((c, vmax), dtype=bool)
        for ci, victims in enumerate(per_cand_victims):
            for vi, victim in enumerate(victims):
                vr = compute_pod_resource_request(victim)
                vr_mat[ci, vi] = (vr.milli_cpu, vr.memory, vr.ephemeral_storage, 1)
                v_valid[ci, vi] = True

        def fits(u):
            free = alloc - u
            return np.all((req_v == 0) | (req_v <= free), axis=1)

        feasible = viable & fits(base)
        used = base.copy()
        reprieved = np.zeros((c, vmax), dtype=bool)
        for vi in range(vmax):
            trial = used + vr_mat[:, vi]
            ok = fits(trial) & v_valid[:, vi] & feasible
            used = np.where(ok[:, None], trial, used)
            reprieved[:, vi] = ok

        out: List[Optional[Candidate]] = []
        for ci, info in enumerate(infos):
            if not feasible[ci]:
                out.append(None)
                continue
            victims = [
                p for vi, p in enumerate(per_cand_victims[ci])
                if not reprieved[ci, vi]
            ]
            if not victims:
                out.append(None)
                continue
            nviol = sum(
                1 for vi, p in enumerate(per_cand_victims[ci])
                if not reprieved[ci, vi] and per_cand_viol[ci][vi]
            )
            victims.sort(
                key=lambda p: (-p.spec.priority, p.metadata.creation_timestamp or 0)
            )
            out.append(Candidate(info.node_name, victims, nviol))
        return out

    def pick_one_node(self, candidates: List[Candidate]) -> Optional[Candidate]:
        """pickOneNodeForPreemption (:397): lexicographic 6-criteria."""
        if not candidates:
            return None
        pool = candidates
        pool = _argmin(pool, lambda c: c.num_pdb_violations)
        if len(pool) > 1:
            pool = _argmin(pool, lambda c: c.victims[0].spec.priority)
        if len(pool) > 1:
            pool = _argmin(
                pool, lambda c: sum(p.spec.priority + (1 << 31) for p in c.victims)
            )
        if len(pool) > 1:
            pool = _argmin(pool, lambda c: len(c.victims))
        if len(pool) > 1:
            # latest "earliest start time among the highest-priority victims"
            # wins (preemption.go:492-509 via util.GetEarliestPodStartTime):
            # prefer the node whose most-important victims are youngest.
            def earliest_high_priority_start(c: Candidate) -> int:
                # victims are sorted by descending priority (see sort above),
                # same invariant the criterion-2 tiebreak relies on
                top = c.victims[0].spec.priority
                return min(
                    (p.metadata.creation_timestamp or 0)
                    for p in c.victims
                    if p.spec.priority == top
                )

            pool = _argmin(pool, lambda c: -earliest_high_priority_start(c))
        return pool[0]

    def preempt(
        self,
        pod: v1.Pod,
        snapshot: Snapshot,
        candidate_nodes: Sequence[str],
        pdbs: Sequence[v1.PodDisruptionBudget] = (),
        max_candidates: Optional[int] = None,
        nominated: Optional[Dict[str, List[v1.Pod]]] = None,
        extenders: Sequence = (),
    ) -> Optional[Candidate]:
        """Evaluate candidates (already device-prefiltered), consult
        preemption-capable extenders, pick one.

        Candidate cap mirrors default_preemption.go:110-127:
        max(100, 10%·n) unless overridden.  Extender callout mirrors
        preemption.go callExtenders → HTTPExtender.ProcessPreemption
        (extender.go:164-207): each interested, preemption-capable extender
        filters the candidate map in turn; a non-ignorable error aborts the
        preemption attempt.
        """
        n = len(snapshot.node_info_list)
        cap = max_candidates or max(100, n // 10)
        node_infos = snapshot.node_info_list
        has_anti = bool(snapshot.have_pods_with_required_anti_affinity_list)
        candidates: List[Candidate] = []
        pool = list(candidate_nodes)
        if len(pool) > cap:
            start = self._offset % len(pool)
            self._offset += cap
            pool = pool[start:] + pool[:start]
        pool = pool[:cap]
        from .api.resource import compute_pod_resource_request

        vectorizable = (
            _is_plain_preemptor(pod, has_anti)
            and not compute_pod_resource_request(pod).scalar_resources
        )
        wants_all_candidates = any(
            getattr(e, "supports_preemption", False) and e.is_interested(pod)
            for e in extenders
        )
        if vectorizable and not wants_all_candidates:
            # shared-tables fast path: ranking needs only the winner, so the
            # per-candidate Candidate materialization (and the per-preemptor
            # table rebuild) is skipped entirely
            tables = self.plain_tables(snapshot, pod.spec.priority or 0, pdbs)
            return self.preempt_plain(pod, tables, pool, nominated=nominated)
        by_name = snapshot.node_info_map
        cand_infos = [by_name[name] for name in pool if name in by_name]
        if vectorizable:
            results = self.select_victims_vectorized(
                pod, cand_infos, pdbs, nominated=nominated
            )
            candidates = [c for c in results if c is not None]
            # an empty result is a legitimate outcome (all candidates
            # infeasible) — do NOT re-run the serial dry-runs for it
        else:
            for info in cand_infos:
                c = self.select_victims_on_node(
                    pod, info, node_infos, pdbs,
                    cluster_has_req_anti_affinity=has_anti,
                    nominated=nominated,
                )
                if c is not None:
                    candidates.append(c)
        candidates = self._call_extenders(pod, candidates, extenders)
        return self.pick_one_node(candidates)

    def _call_extenders(
        self, pod: v1.Pod, candidates: List[Candidate], extenders: Sequence
    ) -> List[Candidate]:
        if not candidates:
            return candidates
        for ext in extenders:
            if not getattr(ext, "supports_preemption", False):
                continue
            if not ext.is_interested(pod):
                continue
            victim_map = {
                c.node_name: {
                    "pods": list(c.victims),
                    "numPDBViolations": c.num_pdb_violations,
                }
                for c in candidates
            }
            filtered = ext.process_preemption(pod, victim_map)
            by_node = {c.node_name: c for c in candidates}
            out = []
            for node, entry in filtered.items():
                c = by_node.get(node)
                if c is None:
                    continue
                keep = {p.uid for p in entry["pods"]}
                victims = [p for p in c.victims if p.uid in keep]
                if victims:
                    out.append(Candidate(node, victims, entry["numPDBViolations"]))
            candidates = out
            if not candidates:
                break
        return candidates


def _argmin(pool, key):
    best = min(key(c) for c in pool)
    return [c for c in pool if key(c) == best]


def _is_plain_preemptor(pod: v1.Pod, cluster_has_req_anti_affinity: bool) -> bool:
    """One predicate for both the per-node fast path and the vectorized
    batch path: no global constraints (own topology spread / pod (anti)
    affinity, or existing-pod required anti-affinity), no host ports, no
    volumes — the regimes where victim eviction only moves resources."""
    aff = pod.spec.affinity
    return not (
        pod.spec.topology_spread_constraints
        or (aff and (aff.pod_affinity or aff.pod_anti_affinity))
        or cluster_has_req_anti_affinity
        or _pod_host_ports(pod)
        or _pod_volumes(pod)
    )


def _pod_host_ports(pod: v1.Pod) -> bool:
    # single source of truth for host-port extraction (node_info's helper)
    return bool(_node_info_host_ports(pod))


def _pod_volumes(pod: v1.Pod) -> bool:
    return bool(getattr(pod.spec, "volumes", None))
