"""Go-envelope baseline: an idealized, vectorized model of the reference's
default scheduler work profile, measured in-process.

The north star (BASELINE.md) is defined against "the default scheduler" — a
compiled Go binary this repo cannot run.  Rather than divide by the repo's
sequential Python oracle (three orders of magnitude slower than Go; the
round-3 strawman), this module measures an OPTIMISTIC stand-in that does the
same *work profile* the Go scheduler does, with every Python-side overhead
vectorized away:

  - one pod at a time (scheduleOne, pkg/scheduler/scheduler.go:496) with
    assume-style state carry between pods (:571);
  - adaptive node sampling: numFeasibleNodesToFind = max(100, n·pct/100),
    pct = 50 − n/125 floored at 5 (scheduler.go:67-76,852-872), scanning
    from the round-robin start index (:990,1025) and stopping at the cap;
  - the default plugin math of the benchmarked workloads (v1beta3 defaults,
    apis/config/v1beta3/default_plugins.go:32-51): NodeResourcesFit
    (LeastAllocated, w=1) filter+score and NodeResourcesBalancedAllocation
    (w=1), evaluated over the sampled nodes as numpy SIMD;
  - selectHost = argmax over scored nodes (scheduler.go:827-848).

Numpy SIMD over the sampled node window is at least as fast as 16 goroutines
of per-node interface calls and map lookups (parallelize/parallelism.go:41-56
fan-out of checkNode, scheduler.go:983-1023), so the measured per-attempt
times LOWER-BOUND what the Go scheduler could achieve on this hardware, and
any vs_go_envelope ratio computed against them is conservative (the real Go
scheduler would be slower per attempt, never faster).

What the model deliberately omits — each omission makes the envelope FASTER,
keeping the bound one-sided: queue pop/lock overhead, snapshot update,
PreFilter state construction, the remaining default plugins (taints, ports,
volumes, affinity — no-ops on the Basic/NorthStar workload shapes), metrics,
and binding API round-trips.
"""

from __future__ import annotations

import time
from typing import List

import numpy as np

from ..api import objects as v1
from ..api.resource import compute_pod_resource_request


def num_feasible_nodes_to_find(n: int) -> int:
    """scheduler.go:852-872 with defaults: percentageOfNodesToScore=0 →
    adaptive 50 − n/125, floor 5; result floored at minFeasibleNodesToFind
    (100)."""
    if n <= 100:
        return n
    pct = 50 - n / 125
    if pct < 5:
        pct = 5
    return max(100, int(n * pct / 100))


class GoEnvelope:
    """Vectorized one-pod-at-a-time scheduler over [N, R] resource arrays."""

    RES = 4  # milliCPU, memory, ephemeral-storage, pod-count

    def __init__(self, nodes: List[v1.Node], sample: bool = True):
        n = len(nodes)
        self.n = n
        self.allocatable = np.zeros((n, self.RES), dtype=np.float64)
        for i, node in enumerate(nodes):
            al = node.status.allocatable or node.status.capacity
            self.allocatable[i] = _quantities(al)
        self.requested = np.zeros((n, self.RES), dtype=np.float64)
        self.next_start = 0  # nextStartNodeIndex (scheduler.go:990,1025)
        # sample=False: score ALL nodes per pod — the work profile the Go
        # scheduler would need to match this repo's dense-scoring optimality
        # (it samples instead, trading placement quality for latency)
        self.sample = sample

    def schedule(self, pods: List[v1.Pod]):
        """Schedule pods sequentially; returns (assignments, attempt_seconds).

        assignments[i] = node index or -1.
        """
        n = self.n
        cap = num_feasible_nodes_to_find(n) if self.sample else n
        lat = np.zeros(len(pods))
        out = np.full(len(pods), -1, dtype=np.int64)
        order0 = np.arange(n)
        for k, pod in enumerate(pods):
            t0 = time.perf_counter()
            req = _pod_request(pod)
            # rotated scan order (round-robin fairness)
            order = np.roll(order0, -self.next_start)
            free = self.allocatable[order] - self.requested[order]
            fits = np.all((req == 0.0) | (req <= free), axis=1)
            # stop after `cap` feasible nodes, in scan order
            idx = np.flatnonzero(fits)
            if idx.size == 0:
                lat[k] = time.perf_counter() - t0
                continue
            found = idx[:cap]
            self.next_start = int(order[found[-1]] + 1) % n if idx.size >= cap else self.next_start
            rows = order[found]
            # LeastAllocated (least_allocated.go:29-57): mean over resources
            # of (cap − req)·100/cap, with the pod's request applied
            alloc = self.allocatable[rows][:, :2]
            used = self.requested[rows][:, :2] + req[:2]
            least = np.mean(
                np.where(alloc > 0, (alloc - used) * 100.0 / np.maximum(alloc, 1), 0.0),
                axis=1,
            )
            # BalancedAllocation (balanced_allocation.go): 100 − 100·std of
            # cpu/mem utilization fractions
            frac = np.where(alloc > 0, used / np.maximum(alloc, 1), 0.0)
            bal = 100.0 - 100.0 * np.std(frac, axis=1)
            score = np.floor(least) + np.floor(bal)
            best = rows[int(np.argmax(score))]
            self.requested[best] += req
            out[k] = best
            lat[k] = time.perf_counter() - t0
        return out, lat


def _quantities(res: dict) -> np.ndarray:
    from ..api.resource import Resource

    r = Resource.from_resource_list(res)
    return np.array(
        [float(r.milli_cpu), float(r.memory), float(r.ephemeral_storage),
         float(r.allowed_pod_number)]
    )


def _pod_request(pod: v1.Pod) -> np.ndarray:
    r = compute_pod_resource_request(pod)
    return np.array(
        [float(r.milli_cpu), float(r.memory), float(r.ephemeral_storage), 1.0]
    )


def envelope_stats(n_nodes: int, measure_pods: int, node_template=None,
                   pod_template=None, sample: bool = True) -> dict:
    """Run the envelope on the bench's node/pod shapes; per-attempt stats."""
    from .workloads import node_default, pod_default

    nodes = [(node_template or node_default)(i) for i in range(n_nodes)]
    pods = [(pod_template or pod_default)(i) for i in range(measure_pods)]
    env = GoEnvelope(nodes, sample=sample)
    t0 = time.perf_counter()
    assigned, lat = env.schedule(pods)
    wall = time.perf_counter() - t0
    lat_s = np.sort(lat)

    def q(p):
        return float(lat_s[min(len(lat_s) - 1, int(round(p * (len(lat_s) - 1))))])

    return {
        "nodes": n_nodes,
        "pods": measure_pods,
        "scheduled": int((assigned >= 0).sum()),
        "sampled_nodes_per_attempt": (
            num_feasible_nodes_to_find(n_nodes) if sample else n_nodes
        ),
        "attempt_ms": {
            "p50": round(1e3 * q(0.50), 4),
            "p90": round(1e3 * q(0.90), 4),
            "p99": round(1e3 * q(0.99), 4),
            "mean": round(1e3 * float(lat.mean()), 4),
            "max": round(1e3 * float(lat.max()), 4),
        },
        "throughput_pods_per_s": round(measure_pods / wall, 1) if wall > 0 else 0.0,
    }
