"""Go-envelope baseline: an idealized, vectorized model of the reference's
default scheduler work profile, measured in-process.

The north star (BASELINE.md) is defined against "the default scheduler" — a
compiled Go binary this repo cannot run.  Rather than divide by the repo's
sequential Python oracle (three orders of magnitude slower than Go; the
round-3 strawman), this module measures an OPTIMISTIC stand-in that does the
same *work profile* the Go scheduler does, with every Python-side overhead
vectorized away:

  - one pod at a time (scheduleOne, pkg/scheduler/scheduler.go:496) with
    assume-style state carry between pods (:571);
  - adaptive node sampling: numFeasibleNodesToFind = max(100, n·pct/100),
    pct = 50 − n/125 floored at 5 (scheduler.go:67-76,852-872), scanning
    from the round-robin start index (:990,1025) and stopping at the cap;
  - the default plugin math of the benchmarked workloads (v1beta3 defaults,
    apis/config/v1beta3/default_plugins.go:32-51): NodeResourcesFit
    (LeastAllocated, w=1) filter+score and NodeResourcesBalancedAllocation
    (w=1), evaluated over the sampled nodes as numpy SIMD;
  - selectHost = argmax over scored nodes (scheduler.go:827-848).

Numpy SIMD over the sampled node window is at least as fast as 16 goroutines
of per-node interface calls and map lookups (parallelize/parallelism.go:41-56
fan-out of checkNode, scheduler.go:983-1023), so the measured per-attempt
times LOWER-BOUND what the Go scheduler could achieve on this hardware, and
any vs_go_envelope ratio computed against them is conservative (the real Go
scheduler would be slower per attempt, never faster).

What the model deliberately omits — each omission makes the envelope FASTER,
keeping the bound one-sided: queue pop/lock overhead, snapshot update,
PreFilter state construction, the remaining default plugins (taints, ports,
volumes, affinity — no-ops on the Basic/NorthStar workload shapes), metrics,
and binding API round-trips.
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..api import objects as v1
from ..api.resource import compute_pod_resource_request


def num_feasible_nodes_to_find(n: int) -> int:
    """scheduler.go:852-872 with defaults: percentageOfNodesToScore=0 →
    adaptive 50 − n/125, floor 5; result floored at minFeasibleNodesToFind
    (100)."""
    if n <= 100:
        return n
    pct = 50 - n / 125
    if pct < 5:
        pct = 5
    return max(100, int(n * pct / 100))


class GoEnvelope:
    """Vectorized one-pod-at-a-time scheduler over [N, R] resource arrays.

    Per-suite work models (each vectorized, so each LOWER-BOUNDS the Go
    cost of the same phase):

    - ``spread`` (PodTopologySpread, podtopologyspread/filtering.go:256-289
      + scoring.go:108-213): per attempt, domain match counts are rebuilt
      from per-node counts (the reference's all-node parallel PreFilter),
      the skew check gates sampled nodes, per-domain scores invert counts.
    - ``ipa`` (InterPodAffinity, interpodaffinity/filtering.go:44-266 +
      scoring.go:79-209): per attempt, topologyPair→count maps are rebuilt
      from per-node selector-match counts (the reference iterates nodes ×
      their affinity pods); required (anti)affinity gates sampled nodes.
    - ``preemption`` (framework/preemption/preemption.go:546 DryRunPreemption
      + defaultpreemption/default_preemption.go:110-139): a failed attempt
      dry-runs max(100, n/10) candidates — per-candidate freed-resource fit
      plus the reprieve sweep over victim slots — evicts the winner's
      victims, and the pod retries as a SECOND attempt (the reference's
      nominate-and-requeue cadence).
    - ``churn_every`` / ``extender_callout_s``: see envelope_for_suite.
    """

    RES = 4  # milliCPU, memory, ephemeral-storage, pod-count
    VCAP = 8  # victim slots per node in the preemption model

    def __init__(self, nodes: List[v1.Node], sample: bool = True,
                 spread: Optional[dict] = None, ipa: Optional[dict] = None,
                 preemption: bool = False, extender_callout_s: float = 0.0,
                 churn_every: int = 0):
        n = len(nodes)
        self.n = n
        self.allocatable = np.zeros((n, self.RES), dtype=np.float64)
        for i, node in enumerate(nodes):
            al = node.status.allocatable or node.status.capacity
            self.allocatable[i] = _quantities(al)
        self.requested = np.zeros((n, self.RES), dtype=np.float64)
        self.next_start = 0  # nextStartNodeIndex (scheduler.go:990,1025)
        self._order0 = np.arange(n)  # hoisted: one arange, rolled per attempt
        # sample=False: score ALL nodes per pod — the work profile the Go
        # scheduler would need to match this repo's dense-scoring optimality
        # (it samples instead, trading placement quality for latency)
        self.sample = sample
        # topology domains: spread/ipa constraints reference a node label;
        # domain_id[i] = dictionary-encoded label value of node i
        self.spread = spread  # {"key": label key, "max_skew": int}
        self.ipa = ipa  # {"key": label key, "anti": bool}
        if spread or ipa:
            key = (spread or ipa)["key"]
            vals = {}
            self.domain_id = np.array(
                [vals.setdefault(
                    (node.metadata.labels or {}).get(key, node.metadata.name),
                    len(vals))
                 for node in nodes], dtype=np.int64)
            self.n_domains = len(vals)
            # per-node count of pods matching the suite's one selector
            # signature (maintained incrementally at bind/evict)
            self.match_count = np.zeros(n, dtype=np.float64)
        self.preemption = preemption
        if preemption:
            self.v_req = np.zeros((n, self.VCAP, self.RES), dtype=np.float64)
            self.v_prio = np.full((n, self.VCAP), np.iinfo(np.int64).max,
                                  dtype=np.int64)
            self.v_count = np.zeros(n, dtype=np.int64)
            # rotating candidate offset (preemption.go GetOffsetAndNumCandidates
            # draws rand.Intn per attempt): successive dry-runs must not
            # re-scan the same first-cap nodes
            self.pre_offset = 0
        self.extender_callout_s = extender_callout_s
        self.churn_every = churn_every
        self._pods_done = 0

    # -- state hooks ------------------------------------------------------

    def place(self, row: int, req: np.ndarray, prio: int = 0,
              matches: bool = False):
        """Record a pod on a node (init pre-scheduling and binds)."""
        self.requested[row] += req
        if (self.spread or self.ipa) and matches:
            self.match_count[row] += 1
        if self.preemption and self.v_count[row] < self.VCAP:
            s = self.v_count[row]
            self.v_req[row, s] = req
            self.v_prio[row, s] = prio
            self.v_count[row] += 1

    def _evict_below(self, row: int, prio: int, need: np.ndarray) -> None:
        """Evict lowest-importance victims below ``prio`` on ``row`` until
        ``need`` fits — the envelope's stand-in for SelectVictimsOnNode's
        minimal set (reprieve order approximated by ascending priority)."""
        order = np.argsort(self.v_prio[row, : self.v_count[row]])
        for vi in order:
            if self.v_prio[row, vi] >= prio:
                break
            free = self.allocatable[row] - self.requested[row]
            if np.all((need == 0.0) | (need <= free)):
                break
            self.requested[row] -= self.v_req[row, vi]
            self.v_prio[row, vi] = np.iinfo(np.int64).max
        # compact the slot arrays
        keep = self.v_prio[row] < np.iinfo(np.int64).max
        cnt = int(keep.sum())
        self.v_req[row, :cnt] = self.v_req[row, keep]
        self.v_prio[row, :cnt] = self.v_prio[row, keep]
        self.v_prio[row, cnt:] = np.iinfo(np.int64).max
        self.v_count[row] = cnt

    # -- the measured loop ------------------------------------------------

    def schedule(self, pods: List[v1.Pod]):
        """Schedule pods sequentially; returns (assignments, attempt_seconds).

        assignments[i] = node index or -1.  A preemption-model pod that
        fails, dry-runs, and retries contributes BOTH attempts to its
        latency sample (summed), matching how the measured path accrues a
        requeued pod's wall time.
        """
        lat = np.zeros(len(pods))
        out = np.full(len(pods), -1, dtype=np.int64)
        for k, pod in enumerate(pods):
            t0 = time.perf_counter()
            if self.churn_every and k and k % self.churn_every == 0:
                # recreate-mode churn: one node swap + one pod event; the
                # reference pays a cache update + queue move scan per event
                row = k % self.n
                self.requested[row] = 0.0
                if self.spread or self.ipa:
                    self.match_count[row] = 0.0
                if self.preemption:
                    self.v_count[row] = 0
                    self.v_prio[row] = np.iinfo(np.int64).max
            best = self._attempt(pod)
            if best < 0 and self.preemption:
                prio = pod.spec.priority or 0
                row = self._dry_run_preemption(pod, prio)
                if row >= 0:
                    self._evict_below(row, prio, _pod_request(pod))
                    best = self._attempt(pod)  # the requeued second attempt
            if best >= 0:
                self.place(best, _pod_request(pod),
                           prio=pod.spec.priority or 0, matches=True)
                out[k] = best
            lat[k] = time.perf_counter() - t0
            if self.extender_callout_s:
                # filter + prioritize callouts per attempt (extender.go:277,
                # 347); modeled, not slept — added to the recorded latency
                lat[k] += 2 * self.extender_callout_s
        return out, lat

    def _attempt(self, pod: v1.Pod) -> int:
        """One scheduling attempt: sampled filter + default-plugin score."""
        n = self.n
        cap = num_feasible_nodes_to_find(n) if self.sample else n
        req = _pod_request(pod)
        # rotated scan order (round-robin fairness)
        order = np.roll(self._order0, -self.next_start)
        free = self.allocatable[order] - self.requested[order]
        fits = np.all((req == 0.0) | (req <= free), axis=1)
        dom_counts = None
        if self.spread or self.ipa:
            # the all-nodes PreFilter map build the reference performs per
            # attempt (16-way parallel there, one bincount here)
            dom_counts = np.bincount(
                self.domain_id, weights=self.match_count,
                minlength=self.n_domains)
        if self.spread is not None and not self.spread.get("schedule_anyway"):
            skew_ok = (dom_counts[self.domain_id[order]] + 1.0
                       - dom_counts.min()) <= self.spread["max_skew"]
            fits &= skew_ok
        if self.ipa is not None and not self.ipa.get("preferred"):
            if self.ipa.get("anti"):
                fits &= dom_counts[self.domain_id[order]] == 0
            else:
                feasible_dom = (dom_counts > 0)
                fits &= feasible_dom[self.domain_id[order]]
        idx = np.flatnonzero(fits)
        if idx.size == 0:
            return -1
        found = idx[:cap]
        if idx.size >= cap:
            self.next_start = int(order[found[-1]] + 1) % n
        rows = order[found]
        # LeastAllocated (least_allocated.go:29-57): mean over resources
        # of (cap − req)·100/cap, with the pod's request applied
        alloc = self.allocatable[rows][:, :2]
        used = self.requested[rows][:, :2] + req[:2]
        least = np.mean(
            np.where(alloc > 0, (alloc - used) * 100.0 / np.maximum(alloc, 1), 0.0),
            axis=1,
        )
        # BalancedAllocation (balanced_allocation.go): 100 − 100·std of
        # cpu/mem utilization fractions
        frac = np.where(alloc > 0, used / np.maximum(alloc, 1), 0.0)
        bal = 100.0 - 100.0 * np.std(frac, axis=1)
        score = np.floor(least) + np.floor(bal)
        if self.spread is not None or self.ipa is not None:
            # spread Score: fewer matching pods in the domain is better
            # (scoring.go:180-213 normalized inversion); affinity Score:
            # more is better (scoring.go:79-209 weighted sums).  w=2 both.
            dcnt = dom_counts[self.domain_id[rows]]
            top = dcnt.max() if dcnt.size else 0.0
            if self.ipa is not None and not self.ipa.get("anti"):
                plane = np.where(top > 0, dcnt * 100.0 / max(top, 1.0), 0.0)
            else:
                plane = np.where(top > 0, (top - dcnt) * 100.0 / max(top, 1.0),
                                 100.0)
            score = score + 2.0 * np.floor(plane)
        return int(rows[int(np.argmax(score))])

    def _dry_run_preemption(self, pod: v1.Pod, prio: int) -> int:
        """DryRunPreemption over max(100, n/10) candidates (vectorized):
        freed-resource fit + the Vcap-step reprieve sweep, then the
        fewest-victims pick (pickOneNodeForPreemption criterion 4, the
        binding one on this suite's uniform-priority victims)."""
        n = self.n
        cap = max(100, n // 10)
        req = _pod_request(pod)
        cand = (np.arange(cap) + self.pre_offset) % n  # rotating offset
        self.pre_offset = (self.pre_offset + cap) % n
        lower = self.v_prio[cand] < prio  # [C, V]
        freed = (self.v_req[cand] * lower[:, :, None]).sum(axis=1)
        free = self.allocatable[cand] - self.requested[cand] + freed
        fits = np.all((req == 0.0) | (req <= free), axis=1)
        if not fits.any():
            return -1
        # reprieve sweep: re-add victims most-important-first while the pod
        # still fits (SelectVictimsOnNode's loop), counting survivors
        used = self.requested[cand] - freed
        order = np.argsort(-self.v_prio[cand], axis=1, kind="stable")
        victims = np.zeros(cap, dtype=np.int64)
        for vi in range(self.VCAP):
            slot = order[:, vi]
            vreq = np.take_along_axis(
                self.v_req[cand], slot[:, None, None], axis=1)[:, 0]
            vlow = np.take_along_axis(lower, slot[:, None], axis=1)[:, 0]
            trial = used + vreq
            ok = vlow & fits & np.all(
                (req == 0.0) | (req <= self.allocatable[cand] - trial), axis=1)
            used = np.where(ok[:, None], trial, used)
            victims += (vlow & fits & ~ok).astype(np.int64)
        victims = np.where(fits & (victims > 0), victims, np.iinfo(np.int64).max)
        best = int(np.argmin(victims))
        if victims[best] == np.iinfo(np.int64).max:
            return -1
        return int(cand[best])


def _quantities(res: dict) -> np.ndarray:
    from ..api.resource import Resource

    r = Resource.from_resource_list(res)
    return np.array(
        [float(r.milli_cpu), float(r.memory), float(r.ephemeral_storage),
         float(r.allowed_pod_number)]
    )


def _pod_request(pod: v1.Pod) -> np.ndarray:
    r = compute_pod_resource_request(pod)
    return np.array(
        [float(r.milli_cpu), float(r.memory), float(r.ephemeral_storage), 1.0]
    )


#: modeled per-callout cost for the extender suite's envelope: loopback TCP
#: round trip + minimal JSON encode/decode in Go's net/http + encoding/json
#: (~40µs RTT + ~60µs serialization at 500-name lists) — deliberately
#: optimistic so the bound stays one-sided
EXTENDER_CALLOUT_S = 100e-6


def suite_envelope_config(suite: str, n_nodes: int, init_pods: int) -> dict:
    """Per-suite envelope setup: node/init-pod templates + the suite's
    dominant default-plugin work model (VERDICT r4 #4 — the Fit-only
    envelope was printed as the comparator for constraint suites whose
    reference cost is the quadratic topology term or preemption dry-runs).
    Keys: node_template, init_template, init_count, init_matches,
    and GoEnvelope kwargs."""
    from . import workloads as w

    base = {"node_template": w.node_default, "init_template": None,
            "init_count": 0, "init_matches": False, "kwargs": {},
            "measure_template": None}
    if suite == "PreferredTopologySpreading":
        base.update(
            node_template=w.node_zoned(w.ZONES3),
            init_template=w.pod_default, init_count=init_pods,
            measure_template=w.pod_preferred_topology_spread,
            kwargs={"spread": {"key": "topology.kubernetes.io/zone",
                               "max_skew": 5, "schedule_anyway": True}},
        )
    elif suite == "SchedulingPreferredPodAffinity":
        base.update(
            node_template=w.node_unique_hostname,
            init_template=w.pod_preferred_affinity("sched-0"),
            init_count=init_pods, init_matches=True,
            measure_template=w.pod_preferred_affinity("sched-1"),
            kwargs={"ipa": {"key": "kubernetes.io/hostname",
                            "anti": False, "preferred": True}},
        )
    elif suite == "SchedulingNodeAffinity":
        base.update(
            node_template=w.node_zoned(["zone1"]),
            init_template=w.pod_node_affinity, init_count=init_pods,
            measure_template=w.pod_node_affinity,
        )
    elif suite == "TopologySpreading":
        base.update(
            node_template=w.node_zoned(w.ZONES3),
            init_template=w.pod_default, init_count=init_pods,
            measure_template=w.pod_topology_spread,
            kwargs={"spread": {"key": "topology.kubernetes.io/zone",
                               "max_skew": 5}},
        )
    elif suite == "SchedulingPodAntiAffinity":
        base.update(
            node_template=w.node_unique_hostname,
            init_template=w.pod_anti_affinity("sched-0"),
            init_count=init_pods, init_matches=True,
            measure_template=w.pod_anti_affinity("sched-1"),
            kwargs={"ipa": {"key": "kubernetes.io/hostname", "anti": True}},
        )
    elif suite == "SchedulingPodAffinity":
        base.update(
            node_template=w.node_zoned(["zone1"]),
            init_template=w.pod_affinity("sched-0"),
            init_count=init_pods, init_matches=True,
            measure_template=w.pod_affinity("sched-1"),
            kwargs={"ipa": {"key": "topology.kubernetes.io/zone",
                            "anti": False}},
        )
    elif suite == "PreemptionBasic":
        base.update(
            init_template=w.pod_low_priority, init_count=init_pods,
            measure_template=w.pod_high_priority,
            kwargs={"preemption": True},
        )
    elif suite == "SchedulingWithMixedChurn":
        base.update(kwargs={"churn_every": 8})
    elif suite == "SchedulingExtender":
        base.update(
            init_template=w.pod_default, init_count=init_pods,
            kwargs={"extender_callout_s": EXTENDER_CALLOUT_S},
        )
    elif suite == "Unschedulable":
        # the 9-cpu fillers cost one full-scan failing attempt each before
        # the window; the measured pods' profile is Basic
        base.update(init_template=w.pod_default, init_count=init_pods)
    else:  # SchedulingBasic / NorthStar / Density
        base.update(init_template=w.pod_default, init_count=init_pods)
    return base


def envelope_stats(n_nodes: int, measure_pods: int, node_template=None,
                   pod_template=None, sample: bool = True,
                   suite: Optional[str] = None, init_pods: int = 0) -> dict:
    """Run the envelope on the bench's node/pod shapes; per-attempt stats.

    With ``suite`` the envelope carries that suite's plugin work model and
    pre-schedules its init pods (suite_envelope_config); without it, the
    Fit+BalancedAllocation profile on default shapes (Basic/NorthStar)."""
    from .workloads import node_default, pod_default

    cfg = suite_envelope_config(suite, n_nodes, init_pods) if suite else None
    if cfg and node_template is None:
        node_template = cfg["node_template"]
    if cfg and pod_template is None:
        pod_template = cfg["measure_template"]
    nodes = [(node_template or node_default)(i) for i in range(n_nodes)]
    pods = [(pod_template or pod_default)(i) for i in range(measure_pods)]
    env = GoEnvelope(nodes, sample=sample, **(cfg["kwargs"] if cfg else {}))
    if cfg and cfg["init_count"] and cfg["init_template"]:
        # round-robin placement mirrors what the measured path's init phase
        # produces (balanced spread) and respects per-node capacity
        for i in range(cfg["init_count"]):
            p = cfg["init_template"](1_000_000 + i)
            env.place(i % n_nodes, _pod_request(p),
                      prio=p.spec.priority or 0, matches=cfg["init_matches"])
    t0 = time.perf_counter()
    assigned, lat = env.schedule(pods)
    wall = time.perf_counter() - t0
    lat_s = np.sort(lat)

    def q(p):
        return float(lat_s[min(len(lat_s) - 1, int(round(p * (len(lat_s) - 1))))])

    return {
        "nodes": n_nodes,
        "pods": measure_pods,
        "scheduled": int((assigned >= 0).sum()),
        "sampled_nodes_per_attempt": (
            num_feasible_nodes_to_find(n_nodes) if sample else n_nodes
        ),
        "attempt_ms": {
            "p50": round(1e3 * q(0.50), 4),
            "p90": round(1e3 * q(0.90), 4),
            "p99": round(1e3 * q(0.99), 4),
            "mean": round(1e3 * float(lat.mean()), 4),
            "max": round(1e3 * float(lat.max()), 4),
        },
        "throughput_pods_per_s": round(measure_pods / wall, 1) if wall > 0 else 0.0,
    }
