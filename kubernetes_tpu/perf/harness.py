"""Config-driven workload DSL + metric collection.

Reference: test/integration/scheduler_perf —
  opcodes createNodes/createPods/createNamespaces/churn/barrier
    (scheduler_perf_test.go:60-71)
  throughput collector: scheduled-pods/s sampled at 1s
    (util.go:278-345, label SchedulingThroughput)
  histogram quantiles p50/p90/p95/p99 from the in-process registry
    (util.go:238-276), emitted as perf-dashboard DataItems (util.go:165)

The workload runs against the in-process sim store + TPUScheduler — the analog
of the reference's in-proc apiserver+etcd with API-object-only nodes.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from ..api import objects as v1
from ..metrics import scheduler_metrics as m
from ..scheduler import TPUScheduler
from ..sim.store import ObjectStore
from ..testutil import make_node, make_pod


@dataclass
class Op:
    """One opcode. kinds: createNodes | createPods | createObjects |
    barrier | churn."""

    opcode: str
    count: int = 0
    node_template: Optional[Callable[[int], v1.Node]] = None
    pod_template: Optional[Callable[[int], v1.Pod]] = None
    collect_metrics: bool = False
    churn_deletes: int = 0
    # createPods only: don't drive the scheduler to completion afterwards
    # (scheduler_perf skipWaitToCompletion — e.g. permanently unschedulable
    # filler pods)
    skip_wait: bool = False
    # createObjects: i → (kind, object) for non-Node/Pod setup objects
    # (PodGroups for the gang suites, services, quotas, ...)
    object_template: Optional[Callable[[int], tuple]] = None
    # createPods only: pods a DRIVEN controller (the make_descheduler
    # hook) creates during the measured window on top of this op's own
    # count — the wait loop and throughput target include them.  A
    # driven pod is recognized by birth rv (> the window's start rv), so
    # init/warm pods never count (TrainingJobFlow: the controller expands
    # TrainingJob CRs into gang pods mid-window)
    driven_pods: int = 0


@dataclass
class Workload:
    name: str
    ops: List[Op] = field(default_factory=list)
    batch_size: int = 64
    # recreate-mode churn hook, called between scheduling cycles of the
    # measured step with (store, cycle_index) — the synchronous analog of
    # scheduler_perf's background churn goroutine
    churn_between_cycles: Optional[Callable] = None
    # () -> (extenders list, cleanup fn): suites measuring the extender path
    make_extenders: Optional[Callable] = None
    # gang suites: members per PodGroup — turns on the gangs/s +
    # time-to-full-slice collectors over the measured window
    gang_size: Optional[int] = None
    # (store, sched) -> controller with sync_once(): a descheduler driven
    # once per measured cycle (the Defrag suite) — turns on the
    # evictions/s collector; with gang_size set, TimeToFullSlice doubles
    # as time-to-free-slice (the window spans defrag + gang bind)
    make_descheduler: Optional[Callable] = None
    # the driven controller is a cluster-autoscaler (AutoscaleGang):
    # collect scale-decision + whatif-fork items instead of evictions/s
    autoscaler: bool = False
    # DRA suites (DeviceClaimGang): collect the claims/s item from the
    # window's dra_claims_allocated_total{result=allocated} delta
    dra: bool = False
    # the driven controller expands TrainingJob CRs (TrainingJobFlow):
    # emit the jobs/s item (a job completes when its gang fully binds)
    # instead of the descheduler evictions item
    trainingjob: bool = False
    # arms the scheduler's adaptive micro-bucket policy (TPUScheduler
    # latency_target_ms): dedup-eligible constraint-free batches split into
    # pow-2 sub-buckets until the recent attempt p99 fits under the target.
    # The harness warms every bucket tier pre-window so the policy's
    # zero-compile gate can engage (see the tier-warm loop below).
    latency_target_ms: Optional[float] = None
    # warm-variant trims for suites whose window provably never runs them:
    # warm_coupled=False skips the synthetic anti-affinity warm (the greedy
    # SCAN variant — minutes of compile at a 131k-node tier the 100k basic
    # suite never routes to); warm_preemption=False keeps the failure-path
    # warm pod at priority 0 (diagnosis still warms; the preemption
    # candidate program — a [K, N, R] level table + [B, N, R] freed tensor,
    # multi-GB at 100k shapes — never compiles because the window's
    # priority-0 pods can never preempt)
    warm_coupled: bool = True
    warm_preemption: bool = True


@dataclass
class DataItem:
    labels: Dict[str, str]
    data: Dict[str, float]
    unit: str

    def to_dict(self):
        return {"labels": self.labels, "data": self.data, "unit": self.unit}


def default_node(i: int) -> v1.Node:
    return (
        make_node().name(f"node-{i:06d}")
        .capacity({"cpu": "32", "memory": "64Gi", "pods": "110"})
        .label("topology.kubernetes.io/zone", f"zone-{i % 16}")
        .obj()
    )


def default_pod(i: int) -> v1.Pod:
    return (
        make_pod().name(f"pod-{i:06d}").uid(f"pod-{i:06d}").namespace("default")
        .label("app", f"app-{i % 10}")
        .req({"cpu": "1", "memory": "2Gi"})
        .obj()
    )


def run_workload(w: Workload, clock=time.perf_counter) -> List[DataItem]:
    from ..metrics.registry import default_registry

    default_registry.reset()
    # re-bind module-level metric objects after reset
    import importlib
    importlib.reload(m)

    store = ObjectStore()
    # pipeline: batch N's binding cycle overlaps batch N+1's device window
    # (the reference's async binding goroutine, scheduler.go:623)
    extenders, ext_cleanup = [], None
    if w.make_extenders is not None:
        extenders, ext_cleanup = w.make_extenders()
    # Span tracing (component_base/trace.py): the in-memory ring feeds the
    # AttemptPhaseLatency item (per-pod attempt records → p50/p90/p99 per
    # phase, reconstructed from spans); with KTPU_TRACE_DIR set, a Chrome
    # trace-event JSONL artifact (one per suite run, Perfetto-loadable) is
    # written alongside — tools/run_suites.sh sets it and gates on both.
    import os as _os

    from ..component_base.trace import (ChromeTraceExporter,
                                        InMemoryExporter, Tracer)

    span_ring = InMemoryExporter(max_spans=262144)
    exporters: List = [span_ring]
    chrome = None
    trace_dir = _os.environ.get("KTPU_TRACE_DIR")
    trace_path = ""
    if trace_dir:
        _os.makedirs(trace_dir, exist_ok=True)
        trace_path = _os.path.join(
            trace_dir, w.name.replace("/", "_") + ".trace.jsonl")
        chrome = ChromeTraceExporter(trace_path)
        exporters.append(chrome)
    # tracer clock == scheduler clock (time.monotonic): scheduler spans
    # stamp explicitly from the scheduler clock, and matching the tracer's
    # default keeps any tracer-clock spans in the same artifact timeline
    tracer = Tracer(clock=time.monotonic, exporters=exporters)
    sched = TPUScheduler(store, batch_size=w.batch_size, pipeline=True,
                         extenders=extenders, tracer=tracer,
                         latency_target_ms=w.latency_target_ms)
    # Pre-size tiers to the run's full extent so no measured cycle pays a
    # DeviceSnapshot shape change (= full program-suite recompile).  The
    # micro-bucket tier warm bursts add up to 5×(batch/2) transient pods on
    # top of the init set — without the headroom the largest burst grows
    # the pod tier mid-warmup and every already-warm program recompiles.
    sched.presize(
        sum(op.count for op in w.ops if op.opcode == "createNodes"),
        # driven pods (created in-window by a controller, not the harness)
        # occupy pod tiers exactly like harness-created ones — leaving them
        # out lets the tier grow mid-window, a full program recompile
        sum(op.count + op.driven_pods
            for op in w.ops if op.opcode == "createPods")
        + (3 * w.batch_size if w.latency_target_ms is not None else 0),
    )
    from ..utils.compilemon import monitor

    monitor.install()
    desched = (w.make_descheduler(store, sched)
               if w.make_descheduler is not None else None)
    items: List[DataItem] = []
    node_idx = 0
    pod_idx = 0
    for op in w.ops:
        if op.opcode == "createNodes":
            tmpl = op.node_template or default_node
            for _ in range(op.count):
                store.create("Node", tmpl(node_idx))
                node_idx += 1
        elif op.opcode == "createObjects":
            # per-OP indices: a workload stacking several createObjects ops
            # (Defrag: stragglers then PodGroups) numbers each template
            # from 0, so cross-referencing templates (gang pods naming
            # their pg-{i}) line up
            for j in range(op.count):
                kind, obj = op.object_template(j)
                store.create(kind, obj)
        elif op.opcode == "createPods":
            tmpl = op.pod_template or default_pod
            if op.collect_metrics:
                # jit warmup BEFORE the measured pods exist: drive FOUR
                # disposable pods through back-to-back cycles so the program
                # variants compile pre-window — pod 1 the full-upload
                # snapshot path, pod 2 the steady-state scatter path, pod 3
                # the coupled greedy-scan variant (anti-affinity), pod 4 the
                # failure path (diagnosis fetch + jitted candidate mask);
                # each is a different traced shape, and compiling one
                # mid-window cost the Unschedulable suite a 6s stall — the
                # reference has no compile phase to exclude
                warm_keys = []  # (namespace, name) — suite templates may be namespaced
                for wi in range(4):
                    if wi == 2 and not w.warm_coupled:
                        # suite window provably never routes to the coupled
                        # scan engine (Workload.warm_coupled)
                        continue
                    warm = (
                        make_pod().name(f"warmup-pod{wi}").uid(f"warmup-pod{wi}")
                        .namespace("default").req({"cpu": "1m"})
                        .label("warmup", "1")
                    )
                    if wi == 2:
                        # a cross-pod-coupled pod routes through the greedy
                        # scan engine (fused_greedy) — a different program
                        # that otherwise compiles on the first anti-affinity
                        # batch inside the window
                        warm = warm.pod_affinity(
                            "kubernetes.io/hostname", {"warmup": "1"}, anti=True
                        )
                    if wi == 3:
                        # an unschedulable pod warms the FAILURE path: the
                        # diagnosis fetch AND the jitted preemption
                        # candidate-mask program (run per failing batch).
                        # Priority 1 makes it preemption-CAPABLE (the earlier
                        # priority-0 warmup pods rank strictly lower, so
                        # can_preempt holds and the ~200-TFLOP cand einsum
                        # compiles HERE, not on the window's first failing
                        # batch — measured 11.7s in-window at 5k/25k);
                        # the 100000-cpu request can't fit any node even
                        # with every victim evicted, so the warm preemption
                        # nominates nothing and disturbs nothing.
                        # warm_preemption=False keeps priority 0: the
                        # failure/diagnosis path still warms, the candidate
                        # program (multi-GB at a 131k tier) never compiles —
                        # sound only when the window can never preempt.
                        warm = warm.req({"cpu": "100000"})
                        if w.warm_preemption:
                            warm = warm.priority(1)
                    warm = warm.obj()
                    store.create("Pod", warm)
                    sched.schedule_cycle()
                    sched.schedule_cycle()  # pipeline: complete + bind it
                    if wi == 2:
                        # delete the anti-affinity warm pod IMMEDIATELY: a
                        # scheduled required-anti-affinity pod makes
                        # host_prepare build existing-pod anti term tables
                        # for EVERY later batch, so the template warms would
                        # warm a program variant the (anti-pod-free) window
                        # never runs — and the window's first batch would
                        # compile the tables-compiled-out variant in-window
                        sched.run_until_idle(max_cycles=4)
                        store.delete("Pod", warm.metadata.namespace,
                                     warm.metadata.name)
                    else:
                        warm_keys.append((warm.metadata.namespace,
                                          warm.metadata.name))
                # …and pods from the SUITE'S OWN template: its label /
                # constraint shapes can differ from the synthetic warmups'
                # sticky caps, and the first template batch would otherwise
                # compile (or cache-load, seconds) its program variant
                # inside the measured window.
                # FOUR template warms covering the {coupled-batch engine} ×
                # {upload path} variant matrix: #0/#1 dispatch TWO template
                # pods — a 2-pod batch of a coupled template (anti/affinity/
                # spread) forms a multi-pod conflict component and routes to
                # the SCAN engine exactly like the window's full batches
                # (1-pod warms route singleton components to the batch
                # engine since the round-6 partitioner, leaving the scan
                # variant to cold-compile mid-window — measured one in-window
                # compile collapsing the scaled anti suite); #2/#3 dispatch
                # ONE pod (the batch-engine variant window TAIL batches may
                # take).  #0 additionally takes the full-upload path via
                # first-seen topology-key registration, #1 rides the steady
                # row-SCATTER path, #2 forces FULL-UPLOAD (dirty bursts past
                # the scatter bucket take it mid-window), #3 scatter again.
                for wi in range(4):
                    for j in range(2 if wi < 2 else 1):
                        warm = tmpl(9_990_000 + 2 * wi + j)
                        # warm pods must be NON-DISRUPTIVE: a high-priority
                        # suite template (PreemptionBasic) would otherwise
                        # preempt init pods that are never restored,
                        # corrupting the measured window's declared initial
                        # state.  preemptionPolicy is data, not shape — the
                        # program variant warms identically.
                        warm.spec.preemption_policy = "Never"
                        warm_keys.append((warm.metadata.namespace,
                                          warm.metadata.name))
                        store.create("Pod", warm)
                    if wi == 2:
                        sched.encoder.force_full_next()
                    sched.schedule_cycle()
                    sched.schedule_cycle()
                if w.latency_target_ms is not None:
                    # Micro-bucket tier warm BURSTS: each pow-2 sub-bucket
                    # pad is a fresh compiled shape, so warm every tier
                    # pre-window with the SUITE'S OWN template (scatter AND
                    # forced-full upload variants — a mid-window dirty
                    # burst takes the full path at whatever tier is
                    # active).  Bursts run 5×tier pods through the REAL
                    # pipelined regime, so the scheduler's per-tier latency
                    # profiles (_tier_p99) are measured, not guessed — the
                    # FIRST window cycle then dispatches at the tier that
                    # fits the target, instead of blowing the window p99
                    # with convergence traffic at full batch size.  5 full
                    # batches per tier because the tier's two shape
                    # compiles (scatter + forced-full executions) stall
                    # the first 2-3 overlapping dispatch→bind windows,
                    # which the profile EMA rightly excludes — the last
                    # batches are both compile-clean AND steady-state
                    # (a 3-batch burst left middle tiers unprofiled and
                    # fed the rest first-execution-inflated samples).
                    for ti, tier in enumerate(sched.bucket_tiers()):
                        burst = []
                        # 100k stride per tier: 5×tier can exceed 10k at
                        # large batch sizes, and colliding warm-pod names
                        # across tiers would break the later tier's burst
                        for j in range(5 * tier):
                            warm = tmpl(9_000_000 + 100_000 * ti + j)
                            warm.spec.preemption_policy = "Never"
                            burst.append((warm.metadata.namespace,
                                          warm.metadata.name))
                            store.create("Pod", warm)
                        sched._forced_bucket = tier
                        sched.schedule_cycle()  # scatter-upload variant
                        sched.encoder.force_full_next()  # full variant next
                        for _ in range(32):
                            s = sched.schedule_cycle()
                            if s.attempted == 0 and s.in_flight == 0:
                                break
                        for ns, name in burst:
                            store.delete("Pod", ns, name)
                    sched._forced_bucket = None
                for ns, name in warm_keys:
                    store.delete("Pod", ns, name)
                if w.latency_target_ms is not None:
                    # settle dispatch: the tier bursts just deleted
                    # thousands of warm pods, and that encoder debt would
                    # otherwise ride the FIRST window dispatch's snapshot
                    # top-up (measured ~450 ms — which IS the window p99
                    # once the window runs at micro-bucket tiers).  Flush
                    # it through one disposable dispatch pre-window.
                    settle = tmpl(9_970_000)
                    settle.spec.preemption_policy = "Never"
                    store.create("Pod", settle)
                    sched.schedule_cycle()
                    sched.schedule_cycle()
                    sched.run_until_idle(max_cycles=4)
                    store.delete("Pod", settle.metadata.namespace,
                                 settle.metadata.name)
                if w.churn_between_cycles is not None:
                    # exercise the churn hook once pre-window: the objects
                    # it creates (service → selector-spread host tables,
                    # churn node/pod) change the fused program's host-aux
                    # pytree, and the first in-window churn batch otherwise
                    # pays that re-trace as an in-window compile
                    def _key(o):
                        return (getattr(o.metadata, "namespace", "") or "",
                                o.metadata.name)

                    pre = {
                        kind: {_key(o) for o in store.list(kind)[0]}
                        for kind in ("Node", "Pod", "Service")
                    }
                    w.churn_between_cycles(store, 0)
                    sched.schedule_cycle()
                    sched.schedule_cycle()
                    # second call with the SAME cycle index exercises the
                    # recreate path (delete + re-add of the churn node/pod/
                    # service), and the full-upload variant is re-warmed
                    # against the churn-present aux structure (service
                    # tables in host_auxes)
                    w.churn_between_cycles(store, 0)
                    sched.encoder.force_full_next()
                    sched.schedule_cycle()
                    sched.schedule_cycle()
                    for kind, had in pre.items():
                        for o in list(store.list(kind)[0]):
                            ns, name = _key(o)
                            if (ns, name) not in had:
                                store.delete(kind, ns, name)
                        # contract: the warm churn calls must only have
                        # CREATED objects — a hook that deleted/renamed
                        # pre-existing state would corrupt the measured
                        # window's declared initial cluster silently
                        now = {_key(o) for o in store.list(kind)[0]}
                        missing = had - now
                        assert not missing, (
                            f"churn hook removed pre-existing {kind} "
                            f"objects during warmup: {sorted(missing)[:4]}"
                        )
            created = []
            for _ in range(op.count):
                p = tmpl(pod_idx)
                store.create("Pod", p)
                created.append(p)
                pod_idx += 1
            if op.collect_metrics:
                # measure only this step: drop attempts recorded while
                # scheduling the init/warmup pods (scheduler_perf collects
                # the metric delta over the measured window, util.go:238-276)
                m.scheduling_attempt_duration.reset()
                pending_names = {(p.namespace, p.metadata.name) for p in created}
                target = len(created) + op.driven_pods
                done = 0
                # keys already counted toward ``done`` — guards both the
                # driven-pod path and re-emitted MODIFIED events of an
                # already-bound pod from double-counting
                counted: set = set()
                # gang suites: per-group bind counts → time-to-full-slice
                # (window start → the gang's LAST member bound)
                gang_counts: Dict[str, int] = {}
                gang_done_t: List[float] = []
                # window start rv: driven-controller pods are born after it
                rv0 = store.current_rv()

                def on_bind(ev):
                    nonlocal done
                    if ev.kind != "Pod" or not ev.obj.spec.node_name:
                        return
                    key = (ev.obj.namespace, ev.obj.metadata.name)
                    if key in pending_names:
                        pending_names.discard(key)
                        counted.add(key)
                    elif (op.driven_pods and ev.resource_version > rv0
                          and key not in counted):
                        counted.add(key)  # driven pod binding in-window
                    else:
                        return
                    done += 1
                    if w.gang_size:
                        from ..gang import POD_GROUP_LABEL

                        g = ev.obj.metadata.labels.get(POD_GROUP_LABEL)
                        if g:
                            gang_counts[g] = gang_counts.get(g, 0) + 1
                            if gang_counts[g] == w.gang_size:
                                gang_done_t.append(clock() - t0)

                unwatch = store.watch(on_bind)
                # per-phase wall snapshot (scheduler.phase_wall): the window
                # delta attributes suite time to host_prepare / partition /
                # dispatch / fetch / bind so a regression names its phase
                phase0 = dict(sched.phase_wall)

                def _claims_allocated() -> float:
                    return sum(
                        v for (labels, v)
                        in m.dra_claims_allocated.items().items()
                        if labels and labels[0] == "allocated")

                # window delta: the warm pods' claim commits must not count
                claims0 = _claims_allocated() if w.dra else 0.0
                # Stop-the-world gen-2 GC pauses (CPython re-scans the
                # whole warmed object graph — 5k Node/NodeInfo trees,
                # compiled batches, programs: measured 120-180 ms each,
                # escalating over the run) land inside individual attempt
                # windows and alone set the micro-bucket window's p99.
                # Freeze the long-lived warmup graph out of the collector
                # for the measured window (the reference's concurrent Go
                # GC has no comparable pause); gen0/1 stay active for the
                # window's own garbage, and unfreeze restores normal
                # collection right after the loop.
                import gc as _gc

                _gc.collect()
                _gc.freeze()
                # span-window start: only the measured window's attempt
                # records feed the per-phase latency item below
                span_ring.clear()
                t0 = clock()
                t_last_progress = t0
                cycle = 0
                stall = 0
                waited = 0.0
                # steady-state split: attempts from cycles with ZERO backend
                # compiles, so the bench can report what the scheduler costs
                # once warm separately from compile-affected cycles
                steady: List[float] = []
                win_c0, win_s0 = monitor.snapshot()
                hist = m.scheduling_attempt_duration
                max_cycles = max(64, 4 * (target // max(w.batch_size, 1) + 1))
                # per-cycle wall times + captured >100ms dispatch traces so a
                # straggler cycle in the artifact is ATTRIBUTABLE (which step
                # of which cycle) rather than a bare max (VERDICT r3 weak #7)
                cycle_durs: List[float] = []
                slow_traces: List[str] = []
                import logging as _logging

                class _TraceTap(_logging.Handler):
                    def emit(self, record):
                        if len(slow_traces) < 16:
                            slow_traces.append(
                                f"cycle {cycle}: " + record.getMessage()
                            )

                _tap = _TraceTap()
                _trace_log = _logging.getLogger("kubernetes_tpu.trace")
                _prev_level = _trace_log.level
                _trace_log.addHandler(_tap)
                _trace_log.setLevel(_logging.INFO)
                try:
                    while done < target and cycle < max_cycles:
                        if w.churn_between_cycles is not None:
                            w.churn_between_cycles(store, cycle)
                        # index into the CAPPED raw-sample list, not count():
                        # they diverge once the histogram drops samples
                        n_samp = len(hist.samples())
                        c_pre = monitor.snapshot()[0]
                        done_pre = done
                        t_cyc = clock()
                        stats = sched.schedule_cycle()
                        if desched is not None:
                            # external snapshot/encoder reader: barrier the
                            # overlapped background sync first
                            sched.join_sync_ahead()
                            desched.sync_once()
                        cycle_durs.append(clock() - t_cyc)
                        if monitor.snapshot()[0] == c_pre:
                            steady.extend(hist.samples()[n_samp:])
                        if done > done_pre:
                            t_last_progress = clock()
                        if stats.attempted == 0 and stats.in_flight == 0 \
                                and done == done_pre:
                            # queue drained this instant, but pods may be waiting
                            # out their backoff (1s→10s) or the unschedulableQ
                            # flush — the reference's flush goroutines just tick;
                            # spin-wait rather than misreading backoff as done.
                            # Active counts too: a driven controller's
                            # sync_once above may have just created pods this
                            # cycle never saw.
                            a, b, u = sched.queue.pending_count()
                            if (a == 0 and b == 0 and u == 0
                                    and stats.waiting == 0) \
                                    or waited > 30.0:
                                break
                            time.sleep(0.02)
                            waited += 0.02
                            continue
                        cycle += 1
                        # progress = binds observed by the watcher, not
                        # just this call's own stats: a descheduler's
                        # quiescence-flush cycles (sync_once) bind pods
                        # whose stats the harness never sees
                        if stats.scheduled == 0 and done == done_pre:
                            stall += 1
                            # permanently unschedulable backlog (e.g. the
                            # Unschedulable suite's 9-cpu fillers) — give up
                            # once nothing progresses for a few cycles
                            if stall >= 8 and waited > 12.0:
                                break
                        else:
                            stall = 0
                            waited = 0.0
                            t_last_progress = clock()
                    # throughput window ends at the LAST bind, not after any
                    # terminal backoff spin-wait — otherwise a tail of permanently
                    # unschedulable pods dilutes the number with sleep time
                    total_s = (t_last_progress if done else clock()) - t0
                    win_c1, win_s1 = monitor.snapshot()
                    unwatch()
                    n_done = done
                    throughput = n_done / total_s if total_s > 0 else 0.0
                    items.append(DataItem(
                        labels={"Name": w.name, "Metric": "SchedulingThroughput"},
                        data={"Average": round(throughput, 1)},
                        unit="pods/s",
                    ))
                    if desched is not None and w.autoscaler:
                        ups = sum(
                            v for (labels, v)
                            in m.autoscaler_scale_decisions.items().items()
                            if len(labels) == 2 and labels[0] == "up"
                            and labels[1] == "applied"
                        )
                        items.append(DataItem(
                            labels={"Name": w.name,
                                    "Metric": "AutoscalerScaleUps"},
                            data={"Count": float(ups)},
                            unit="decisions",
                        ))
                        forks = m.whatif_forks.value(())
                        items.append(DataItem(
                            labels={"Name": w.name, "Metric": "WhatIfForks"},
                            data={"Count": float(forks),
                                  "PerSecond": (round(forks / total_s, 2)
                                                if total_s > 0 else 0.0)},
                            unit="forks/s",
                        ))
                    elif desched is not None and w.trainingjob:
                        jobs = float(len(gang_done_t))
                        items.append(DataItem(
                            labels={"Name": w.name,
                                    "Metric": "TrainingJobThroughput"},
                            data={"Jobs": jobs,
                                  "PerSecond": (round(jobs / total_s, 2)
                                                if total_s > 0 else 0.0)},
                            unit="jobs/s",
                        ))
                    elif desched is not None:
                        evicted = sum(
                            v for (labels, v)
                            in m.descheduler_evictions.items().items()
                            if len(labels) == 2
                            and labels[1] in ("evicted", "overridden")
                        )
                        items.append(DataItem(
                            labels={"Name": w.name,
                                    "Metric": "DeschedulerEvictions"},
                            data={"Count": float(evicted),
                                  "PerSecond": (round(evicted / total_s, 2)
                                                if total_s > 0 else 0.0)},
                            unit="evictions/s",
                        ))
                    if w.dra:
                        allocated = _claims_allocated() - claims0
                        items.append(DataItem(
                            labels={"Name": w.name,
                                    "Metric": "ClaimsAllocated"},
                            data={"Count": float(allocated),
                                  "PerSecond": (round(allocated / total_s, 2)
                                                if total_s > 0 else 0.0)},
                            unit="claims/s",
                        ))
                    if w.gang_size:
                        gd = sorted(gang_done_t)

                        def _gq(q: float) -> float:
                            if not gd:
                                return 0.0
                            return gd[min(len(gd) - 1,
                                          max(0, int(round(q * (len(gd) - 1)))))]

                        items.append(DataItem(
                            labels={"Name": w.name, "Metric": "GangThroughput"},
                            data={"Average": (round(len(gd) / total_s, 2)
                                              if total_s > 0 else 0.0),
                                  "Gangs": float(len(gd))},
                            unit="gangs/s",
                        ))
                        items.append(DataItem(
                            labels={"Name": w.name, "Metric": "TimeToFullSlice"},
                            data={"Perc50": _gq(0.50), "Perc90": _gq(0.90),
                                  "Max": gd[-1] if gd else 0.0},
                            unit="s",
                        ))
                    samples = sorted(hist.samples())

                    def _exact(vals: List[float], q: float) -> float:
                        """Nearest-rank quantile of a pre-sorted plain list (the
                        steady-state split below isn't a Histogram; the histogram
                        path uses Histogram.exact_quantile — same definition)."""
                        if not vals:
                            return 0.0
                        return vals[min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))]

                    items.append(DataItem(
                        labels={
                            "Name": w.name,
                            "Metric": "scheduler_scheduling_attempt_duration_seconds",
                        },
                        data={
                            "Perc50": hist.quantile(0.50),
                            "Perc90": hist.quantile(0.90),
                            "Perc95": hist.quantile(0.95),
                            "Perc99": hist.quantile(0.99),
                            "Average": hist.sum() / max(hist.count(), 1),
                            # exact quantiles from raw samples — the bucket ones
                            # above saturate at the top bucket edge (round-2 p99
                            # railed at 16.384s); these never do
                            "ExactPerc50": hist.exact_quantile(0.50),
                            "ExactPerc90": hist.exact_quantile(0.90),
                            "ExactPerc99": hist.exact_quantile(0.99),
                            "Max": samples[-1] if samples else 0.0,
                        },
                        unit="s",
                    ))
                    steady.sort()
                    items.append(DataItem(
                        labels={
                            "Name": w.name,
                            "Metric": "attempt_duration_steady_state",
                        },
                        data={
                            "Perc50": _exact(steady, 0.50),
                            "Perc90": _exact(steady, 0.90),
                            "Perc99": _exact(steady, 0.99),
                            "Max": steady[-1] if steady else 0.0,
                            "Count": float(len(steady)),
                            "TotalCount": float(len(samples)),
                        },
                        unit="s",
                    ))
                finally:
                    _gc.unfreeze()
                    _trace_log.removeHandler(_tap)
                    _trace_log.setLevel(_prev_level)
                cyc = sorted(cycle_durs)

                def _cq(q):
                    if not cyc:
                        return 0.0
                    return cyc[min(len(cyc) - 1, max(0, int(round(q * (len(cyc) - 1)))))]

                items.append(DataItem(
                    labels={
                        "Name": w.name,
                        "Metric": "CycleDurations",
                        # slow-dispatch step traces captured in-window, so a
                        # straggler max cycle is attributable line-by-line
                        "SlowTraces": " | ".join(slow_traces)[:4000],
                    },
                    data={
                        "Perc50": _cq(0.50),
                        "Perc99": _cq(0.99),
                        "Max": cyc[-1] if cyc else 0.0,
                        "Count": float(len(cyc)),
                    },
                    unit="s",
                ))
                items.append(DataItem(
                    labels={"Name": w.name, "Metric": "XLACompilesInWindow"},
                    data={
                        "Count": float(win_c1 - win_c0),
                        "Seconds": round(win_s1 - win_s0, 3),
                    },
                    unit="compiles",
                ))
                items.append(DataItem(
                    labels={"Name": w.name, "Metric": "PhaseWallBreakdown"},
                    data={
                        k: round(sched.phase_wall[k] - phase0.get(k, 0.0), 4)
                        for k in sched.phase_wall
                    },
                    unit="s",
                ))
                # per-phase attempt latency reconstructed FROM SPANS: the
                # attempt roots carry one record per pod with the three
                # tiling phases (dispatch/device/bind — they sum exactly to
                # that pod's attempt) plus queue_wait; Coverage compares
                # the sum of tiling p50s against the measured end-to-end
                # attempt p50 (the no-unattributed-wall-clock contract the
                # run_suites.sh gate enforces at 10%)
                recs = span_ring.attempt_records()
                ph_data: Dict[str, float] = {"Records": float(len(recs))}
                for ph in ("dispatch", "device", "bind", "queue_wait"):
                    vals = sorted(r[ph] for r in recs)
                    for qname, q in (("Perc50", 0.50), ("Perc90", 0.90),
                                     ("Perc99", 0.99)):
                        ph_data[f"{ph}_{qname}"] = _exact(vals, q)
                ph_data["SumPerc50"] = sum(
                    ph_data[f"{p}_Perc50"] for p in ("dispatch", "device",
                                                     "bind"))
                ph_data["AttemptPerc50"] = hist.exact_quantile(0.50)
                ph_data["Coverage"] = (
                    ph_data["SumPerc50"] / ph_data["AttemptPerc50"]
                    if ph_data["AttemptPerc50"] > 0 else 0.0)
                items.append(DataItem(
                    labels={"Name": w.name, "Metric": "AttemptPhaseLatency",
                            "TraceArtifact": trace_path},
                    data=ph_data,
                    unit="s",
                ))
            elif not op.skip_wait:
                sched.run_until_idle()
        elif op.opcode == "barrier":
            sched.run_until_idle()
        elif op.opcode == "churn":
            pods, _ = store.list("Pod")
            rng = np.random.default_rng(0)
            for p in rng.choice(pods, size=min(op.churn_deletes, len(pods)), replace=False):
                store.delete("Pod", p.namespace, p.metadata.name)
            sched.run_until_idle()
        else:
            raise ValueError(f"unknown opcode {op.opcode}")
    sched.close()  # release the store watch + extender callout pool
    if chrome is not None:
        chrome.close()  # terminate the JSON array so the artifact loads
    if ext_cleanup is not None:
        ext_cleanup()
    return items


def data_items_to_json(items: List[DataItem]) -> str:
    """Perf-dashboard JSON shape (util.go:165 dataItems2JSONFile)."""
    return json.dumps({"version": "v1", "dataItems": [i.to_dict() for i in items]})
