"""Named benchmark workloads ported from the reference's scheduler_perf suite.

Reference: test/integration/scheduler_perf/config/performance-config.yaml
(+ the pod/node templates it references).  Each suite mirrors the reference
shape — node template (4 cpu / 32Gi / 110 pods, node-default.yaml), pod
templates (100m/500Mi default pod, 900m low-priority, 3000m priority-10
high-priority, 9-cpu unschedulable, color-selector affinity/spread pods) —
scaled by (initNodes, initPods, measurePods) params.

Sizes follow the reference's named workloads; `scale` lets tests run the
same shapes tiny.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..api import objects as v1
from ..testutil import make_node, make_pod
from .harness import Op, Workload

ZONES3 = ["moon-1", "moon-2", "moon-3"]


def node_default(i: int) -> v1.Node:
    return (
        make_node().name(f"node-{i:06d}")
        .capacity({"cpu": "4", "memory": "32Gi", "pods": "110"})
        .obj()
    )


def node_unique_hostname(i: int) -> v1.Node:
    return (
        make_node().name(f"node-{i:06d}")
        .capacity({"cpu": "4", "memory": "32Gi", "pods": "110"})
        .label("kubernetes.io/hostname", f"node-{i:06d}")
        .obj()
    )


def node_zoned(zones: List[str]) -> Callable[[int], v1.Node]:
    def tmpl(i: int) -> v1.Node:
        return (
            make_node().name(f"node-{i:06d}")
            .capacity({"cpu": "4", "memory": "32Gi", "pods": "110"})
            .label("topology.kubernetes.io/zone", zones[i % len(zones)])
            .obj()
        )

    return tmpl


def _base_pod(i: int, prefix: str, ns: str = "default"):
    return (
        make_pod().name(f"{prefix}-{i:06d}").uid(f"{prefix}-{i:06d}")
        .namespace(ns)
    )


def pod_default(i: int, ns: str = "default") -> v1.Pod:
    return _base_pod(i, "pod", ns).req({"cpu": "100m", "memory": "500Mi"}).obj()


def pod_low_priority(i: int) -> v1.Pod:
    return _base_pod(i, "low", "default").req(
        {"cpu": "900m", "memory": "500Mi"}
    ).obj()


def pod_high_priority(i: int) -> v1.Pod:
    return (
        _base_pod(i, "high", "default")
        .req({"cpu": "3000m", "memory": "500Mi"})
        .priority(10)
        .obj()
    )


def pod_large_cpu(i: int) -> v1.Pod:
    return _base_pod(i, "large", "default").req(
        {"cpu": "9", "memory": "500Mi"}
    ).obj()


def pod_anti_affinity(ns: str) -> Callable[[int], v1.Pod]:
    """pod-with-pod-anti-affinity.yaml: color=green, required anti-affinity
    on kubernetes.io/hostname across sched-0/sched-1."""

    def tmpl(i: int) -> v1.Pod:
        return (
            _base_pod(i, f"anti-{ns}", ns)
            .req({"cpu": "100m", "memory": "500Mi"})
            .label("color", "green")
            .pod_affinity(
                "kubernetes.io/hostname", {"color": "green"}, anti=True,
                namespaces=["sched-0", "sched-1"],
            )
            .obj()
        )

    return tmpl


def pod_affinity(ns: str) -> Callable[[int], v1.Pod]:
    """pod-with-pod-affinity.yaml: color=blue, required affinity on zone."""

    def tmpl(i: int) -> v1.Pod:
        return (
            _base_pod(i, f"aff-{ns}", ns)
            .req({"cpu": "100m", "memory": "500Mi"})
            .label("color", "blue")
            .pod_affinity(
                "topology.kubernetes.io/zone", {"color": "blue"},
                namespaces=["sched-0", "sched-1"],
            )
            .obj()
        )

    return tmpl


def pod_topology_spread(i: int) -> v1.Pod:
    """pod-with-topology-spreading.yaml: maxSkew=5 DoNotSchedule on zone."""
    return (
        _base_pod(i, "spread", "default")
        .req({"cpu": "100m", "memory": "500Mi"})
        .label("color", "blue")
        .topology_spread(
            5, "topology.kubernetes.io/zone", labels={"color": "blue"}
        )
        .obj()
    )


def pod_preferred_topology_spread(i: int) -> v1.Pod:
    """pod-with-preferred-topology-spreading.yaml: maxSkew=5 ScheduleAnyway."""
    return (
        _base_pod(i, "pspread", "default")
        .req({"cpu": "100m", "memory": "500Mi"})
        .label("color", "blue")
        .topology_spread(
            5, "topology.kubernetes.io/zone",
            when_unsatisfiable=v1.SCHEDULE_ANYWAY,
            labels={"color": "blue"},
        )
        .obj()
    )


def pod_node_affinity(i: int) -> v1.Pod:
    """pod-with-node-affinity.yaml: required node affinity zone In
    {zone1, zone2}."""
    return (
        _base_pod(i, "naff", "default")
        .req({"cpu": "100m", "memory": "500Mi"})
        .node_affinity_in("topology.kubernetes.io/zone", ["zone1", "zone2"])
        .obj()
    )


def pod_preferred_affinity(ns: str) -> Callable[[int], v1.Pod]:
    """pod-with-preferred-pod-affinity.yaml: color=red, PREFERRED (w=1)
    affinity on hostname across sched-0/sched-1."""

    def tmpl(i: int) -> v1.Pod:
        return (
            _base_pod(i, f"paff-{ns}", ns)
            .req({"cpu": "100m", "memory": "500Mi"})
            .label("color", "red")
            .pod_affinity(
                "kubernetes.io/hostname", {"color": "red"}, weight=1,
                namespaces=["sched-1", "sched-0"],
            )
            .obj()
        )

    return tmpl


@dataclass
class Suite:
    name: str
    build: Callable[[int, int, int], Workload]  # (initNodes, initPods, measurePods)
    sizes: Dict[str, tuple]  # workload name → (initNodes, initPods, measurePods)
    # per-suite device batch override (None = the build's default): an int,
    # or a dict keyed by size name for suites whose sizes want different
    # operating points.  The deep-queue NorthStar runs B=512: the tunnel's
    # fixed per-cycle cost (~150ms chained dispatch + ~100ms fetch)
    # dominates the ~10ms of device compute, so doubling the batch nearly
    # doubles throughput — measured 1002 → 2024 pods/s (256 → 512) with
    # attempt p99 DROPPING 0.94 → 0.62s (fewer cycles per backlog wave);
    # 1024 pushed p99 to 0.90s for +13% throughput — past the knee
    # (tools/profile_suite.py, round 5).
    batch_size: Optional[object] = None
    # arms the scheduler's adaptive micro-bucket policy (round 15): float
    # ms or a dict keyed by size name (None = off, the full-batch shape).
    # Suites with a target get per-tier warm bursts pre-window (harness).
    latency_target_ms: Optional[object] = None


def _basic(n, p, mp) -> Workload:
    w = Workload(
        name="SchedulingBasic",
        ops=[
            Op("createNodes", n, node_template=node_default),
            Op("createPods", p, pod_template=pod_default),
            Op("createPods", mp, pod_template=pod_default, collect_metrics=True),
        ],
        batch_size=256,
    )
    if n >= 50_000:
        # production-scale shape (NorthStar/100kNodes): the window is
        # priority-0 uncoupled template pods, so the greedy-SCAN warm
        # variant and the preemption candidate program can never run — at a
        # 131k-node tier each would cost minutes of compile (and the cand
        # program a multi-GB freed tensor) for a path the suite never takes
        w.warm_coupled = False
        w.warm_preemption = False
    return w


def _anti_affinity(n, p, mp) -> Workload:
    return Workload(
        name="SchedulingPodAntiAffinity",
        ops=[
            Op("createNodes", n, node_template=node_unique_hostname),
            Op("createPods", p, pod_template=pod_anti_affinity("sched-0")),
            Op("createPods", mp, pod_template=pod_anti_affinity("sched-1"),
               collect_metrics=True),
        ],
        batch_size=256,
    )


def _affinity(n, p, mp) -> Workload:
    return Workload(
        name="SchedulingPodAffinity",
        ops=[
            Op("createNodes", n, node_template=node_zoned(["zone1"])),
            Op("createPods", p, pod_template=pod_affinity("sched-0")),
            Op("createPods", mp, pod_template=pod_affinity("sched-1"),
               collect_metrics=True),
        ],
        batch_size=256,
    )


def _topology(n, p, mp) -> Workload:
    return Workload(
        name="TopologySpreading",
        ops=[
            Op("createNodes", n, node_template=node_zoned(ZONES3)),
            Op("createPods", p, pod_template=pod_default),
            Op("createPods", mp, pod_template=pod_topology_spread,
               collect_metrics=True),
        ],
        batch_size=256,
    )


def _node_affinity(n, p, mp) -> Workload:
    return Workload(
        name="SchedulingNodeAffinity",
        ops=[
            Op("createNodes", n, node_template=node_zoned(["zone1"])),
            Op("createPods", p, pod_template=pod_node_affinity),
            Op("createPods", mp, pod_template=pod_node_affinity,
               collect_metrics=True),
        ],
        batch_size=256,
    )


def _preferred_affinity(n, p, mp) -> Workload:
    return Workload(
        name="SchedulingPreferredPodAffinity",
        ops=[
            Op("createNodes", n, node_template=node_unique_hostname),
            Op("createPods", p, pod_template=pod_preferred_affinity("sched-0")),
            Op("createPods", mp,
               pod_template=pod_preferred_affinity("sched-1"),
               collect_metrics=True),
        ],
        batch_size=256,
    )


def _preferred_topology(n, p, mp) -> Workload:
    return Workload(
        name="PreferredTopologySpreading",
        ops=[
            Op("createNodes", n, node_template=node_zoned(ZONES3)),
            Op("createPods", p, pod_template=pod_default),
            Op("createPods", mp, pod_template=pod_preferred_topology_spread,
               collect_metrics=True),
        ],
        batch_size=256,
    )


def _preemption(n, p, mp) -> Workload:
    return Workload(
        name="PreemptionBasic",
        ops=[
            Op("createNodes", n, node_template=node_default),
            Op("createPods", p, pod_template=pod_low_priority),
            Op("createPods", mp, pod_template=pod_high_priority,
               collect_metrics=True),
        ],
        batch_size=256,
    )


def _unschedulable(n, p, mp) -> Workload:
    return Workload(
        name="Unschedulable",
        ops=[
            Op("createNodes", n, node_template=node_default),
            # 9-cpu pods can never fit a 4-cpu node; they churn the
            # unschedulable queue while the measured pods schedule
            Op("createPods", p, pod_template=pod_large_cpu,
               skip_wait=True),
            Op("createPods", mp, pod_template=pod_default,
               collect_metrics=True),
        ],
        batch_size=256,
    )


def _extender(n, p, mp) -> Workload:
    """SchedulingBasic shape with ONE HTTP extender on the path — measures
    the round-based extender cadence (VERDICT r3 weak #5: within 3× of the
    no-extender path).  The extender runs in a SUBPROCESS, as a real
    extender would (the reference's is a separate binary by definition):
    the protocol cost measured is the scheduler-side client + wire, not
    the extender's own handler sharing the scheduler's GIL."""
    import multiprocessing as mp_

    from ..extender import ExtenderConfig, HTTPExtender

    def make_extenders():
        # the subprocess target lives in extender.py: a spawn child imports
        # only stdlib modules, not the jax stack behind the perf package
        from functools import partial

        from ..extender import run_subprocess_score_server, uniform_score_fn

        ctx = mp_.get_context("spawn")
        parent, child = ctx.Pipe()
        proc = ctx.Process(
            target=partial(run_subprocess_score_server, uniform_score_fn),
            args=(child,), daemon=True)
        proc.start()
        if not parent.poll(60):
            proc.terminate()
            raise RuntimeError("extender subprocess failed to start")
        port = parent.recv()
        ext = HTTPExtender(ExtenderConfig(
            url_prefix=f"http://127.0.0.1:{port}", filter_verb="filter",
            prioritize_verb="prioritize", weight=1, node_cache_capable=True,
        ))

        def stop():
            ext.close()
            proc.terminate()
            proc.join(timeout=5)

        return [ext], stop

    return Workload(
        name="SchedulingExtender",
        ops=[
            Op("createNodes", n, node_template=node_default),
            Op("createPods", p, pod_template=pod_default),
            Op("createPods", mp, pod_template=pod_default, collect_metrics=True),
        ],
        batch_size=256,
        make_extenders=make_extenders,
    )


GANG_SIZE = 8  # members per slice job (one multi-host TPU slice)


def node_sliced(gang_size: int = GANG_SIZE) -> Callable[[int], v1.Node]:
    """One TPU host VM per node, ``gang_size`` hosts per slice — the slice
    label feeds the Coscheduling anchor-slice score plane."""
    from ..gang import SLICE_LABEL

    def tmpl(i: int) -> v1.Node:
        return (
            make_node().name(f"node-{i:06d}")
            .capacity({"cpu": "4", "memory": "32Gi", "pods": "110"})
            .label(SLICE_LABEL, f"slice-{i // gang_size:05d}")
            .obj()
        )

    return tmpl


def pod_gang(gang_size: int = GANG_SIZE) -> Callable[[int], v1.Pod]:
    """Gang member i belongs to PodGroup pg-{i // gang_size}; the 3-cpu
    request packs ONE member per 4-cpu host (a slice job owns its hosts).
    Harness warmup indices (≥9M) yield plain pods: warms must exercise the
    normal bind path, not park at the quorum gate behind a group that
    doesn't exist."""
    from ..gang import POD_GROUP_LABEL

    def tmpl(i: int) -> v1.Pod:
        if i >= 9_000_000:
            return pod_default(i)
        return (
            _base_pod(i, "gang", "default")
            .label(POD_GROUP_LABEL, f"pg-{i // gang_size:05d}")
            .req({"cpu": "3000m", "memory": "500Mi"})
            .obj()
        )

    return tmpl


def podgroup_template(gang_size: int = GANG_SIZE) -> Callable[[int], tuple]:
    def tmpl(i: int):
        pg = v1.PodGroup(
            metadata=v1.ObjectMeta(name=f"pg-{i:05d}", namespace="default"),
            min_member=gang_size,
            schedule_timeout_seconds=60,
        )
        return ("PodGroup", pg)

    return tmpl


def _gang_basic(n, p, mp) -> Workload:
    # a scaled-down dev run may shrink mp below the slice size: shrink the
    # gang with it so every group can still reach quorum
    gs = GANG_SIZE if mp >= GANG_SIZE else max(2, mp)
    ngangs = max(1, mp // gs)
    return Workload(
        name="GangBasic",
        ops=[
            Op("createNodes", n, node_template=node_sliced(gs)),
            Op("createObjects", ngangs, object_template=podgroup_template(gs)),
            Op("createPods", ngangs * gs, pod_template=pod_gang(gs),
               collect_metrics=True),
        ],
        batch_size=64,
        gang_size=gs,
    )


def straggler_per_host() -> Callable[[int], v1.Pod]:
    """Straggler i lands PRE-BOUND on host i (a 2-cpu pod on a 4-cpu
    host): with one on EVERY host no slice — and no cross-slice set of
    hosts — can take a 3-cpu gang member, so the gangs are genuinely
    blocked until the descheduler frees whole slices.  Pre-binding keeps
    the fragmentation pattern deterministic and affinity-free (the
    what-if planner refuses affinity-carrying victims by contract).
    Warmup indices (≥9M) yield tiny UNBOUND pods that fit beside any
    straggler — warms must exercise the normal bind path."""

    def tmpl(i: int) -> v1.Pod:
        if i >= 9_000_000:
            return (_base_pod(i, "stragwarm", "default")
                    .req({"cpu": "1m"}).obj())
        return (
            _base_pod(i, "strag", "default")
            .req({"cpu": "2000m", "memory": "500Mi"})
            .label("strag", "1")
            .node(f"node-{i:06d}")
            .obj()
        )

    return tmpl


def _defrag(n, p, mp) -> Workload:
    """Defrag: every host starts fragmented by a pre-bound straggler; the
    gangs are unschedulable until the descheduler's slice-defrag policy
    evicts whole straggler sets (each plan scored by ONE device what-if
    solve) — measures time-to-free-slice (TimeToFullSlice spans defrag +
    gang bind) and evictions/s (DeschedulerEvictions)."""
    from ..descheduler import DeschedulerController, SliceDefragmentation

    gs = GANG_SIZE if mp >= GANG_SIZE else max(2, mp)
    n_slices = max(1, n // gs)
    ngangs = max(1, min(mp // gs, n_slices))
    stragglers = min(p, n) if p else n
    strag_tmpl = straggler_per_host()
    gang_tmpl = pod_gang(gs)

    def make_descheduler(store, sched):
        # 16 gangs served per sync keeps the 5k size (312 waiting gangs)
        # inside the harness's cycle budget; each freed slice costs gs
        # straggler evictions
        return DeschedulerController(
            store, sched,
            policies=[SliceDefragmentation(max_gangs_per_sync=16)],
            max_evictions_per_sync=16 * gs,
        )

    return Workload(
        name="Defrag",
        ops=[
            Op("createNodes", n, node_template=node_sliced(gs)),
            # stragglers ride createPods (presize counts them into the pod
            # tier — no mid-window growth recompile); pre-bound, so the
            # post-op run_until_idle is a no-op
            Op("createPods", stragglers, pod_template=strag_tmpl),
            Op("createObjects", ngangs, object_template=podgroup_template(gs)),
            # the harness's global pod index continues past the
            # stragglers: shift so gang pod i still references pg-{i//gs}
            Op("createPods", ngangs * gs,
               pod_template=lambda i: gang_tmpl(
                   i if i >= 9_000_000 else i - stragglers),
               collect_metrics=True),
        ],
        batch_size=64,
        gang_size=gs,
        make_descheduler=make_descheduler,
    )


def _autoscale_gang(n, p, mp) -> Workload:
    """AutoscaleGang: gang demand outnumbers the initial capacity — only
    the first slices' worth of gangs can seat; the rest starve until the
    cluster-autoscaler simulates and applies scale-ups from a NodeGroup
    (whole fresh slices per decision, whatif node-add forks).  Measures
    time-to-capacity (TimeToFullSlice spans starve → scale-up → bind),
    scale decisions applied, and whatif plans/s.  Mid-window node-tier
    growth (and its recompiles) is the measured cost by design — a
    scale-up on a live cluster pays exactly that."""
    from ..autoscaler import ClusterAutoscaler, NodeGroup

    gs = GANG_SIZE if mp >= GANG_SIZE else max(2, mp)
    ngangs = max(1, mp // gs)
    need = ngangs * gs

    def nodegroup_template(i: int):
        ng = NodeGroup(
            metadata=v1.ObjectMeta(name="asg", namespace="default"),
            min_size=0, max_size=need + gs,
            capacity={"cpu": "4", "memory": "32Gi", "pods": "110"},
            slice_size=gs,
        )
        return ("NodeGroup", ng)

    def make_autoscaler(store, sched):
        # one sync per measured cycle; candidate-size fan-out capped so a
        # sync's vmapped solve stays a handful of forks
        return ClusterAutoscaler(store, sched, max_simulated_sizes=4)

    return Workload(
        name="AutoscaleGang",
        ops=[
            Op("createNodes", n, node_template=node_sliced(gs)),
            Op("createObjects", 1, object_template=nodegroup_template),
            Op("createObjects", ngangs, object_template=podgroup_template(gs)),
            Op("createPods", ngangs * gs, pod_template=pod_gang(gs),
               collect_metrics=True),
        ],
        batch_size=64,
        gang_size=gs,
        make_descheduler=make_autoscaler,
        autoscaler=True,
    )


# --- Dynamic resource allocation (DRA) --------------------------------------

CHIPS_PER_HOST = 4  # chips each host's ResourceSlice publishes

# warm-pod offsets the harness's template warms actually dispatch
# (9_990_000 + 2*wi + j — see harness.py); the warm pool provisions one
# claim + one singleton PodGroup per offset so claim-carrying warm batches
# compile the SAME program variant (gang aux + claim planes) as the window
DRA_WARM_POOL = 8


def dra_class_template(i: int) -> tuple:
    from ..dra.api import DeviceClass

    return ("DeviceClass",
            DeviceClass(metadata=v1.ObjectMeta(name="tpu")))


def dra_slice_template(gang_size: int = GANG_SIZE) -> Callable[[int], tuple]:
    """ResourceSlice j publishes host node-j's chips into pool slice-{j//gs}
    — the TPU driver's per-node inventory, one slice label per pool."""
    from ..dra.api import (ATTR_CHIP_INDEX, ATTR_HOST, ATTR_MEMORY,
                           ATTR_SLICE, Device, ResourceSlice)

    def tmpl(j: int) -> tuple:
        host = f"node-{j:06d}"
        sl = f"slice-{j // gang_size:05d}"
        devs = [
            # device names carry the host: unique within the pool (several
            # hosts publish into one slice's pool), so "<pool>/<device>"
            # pins (slice, host, chip) exactly
            Device(name=f"{host}-chip{c}", attributes={
                ATTR_SLICE: sl, ATTR_HOST: host,
                ATTR_CHIP_INDEX: str(c), ATTR_MEMORY: "16",
            })
            for c in range(CHIPS_PER_HOST)
        ]
        return ("ResourceSlice", ResourceSlice(
            metadata=v1.ObjectMeta(name=f"rs-{host}"),
            node_name=host, pool=sl, devices=devs))

    return tmpl


def dra_claim_template(j: int) -> tuple:
    from ..dra.api import DeviceRequest, ResourceClaim

    return ("ResourceClaim", ResourceClaim(
        metadata=v1.ObjectMeta(name=f"gangclaim-{j:06d}",
                               namespace="default"),
        request=DeviceRequest(device_class_name="tpu",
                              count=CHIPS_PER_HOST)))


def dra_warm_node(n: int) -> Callable[[int], v1.Node]:
    """One dedicated warm host (index n, its own slice label): warm pods
    pin here via node selector, so the chips their claims consume — left
    Reserved when the harness deletes the warm pods — never shrink a
    production slice below a gang's demand."""
    from ..gang import SLICE_LABEL

    def tmpl(i: int) -> v1.Node:
        return (
            make_node().name(f"node-{i:06d}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
            .label("dra-warm", "1")
            .label(SLICE_LABEL, "slice-warm")
            .obj()
        )

    return tmpl


def dra_warm_slice(n: int) -> Callable[[int], tuple]:
    from ..dra.api import (ATTR_CHIP_INDEX, ATTR_HOST, ATTR_SLICE, Device,
                           ResourceSlice)

    def tmpl(j: int) -> tuple:
        host = f"node-{n:06d}"
        devs = [
            Device(name=f"chip{c}", attributes={
                ATTR_SLICE: "slice-warm", ATTR_HOST: host,
                ATTR_CHIP_INDEX: str(c),
            })
            for c in range(2 * DRA_WARM_POOL)
        ]
        return ("ResourceSlice", ResourceSlice(
            metadata=v1.ObjectMeta(name=f"rs-{host}"),
            node_name=host, pool="slice-warm", devices=devs))

    return tmpl


def dra_warm_claim_template(j: int) -> tuple:
    from ..dra.api import DeviceRequest, ResourceClaim

    return ("ResourceClaim", ResourceClaim(
        metadata=v1.ObjectMeta(name=f"warmclaim-{j}", namespace="default"),
        request=DeviceRequest(device_class_name="tpu", count=1)))


def dra_warm_group_template(j: int) -> tuple:
    # min_member=1: the warm singleton gang reaches quorum instantly, so
    # the warm batch runs the FULL gang+claim program (anchor plane, claim
    # filter/score, Reserve, PreBind CAS commit) end to end
    pg = v1.PodGroup(
        metadata=v1.ObjectMeta(name=f"wg-{j}", namespace="default"),
        min_member=1, schedule_timeout_seconds=60,
    )
    return ("PodGroup", pg)


def pod_claim_gang(gang_size: int = GANG_SIZE) -> Callable[[int], v1.Pod]:
    """Gang member i claims its host's whole chip inventory (one named
    ResourceClaim per member, pre-created); warm indices (≥9M) yield
    singleton-gang pods claiming ONE warm-pool chip, pinned to the warm
    host — the claim-carrying program variants must all be warm before
    the window (run_suites.sh holds this suite at zero in-window
    compiles)."""
    from ..gang import POD_GROUP_LABEL

    def tmpl(i: int) -> v1.Pod:
        if i >= 9_000_000:
            k = i - 9_990_000
            return (
                _base_pod(i, "dwarm", "default")
                .label(POD_GROUP_LABEL, f"wg-{k}")
                .req({"cpu": "100m", "memory": "100Mi"})
                .node_selector({"dra-warm": "1"})
                .claim(f"warmclaim-{k}")
                .obj()
            )
        return (
            _base_pod(i, "dgang", "default")
            .label(POD_GROUP_LABEL, f"pg-{i // gang_size:05d}")
            .req({"cpu": "3000m", "memory": "500Mi"})
            .claim(f"gangclaim-{i:06d}")
            .obj()
        )

    return tmpl


def _device_claim_gang(n, p, mp) -> Workload:
    """DeviceClaimGang: GangBasic's all-or-nothing slice jobs, each member
    carrying a named ResourceClaim for its host's chips — the anchor-slice
    score consumes claim demand, Filter/Score run the batched claim
    planes, Reserve/PreBind allocate named devices with CAS exactly-once.
    Measures claims/s alongside gangs/s + time-to-full-slice."""
    gs = GANG_SIZE if mp >= GANG_SIZE else max(2, mp)
    ngangs = max(1, mp // gs)
    return Workload(
        name="DeviceClaimGang",
        ops=[
            Op("createNodes", n, node_template=node_sliced(gs)),
            Op("createNodes", 1, node_template=dra_warm_node(n)),
            Op("createObjects", 1, object_template=dra_class_template),
            Op("createObjects", n, object_template=dra_slice_template(gs)),
            Op("createObjects", 1, object_template=dra_warm_slice(n)),
            Op("createObjects", DRA_WARM_POOL,
               object_template=dra_warm_claim_template),
            Op("createObjects", DRA_WARM_POOL,
               object_template=dra_warm_group_template),
            Op("createObjects", ngangs, object_template=podgroup_template(gs)),
            Op("createObjects", ngangs * gs, object_template=dra_claim_template),
            Op("createPods", ngangs * gs, pod_template=pod_claim_gang(gs),
               collect_metrics=True),
        ],
        batch_size=64,
        gang_size=gs,
        dra=True,
    )


# --- TrainingJob custom-workload suite --------------------------------------


def trainingjob_crd_object(j: int) -> tuple:
    from ..apiextensions.api import CustomResourceDefinition
    from ..controllers.trainingjob import TRAININGJOB_CRD

    return ("CustomResourceDefinition",
            CustomResourceDefinition.from_dict(TRAININGJOB_CRD))


def trainingjob_template(replicas: int,
                         chips: int = CHIPS_PER_HOST) -> Callable[[int], tuple]:
    """TrainingJob CR j: ``replicas`` members, each claiming its host's
    whole chip inventory — the controller expands these into the same
    gang+claim object graph DeviceClaimGang pre-creates by hand."""
    from ..apiextensions.api import CustomResourceDefinition, make_kind_type
    from ..controllers.trainingjob import TRAININGJOB_CRD, TRAININGJOB_GROUP

    typ = make_kind_type(CustomResourceDefinition.from_dict(TRAININGJOB_CRD))

    def tmpl(j: int) -> tuple:
        return ("TrainingJob", typ.from_dict({
            "apiVersion": f"{TRAININGJOB_GROUP}/v1",
            "kind": "TrainingJob",
            "metadata": {"name": f"job-{j:05d}", "namespace": "default"},
            "spec": {"replicas": replicas, "chipsPerReplica": chips},
        }))

    return tmpl


def _trainingjob_flow(n, p, mp) -> Workload:
    """TrainingJobFlow: the multi-tenant workload API measured end to end.
    TrainingJob CRs (a CRD-defined custom kind, not a built-in) sit in the
    store at window start; the DRIVEN TrainingJobController expands each
    into PodGroup + member pods + named ResourceClaims INSIDE the measured
    window, and the gang + device-claim pipeline schedules them — jobs/s
    (time-to-full-slice per job) is the headline, pods/s + claims/s ride
    along.  Every measured pod is controller-born (``driven_pods``); the
    warm pool is DeviceClaimGang's, so the claim-carrying program variants
    are warm and the window holds zero compiles."""
    gs = GANG_SIZE if mp >= GANG_SIZE else max(2, mp)
    njobs = max(1, mp // gs)

    def make_controller(store, sched):
        from ..controllers.trainingjob import TrainingJobController

        return TrainingJobController(store, sched)

    return Workload(
        name="TrainingJobFlow",
        ops=[
            Op("createNodes", n, node_template=node_sliced(gs)),
            Op("createNodes", 1, node_template=dra_warm_node(n)),
            Op("createObjects", 1, object_template=dra_class_template),
            Op("createObjects", n, object_template=dra_slice_template(gs)),
            Op("createObjects", 1, object_template=dra_warm_slice(n)),
            Op("createObjects", DRA_WARM_POOL,
               object_template=dra_warm_claim_template),
            Op("createObjects", DRA_WARM_POOL,
               object_template=dra_warm_group_template),
            Op("createObjects", 1, object_template=trainingjob_crd_object),
            Op("createObjects", njobs, object_template=trainingjob_template(gs)),
            Op("createPods", 0, pod_template=pod_claim_gang(gs),
               collect_metrics=True, driven_pods=njobs * gs),
        ],
        batch_size=64,
        gang_size=gs,
        dra=True,
        trainingjob=True,
        make_descheduler=make_controller,
    )


# --- stateful / volume-topology suites --------------------------------------

STS_CLASS = "sts-local"
STS_CHURN_SLOTS = 8


def sts_class_template(j: int) -> tuple:
    sc = v1.StorageClass(volume_binding_mode=v1.VOLUME_BINDING_WAIT)
    sc.metadata.name = STS_CLASS
    return ("StorageClass", sc)


def pv_local_template(n: int, offset: int = 0,
                      prefix: str = "sts") -> Callable[[int], tuple]:
    """Local PV j pinned to host (offset+j) % n — WaitForFirstConsumer
    inventory the VolumeBinding plugin matches at Filter time."""

    def tmpl(j: int) -> tuple:
        pv = v1.PersistentVolume(capacity={"storage": "10Gi"},
                                 storage_class_name=STS_CLASS)
        pv.metadata.name = f"{prefix}-pv-{j:06d}"
        pv.node_affinity = v1.NodeSelector(node_selector_terms=[
            v1.NodeSelectorTerm(match_expressions=[
                v1.NodeSelectorRequirement(
                    key="kubernetes.io/hostname", operator=v1.OP_IN,
                    values=[f"node-{(offset + j) % n:06d}"],
                )
            ])
        ])
        return ("PersistentVolume", pv)

    return tmpl


def pvc_wffc_template(prefix: str) -> Callable[[int], tuple]:
    def tmpl(j: int) -> tuple:
        pvc = v1.PersistentVolumeClaim(storage_class_name=STS_CLASS,
                                       requested_storage="5Gi")
        pvc.metadata.name = f"{prefix}-{j:06d}"
        pvc.metadata.namespace = "default"
        return ("PersistentVolumeClaim", pvc)

    return tmpl


def pod_stateful(i: int) -> v1.Pod:
    if i >= 9_000_000:
        return pod_default(i)  # warm pods must bind without a PVC
    return (
        _base_pod(i, "sts", "default")
        .req({"cpu": "100m", "memory": "500Mi"})
        .pvc(f"sts-data-{i:06d}")
        .obj()
    )


def _stateful_churn(n, p, mp) -> Workload:
    """StatefulChurn: every measured pod binds its own WaitForFirstConsumer
    PVC to a node-local PV (the VolumeBinding Reserve/PreBind path at
    scale), while a churn hook recreates StatefulSet-shaped pods whose
    PVCs are ALREADY bound — each recreated pod must follow its volume."""

    def churn_pvc_template(j: int) -> tuple:
        pvc = v1.PersistentVolumeClaim(storage_class_name=STS_CLASS,
                                       requested_storage="5Gi")
        pvc.metadata.name = f"churn-data-{j:03d}"
        pvc.metadata.namespace = "default"
        return ("PersistentVolumeClaim", pvc)

    def churn(store, cycle: int):
        # recreate-mode stateful churn: the pod dies, its PVC (and the PV
        # the first bind chose) survives — the reference StatefulSet shape
        k = cycle % STS_CHURN_SLOTS
        name = f"sts-churn-{k:03d}"
        if store.get("Pod", "default", name) is not None:
            store.delete("Pod", "default", name)
        store.create(
            "Pod",
            make_pod().name(name).uid(f"{name}-{cycle}").namespace("default")
            .req({"cpu": "100m", "memory": "500Mi"})
            .pvc(f"churn-data-{k:03d}").obj(),
        )

    return Workload(
        name="StatefulChurn",
        ops=[
            Op("createNodes", n, node_template=node_default),
            Op("createObjects", 1, object_template=sts_class_template),
            Op("createObjects", mp, object_template=pv_local_template(n)),
            Op("createObjects", STS_CHURN_SLOTS,
               object_template=pv_local_template(n, offset=mp,
                                                 prefix="churn")),
            Op("createObjects", mp, object_template=pvc_wffc_template("sts-data")),
            Op("createObjects", STS_CHURN_SLOTS,
               object_template=churn_pvc_template),
            Op("createPods", mp, pod_template=pod_stateful,
               collect_metrics=True),
        ],
        batch_size=256,
        churn_between_cycles=churn,
    )


def pod_volume_zone_spread(i: int) -> v1.Pod:
    if i >= 9_000_000:
        return pod_default(i)
    return (
        _base_pod(i, "vzs", "default")
        .req({"cpu": "100m", "memory": "500Mi"})
        .label("color", "blue")
        .topology_spread(
            5, "topology.kubernetes.io/zone", labels={"color": "blue"}
        )
        .pvc(f"vzs-data-{i:06d}")
        .obj()
    )


def _volume_zone_spread(n, p, mp) -> Workload:
    """VolumeZoneSpread: each measured pod carries a PVC already bound to
    a ZONAL PV (VolumeZone filters its nodes to the PV's zone) plus a
    DoNotSchedule zone-spread constraint — the two planes must agree, the
    reference's zonal-StatefulSet shape."""

    def pv_zonal_template(j: int) -> tuple:
        pv = v1.PersistentVolume(capacity={"storage": "10Gi"})
        pv.metadata.name = f"vzs-pv-{j:06d}"
        pv.metadata.labels = {
            "topology.kubernetes.io/zone": ZONES3[j % len(ZONES3)]}
        pv.claim_ref = f"default/vzs-data-{j:06d}"
        return ("PersistentVolume", pv)

    def pvc_bound_template(j: int) -> tuple:
        pvc = v1.PersistentVolumeClaim(volume_name=f"vzs-pv-{j:06d}",
                                       requested_storage="5Gi")
        pvc.metadata.name = f"vzs-data-{j:06d}"
        pvc.metadata.namespace = "default"
        pvc.phase = "Bound"
        return ("PersistentVolumeClaim", pvc)

    return Workload(
        name="VolumeZoneSpread",
        ops=[
            Op("createNodes", n, node_template=node_zoned(ZONES3)),
            Op("createObjects", mp, object_template=pv_zonal_template),
            Op("createObjects", mp, object_template=pvc_bound_template),
            Op("createPods", mp, pod_template=pod_volume_zone_spread,
               collect_metrics=True),
        ],
        batch_size=256,
    )


def _mixed_churn(n, p, mp) -> Workload:
    def churn(store, cycle: int):
        # recreate-mode churn (SchedulingWithMixedChurn): one node, one
        # high-priority pod, one service recreated per interval
        name = f"churn-node-{cycle % 8:03d}"
        old = store.get("Node", "", name)
        if old is not None:
            store.delete("Node", "", name)
        store.create(
            "Node",
            make_node().name(name)
            .capacity({"cpu": "4", "memory": "32Gi", "pods": "110"}).obj(),
        )
        pname = f"churn-pod-{cycle % 8:03d}"
        if store.get("Pod", "default", pname) is not None:
            store.delete("Pod", "default", pname)
        store.create(
            "Pod",
            make_pod().name(pname).uid(f"{pname}-{cycle}")
            .namespace("default").priority(10)
            .req({"cpu": "1", "memory": "500Mi"}).obj(),
        )
        svc = v1.Service(
            metadata=v1.ObjectMeta(name=f"churn-svc-{cycle % 8:03d}",
                                   namespace="default"),
            selector={"app": "none"},
        )
        if store.get("Service", "default", svc.metadata.name) is not None:
            store.delete("Service", "default", svc.metadata.name)
        store.create("Service", svc)

    return Workload(
        name="SchedulingWithMixedChurn",
        ops=[
            Op("createNodes", n, node_template=node_default),
            Op("createPods", mp, pod_template=pod_default,
               collect_metrics=True),
        ],
        batch_size=256,
        churn_between_cycles=churn,
    )


SUITES: Dict[str, Suite] = {
    s.name: s
    for s in [
        Suite("SchedulingBasic", _basic,
              {"500Nodes": (500, 500, 1000), "5000Nodes": (5000, 1000, 1000)},
              batch_size={"5000Nodes": 512},
              # the attempt-latency attack (round 15): micro-bucket the
              # 512-batch until attempt p99 fits the budget — the
              # committed A/B lives in BENCH_r15_LATENCY.json and
              # run_suites.sh gates future passes against it
              latency_target_ms={"5000Nodes": 140.0}),
        Suite("SchedulingPodAntiAffinity", _anti_affinity,
              {"500Nodes": (500, 100, 400), "5000Nodes": (5000, 1000, 1000)},
              # coupled batches run the greedy scan: per-pod device cost is
              # linear in B, so B=512 amortizes only the fixed tunnel
              # rounds — measured 512.0 → 642.8 pods/s same-weather
              batch_size={"5000Nodes": 512}),
        Suite("SchedulingPodAffinity", _affinity,
              {"500Nodes": (500, 500, 1000), "5000Nodes": (5000, 5000, 1000)},
              batch_size={"5000Nodes": 512}),
        Suite("TopologySpreading", _topology,
              {"500Nodes": (500, 1000, 1000), "5000Nodes": (5000, 5000, 2000)},
              batch_size={"5000Nodes": 512}),
        Suite("PreferredTopologySpreading", _preferred_topology,
              {"500Nodes": (500, 1000, 1000), "5000Nodes": (5000, 5000, 2000)},
              batch_size={"5000Nodes": 512}),
        Suite("SchedulingNodeAffinity", _node_affinity,
              {"500Nodes": (500, 500, 1000), "5000Nodes": (5000, 5000, 1000)},
              batch_size={"5000Nodes": 512}),
        Suite("SchedulingPreferredPodAffinity", _preferred_affinity,
              {"500Nodes": (500, 500, 1000), "5000Nodes": (5000, 5000, 1000)},
              batch_size={"5000Nodes": 512}),
        Suite("PreemptionBasic", _preemption,
              {"500Nodes": (500, 2000, 500), "5000Nodes": (5000, 20000, 5000)},
              # 5k: every measured pod needs a fail→preempt→retry pair of
              # cycles; amortizing the fixed tunnel cost over 512 attempts
              # per cycle nearly halves the pair's wall share
              batch_size={"5000Nodes": 512}),
        Suite("Unschedulable", _unschedulable,
              {"500Nodes/200InitPods": (500, 200, 1000),
               "5000Nodes/200InitPods": (5000, 200, 5000)},
              batch_size={"5000Nodes/200InitPods": 512}),
        Suite("SchedulingWithMixedChurn", _mixed_churn,
              {"1000Nodes": (1000, 0, 1000), "5000Nodes": (5000, 0, 2000)},
              batch_size={"5000Nodes": 512}),
        # Gang scheduling: N/8 slice jobs of 8 members, one member per
        # host, capacity slightly over the job count (every gang lands);
        # measures gangs/s + time-to-full-slice alongside pods/s
        Suite("GangBasic", _gang_basic,
              {"64Nodes": (64, 0, 56), "500Nodes": (500, 0, 480),
               "5000Nodes": (5000, 0, 4800)},
              batch_size={"5000Nodes": 512}),
        # Cluster autoscaler: initial capacity seats ~1/4 of the gangs;
        # the rest starve until simulated-then-applied scale-ups add
        # whole slices — see _autoscale_gang.  Sizes are (initial nodes,
        # 0, measured gang pods); the autoscaler grows the cluster toward
        # the pod count's host demand.
        Suite("AutoscaleGang", _autoscale_gang,
              {"64Nodes": (16, 0, 56), "500Nodes": (120, 0, 480),
               "5000Nodes": (1200, 0, 4800)},
              batch_size={"5000Nodes": 512}),
        # Gang scheduling with named-device claims: every member carries a
        # ResourceClaim for its host's chips; the anchor-slice plane
        # consumes claim demand and PreBind CAS-commits allocations — see
        # _device_claim_gang.  Zero-in-window-compile gated in
        # run_suites.sh (the claim planes ride the warm program variants).
        Suite("DeviceClaimGang", _device_claim_gang,
              {"64Nodes": (64, 0, 56), "500Nodes": (500, 0, 480),
               "5000Nodes": (5000, 0, 4800)},
              batch_size={"5000Nodes": 512}),
        # TrainingJob custom workload: a CRD-defined kind a driven
        # controller expands into gang + claim objects INSIDE the measured
        # window — jobs/s + time-to-full-slice for the controller→
        # scheduler pipeline — see _trainingjob_flow
        Suite("TrainingJobFlow", _trainingjob_flow,
              {"64Nodes": (64, 0, 56), "500Nodes": (500, 0, 480),
               "5000Nodes": (5000, 0, 4800)},
              batch_size={"5000Nodes": 512}),
        # Stateful workloads: WFFC PVC-per-pod binding at scale plus
        # recreate-churn of already-bound StatefulSet pods — see
        # _stateful_churn
        Suite("StatefulChurn", _stateful_churn,
              {"500Nodes": (500, 0, 1000), "5000Nodes": (5000, 0, 2000)},
              batch_size={"5000Nodes": 512}),
        # Zonal volumes × zone spread: VolumeZone filter + DoNotSchedule
        # spread on the same axis — see _volume_zone_spread
        Suite("VolumeZoneSpread", _volume_zone_spread,
              {"500Nodes": (500, 0, 1000), "5000Nodes": (5000, 0, 2000)},
              batch_size={"5000Nodes": 512}),
        # Descheduler: every HOST fragmented by a pre-bound straggler,
        # gangs blocked until the defrag policy frees whole slices — see
        # _defrag
        Suite("Defrag", _defrag,
              {"64Nodes": (64, 64, 32), "500Nodes": (512, 512, 256),
               "5000Nodes": (5000, 5000, 2496)},
              batch_size={"5000Nodes": 512}),
        # extender batch 384: large enough to amortize the per-batch fixed
        # tunnel rounds (fused prepare+first-plane), but UNDER the node
        # count — the one-commit-per-node round rule defers (batch − nodes)
        # pods into extra full-priced device rounds at 512 (measured: 384
        # commits every pod in round one, p99 1.1s vs 1.9s)
        Suite("SchedulingExtender", _extender,
              {"500Nodes": (500, 500, 1000)}, batch_size=384),
        # The north-star config (BASELINE.md): 5k nodes, 10k pending pods,
        # measured per-attempt.  100kNodes is the production-scale claim
        # made LIVE (ROADMAP item 1): 100,352 nodes — the exact
        # SCALE_100K_EXEC node count — scheduled end to end through the
        # full control plane (store → watch → cache → incremental encoder
        # sync → fused dedup cycle → reserve/bind), not a one-shot
        # assignment artifact.  Same zero-in-window-compile discipline as
        # the 5k table (gate_zero_compiles in tools/run_suites.sh).
        Suite("NorthStar", _basic,
              {"5000Nodes/10000Pods": (5000, 2000, 10000),
               "100kNodes": (100_352, 0, 2000)},
              batch_size={"5000Nodes/10000Pods": 512, "100kNodes": 256},
              # 5k only: at the 131k-node tier each sub-bucket pad is
              # minutes of warm compile and the committed 100k row has no
              # same-hardware A/B yet — arm it there once measured
              latency_target_ms={"5000Nodes/10000Pods": 200.0}),
        # The reference's historic density target (scheduler_perf README:
        # 30k pods on 1000 fake nodes; 3k pods on 100 nodes).  B=512 on the
        # deep 30k backlog: 647 (r4 artifact) → 1143-1478 across round-5
        # passes (the committed density.json holds the current one; same
        # tunnel-round amortization as NorthStar, weather moves passes ±2×)
        Suite("Density", _basic,
              {"1000Nodes/30000Pods": (1000, 0, 30000),
               "100Nodes/3000Pods": (100, 0, 3000)},
              batch_size={"1000Nodes/30000Pods": 512}),
    ]
}


def build_workload(suite: str, size: str, scale: float = 1.0,
                   batch_size: Optional[int] = None) -> Workload:
    s = SUITES[suite]
    n, p, mp = s.sizes[size]
    if scale != 1.0:
        n = max(4, int(n * scale))
        p = max(0, int(p * scale))
        mp = max(2, int(mp * scale))
    w = s.build(n, p, mp)
    w.name = f"{suite}/{size}"
    suite_batch = s.batch_size
    if isinstance(suite_batch, dict):
        suite_batch = suite_batch.get(size)
    if batch_size is not None:
        w.batch_size = batch_size
    elif suite_batch is not None:
        # cap the suite's batch at the scaled backlog: a scale=0.1 dev run
        # must not pad every cycle (and its compiled programs) to the full
        # 512 when only ~100 pods ever queue
        from ..state.units import pow2_round_up

        w.batch_size = min(suite_batch, max(16, pow2_round_up(mp)))
    lt = s.latency_target_ms
    if isinstance(lt, dict):
        lt = lt.get(size)
    if lt is not None:
        w.latency_target_ms = float(lt)
    return w
