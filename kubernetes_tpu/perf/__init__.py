"""Benchmark harness (reference: test/integration/scheduler_perf)."""

from .harness import Workload, Op, run_workload, DataItem  # noqa: F401
