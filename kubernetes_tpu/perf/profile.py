"""Profile mode: cProfile over a measured scheduling window.

VERDICT r2 weak #3: steady-state host overhead was ~170× device time and
nothing in-repo could say where it went.  This runs a workload's measured
window under cProfile and prints the top cumulative functions, so host-path
fixes are driven by data.  Usage:

    python -m kubernetes_tpu.perf.profile [suite] [size] [scale] [topN]

Defaults: NorthStar 5000Nodes/10000Pods scale=0.1 top=40.
"""

from __future__ import annotations

import cProfile
import io
import pstats
import sys


def profile_workload(suite: str, size: str, scale: float, top: int = 40) -> str:
    import json

    from .harness import run_workload
    from .workloads import build_workload

    w = build_workload(suite, size, scale=scale)
    prof = cProfile.Profile()
    prof.enable()
    items = run_workload(w)
    prof.disable()
    out = io.StringIO()
    # per-phase wall breakdown first (also emitted in the bench JSON via
    # the PhaseWallBreakdown data item): the cProfile table says which
    # FUNCTIONS are hot, this says which scheduler PHASE the window spent
    # its wall on — host_prepare / partition / dispatch / fetch / bind
    phase = next(
        (i.data for i in items
         if i.labels.get("Metric") == "PhaseWallBreakdown"), None)
    if phase is not None:
        total = sum(phase.values()) or 1.0
        out.write("Per-phase wall over the measured window (s):\n")
        for k, v in sorted(phase.items(), key=lambda kv: -kv[1]):
            out.write(f"  {k:<14}{v:>9.3f}  ({100 * v / total:5.1f}%)\n")
        out.write(json.dumps({"phase_wall_s": phase}) + "\n\n")
    # per-phase ATTEMPT latency from the span tracer's per-pod records
    # (harness AttemptPhaseLatency): where a single pod's attempt p50/p99
    # goes, phase by phase — the wall table above is aggregate, this is
    # per-attempt (the ROADMAP item-3c latency-attack view)
    apl = next(
        (i.data for i in items
         if i.labels.get("Metric") == "AttemptPhaseLatency"), None)
    if apl is not None:
        out.write("Per-phase attempt latency (ms, from spans):\n")
        for ph in ("dispatch", "device", "bind", "queue_wait"):
            out.write(
                f"  {ph:<12}p50 {apl.get(f'{ph}_Perc50', 0) * 1e3:>9.3f}"
                f"  p90 {apl.get(f'{ph}_Perc90', 0) * 1e3:>9.3f}"
                f"  p99 {apl.get(f'{ph}_Perc99', 0) * 1e3:>9.3f}\n")
        out.write(
            f"  sum(tiling p50) {apl.get('SumPerc50', 0) * 1e3:.3f}ms vs "
            f"attempt p50 {apl.get('AttemptPerc50', 0) * 1e3:.3f}ms "
            f"(coverage {apl.get('Coverage', 0):.2f}x)\n\n")
    stats = pstats.Stats(prof, stream=out)
    stats.sort_stats("cumulative").print_stats(top)
    return out.getvalue()


def main(argv):
    suite = argv[1] if len(argv) > 1 else "NorthStar"
    size = argv[2] if len(argv) > 2 else "5000Nodes/10000Pods"
    scale = float(argv[3]) if len(argv) > 3 else 0.1
    top = int(argv[4]) if len(argv) > 4 else 40
    print(profile_workload(suite, size, scale, top))


if __name__ == "__main__":
    main(sys.argv)
