"""Declarative object builders for tests and benchmarks.

Reference: pkg/scheduler/testing/wrappers.go:139-144 (``st.MakePod().Name("p")
.Req(...).Obj()`` style). Fluent builders returning api objects.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .api import objects as v1


class PodWrapper:
    def __init__(self):
        self._pod = v1.Pod()
        self._pod.spec.containers = [v1.Container(name="c0", image="pause")]

    def obj(self) -> v1.Pod:
        return self._pod

    def name(self, n: str) -> "PodWrapper":
        self._pod.metadata.name = n
        return self

    def namespace(self, ns: str) -> "PodWrapper":
        self._pod.metadata.namespace = ns
        return self

    def uid(self, uid: str) -> "PodWrapper":
        self._pod.metadata.uid = uid
        return self

    def label(self, k: str, v: str) -> "PodWrapper":
        self._pod.metadata.labels[k] = v
        return self

    def labels(self, labels: Dict[str, str]) -> "PodWrapper":
        self._pod.metadata.labels.update(labels)
        return self

    def creation_timestamp(self, t: float) -> "PodWrapper":
        self._pod.metadata.creation_timestamp = t
        return self

    def req(self, requests: Dict[str, object]) -> "PodWrapper":
        """Set requests on the (single) default container."""
        self._pod.spec.containers[0].resources.requests = dict(requests)
        return self

    def container_req(self, requests: Dict[str, object]) -> "PodWrapper":
        """Append an extra container with the given requests."""
        idx = len(self._pod.spec.containers)
        self._pod.spec.containers.append(
            v1.Container(
                name=f"c{idx}",
                image="pause",
                resources=v1.ResourceRequirements(requests=dict(requests)),
            )
        )
        return self

    def init_req(self, requests: Dict[str, object]) -> "PodWrapper":
        idx = len(self._pod.spec.init_containers)
        self._pod.spec.init_containers.append(
            v1.Container(
                name=f"init{idx}",
                image="pause",
                resources=v1.ResourceRequirements(requests=dict(requests)),
            )
        )
        return self

    def overhead(self, rl: Dict[str, object]) -> "PodWrapper":
        self._pod.spec.overhead = dict(rl)
        return self

    def node(self, name: str) -> "PodWrapper":
        self._pod.spec.node_name = name
        return self

    def node_selector(self, sel: Dict[str, str]) -> "PodWrapper":
        self._pod.spec.node_selector = dict(sel)
        return self

    def node_affinity_in(self, key: str, values: List[str]) -> "PodWrapper":
        self._require_node_affinity().node_selector_terms.append(
            v1.NodeSelectorTerm(
                match_expressions=[
                    v1.NodeSelectorRequirement(key=key, operator=v1.OP_IN, values=values)
                ]
            )
        )
        return self

    def preferred_node_affinity(
        self, weight: int, key: str, values: List[str]
    ) -> "PodWrapper":
        aff = self._ensure_affinity()
        if aff.node_affinity is None:
            aff.node_affinity = v1.NodeAffinity()
        aff.node_affinity.preferred.append(
            v1.PreferredSchedulingTerm(
                weight=weight,
                preference=v1.NodeSelectorTerm(
                    match_expressions=[
                        v1.NodeSelectorRequirement(
                            key=key, operator=v1.OP_IN, values=values
                        )
                    ]
                ),
            )
        )
        return self

    def pod_affinity(
        self, topology_key: str, labels: Dict[str, str], anti: bool = False,
        weight: Optional[int] = None, namespaces: Optional[List[str]] = None,
    ) -> "PodWrapper":
        """Add a required (weight=None) or preferred pod (anti-)affinity exact-match term."""
        aff = self._ensure_affinity()
        term = v1.PodAffinityTerm(
            label_selector=v1.LabelSelector(match_labels=dict(labels)),
            topology_key=topology_key,
            namespaces=list(namespaces or []),
        )
        target_attr = "pod_anti_affinity" if anti else "pod_affinity"
        pa = getattr(aff, target_attr)
        if pa is None:
            pa = v1.PodAffinity()
            setattr(aff, target_attr, pa)
        if weight is None:
            pa.required.append(term)
        else:
            pa.preferred.append(
                v1.WeightedPodAffinityTerm(weight=weight, pod_affinity_term=term)
            )
        return self

    def toleration(
        self, key: str, value: str = "", effect: str = "",
        operator: str = v1.TOLERATION_OP_EQUAL,
        toleration_seconds: Optional[int] = None,
    ) -> "PodWrapper":
        self._pod.spec.tolerations.append(
            v1.Toleration(key=key, operator=operator, value=value,
                          effect=effect,
                          toleration_seconds=toleration_seconds)
        )
        return self

    def priority(self, p: int) -> "PodWrapper":
        self._pod.spec.priority = p
        return self

    def scheduler_name(self, n: str) -> "PodWrapper":
        self._pod.spec.scheduler_name = n
        return self

    def host_port(self, port: int, protocol: str = "TCP", host_ip: str = "") -> "PodWrapper":
        self._pod.spec.containers[0].ports.append(
            v1.ContainerPort(
                container_port=port, host_port=port, protocol=protocol, host_ip=host_ip
            )
        )
        return self

    def topology_spread(
        self,
        max_skew: int,
        topology_key: str,
        when_unsatisfiable: str = v1.DO_NOT_SCHEDULE,
        labels: Optional[Dict[str, str]] = None,
        min_domains: Optional[int] = None,
    ) -> "PodWrapper":
        self._pod.spec.topology_spread_constraints.append(
            v1.TopologySpreadConstraint(
                max_skew=max_skew,
                topology_key=topology_key,
                when_unsatisfiable=when_unsatisfiable,
                label_selector=v1.LabelSelector(match_labels=dict(labels or {})),
                min_domains=min_domains,
            )
        )
        return self

    def pvc(self, claim_name: str) -> "PodWrapper":
        self._pod.spec.volumes.append(
            v1.Volume(name=f"vol-{claim_name}", pvc_name=claim_name)
        )
        return self

    def claim(self, claim_name: str, name: str = "") -> "PodWrapper":
        """Reference an existing ResourceClaim by object name."""
        self._pod.spec.resource_claims.append(
            v1.PodResourceClaim(name=name or claim_name,
                                resource_claim_name=claim_name)
        )
        return self

    def claim_template(self, template_name: str, name: str = "") -> "PodWrapper":
        """Reference a ResourceClaimTemplate (claim stamped per pod)."""
        self._pod.spec.resource_claims.append(
            v1.PodResourceClaim(name=name or template_name,
                                resource_claim_template_name=template_name)
        )
        return self

    def nominated_node_name(self, n: str) -> "PodWrapper":
        self._pod.status.nominated_node_name = n
        return self

    def terminating(self) -> "PodWrapper":
        self._pod.metadata.deletion_timestamp = 1.0
        return self

    def phase(self, p: str) -> "PodWrapper":
        self._pod.status.phase = p
        return self

    def owner_reference(self, kind: str, name: str, uid: str = "") -> "PodWrapper":
        self._pod.metadata.owner_references.append(
            v1.OwnerReference(kind=kind, name=name, uid=uid or name, controller=True)
        )
        return self

    def _ensure_affinity(self) -> v1.Affinity:
        if self._pod.spec.affinity is None:
            self._pod.spec.affinity = v1.Affinity()
        return self._pod.spec.affinity

    def _require_node_affinity(self) -> v1.NodeSelector:
        aff = self._ensure_affinity()
        if aff.node_affinity is None:
            aff.node_affinity = v1.NodeAffinity()
        if aff.node_affinity.required is None:
            aff.node_affinity.required = v1.NodeSelector()
        return aff.node_affinity.required


class NodeWrapper:
    def __init__(self):
        self._node = v1.Node()
        self.capacity({"cpu": "32", "memory": "64Gi", "pods": "110"})

    def obj(self) -> v1.Node:
        return self._node

    def name(self, n: str) -> "NodeWrapper":
        self._node.metadata.name = n
        # the kubelet labels every node with its hostname on registration
        self._node.metadata.labels.setdefault("kubernetes.io/hostname", n)
        return self

    def label(self, k: str, v: str) -> "NodeWrapper":
        self._node.metadata.labels[k] = v
        return self

    def capacity(self, rl: Dict[str, object]) -> "NodeWrapper":
        self._node.status.capacity = dict(rl)
        self._node.status.allocatable = dict(rl)
        return self

    def allocatable(self, rl: Dict[str, object]) -> "NodeWrapper":
        self._node.status.allocatable = dict(rl)
        return self

    def taint(self, key: str, value: str = "", effect: str = v1.TAINT_NO_SCHEDULE) -> "NodeWrapper":
        self._node.spec.taints.append(v1.Taint(key=key, value=value, effect=effect))
        return self

    def unschedulable(self, u: bool = True) -> "NodeWrapper":
        self._node.spec.unschedulable = u
        return self

    def image(self, name: str, size_bytes: int) -> "NodeWrapper":
        self._node.status.images.append(
            v1.ContainerImage(names=[name], size_bytes=size_bytes)
        )
        return self


def make_pod() -> PodWrapper:
    return PodWrapper()


def make_node() -> NodeWrapper:
    return NodeWrapper()
