"""Job controller (reference: pkg/controller/job/job_controller.go syncJob —
keep ≤ parallelism active pods until completions succeed)."""

from __future__ import annotations

from ..api import objects as v1
from ..sim.store import ObjectStore
from .replicaset import _owned_pods, make_pod_from_template


class JobController:
    def __init__(self, store: ObjectStore, clock=None):
        import time

        self.store = store
        self.clock = clock or time.time

    def sync_once(self) -> bool:
        changed = False
        jobs, _ = self.store.list("Job")
        for job in jobs:
            if job.completed:
                continue
            pods = _owned_pods(self.store, "Job", job)
            succeeded = sum(1 for p in pods if p.status.phase == v1.POD_SUCCEEDED)
            active = [
                p for p in pods
                if p.status.phase in (v1.POD_PENDING, v1.POD_RUNNING)
                and p.metadata.deletion_timestamp is None
            ]
            want_active = min(job.parallelism, job.completions - succeeded)
            if succeeded >= job.completions:
                job.completed = True
                job.completion_time = self.clock()  # JobStatus.completionTime
                job.status_succeeded = succeeded
                job.status_active = 0
                self.store.update("Job", job)
                changed = True
                continue
            for _ in range(max(0, want_active - len(active))):
                self.store.create(
                    "Pod", make_pod_from_template("Job", job, job.template)
                )
                changed = True
            if job.status_succeeded != succeeded or job.status_active != len(active):
                job.status_succeeded = succeeded
                job.status_active = len(active)
                self.store.update("Job", job)
                changed = True
        return changed
