"""Garbage collector (reference: pkg/controller/garbagecollector — delete
objects whose controller ownerReference no longer exists; cascade)."""

from __future__ import annotations

from ..sim.store import ObjectStore

OWNABLE_KINDS = ("Pod", "ReplicaSet")
OWNER_KINDS = {"ReplicaSet", "Deployment", "Job"}


class GarbageCollector:
    def __init__(self, store: ObjectStore):
        self.store = store

    def _owner_exists(self, ref, namespace: str) -> bool:
        if ref.kind not in OWNER_KINDS:
            return True  # unknown owner kinds are left alone
        objs, _ = self.store.list(ref.kind)
        return any(
            o.metadata.uid == ref.uid and o.metadata.namespace == namespace
            for o in objs
        )

    def sync_once(self) -> bool:
        changed = False
        for kind in OWNABLE_KINDS:
            objs, _ = self.store.list(kind)
            for o in objs:
                refs = [r for r in o.metadata.owner_references if r.controller]
                if not refs:
                    continue
                if not any(self._owner_exists(r, o.metadata.namespace) for r in refs):
                    self.store.delete(kind, o.metadata.namespace, o.metadata.name)
                    changed = True
        return changed
