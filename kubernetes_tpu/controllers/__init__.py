"""Controller loops (reference L4a: pkg/controller, registered in
cmd/kube-controller-manager/app/controllermanager.go:402-449)."""

from .manager import ControllerManager  # noqa: F401
from .replicaset import ReplicaSetController  # noqa: F401
from .deployment import DeploymentController  # noqa: F401
from .job import JobController  # noqa: F401
from .nodelifecycle import NodeLifecycleController  # noqa: F401
from .garbagecollector import GarbageCollector  # noqa: F401
from .disruption import DisruptionController  # noqa: F401
from .statefulset import StatefulSetController  # noqa: F401
from .daemonset import DaemonSetController  # noqa: F401
from .podautoscaler import HorizontalPodAutoscalerController  # noqa: F401
