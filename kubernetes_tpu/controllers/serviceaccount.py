"""ServiceAccount controller.

Reference: pkg/controller/serviceaccount/serviceaccounts_controller.go —
every Active namespace gets a 'default' ServiceAccount; recreated if deleted.
"""

from __future__ import annotations

from ..api import objects as v1
from ..sim.store import ObjectStore


class ServiceAccountController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def sync_once(self) -> bool:
        changed = False
        namespaces, _ = self.store.list("Namespace")
        for ns in namespaces:
            if ns.status_phase != "Active" or ns.metadata.deletion_timestamp:
                continue
            if self.store.get("ServiceAccount", ns.metadata.name,
                              "default") is None:
                sa = v1.ServiceAccount(
                    metadata=v1.ObjectMeta(name="default",
                                           namespace=ns.metadata.name),
                )
                self.store.create("ServiceAccount", sa)
                changed = True
        return changed
