"""Node IPAM controller: allocate a podCIDR per node from the cluster CIDR.

Reference: pkg/controller/nodeipam/node_ipam_controller.go +
ipam/range_allocator.go — each new node gets the next free /node-mask subnet
of --cluster-cidr; the subnet returns to the pool when the node goes away.
Stateless reconcile: the used-set is recomputed from live nodes each sync,
so restart recovery is the same code path (the reference rebuilds its
cidr_set from informer state the same way, range_allocator.go Occupy)."""

from __future__ import annotations

import ipaddress

from ..sim.store import ObjectStore


class NodeIpamController:
    def __init__(self, store: ObjectStore,
                 cluster_cidr: str = "10.244.0.0/16",
                 node_mask: int = 24):
        self.store = store
        self.cluster = ipaddress.ip_network(cluster_cidr)
        self.node_mask = node_mask
        if node_mask < self.cluster.prefixlen:
            raise ValueError(
                f"node mask /{node_mask} larger than cluster {cluster_cidr}")

    def sync_once(self) -> bool:
        nodes, _ = self.store.list("Node")
        used = set()
        pending = []
        for node in nodes:
            cidr = node.spec.pod_cidr
            if cidr:
                used.add(cidr)
            else:
                pending.append(node)
        if not pending:
            return False
        # deterministic node order (the reference serializes through one
        # workqueue); subnets() yields in address order
        pending.sort(key=lambda n: n.metadata.name)
        free = (
            str(s) for s in self.cluster.subnets(new_prefix=self.node_mask)
            if str(s) not in used
        )
        changed = False
        for node in pending:
            cidr = next(free, None)
            if cidr is None:
                # pool exhausted — remaining nodes stay pending, loudly
                # (the reference records a CIDRNotAvailable event)
                from ..component_base import logging as klog

                klog.error_s(
                    None, "CIDRNotAvailable: cluster CIDR exhausted",
                    cluster=str(self.cluster), node=node.metadata.name,
                    pending=len(pending),
                )
                break
            node.spec.pod_cidr = cidr
            used.add(cidr)
            self.store.update("Node", node)
            changed = True
        return changed
