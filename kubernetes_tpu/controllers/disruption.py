"""Disruption controller: maintains PDB.status.disruptionsAllowed.

Reference: pkg/controller/disruption/disruption.go — trySync/updatePdbStatus:
  expectedCount, desiredHealthy from spec.minAvailable / spec.maxUnavailable
  (integer or percentage); currentHealthy = count of healthy matching pods;
  disruptionsAllowed = max(0, currentHealthy - desiredHealthy).

Round-2 VERDICT: preemption consumed budgets nothing ever updated — this loop
closes that cycle: victims deleted by the scheduler reduce currentHealthy on
the next sync, so budgets drain and replenish as replacements get scheduled.

Deviation (documented): percentage forms scale against the count of matching
pods rather than the owning controllers' .spec.replicas sum (the sim has no
scale subresource); for the PDB suites both counts coincide once replacements
are created.  "Healthy" in the sim = the pod is bound to a node (no kubelet
Ready condition exists here).
"""

from __future__ import annotations

import math
from typing import List

from ..api import objects as v1
from ..api.labels import match_label_selector
from ..sim.store import ObjectStore


def _parse_intstr(v, total: int) -> int:
    """IntOrString: plain int, or "NN%" rounded UP (intstr.GetScaledValueFromIntOrPercent
    with roundUp=true, as the disruption controller uses for minAvailable)."""
    if v is None:
        return 0
    if isinstance(v, int):
        return v
    s = str(v).strip()
    if s.endswith("%"):
        return math.ceil(int(s[:-1]) * total / 100)
    return int(s)


def sync_pdbs(store: ObjectStore) -> int:
    """One reconcile pass over every PDB; returns PDBs updated."""
    pdbs, _ = store.list("PodDisruptionBudget")
    pods, _ = store.list("Pod")
    updated = 0
    for pdb in pdbs:
        matching: List[v1.Pod] = [
            p for p in pods
            if p.namespace == pdb.metadata.namespace
            and pdb.selector is not None
            and match_label_selector(pdb.selector, p.metadata.labels)
        ]
        expected = len(matching)
        healthy = sum(1 for p in matching if p.spec.node_name)
        if pdb.max_unavailable is not None:
            # maxUnavailable: desiredHealthy = expected - scaled(maxUnavailable)
            desired = expected - _parse_intstr(pdb.max_unavailable, expected)
        elif pdb.min_available is not None:
            desired = _parse_intstr(pdb.min_available, expected)
        else:
            desired = 0
        desired = max(0, desired)
        allowed = max(0, healthy - desired)
        if (pdb.expected_pods, pdb.current_healthy, pdb.desired_healthy,
                pdb.disruptions_allowed) != (expected, healthy, desired, allowed):
            pdb.expected_pods = expected
            pdb.current_healthy = healthy
            pdb.desired_healthy = desired
            pdb.disruptions_allowed = allowed
            store.update("PodDisruptionBudget", pdb)
            updated += 1
    return updated


class DisruptionController:
    """Loop wrapper matching the other controllers' run-once interface."""

    name = "disruption"

    def __init__(self, store: ObjectStore):
        self.store = store

    def sync_once(self) -> bool:
        return sync_pdbs(self.store) > 0
