"""Horizontal Pod Autoscaler (reference: pkg/controller/podautoscaler/horizontal.go).

Core replica math kept exactly (horizontal.go calcPlainMetricReplicas /
GetResourceReplicas):

    usageRatio      = currentUtilization / targetUtilization
    desiredReplicas = ceil(currentReplicas * usageRatio)

bounded to [minReplicas, maxReplicas], with the reference's tolerance band
(|ratio-1| <= 0.1 → no scale, horizontal.go defaultTolerance) and the
scale-up limiter (max(2*current, 4), scaleUpLimit*).

The sim has no metrics-server: a ``metrics_fn(pod) -> float`` supplies each
pod's current utilization (percent of request), the seam where the resource
metrics pipeline plugs in.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api import objects as v1
from ..sim.store import ObjectStore

TOLERANCE = 0.1  # horizontal.go defaultTolerance


@dataclass
class HorizontalPodAutoscaler:
    """autoscaling/v2 HPA — the subset the controller reads."""

    metadata: "v1.ObjectMeta" = field(default_factory=lambda: v1.ObjectMeta())
    target_kind: str = "Deployment"
    target_name: str = ""
    min_replicas: int = 1
    max_replicas: int = 10
    target_utilization: float = 80.0  # percent
    status_desired: int = 0

    kind = "HorizontalPodAutoscaler"

    @classmethod
    def from_dict(cls, d):
        spec = d.get("spec") or {}
        ref = spec.get("scaleTargetRef") or {}
        metrics = spec.get("metrics") or []
        target = 80.0
        for mtr in metrics:
            res = (mtr.get("resource") or {}).get("target") or {}
            if "averageUtilization" in res:
                target = float(res["averageUtilization"])
        return cls(
            metadata=v1.ObjectMeta.from_dict(d.get("metadata") or {}),
            target_kind=ref.get("kind", "Deployment"),
            target_name=ref.get("name", ""),
            min_replicas=int(spec.get("minReplicas", 1)),
            max_replicas=int(spec.get("maxReplicas", 10)),
            target_utilization=target,
        )


def _scale_up_limit(current: int) -> int:
    """horizontal.go scaleUpLimitFactor=2, scaleUpLimitMinimum=4."""
    return max(2 * current, 4)


class HorizontalPodAutoscalerController:
    def __init__(self, store: ObjectStore,
                 metrics_fn: Optional[Callable[[v1.Pod], float]] = None):
        self.store = store
        # no metrics source → no scaling decisions (the reference likewise
        # holds when the metrics pipeline returns no samples,
        # horizontal.go computeReplicasForMetrics error path)
        self.metrics_fn = metrics_fn

    def sync_once(self) -> bool:
        changed = False
        hpas, _ = self.store.list("HorizontalPodAutoscaler")
        for hpa in hpas:
            target = self.store.get(
                hpa.target_kind, hpa.metadata.namespace, hpa.target_name
            )
            if target is None:
                continue
            from .replicaset import _owned_pods

            # utilization over the target's RUNNING pods; scale math over the
            # spec'd replica count (the reference uses the scale subresource)
            pods = []
            if hpa.target_kind == "Deployment":
                # pods are owned by the deployment's replicasets
                rss, _ = self.store.list("ReplicaSet")
                for rs in rss:
                    for ref in rs.metadata.owner_references:
                        if ref.kind == "Deployment" and ref.uid == target.metadata.uid:
                            pods.extend(_owned_pods(self.store, "ReplicaSet", rs))
            else:
                pods = _owned_pods(self.store, hpa.target_kind, target)
            scheduled = [p for p in pods if p.spec.node_name]
            current = target.replicas
            if not scheduled or self.metrics_fn is None:
                continue
            utilization = sum(self.metrics_fn(p) for p in scheduled) / len(scheduled)
            ratio = utilization / max(hpa.target_utilization, 1e-9)
            if abs(ratio - 1.0) <= TOLERANCE:
                desired = current  # within tolerance — no scale
            else:
                desired = math.ceil(current * ratio)
            desired = min(desired, _scale_up_limit(current))
            desired = max(hpa.min_replicas, min(hpa.max_replicas, desired))
            if desired != current:
                target.replicas = desired
                self.store.update(hpa.target_kind, target)
                changed = True
            if hpa.status_desired != desired:
                hpa.status_desired = desired
                self.store.update("HorizontalPodAutoscaler", hpa)
                changed = True
        return changed
