"""TTL-after-finished controller.

Reference: pkg/controller/ttlafterfinished/ttlafterfinished_controller.go —
finished Jobs with spec.ttlSecondsAfterFinished are deleted once the TTL
elapses past status.completionTime; their pods go with them (the sim GC's
owner-reference cascade handles that).
"""

from __future__ import annotations

import time

from ..sim.store import ObjectStore


class TTLAfterFinishedController:
    def __init__(self, store: ObjectStore, clock=None):
        self.store = store
        self.clock = clock or time.time

    def sync_once(self) -> bool:
        changed = False
        now = self.clock()
        jobs, _ = self.store.list("Job")
        for job in jobs:
            ttl = job.ttl_seconds_after_finished
            if ttl is None or not job.completed:
                continue
            done_at = job.completion_time
            if done_at is None:
                # finished before completion_time existed: stamp now so the
                # TTL counts from first observation (controller restart path)
                job.completion_time = now
                self.store.update("Job", job)
                changed = True
                continue
            if now - done_at >= ttl:
                self.store.delete("Job", job.metadata.namespace,
                                  job.metadata.name)
                changed = True
        return changed
