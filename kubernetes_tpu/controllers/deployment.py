"""Deployment controller (reference: pkg/controller/deployment/deployment_controller.go
syncDeployment — owns ReplicaSets; rollout = new RS scaled up, old scaled down)."""

from __future__ import annotations

import hashlib
import json

from ..api import objects as v1
from ..sim.store import ObjectStore


def _template_hash(template: v1.PodTemplateSpec) -> str:
    blob = json.dumps(
        {
            "labels": template.labels,
            "containers": [
                (c.name, c.image, sorted((c.resources.requests or {}).items()))
                for c in template.spec.containers
            ],
            "nodeSelector": sorted(template.spec.node_selector.items()),
        },
        sort_keys=True, default=str,
    )
    return hashlib.sha1(blob.encode()).hexdigest()[:10]


class DeploymentController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def sync_once(self) -> bool:
        changed = False
        deps, _ = self.store.list("Deployment")
        rss, _ = self.store.list("ReplicaSet")
        for dep in deps:
            owned = [
                rs for rs in rss
                if any(r.kind == "Deployment" and r.uid == dep.metadata.uid
                       for r in rs.metadata.owner_references)
            ]
            h = _template_hash(dep.template)
            current_name = f"{dep.metadata.name}-{h}"
            current = next((rs for rs in owned if rs.metadata.name == current_name), None)
            if current is None:
                rs = v1.ReplicaSet(
                    selector=dep.selector, replicas=dep.replicas,
                    template=dep.template,
                )
                rs.metadata.namespace = dep.metadata.namespace
                rs.metadata.name = current_name
                rs.metadata.owner_references = [
                    v1.OwnerReference(kind="Deployment", name=dep.metadata.name,
                                      uid=dep.metadata.uid, controller=True)
                ]
                rs.template.labels = dict(dep.template.labels)
                self.store.create("ReplicaSet", rs)
                changed = True
            elif current.replicas != dep.replicas:
                current.replicas = dep.replicas
                self.store.update("ReplicaSet", current)
                changed = True
            # scale down superseded ReplicaSets (recreate-ish rollout)
            for rs in owned:
                if rs.metadata.name != current_name and rs.replicas != 0:
                    rs.replicas = 0
                    self.store.update("ReplicaSet", rs)
                    changed = True
        return changed
