"""Namespace lifecycle controller.

Reference: pkg/controller/namespace/namespace_controller.go +
deletion/namespaced_resources_deleter.go — a namespace with a deletion
timestamp moves to Terminating, every namespaced object in it is deleted,
and once the namespace is empty the kubernetes finalizer is removed and the
namespace itself goes away.
"""

from __future__ import annotations

from ..sim.store import ObjectStore


class NamespaceController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def sync_once(self) -> bool:
        changed = False
        namespaces, _ = self.store.list("Namespace")
        for ns in namespaces:
            if ns.metadata.deletion_timestamp is None:
                continue
            if ns.status_phase != "Terminating":
                ns.status_phase = "Terminating"
                self.store.update("Namespace", ns)
                changed = True
            contents = self.store.list_namespaced(ns.metadata.name)
            for kind, obj in contents:
                self.store.delete(kind, ns.metadata.name, obj.metadata.name)
                changed = True
            if not self.store.list_namespaced(ns.metadata.name):
                # deleteNamespace: finalizer removal lets the apiserver drop it
                ns.finalizers = [f for f in ns.finalizers if f != "kubernetes"]
                self.store.delete("Namespace", "", ns.metadata.name)
                changed = True
        return changed
