"""CronJob controller.

Reference: pkg/controller/cronjob/cronjob_controllerv2.go (syncCronJob) +
utils.go (mostRecentScheduleTime / nextScheduleTime).  Five-field cron with
``*``, ``*/step``, ranges, and lists; times are epoch seconds interpreted in
UTC.  Per sync, the most recent unmet schedule time in
(last_schedule_time, now] fires ONE job — older misses are skipped, and a
startingDeadlineSeconds window discards fires older than the deadline
(the "too many missed start times" discipline without the 100-miss warning).
"""

from __future__ import annotations

import time
from typing import List, Optional

from ..api import objects as v1
from ..sim.store import ObjectStore


def _parse_field(field: str, lo: int, hi: int) -> Optional[frozenset]:
    """One cron field → allowed-value set; None means every value."""
    if field == "*":
        return None
    out = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part == "*":
            lo2, hi2 = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            lo2, hi2 = int(a), int(b)
        else:
            lo2 = hi2 = int(part)
        out.update(range(lo2, hi2 + 1, step))
    return frozenset(out)


class CronSchedule:
    """Parsed five-field cron expression matching UTC minute boundaries."""

    FIELDS = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))

    def __init__(self, expr: str):
        parts = expr.split()
        if len(parts) != 5:
            raise ValueError(f"cron expression needs 5 fields: {expr!r}")
        self.sets = [
            _parse_field(p, lo, hi)
            for p, (lo, hi) in zip(parts, self.FIELDS)
        ]

    def matches(self, epoch: float) -> bool:
        t = time.gmtime(int(epoch))
        minute, hour, dom, mon, dow_set = self.sets
        dow = (t.tm_wday + 1) % 7  # tm_wday: Mon=0 → cron: Sun=0
        if not ((minute is None or t.tm_min in minute)
                and (hour is None or t.tm_hour in hour)
                and (mon is None or t.tm_mon in mon)):
            return False
        dom_ok = dom is None or t.tm_mday in dom
        dow_ok = dow_set is None or dow in dow_set
        if dom is not None and dow_set is not None:
            # standard cron (and robfig/cron, which k8s uses): when BOTH
            # day fields are restricted, a time matching EITHER fires
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def most_recent(self, after: float, now: float) -> Optional[float]:
        """Latest matching minute boundary in (after, now], or None.

        Scans backward from ``now`` one minute at a time, bounded — callers
        pass a deadline-trimmed ``after`` so the scan stays short."""
        t = int(now) // 60 * 60
        floor = int(after)
        for _ in range(10 * 366 * 24 * 60):  # hard bound: ten years of minutes
            if t <= floor:
                return None
            if self.matches(t):
                return float(t)
            t -= 60
        return None


class CronJobController:
    def __init__(self, store: ObjectStore, clock=None):
        self.store = store
        self.clock = clock or time.time
        # per-cronjob floor of the already-scanned range: without it a
        # rarely/never-matching schedule re-scans its whole history (up to
        # millions of gmtime calls) on EVERY sync, since nothing fires and
        # last_schedule_time never advances
        self._scan_floor: dict = {}

    def _active_jobs(self, cj) -> List[v1.Job]:
        jobs, _ = self.store.list("Job")
        return [
            j for j in jobs
            if j.metadata.namespace == cj.metadata.namespace
            and not j.completed
            and any(o.kind == "CronJob" and o.name == cj.metadata.name
                    for o in (j.metadata.owner_references or []))
        ]

    def sync_once(self) -> bool:
        changed = False
        now = self.clock()
        cronjobs, _ = self.store.list("CronJob")
        for cj in cronjobs:
            if cj.suspend:
                continue
            try:
                sched = CronSchedule(cj.schedule)
            except ValueError:
                continue  # unparseable schedule: recorded by events upstream
            after = cj.last_schedule_time
            if after is None:
                after = cj.metadata.creation_timestamp or (now - 600)
            if cj.starting_deadline_seconds is not None:
                after = max(after, now - cj.starting_deadline_seconds)
            uid = cj.metadata.uid or cj.metadata.name
            after = max(after, self._scan_floor.get(uid, after))
            due = sched.most_recent(after, now)
            if due is None:
                self._scan_floor[uid] = now  # scanned through `now`: no match
                continue
            active = self._active_jobs(cj)
            if active and cj.concurrency_policy == "Forbid":
                continue
            if active and cj.concurrency_policy == "Replace":
                for j in active:
                    self.store.delete("Job", j.metadata.namespace,
                                      j.metadata.name)
            name = f"{cj.metadata.name}-{int(due) // 60}"
            if self.store.get("Job", cj.metadata.namespace, name) is None:
                job = v1.Job(
                    metadata=v1.ObjectMeta(
                        name=name, namespace=cj.metadata.namespace,
                        uid=f"{cj.metadata.uid or cj.metadata.name}-{int(due)}",
                        creation_timestamp=now,
                        owner_references=[v1.OwnerReference(
                            kind="CronJob", name=cj.metadata.name,
                            uid=cj.metadata.uid, controller=True,
                        )],
                    ),
                    completions=cj.job_completions,
                    parallelism=cj.job_parallelism,
                    template=cj.job_template,
                )
                self.store.create("Job", job)
            cj.last_schedule_time = due
            self.store.update("CronJob", cj)
            changed = True
        return changed
