"""TrainingJob controller: a CUSTOM workload kind riding the full stack.

TrainingJob is not a built-in — it is defined by a CustomResourceDefinition
(``TRAININGJOB_CRD``) and served by the dynamic-kind registrar like any
tenant CRD.  The controller proves the multi-tenant surface end to end: it
informer-watches its own custom kind through the same (list, watch)
machinery built-ins use, and expands each job into the gang + device-claim
objects the scheduler already understands:

  TrainingJob tj (replicas=R, chipsPerReplica=C, deviceClassName=D)
    → PodGroup   tj-<name>            (min_member=R: all-or-nothing)
    → ResourceClaimTemplate tj-<name> (count=C, class D — the template a
                                       late-added replica would stamp)
    → ResourceClaim tj-<name>-<i>     (named per-member claim, i < R)
    → Pod        tj-<name>-<i>        (gang label + claim reference)

so scheduling flows through gang anchor-slice election and named-chip
allocation with ZERO scheduler changes — the point of the exercise: a CRD
plus a controller is a complete workload API.

Exactly-once expansion: every child name is a pure function of the job
name + member index, and creates treat "already exists" as success — a
replayed event, a controller restart, or two live controllers racing
converge on the same objects (the reference's deterministic-name analog of
generateName + ownerRef adoption).
"""

from __future__ import annotations

import copy
from typing import List, Mapping, Optional

from ..api import objects as v1
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from ..sim.store import ObjectStore

TRAININGJOB_KIND = "TrainingJob"
TRAININGJOB_GROUP = "workloads.tpu.dev"

# the CRD manifest that defines the kind — tests, the perf suite, and
# deployments create this object; the attached registrar does the rest
TRAININGJOB_CRD = {
    "apiVersion": "apiextensions.k8s.io/v1",
    "kind": "CustomResourceDefinition",
    "metadata": {"name": f"trainingjobs.{TRAININGJOB_GROUP}"},
    "spec": {
        "group": TRAININGJOB_GROUP,
        "scope": "Namespaced",
        "names": {"plural": "trainingjobs", "singular": "trainingjob",
                  "kind": TRAININGJOB_KIND},
        "versions": [{
            "name": "v1", "served": True, "storage": True,
            "schema": {"openAPIV3Schema": {
                "type": "object",
                "properties": {
                    "spec": {
                        "type": "object",
                        "required": ["replicas", "chipsPerReplica"],
                        "properties": {
                            "replicas": {"type": "integer", "minimum": 1},
                            "chipsPerReplica": {"type": "integer",
                                                "minimum": 1},
                            "deviceClassName": {"type": "string"},
                        },
                    },
                    "status": {"type": "object"},
                },
            }},
        }],
    },
}


def install_trainingjob_crd(store: ObjectStore, scheme) -> None:
    """Create the TrainingJob CRD (idempotent).  A dynamic-kind registrar
    attached to ``store`` installs the kind; ``scheme`` only decodes the
    manifest here."""
    try:
        store.create("CustomResourceDefinition",
                     scheme.decode(TRAININGJOB_CRD))
    except ValueError:
        pass  # already installed


def _member_name(job_name: str, i: int) -> str:
    return f"tj-{job_name}-{i}"


def _group_name(job_name: str) -> str:
    return f"tj-{job_name}"


class TrainingJobController:
    """Expand TrainingJob custom resources into gang + claim objects.

    ``run()`` informer-watches the custom kind and reconciles on every
    event (the controller shape); ``sync_once()`` is the harness-driven
    full reconcile (list every job, expand what's missing) — both funnel
    into the same idempotent ``_expand``."""

    def __init__(self, store: ObjectStore, sched=None, *,
                 cpu_per_replica: str = "3000m",
                 memory_per_replica: str = "500Mi"):
        # ``sched`` is accepted (and ignored) for make_descheduler hook
        # signature parity — the controller only talks to the store
        self.store = store
        self.cpu_per_replica = cpu_per_replica
        self.memory_per_replica = memory_per_replica
        self._informer = None

    # --- informer plane ------------------------------------------------------

    def run(self) -> "TrainingJobController":
        """Start the informer: list+watch TrainingJob through the shared
        informer machinery (the same path Reflector-driven built-ins use
        — over a store here, over HTTP when given an HTTPApiClient-backed
        store facade)."""
        from ..client.informer import SharedInformer

        self._informer = SharedInformer(self.store, TRAININGJOB_KIND)
        self._informer.add_event_handler(
            on_add=lambda job: self._expand(job),
            on_update=lambda old, job: self._expand(job),
        )
        self._informer.run()
        return self

    def close(self) -> None:
        if self._informer is not None:
            self._informer.reflector.stop()
            self._informer = None

    # --- reconcile -----------------------------------------------------------

    def sync_once(self) -> bool:
        changed = False
        jobs, _ = self.store.list(TRAININGJOB_KIND)
        for job in jobs:
            changed |= self._expand(job)
        return changed

    def _expand(self, job) -> bool:
        """One job → its gang/claim/pod children + status write-back.
        Every create is name-deterministic and exists-tolerant, so this is
        safe to run any number of times from any replica."""
        spec = job.spec or {}
        try:
            replicas = int(spec.get("replicas", 0))
            chips = int(spec.get("chipsPerReplica", 0))
        except (TypeError, ValueError):
            replicas, chips = 0, 0
        if replicas < 1 or chips < 1:
            klog.V(2).info_s("TrainingJob skipped: invalid spec",
                             job=job.metadata.name)
            return False
        ns = job.metadata.namespace or "default"
        name = job.metadata.name
        device_class = str(spec.get("deviceClassName") or "tpu")
        created = 0
        created += self._ensure_group(ns, name, replicas)
        created += self._ensure_claims(ns, name, replicas, chips,
                                       device_class)
        created += self._ensure_pods(ns, job, replicas)
        created += self._write_status(job, replicas)
        m.trainingjob_expansions.inc(("expanded" if created else "steady",))
        if created:
            klog.V(1).info_s("TrainingJob expanded", job=f"{ns}/{name}",
                             replicas=replicas, chips_per_replica=chips,
                             objects_created=created)
        return bool(created)

    def _create(self, kind: str, obj) -> int:
        try:
            self.store.create(kind, obj)
            return 1
        except ValueError:
            return 0  # exists: a concurrent/replayed expansion won

    def _ensure_group(self, ns: str, name: str, replicas: int) -> int:
        pg = v1.PodGroup(
            metadata=v1.ObjectMeta(name=_group_name(name), namespace=ns),
            min_member=replicas, schedule_timeout_seconds=60)
        return self._create("PodGroup", pg)

    def _ensure_claims(self, ns: str, name: str, replicas: int, chips: int,
                       device_class: str) -> int:
        from ..dra.api import (DeviceRequest, ResourceClaim,
                               ResourceClaimTemplate)

        n = self._create("ResourceClaimTemplate", ResourceClaimTemplate(
            metadata=v1.ObjectMeta(name=_group_name(name), namespace=ns),
            request=DeviceRequest(device_class_name=device_class,
                                  count=chips)))
        for i in range(replicas):
            n += self._create("ResourceClaim", ResourceClaim(
                metadata=v1.ObjectMeta(name=_member_name(name, i),
                                       namespace=ns),
                request=DeviceRequest(device_class_name=device_class,
                                      count=chips)))
        return n

    def _ensure_pods(self, ns: str, job, replicas: int) -> int:
        from ..gang import POD_GROUP_LABEL

        n = 0
        for i in range(replicas):
            member = _member_name(job.metadata.name, i)
            pod = v1.Pod()
            pod.metadata.name = member
            pod.metadata.uid = member
            pod.metadata.namespace = ns
            pod.metadata.labels = {
                POD_GROUP_LABEL: _group_name(job.metadata.name),
                "trainingjob": job.metadata.name,
            }
            pod.metadata.owner_references = [v1.OwnerReference(
                kind=TRAININGJOB_KIND, name=job.metadata.name,
                uid=job.metadata.uid, controller=True)]
            pod.spec.containers = [v1.Container(name="trainer", image="pause")]
            # one member per TPU host VM: the 3-cpu request packs exactly
            # one onto a 4-cpu host, so a gang owns whole slices
            pod.spec.containers[0].resources.requests = {
                "cpu": self.cpu_per_replica,
                "memory": self.memory_per_replica,
            }
            pod.spec.resource_claims = [v1.PodResourceClaim(
                name=member, resource_claim_name=member)]
            n += self._create("Pod", pod)
        return n

    def _write_status(self, job, replicas: int) -> int:
        """Best-effort phase write-back into the CR's status: Pending (no
        member bound), Scheduling (some), Running (all R bound).  A CAS
        loser just skips — the next sync recomputes from scratch."""
        from ..sim.store import StaleResourceVersion

        ns = job.metadata.namespace or "default"
        bound = 0
        for i in range(replicas):
            p = self.store.get("Pod", ns, _member_name(job.metadata.name, i))
            if p is not None and p.spec.node_name:
                bound += 1
        phase = ("Running" if bound >= replicas
                 else "Scheduling" if bound else "Pending")
        status = job.body.get("status") or {}
        if status.get("phase") == phase and \
                status.get("boundReplicas") == bound:
            return 0
        fresh = self.store.get(TRAININGJOB_KIND, ns, job.metadata.name)
        if fresh is None:
            return 0  # job deleted mid-sync
        fresh = copy.deepcopy(fresh)
        fresh.body.setdefault("status", {})
        fresh.body["status"]["phase"] = phase
        fresh.body["status"]["boundReplicas"] = bound
        try:
            self.store.update(TRAININGJOB_KIND, fresh,
                              expected_rv=int(
                                  fresh.metadata.resource_version or 0))
        except (StaleResourceVersion, ValueError):
            return 0
        return 1
