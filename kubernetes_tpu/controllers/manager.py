"""Controller manager: registers loops, drives reconciliation.

Reference: cmd/kube-controller-manager/app/controllermanager.go:174 (Run) and
the NewControllerInitializers map :402-449.  No goroutines — callers (tests,
sim harness) drive sync_all(); each controller keeps its own workqueue.
"""

from __future__ import annotations

from typing import Dict, List

from ..sim.store import ObjectStore


class ControllerManager:
    def __init__(self, store: ObjectStore, clock=None):
        import time

        self.store = store
        self.clock = clock or time.monotonic
        self.controllers: List[object] = []

    def register(self, controller) -> "ControllerManager":
        self.controllers.append(controller)
        return self

    def register_defaults(self) -> "ControllerManager":
        from .cronjob import CronJobController
        from .deployment import DeploymentController
        from .disruption import DisruptionController
        from .endpoints import EndpointsController, EndpointSliceController
        from .garbagecollector import GarbageCollector
        from .job import JobController
        from .namespace import NamespaceController
        from .nodelifecycle import NodeLifecycleController
        from .replicaset import ReplicaSetController
        from .resourcequota import ResourceQuotaController
        from .serviceaccount import ServiceAccountController
        from .statefulset import StatefulSetController
        from .daemonset import DaemonSetController
        from .podautoscaler import HorizontalPodAutoscalerController
        from .ttlafterfinished import TTLAfterFinishedController

        self.register(NamespaceController(self.store))
        self.register(ServiceAccountController(self.store))
        self.register(DeploymentController(self.store))
        self.register(ReplicaSetController(self.store))
        self.register(StatefulSetController(self.store))
        self.register(DaemonSetController(self.store))
        self.register(CronJobController(self.store, clock=self.clock))
        self.register(JobController(self.store, clock=self.clock))
        self.register(TTLAfterFinishedController(self.store, clock=self.clock))
        self.register(NodeLifecycleController(self.store, clock=self.clock))
        self.register(DisruptionController(self.store))
        self.register(HorizontalPodAutoscalerController(self.store))
        self.register(EndpointsController(self.store))
        self.register(EndpointSliceController(self.store))
        self.register(ResourceQuotaController(self.store))
        self.register(GarbageCollector(self.store))
        return self

    def sync_all(self, rounds: int = 3) -> None:
        """Run every controller's reconcile until quiescent (bounded)."""
        for _ in range(rounds):
            changed = False
            for c in self.controllers:
                changed = bool(c.sync_once()) or changed
            if not changed:
                break
