"""Controller manager: registers loops, drives reconciliation.

Reference: cmd/kube-controller-manager/app/controllermanager.go:174 (Run) and
the NewControllerInitializers map :402-449.  No goroutines — callers (tests,
sim harness) drive sync_all(); each controller keeps its own workqueue.
"""

from __future__ import annotations

from typing import Dict, List

from ..sim.store import ObjectStore


class ControllerManager:
    def __init__(self, store: ObjectStore, clock=None):
        import time

        self.store = store
        self.clock = clock or time.monotonic
        self.controllers: List[object] = []

    def register(self, controller) -> "ControllerManager":
        self.controllers.append(controller)
        return self

    def register_defaults(self, cluster_cidr: str = "10.244.0.0/16",
                          node_cidr_mask: int = 24) -> "ControllerManager":
        """``cluster_cidr``/``node_cidr_mask`` configure the NodeIpam loop
        (--cluster-cidr / --node-cidr-mask-size); the /16-with-/24 default
        covers 256 nodes — size it to the cluster (a 100k-node sim wants
        e.g. 10.0.0.0/8 with /25)."""
        from .cronjob import CronJobController
        from .deployment import DeploymentController
        from .disruption import DisruptionController
        from .endpoints import EndpointsController, EndpointSliceController
        from .garbagecollector import GarbageCollector
        from .job import JobController
        from .namespace import NamespaceController
        from .nodelifecycle import NodeLifecycleController
        from .replicaset import ReplicaSetController
        from .resourcequota import ResourceQuotaController
        from .serviceaccount import ServiceAccountController
        from .statefulset import StatefulSetController
        from .daemonset import DaemonSetController
        from .nodeipam import NodeIpamController
        from .podautoscaler import HorizontalPodAutoscalerController
        from .ttlafterfinished import TTLAfterFinishedController
        from .volumebinder import (
            AttachDetachController,
            PersistentVolumeBinderController,
        )

        self.register(NamespaceController(self.store))
        self.register(ServiceAccountController(self.store))
        self.register(DeploymentController(self.store))
        self.register(ReplicaSetController(self.store))
        self.register(StatefulSetController(self.store))
        self.register(DaemonSetController(self.store))
        self.register(CronJobController(self.store, clock=self.clock))
        self.register(JobController(self.store, clock=self.clock))
        self.register(TTLAfterFinishedController(self.store, clock=self.clock))
        self.register(NodeLifecycleController(self.store, clock=self.clock))
        self.register(DisruptionController(self.store))
        self.register(HorizontalPodAutoscalerController(self.store))
        self.register(EndpointsController(self.store))
        self.register(EndpointSliceController(self.store))
        self.register(ResourceQuotaController(self.store))
        self.register(NodeIpamController(self.store, cluster_cidr=cluster_cidr,
                                         node_mask=node_cidr_mask))
        self.register(PersistentVolumeBinderController(self.store))
        self.register(AttachDetachController(self.store))
        self.register(GarbageCollector(self.store))
        return self

    def sync_all(self, rounds: int = 3) -> None:
        """Run every controller's reconcile until quiescent (bounded)."""
        for _ in range(rounds):
            changed = False
            for c in self.controllers:
                changed = bool(c.sync_once()) or changed
            if not changed:
                break
