"""ReplicaSet controller (reference: pkg/controller/replicaset/replica_set.go
syncReplicaSet — create/delete pods to match .spec.replicas)."""

from __future__ import annotations

import itertools
from typing import List

from ..api import objects as v1
from ..api.labels import match_label_selector
from ..sim.store import ObjectStore

_suffix = itertools.count()


def _owned_pods(store: ObjectStore, owner_kind: str, owner) -> List[v1.Pod]:
    pods, _ = store.list("Pod")
    out = []
    for p in pods:
        if p.namespace != owner.metadata.namespace:
            continue
        for ref in p.metadata.owner_references:
            if ref.kind == owner_kind and ref.uid == owner.metadata.uid:
                out.append(p)
                break
    return out


def make_pod_from_template(owner_kind: str, owner, template: v1.PodTemplateSpec) -> v1.Pod:
    import copy

    pod = v1.Pod()
    pod.metadata.namespace = owner.metadata.namespace
    pod.metadata.name = f"{owner.metadata.name}-{next(_suffix):05x}"
    pod.metadata.labels = dict(template.labels)
    pod.metadata.owner_references = [
        v1.OwnerReference(
            kind=owner_kind, name=owner.metadata.name, uid=owner.metadata.uid,
            controller=True,
        )
    ]
    pod.spec = copy.deepcopy(template.spec)
    if not pod.spec.containers:
        pod.spec.containers = [v1.Container(name="c0", image="pause")]
    return pod


class ReplicaSetController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def sync_once(self) -> bool:
        changed = False
        rss, _ = self.store.list("ReplicaSet")
        for rs in rss:
            pods = [
                p for p in _owned_pods(self.store, "ReplicaSet", rs)
                if p.status.phase not in (v1.POD_SUCCEEDED, v1.POD_FAILED)
                and p.metadata.deletion_timestamp is None
            ]
            diff = rs.replicas - len(pods)
            if diff > 0:
                for _ in range(diff):
                    self.store.create(
                        "Pod", make_pod_from_template("ReplicaSet", rs, rs.template)
                    )
                changed = True
            elif diff < 0:
                # prefer deleting unscheduled pods first (controller_utils
                # ActivePodsWithRanks ordering, simplified)
                pods.sort(key=lambda p: (bool(p.spec.node_name),))
                for p in pods[: -diff]:
                    self.store.delete("Pod", p.namespace, p.metadata.name)
                changed = True
            ready = sum(1 for p in pods if p.status.phase == v1.POD_RUNNING)
            if rs.status_replicas != len(pods) or rs.status_ready_replicas != ready:
                rs.status_replicas = len(pods)
                rs.status_ready_replicas = ready
                self.store.update("ReplicaSet", rs)
        return changed
