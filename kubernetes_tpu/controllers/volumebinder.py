"""PersistentVolume binder + attach-detach controllers.

Reference: pkg/controller/volume/persistentvolume/pv_controller.go
(syncUnboundClaim/syncVolume: Immediate-mode claims bind to the
smallest-fitting available PV; a bound PV whose claim vanished becomes
Released) and pkg/controller/volume/attachdetach/attach_detach_controller.go
(desired state = volumes of scheduled pods per node; node.status
volumesAttached reconciled to it).

WaitForFirstConsumer claims are explicitly NOT handled here — the
scheduler's VolumeBinding plugin owns them (plugins/volumes.py), exactly
the reference's split (pv_controller skips WaitForFirstConsumer claims
until a pod triggers provisioning/binding)."""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..api import objects as v1
from ..api.resource import parse_quantity_exact
from ..chaos.faults import CRASH_MID_PROVISION, maybe_crash
from ..sim.store import ObjectStore


def _storage(q) -> object:
    try:
        return parse_quantity_exact(q or 0)
    except (ValueError, ArithmeticError):
        return 0


class PersistentVolumeBinderController:
    """Immediate-mode PVC ↔ PV binding (the control-plane half of pkg/volume)."""

    def __init__(self, store: ObjectStore):
        self.store = store

    def _binding_mode(self, class_name: Optional[str]) -> str:
        if not class_name:
            return v1.VOLUME_BINDING_IMMEDIATE
        sc = self.store.get("StorageClass", "", class_name)
        if sc is None:
            return v1.VOLUME_BINDING_IMMEDIATE
        return sc.volume_binding_mode

    def sync_once(self) -> bool:
        changed = False
        pvs, _ = self.store.list("PersistentVolume")
        pvcs, _ = self.store.list("PersistentVolumeClaim")
        claims_by_key = {
            f"{c.metadata.namespace}/{c.metadata.name}": c for c in pvcs
        }
        pvs_by_name = {pv.metadata.name: pv for pv in pvs}
        # release PVs whose claim is gone OR bound elsewhere (the reference
        # compares ClaimRef UID; a delete+recreate of a same-named claim
        # that bound a different volume must not leak this one).  Retain
        # policy modeled by clearing claim_ref so the volume is
        # re-matchable, the sim's recycle policy.
        for pv in pvs:
            if not pv.claim_ref:
                continue
            claim = claims_by_key.get(pv.claim_ref)
            if claim is None or (claim.volume_name
                                 and claim.volume_name != pv.metadata.name):
                pv.claim_ref = None
                self.store.update("PersistentVolume", pv)
                changed = True
            elif not claim.volume_name:
                # half-applied binding (a crash at CRASH_MID_PROVISION: the
                # PV's claimRef landed, the PVC write never did) — COMPLETE
                # it rather than release, the reference syncVolume's
                # volume-bound/claim-unbound arm.  Exactly once: the PV
                # holds its reserve through the crash, and this repair is
                # the single claim-side write that consumes it.
                claim.volume_name = pv.metadata.name
                claim.phase = "Bound"
                self.store.update("PersistentVolumeClaim", claim)
                changed = True
        available = [pv for pv in pvs if not pv.claim_ref]
        for pvc in pvcs:
            key = f"{pvc.metadata.namespace}/{pvc.metadata.name}"
            if pvc.volume_name:
                # pre-bound claim (spec.volumeName set by the user): stamp
                # the PV's claimRef too — syncUnboundClaim's static-binding
                # arm; a claim naming a missing or foreign PV stays Pending
                pv = pvs_by_name.get(pvc.volume_name)
                if pv is None or (pv.claim_ref and pv.claim_ref != key):
                    continue
                if pv.claim_ref != key:
                    pv.claim_ref = key
                    self.store.update("PersistentVolume", pv)
                    if pv in available:
                        available.remove(pv)
                    changed = True
                if pvc.phase != "Bound":
                    pvc.phase = "Bound"
                    self.store.update("PersistentVolumeClaim", pvc)
                    changed = True
                continue
            mode = self._binding_mode(pvc.storage_class_name)
            if mode != v1.VOLUME_BINDING_IMMEDIATE:
                continue  # the scheduler's VolumeBinding plugin owns these
            need = _storage(pvc.requested_storage)
            fits = [
                pv for pv in available
                if (pv.storage_class_name or "") == (pvc.storage_class_name or "")
                and _storage(pv.capacity.get("storage")) >= need
                and (not pvc.access_modes
                     or set(pvc.access_modes) <= set(pv.access_modes))
            ]
            if not fits:
                continue
            # smallest fitting volume wins, name tie-break — the SAME key
            # the scheduler plugin uses (plugins/volumes.py smallest-fit) so
            # binder and plugin choose identically on identical inputs
            best = min(fits, key=lambda pv: (
                _storage(pv.capacity.get("storage")), pv.metadata.name))
            best.claim_ref = key
            pvc.volume_name = best.metadata.name
            pvc.phase = "Bound"
            self.store.update("PersistentVolume", best)
            # kill-point: the PV side of the bind is durable, the PVC side
            # is not — the repair arm above must converge this state
            maybe_crash(CRASH_MID_PROVISION)
            self.store.update("PersistentVolumeClaim", pvc)
            available.remove(best)
            changed = True
        return changed


class AttachDetachController:
    """Reconcile node.status.volumesAttached to the PVs of each node's
    scheduled pods (desired-state-of-world → actual, attach_detach_controller
    reconciler.go) — the sim has no real attach operation, so actual ==
    desired after one sync, which is the reference's steady state."""

    def __init__(self, store: ObjectStore):
        self.store = store

    def sync_once(self) -> bool:
        pods, _ = self.store.list("Pod")
        pvcs = {
            f"{c.metadata.namespace}/{c.metadata.name}": c
            for c in self.store.list("PersistentVolumeClaim")[0]
        }
        desired: Dict[str, Set[str]] = {}
        for pod in pods:
            node = pod.spec.node_name
            # terminated pods release their attachments (the reference's
            # desired-state-of-world excludes Succeeded/Failed pods)
            if not node or pod.status.phase in ("Succeeded", "Failed"):
                continue
            for vol in getattr(pod.spec, "volumes", None) or []:
                pvc_name = getattr(vol, "pvc_name", "")
                if not pvc_name:
                    continue
                claim = pvcs.get(f"{pod.metadata.namespace}/{pvc_name}")
                if claim is not None and claim.volume_name:
                    desired.setdefault(node, set()).add(claim.volume_name)
        changed = False
        for node in self.store.list("Node")[0]:
            want = sorted(desired.get(node.metadata.name, ()))
            if node.status.volumes_attached != want:
                node.status.volumes_attached = want
                self.store.update("Node", node)
                changed = True
        return changed
