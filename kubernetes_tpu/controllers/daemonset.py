"""DaemonSet controller (reference: pkg/controller/daemon/daemon_controller.go).

One pod per eligible node.  Like the reference since 1.12, daemon pods are
NOT bound directly by the controller: each created pod carries a required
node affinity pinning it to its target node via the metadata.name match field
(daemon_controller.go util.ReplaceDaemonSetPodNodeNameNodeAffinity) and goes
through the scheduler like any other pod — so taints/unschedulable/resource
checks all apply through the normal plugin set.
"""

from __future__ import annotations

import copy

from ..api import objects as v1
from ..sim.store import ObjectStore
from .replicaset import _owned_pods


class DaemonSetController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def _make_pod(self, ds: v1.DaemonSet, node_name: str) -> v1.Pod:
        pod = v1.Pod()
        pod.metadata.namespace = ds.metadata.namespace
        pod.metadata.name = f"{ds.metadata.name}-{node_name}"
        pod.metadata.labels = dict(ds.template.labels)
        pod.metadata.owner_references = [
            v1.OwnerReference(
                kind="DaemonSet", name=ds.metadata.name,
                uid=ds.metadata.uid, controller=True,
            )
        ]
        pod.spec = copy.deepcopy(ds.template.spec)
        if not pod.spec.containers:
            pod.spec.containers = [v1.Container(name="c0", image="pause")]
        # pin to the node through the scheduler (not direct binding)
        pod.spec.affinity = pod.spec.affinity or v1.Affinity()
        pod.spec.affinity.node_affinity = v1.NodeAffinity(
            required=v1.NodeSelector(node_selector_terms=[
                v1.NodeSelectorTerm(match_fields=[
                    v1.NodeSelectorRequirement(
                        key="metadata.name", operator=v1.OP_IN, values=[node_name]
                    )
                ])
            ])
        )
        return pod

    def sync_once(self) -> bool:
        changed = False
        sets, _ = self.store.list("DaemonSet")
        if not sets:
            return False
        nodes, _ = self.store.list("Node")
        for ds in sets:
            pods = _owned_pods(self.store, "DaemonSet", ds)
            by_node = {}
            for p in pods:
                target = p.metadata.name[len(ds.metadata.name) + 1:]
                by_node[target] = p
            desired = 0
            for node in nodes:
                if node.spec.unschedulable:
                    continue  # shouldSchedule=false for cordoned nodes
                desired += 1
                if node.metadata.name not in by_node:
                    self.store.create("Pod", self._make_pod(ds, node.metadata.name))
                    changed = True
            # remove daemon pods for deleted nodes
            live = {n.metadata.name for n in nodes}
            for target, p in by_node.items():
                if target not in live:
                    self.store.delete("Pod", p.namespace, p.metadata.name)
                    changed = True
            current = sum(1 for p in by_node.values() if p.spec.node_name)
            if (ds.status_desired, ds.status_current) != (desired, current):
                ds.status_desired = desired
                ds.status_current = current
                self.store.update("DaemonSet", ds)
        return changed
