"""Endpoints + EndpointSlice controllers.

Reference: pkg/controller/endpoint/endpoints_controller.go (Endpoints per
Service from selector-matched pods; ready vs notReady split) and
pkg/controller/endpointslice (discovery/v1 slices, ≤100 endpoints per slice,
kubernetes.io/service-name label ties slices to their Service).

Pod IPs: real kubelets report status.podIP; hollow nodes don't, so a
deterministic sim IP is derived from the pod UID when absent — the
controller's grouping/slicing behavior is what's under test, not IPAM.
"""

from __future__ import annotations

import zlib
from typing import List, Tuple

from ..api import objects as v1
from ..sim.store import ObjectStore

MAX_ENDPOINTS_PER_SLICE = 100


def _pod_ip(pod: v1.Pod) -> str:
    if pod.status.pod_ip:
        return pod.status.pod_ip
    # zlib.crc32, not hash(): str hash is randomized per process, which made
    # sim endpoints nondeterministic across runs
    h = zlib.crc32((pod.metadata.uid or pod.metadata.name).encode())
    return f"10.{(h >> 16) & 255}.{(h >> 8) & 255}.{h & 255}"


def _service_pods(store: ObjectStore, svc) -> Tuple[List[v1.Pod], List[v1.Pod]]:
    """(ready, not_ready) pods selected by the service, in name order."""
    if not svc.selector:
        return [], []
    pods, _ = store.list("Pod")
    ready, not_ready = [], []
    for p in sorted(pods, key=lambda p: p.metadata.name):
        if p.metadata.namespace != svc.metadata.namespace:
            continue
        if p.metadata.deletion_timestamp is not None:
            continue
        labels = p.metadata.labels or {}
        if any(labels.get(k) != want for k, want in svc.selector.items()):
            continue
        if not p.spec.node_name:
            continue  # unscheduled pods have no endpoint yet
        if p.status.phase == v1.POD_RUNNING:
            ready.append(p)
        elif p.status.phase == v1.POD_PENDING:
            not_ready.append(p)
    return ready, not_ready


def _addr(pod: v1.Pod) -> v1.EndpointAddress:
    return v1.EndpointAddress(
        ip=_pod_ip(pod), node_name=pod.spec.node_name or "",
        target_name=pod.metadata.name,
    )


class EndpointsController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def sync_once(self) -> bool:
        changed = False
        services, _ = self.store.list("Service")
        for svc in services:
            ready, not_ready = _service_pods(self.store, svc)
            subset = v1.EndpointSubset(
                addresses=[_addr(p) for p in ready],
                not_ready_addresses=[_addr(p) for p in not_ready],
            )
            want = [subset] if (ready or not_ready) else []
            cur = self.store.get("Endpoints", svc.metadata.namespace,
                                 svc.metadata.name)
            if cur is None:
                ep = v1.Endpoints(
                    metadata=v1.ObjectMeta(name=svc.metadata.name,
                                           namespace=svc.metadata.namespace),
                    subsets=want,
                )
                self.store.create("Endpoints", ep)
                changed = True
            elif _subset_key(cur.subsets) != _subset_key(want):
                cur.subsets = want
                self.store.update("Endpoints", cur)
                changed = True
        # services gone → endpoints garbage
        eps, _ = self.store.list("Endpoints")
        live = {(s.metadata.namespace, s.metadata.name) for s in services}
        for ep in eps:
            if (ep.metadata.namespace, ep.metadata.name) not in live:
                self.store.delete("Endpoints", ep.metadata.namespace,
                                  ep.metadata.name)
                changed = True
        return changed


class EndpointSliceController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def sync_once(self) -> bool:
        changed = False
        services, _ = self.store.list("Service")
        want_names = set()
        for svc in services:
            ready, not_ready = _service_pods(self.store, svc)
            endpoints = [
                v1.Endpoint(addresses=[_pod_ip(p)], ready=True,
                            node_name=p.spec.node_name or "",
                            target_name=p.metadata.name)
                for p in ready
            ] + [
                v1.Endpoint(addresses=[_pod_ip(p)], ready=False,
                            node_name=p.spec.node_name or "",
                            target_name=p.metadata.name)
                for p in not_ready
            ]
            for i in range(0, max(1, len(endpoints)), MAX_ENDPOINTS_PER_SLICE):
                chunk = endpoints[i:i + MAX_ENDPOINTS_PER_SLICE]
                name = f"{svc.metadata.name}-{i // MAX_ENDPOINTS_PER_SLICE}"
                want_names.add((svc.metadata.namespace, name))
                cur = self.store.get("EndpointSlice", svc.metadata.namespace,
                                     name)
                if cur is None:
                    sl = v1.EndpointSlice(
                        metadata=v1.ObjectMeta(
                            name=name, namespace=svc.metadata.namespace,
                            labels={"kubernetes.io/service-name":
                                    svc.metadata.name},
                        ),
                        endpoints=chunk,
                    )
                    self.store.create("EndpointSlice", sl)
                    changed = True
                elif _ep_key(cur.endpoints) != _ep_key(chunk):
                    cur.endpoints = chunk
                    self.store.update("EndpointSlice", cur)
                    changed = True
        slices, _ = self.store.list("EndpointSlice")
        for sl in slices:
            if (sl.metadata.namespace, sl.metadata.name) not in want_names:
                self.store.delete("EndpointSlice", sl.metadata.namespace,
                                  sl.metadata.name)
                changed = True
        return changed


def _subset_key(subsets) -> tuple:
    return tuple(
        (tuple((a.ip, a.node_name, a.target_name) for a in s.addresses),
         tuple((a.ip, a.node_name, a.target_name)
               for a in s.not_ready_addresses))
        for s in subsets
    )


def _ep_key(endpoints) -> tuple:
    return tuple(
        (tuple(e.addresses), e.ready, e.node_name, e.target_name)
        for e in endpoints
    )
