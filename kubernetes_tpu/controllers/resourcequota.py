"""ResourceQuota status controller.

Reference: pkg/controller/resourcequota/resource_quota_controller.go syncs
status.used from observed objects; enforcement happens at admission
(sim/store.py _admit_quota, the plugin/pkg/admission/resourcequota analog).
"""

from __future__ import annotations

from ..api.resource import compute_pod_resource_request
from ..sim.store import ObjectStore


def _fmt_milli(milli: int) -> str:
    return f"{milli}m" if milli % 1000 else str(milli // 1000)


class ResourceQuotaController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def sync_once(self) -> bool:
        changed = False
        quotas, _ = self.store.list("ResourceQuota")
        if not quotas:
            return False
        pods, _ = self.store.list("Pod")
        by_ns: dict = {}
        for p in pods:
            if p.status.phase in ("Succeeded", "Failed"):
                continue  # terminal pods release their quota share
            by_ns.setdefault(p.metadata.namespace, []).append(p)
        for q in quotas:
            ns_pods = by_ns.get(q.metadata.namespace, [])
            cpu = sum(compute_pod_resource_request(p).milli_cpu
                      for p in ns_pods)
            mem = sum(compute_pod_resource_request(p).memory for p in ns_pods)
            used = {}
            for key in q.hard:
                if key in ("pods", "count/pods"):
                    used[key] = str(len(ns_pods))
                elif key in ("cpu", "requests.cpu"):
                    used[key] = _fmt_milli(cpu)
                elif key in ("memory", "requests.memory"):
                    used[key] = str(mem)
            if q.status_used != used or q.status_hard != dict(q.hard):
                q.status_used = used
                q.status_hard = dict(q.hard)
                self.store.update("ResourceQuota", q)
                changed = True
        return changed
