"""Partition-tolerant node lifecycle: zone-aware health aggregation with
rate-limited eviction queues, a NoExecute taint manager with
tolerationSeconds countdowns, and gang-aware slice repair.

Reference: pkg/controller/nodelifecycle/node_lifecycle_controller.go (1550
LoC) — monitorNodeHealth marks nodes whose kubelet Lease went stale past
--node-monitor-grace-period as Ready=Unknown and taints them
node.kubernetes.io/unreachable:NoExecute; per-zone ``zoneStates`` aggregate
Ready counts into three modes that retune each zone's RateLimitedTimedQueue
(``setLimiterInZone``); the NoExecuteTaintManager
(pkg/controller/nodelifecycle/scheduler/taint_manager.go) evicts pods from
tainted nodes honoring tolerationSeconds countdowns anchored on
Taint.TimeAdded.

Mapping and deliberate deviations:

  - **Zone modes** (``ComputeZoneState``): Normal (all ready),
    PartialDisruption (≥ unhealthy-zone-threshold of the zone NotReady, and
    more than 2 nodes down — the upstream guard), FullDisruption (zero
    ready nodes).  Mode drives the zone queue's token bucket: Normal → the
    primary eviction rate (--node-eviction-rate, 0.1/s), Partial → the
    secondary rate (--secondary-node-eviction-rate, 0.01/s) for zones
    larger than ``large_zone_threshold`` and a FULL STOP for small zones
    (upstream's small-cluster handling).
  - **FullDisruption freezes evictions** for that zone (timed countdowns
    included).  DOCUMENTED DEVIATION: upstream only freezes when ALL zones
    are fully disrupted (the master-partition heuristic) and evicts a
    single dark zone at the normal rate; here a whole zone going dark is
    treated as indistinguishable from a network partition — for the TPU
    north star, deleting an entire zone's training gangs on a partition
    signal is the worst possible failure amplification.  The taints still
    land (new work is masked away from the dark zone); only deletion is
    withheld until the zone either partially recovers or heals.
    Zones smaller than ``full_disruption_min_nodes`` never freeze: a 1-2
    node "zone" dying is indistinguishable from plain node death and the
    basic elastic-recovery loop (evict → controllers recreate → reschedule
    elsewhere) must keep working.
  - **Eviction rate = node-sweep rate**, exactly the upstream shape: the
    rate-limited unit in ``zonePodEvictor``/``zoneNoExecuteTainter`` is a
    NODE, not a pod.  A popped node's sweep evicts its non-tolerating pods
    through the shared PDB gate (descheduler/evictions.py); refused pods
    retry on later syncs WITHOUT consuming fresh tokens (the PR-5
    replenish-and-drain contract — a still-down node must eventually drain
    without ever violating a PDB).
  - **tolerationSeconds** (the ISSUE-13 bugfix): a toleration matching the
    unreachable taint with ``tolerationSeconds=None`` tolerates FOREVER
    (never evicted); ``tolerationSeconds=N`` enters the timed eviction
    queue and survives exactly N seconds from Taint.TimeAdded — upstream
    semantics, where the seed code evicted such pods immediately.  Lease
    recovery removes the taint and CANCELS pending countdowns, so a
    flapping node stops churning workloads.
  - **Gang-aware slice repair**: a swept node carrying bound members of a
    PodGroup fails the WHOLE gang atomically — every bound member
    (wherever it is) is gate-checked first and evicted only if ALL pass,
    the PodGroup phase resets to Pending, and ``gang_repairs_total``
    counts the repair once.  The scheduler's GangDirectory sees the
    deletes through its watch stream and requeues the remainder as one
    gang; an attached directory is additionally told directly
    (``repair``) so waiting members reject without waiting for events.

All deadline math runs on the INJECTED clock, so chaos replays with a fake
clock are deterministic; same seed → same kill sequence → same sweeps.
"""

from __future__ import annotations

import heapq
import itertools
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api import objects as v1
from ..chaos.faults import CRASH_MID_ZONE_EVICT, maybe_crash
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from ..sim.store import ObjectStore

UNREACHABLE_TAINT = "node.kubernetes.io/unreachable"
NOT_READY_TAINT = "node.kubernetes.io/not-ready"
ZONE_LABEL = "topology.kubernetes.io/zone"
DEFAULT_GRACE_PERIOD = 40.0  # node-monitor-grace-period default

# zone disruption modes (node_lifecycle_controller.go ZoneState)
ZONE_NORMAL = "Normal"
ZONE_PARTIAL = "PartialDisruption"
ZONE_FULL = "FullDisruption"
ZONE_STATE_CODE = {ZONE_NORMAL: 0, ZONE_PARTIAL: 1, ZONE_FULL: 2}

DEFAULT_UNHEALTHY_ZONE_THRESHOLD = 0.55  # --unhealthy-zone-threshold
DEFAULT_LARGE_ZONE_THRESHOLD = 50        # largeClusterSizeThreshold
DEFAULT_EVICTION_QPS = 0.1               # --node-eviction-rate
DEFAULT_SECONDARY_EVICTION_QPS = 0.01    # --secondary-node-eviction-rate
DEFAULT_EVICTION_BURST = 1               # scheduler.EvictionRateLimiterBurst
# zones below this NotReady count never enter the FullDisruption freeze
# (mirrors upstream's ``notReadyNodes > 2`` partial-disruption guard)
DEFAULT_FULL_DISRUPTION_MIN_NODES = 3


def _set_condition(node: v1.Node, cond_type: str, status: str):
    for c in node.status.conditions:
        if c.get("type") == cond_type:
            c["status"] = status
            return
    node.status.conditions.append({"type": cond_type, "status": status})


class TokenBucket:
    """flowcontrol.NewTokenBucketRateLimiter on the injected clock.

    ``set_rate`` settles the accrual at the OLD rate first, so a mode flip
    mid-interval never retroactively re-prices elapsed time."""

    def __init__(self, qps: float, burst: int, clock, now: float = None):
        self.qps = float(qps)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock() if now is None else now

    def _refill(self, now: float) -> None:
        if now > self._last:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.qps)
        self._last = now

    def set_rate(self, qps: float, now: float) -> None:
        if qps == self.qps:
            return
        self._refill(now)
        self.qps = float(qps)
        if qps <= 0:
            # a freeze means FROZEN: banked burst must not leak one last
            # eviction into a zone that just went fully dark
            self._tokens = 0.0

    def try_take(self, now: float) -> bool:
        self._refill(now)
        if self.qps <= 0 or self._tokens < 1.0:
            return False
        self._tokens -= 1.0
        return True


class RateLimitedTimedQueue:
    """Per-zone FIFO of nodes awaiting their eviction sweep, popped at the
    zone's current token rate (the upstream RateLimitedTimedQueue, minus
    the retry-backoff machinery — refused sweeps retry via the controller's
    draining set, not by re-queuing).  ``remove`` is the cancellation hook
    lease recovery uses."""

    def __init__(self, limiter: TokenBucket):
        self.limiter = limiter
        self._items: "OrderedDict[str, None]" = OrderedDict()

    def add(self, node: str) -> None:
        if node not in self._items:
            self._items[node] = None

    def remove(self, node: str) -> bool:
        if node in self._items:
            del self._items[node]
            return True
        return False

    def __contains__(self, node: str) -> bool:
        return node in self._items

    def __len__(self) -> int:
        return len(self._items)

    def try_pop(self, now: float) -> Optional[str]:
        if not self._items or not self.limiter.try_take(now):
            return None
        node, _ = self._items.popitem(last=False)
        return node


@dataclass
class _ZoneHealth:
    queue: RateLimitedTimedQueue
    mode: str = ZONE_NORMAL
    ready: int = 0
    not_ready: int = 0


class NoExecuteTaintManager:
    """The tolerationSeconds timed eviction queue.

    Entries key on (namespace/name, node); a heap orders deadlines, a live
    dict arbitrates (lazy cancellation: a popped entry whose dict record
    disagrees is a ghost).  Deadlines anchor on Taint.TimeAdded, so a
    successor controller resumes the SAME countdowns instead of granting
    dead nodes' pods a fresh window."""

    def __init__(self):
        self._heap: List[Tuple[float, int, str, str]] = []
        self._pending: Dict[str, Tuple[float, str]] = {}  # pod → (at, node)
        self._seq = itertools.count()

    def schedule(self, pod_key: str, node: str, fire_at: float) -> None:
        cur = self._pending.get(pod_key)
        if cur is not None and cur == (fire_at, node):
            return  # already scheduled (idempotent re-registration)
        self._pending[pod_key] = (fire_at, node)
        heapq.heappush(self._heap, (fire_at, next(self._seq), pod_key, node))

    def cancel_node(self, node: str) -> int:
        victims = [k for k, (_, n) in self._pending.items() if n == node]
        for k in victims:
            del self._pending[k]
        return len(victims)

    def pending_on(self, pod_key: str) -> bool:
        return pod_key in self._pending

    def __len__(self) -> int:
        return len(self._pending)

    def due(self, now: float) -> List[Tuple[str, str]]:
        """Pop every (pod_key, node) whose deadline passed.  A deferred
        entry (frozen zone) must be re-``schedule``d by the caller."""
        out: List[Tuple[str, str]] = []
        while self._heap and self._heap[0][0] <= now:
            fire_at, _, pod_key, node = heapq.heappop(self._heap)
            live = self._pending.get(pod_key)
            if live is None or live != (fire_at, node):
                continue  # cancelled or rescheduled — ghost entry
            del self._pending[pod_key]
            out.append((pod_key, node))
        return out


class NodeLifecycleController:
    def __init__(self, store: ObjectStore,
                 grace_period: float = DEFAULT_GRACE_PERIOD,
                 clock=time.monotonic, eviction_api=None,
                 gang_directory=None,
                 zone_label: str = ZONE_LABEL,
                 unhealthy_zone_threshold: float = DEFAULT_UNHEALTHY_ZONE_THRESHOLD,
                 large_zone_threshold: int = DEFAULT_LARGE_ZONE_THRESHOLD,
                 eviction_qps: float = DEFAULT_EVICTION_QPS,
                 secondary_eviction_qps: float = DEFAULT_SECONDARY_EVICTION_QPS,
                 eviction_burst: int = DEFAULT_EVICTION_BURST,
                 full_disruption_min_nodes: int = DEFAULT_FULL_DISRUPTION_MIN_NODES):
        from ..descheduler.evictions import EvictionAPI

        self.store = store
        self.grace = grace_period
        self.clock = clock
        self.zone_label = zone_label
        self.unhealthy_zone_threshold = unhealthy_zone_threshold
        self.large_zone_threshold = large_zone_threshold
        self.eviction_qps = eviction_qps
        self.secondary_eviction_qps = secondary_eviction_qps
        self.eviction_burst = eviction_burst
        self.full_disruption_min_nodes = full_disruption_min_nodes
        # every pod-killing path goes through the shared eviction gate
        # (descheduler/evictions.py): a not-ready node's sweep can never
        # zero out a PDB-protected workload in one pass.  DOCUMENTED
        # DEVIATION from the reference taint manager, which deletes
        # NoExecute-evicted pods unconditionally; refused pods survive the
        # sweep and retry on later syncs as budget replenishes.
        self.evictions = eviction_api or EvictionAPI(store, clock=clock)
        # optional in-process GangDirectory (the scheduler's): repairs also
        # reject still-waiting members directly instead of waiting for the
        # watch stream to deliver the deletes
        self.gangs = gang_directory
        self.zones: Dict[str, _ZoneHealth] = {}
        self.taint_manager = NoExecuteTaintManager()
        # nodes whose sweep ran at least once and which are still down:
        # PDB-refused pods retry here every sync without new tokens
        self._draining: Set[str] = set()
        # node → when this controller first saw it WITHOUT a lease: grace
        # for never-heartbeat nodes anchors here (no persisted timestamp
        # shares the injected clock's time base), bounding the
        # registered-but-kubelet-died blind spot instead of exempting it
        # forever
        self._no_lease_since: Dict[str, float] = {}

    # --- zone bookkeeping -----------------------------------------------------

    def _zone_of(self, node: v1.Node) -> str:
        return node.metadata.labels.get(self.zone_label, "")

    def _zone(self, zone: str) -> _ZoneHealth:
        z = self.zones.get(zone)
        if z is None:
            z = _ZoneHealth(queue=RateLimitedTimedQueue(TokenBucket(
                self.eviction_qps, self.eviction_burst, self.clock)))
            self.zones[zone] = z
        return z

    def zone_mode(self, zone: str) -> str:
        z = self.zones.get(zone)
        return z.mode if z is not None else ZONE_NORMAL

    @property
    def draining(self) -> frozenset:
        """Nodes whose rate-limited sweep has run and which are still down
        (PDB-refused pods retry here each sync).  The storm soak reads this
        as the token-bounded sweep count."""
        return frozenset(self._draining)

    def _compute_zone_states(self, nodes: List[v1.Node], now: float) -> None:
        """ComputeZoneState + setLimiterInZone over the just-written
        conditions; gauges updated per zone every sync."""
        counts: Dict[str, Tuple[int, int]] = {}
        for node in nodes:
            zone = self._zone_of(node)
            ready, not_ready = counts.get(zone, (0, 0))
            if v1.node_is_ready(node):
                ready += 1
            else:
                not_ready += 1
            counts[zone] = (ready, not_ready)
        for zone, (ready, not_ready) in counts.items():
            z = self._zone(zone)
            z.ready, z.not_ready = ready, not_ready
            total = ready + not_ready
            if not_ready == 0:
                mode = ZONE_NORMAL
            elif ready == 0 and not_ready >= self.full_disruption_min_nodes:
                mode = ZONE_FULL
            elif (not_ready / total >= self.unhealthy_zone_threshold
                  and not_ready > 2):
                mode = ZONE_PARTIAL
            else:
                mode = ZONE_NORMAL
            if mode != z.mode:
                klog.V(2).info_s("Zone disruption state changed", zone=zone,
                                 old=z.mode, new=mode, ready=ready,
                                 not_ready=not_ready)
                z.mode = mode
            if mode == ZONE_FULL:
                qps = 0.0  # frozen (see module docstring deviation note)
            elif mode == ZONE_PARTIAL:
                qps = (self.secondary_eviction_qps
                       if total > self.large_zone_threshold else 0.0)
            else:
                qps = self.eviction_qps
            z.queue.limiter.set_rate(qps, now)
            m.node_lifecycle_zone_state.set(ZONE_STATE_CODE[mode], (zone,))
        # zones whose last node disappeared: report Normal and drop state
        for zone in [zn for zn in self.zones if zn not in counts]:
            m.node_lifecycle_zone_state.set(0, (zone,))
            m.node_lifecycle_queue_depth.set(0, (zone,))
            del self.zones[zone]

    # --- toleration semantics -------------------------------------------------

    @staticmethod
    def _unreachable_taint(time_added: float) -> v1.Taint:
        return v1.Taint(key=UNREACHABLE_TAINT, effect=v1.TAINT_NO_EXECUTE,
                        time_added=time_added)

    @staticmethod
    def _matching_tolerations(pod: v1.Pod) -> List[v1.Toleration]:
        probe = v1.Taint(key=UNREACHABLE_TAINT, effect=v1.TAINT_NO_EXECUTE)
        return [t for t in pod.spec.tolerations if t.tolerates(probe)]

    @classmethod
    def _tolerates_forever(cls, pod: v1.Pod) -> bool:
        """Upstream GetMatchingTolerations: any matching toleration with
        tolerationSeconds UNSET tolerates the taint indefinitely."""
        return any(t.toleration_seconds is None
                   for t in cls._matching_tolerations(pod))

    @classmethod
    def _toleration_deadline(cls, pod: v1.Pod,
                             taint_added: float) -> Optional[float]:
        """Earliest tolerationSeconds expiry (minTolerationTime), None when
        no bounded toleration matches."""
        secs = [t.toleration_seconds for t in cls._matching_tolerations(pod)
                if t.toleration_seconds is not None]
        if not secs:
            return None
        return taint_added + float(min(secs))

    def _register_countdowns(self, node_name: str, taint_added: float,
                             pods: List[v1.Pod]) -> None:
        """Enter every bounded-toleration pod on ``node_name`` into the
        timed eviction queue.  Idempotent (re-run by a successor after a
        crash) and anchored on Taint.TimeAdded, never "now"."""
        for p in pods:
            if p.spec.node_name != node_name:
                continue
            if self._tolerates_forever(p):
                continue
            deadline = self._toleration_deadline(p, taint_added)
            if deadline is not None:
                self.taint_manager.schedule(p.key(), node_name, deadline)

    # --- the sync loop --------------------------------------------------------

    def sync_once(self) -> bool:
        changed = False
        now = self.clock()
        nodes, _ = self.store.list("Node")
        pods: Optional[List[v1.Pod]] = None  # listed lazily, once per sync
        by_node: Dict[str, List[v1.Pod]] = {}

        def all_pods() -> List[v1.Pod]:
            nonlocal pods
            if pods is None:
                pods = self.store.list("Pod")[0]
                for p in pods:
                    if p.spec.node_name:
                        by_node.setdefault(p.spec.node_name, []).append(p)
            return pods

        def node_pods(name: str) -> List[v1.Pod]:
            # one node-name index per sync: a 60-node outage must not
            # rescan the whole pod list once per down node per sync
            all_pods()
            return by_node.get(name, [])

        # 1. monitorNodeHealth: lease staleness → taint/untaint + queue/cancel
        for node in nodes:
            name = node.metadata.name
            lease = self.store.get("Lease", "kube-node-lease", name)
            if lease is not None:
                self._no_lease_since.pop(name, None)
                stale = (now - lease.renew_time) > self.grace
            else:
                # a node whose lease never existed hasn't heartbeat yet —
                # but the exemption is TIME-BOUNDED: grace anchors on this
                # controller's first no-lease observation, so a node whose
                # kubelet died before its first renewal is still detected
                # (short-lived test fixtures stay untouched within grace)
                first = self._no_lease_since.setdefault(name, now)
                stale = (now - first) > self.grace
            taint = next((t for t in node.spec.taints
                          if t.key == UNREACHABLE_TAINT), None)
            zone = self._zone_of(node)
            if stale and taint is None:
                node.spec.taints.append(self._unreachable_taint(now))
                _set_condition(node, "Ready", "Unknown")
                self.store.update("Node", node)
                changed = True
                self._register_countdowns(name, now, node_pods(name))
                # kill-point: the taint/condition write is durable in the
                # store, the eviction sweep has NOT run — a successor must
                # resume the sweep exactly-once from the taint alone
                maybe_crash(CRASH_MID_ZONE_EVICT)
                self._zone(zone).queue.add(name)
            elif stale and taint is not None:
                # ongoing outage (or a successor resuming after a crash):
                # make sure the node is queued or draining and the
                # countdowns exist — both re-registrations are idempotent,
                # and deadlines anchor on the PERSISTED TimeAdded
                if taint.time_added is None:
                    # a taint persisted by pre-round-13 code (or written
                    # externally) carries no anchor: backfill ONCE so the
                    # countdown deadline stops sliding forward every sync
                    # (and re-registration stays heap-idempotent)
                    taint.time_added = now
                    self.store.update("Node", node)
                    changed = True
                if name not in self._draining:
                    self._zone(zone).queue.add(name)
                self._register_countdowns(name, taint.time_added,
                                           node_pods(name))
            elif not stale and taint is not None and lease is not None:
                # lease recovery: untaint, restore Ready, and CANCEL every
                # pending eviction for the node — a flapping node must not
                # churn workloads (the ISSUE-13 flap contract)
                node.spec.taints = [t for t in node.spec.taints
                                    if t.key != UNREACHABLE_TAINT]
                _set_condition(node, "Ready", "True")
                self.store.update("Node", node)
                cancelled = self.taint_manager.cancel_node(name)
                if self._zone(zone).queue.remove(name):
                    cancelled += 1
                self._draining.discard(name)
                if cancelled:
                    m.node_lifecycle_evictions.inc(
                        (self.zone_mode(zone), "cancelled"), by=cancelled)
                klog.V(2).info_s("Node lease recovered; untainted",
                                 node=name, cancelled_evictions=cancelled)
                changed = True

        # 2. zoneStates: aggregate the just-written conditions, retune the
        # per-zone limiters (Normal/Partial/Full)
        self._compute_zone_states(nodes, now)

        # 3. rate-limited node sweeps (zonePodEvictor pops)
        node_zone = {n.metadata.name: self._zone_of(n) for n in nodes}
        live = {n.metadata.name for n in nodes}
        for gone in set(self._no_lease_since) - live:
            del self._no_lease_since[gone]
        swept_now: Set[str] = set()
        for zone, z in self.zones.items():
            if z.mode == ZONE_FULL:
                continue  # frozen: a dark zone's queue holds
            # purge queued nodes whose Node object was deleted BEFORE
            # popping: a dead entry must not burn the zone's only token
            # (100 s of secondary-rate delay for a no-op sweep)
            for name in [n for n in z.queue._items if n not in live]:
                z.queue.remove(name)
            while True:
                name = z.queue.try_pop(now)
                if name is None:
                    break
                changed = self._sweep(name, zone, node_pods(name),
                                      all_pods()) or changed
                self._draining.add(name)
                swept_now.add(name)

        # 4. drain retries: swept nodes still down retry their refused
        # evictions each sync (budget replenishes as replacements land) —
        # no fresh tokens; the rate limit bounds NEW node sweeps only.
        # Nodes whose FIRST sweep just ran in step 3 skip this sync's
        # retry: a second pass at the same instant would hit the gate (and
        # the eviction metrics) twice for the same refusals.
        for name in sorted(self._draining):
            if name not in live:
                self._draining.discard(name)
                continue
            if name in swept_now:
                continue
            zone = node_zone.get(name, "")
            if self.zone_mode(zone) == ZONE_FULL:
                continue
            changed = self._sweep(name, zone, node_pods(name),
                                  all_pods()) or changed

        # 5. taint-manager countdown expiries
        for pod_key, node_name in self.taint_manager.due(now):
            zone = node_zone.get(node_name, "")
            mode = self.zone_mode(zone)
            if mode == ZONE_FULL:
                # frozen zone: defer, re-check next sync (deadline kept)
                self.taint_manager.schedule(pod_key, node_name, now)
                m.node_lifecycle_evictions.inc((mode, "deferred"))
                continue
            ns, _, pname = pod_key.partition("/")
            pod = self.store.get("Pod", ns, pname)
            if pod is None or pod.spec.node_name != node_name:
                continue  # gone or rescheduled — nothing to evict
            gk = self._gang_key(pod)
            if gk is not None:
                # a gang member may ONLY leave through the atomic repair:
                # a deferred repair (a sibling's PDB refused) re-arms the
                # countdown instead of falling through to a lone eviction
                # — never a half-evicted gang
                changed = self._repair_gang(gk, mode, all_pods()) or changed
                if self.store.get("Pod", ns, pname) is not None:
                    self.taint_manager.schedule(pod_key, node_name, now)
                continue
            result = self.evictions.evict(
                pod, reason=f"toleration expired on unreachable node "
                            f"{node_name}",
                policy="nodelifecycle")
            m.node_lifecycle_evictions.inc((mode, self._verdict(result)))
            if not result.allowed:
                # PDB-refused: keep the countdown live, retry next sync
                self.taint_manager.schedule(pod_key, node_name, now)
            changed = changed or result.evicted

        # queue-depth gauges LAST so `ktpu nodehealth` sees post-sync truth
        for zone, z in self.zones.items():
            m.node_lifecycle_queue_depth.set(len(z.queue), (zone,))
        return changed

    @staticmethod
    def _verdict(result) -> str:
        if result.evicted:
            return "evicted"
        if not result.allowed:
            return "refused"
        if result.reason == "pod already gone":
            return "missing"
        return "error"

    # --- the per-node eviction sweep ------------------------------------------

    def _sweep(self, node_name: str, zone: str, pods: List[v1.Pod],
               full_pods: List[v1.Pod]) -> bool:
        """NoExecute eviction for one popped node: non-tolerating pods
        evict through the shared gate NOW; forever-tolerations are skipped;
        bounded tolerations ride the timed queue; bound gang members route
        to the atomic whole-gang repair.  ``pods`` is the node's own pod
        list (the per-sync index); ``full_pods`` the whole cluster's (gang
        members live on other hosts too)."""
        mode = self.zone_mode(zone)
        evicted = False
        pdbs = None
        gang_keys: List[str] = []
        seen_gangs: Set[str] = set()
        for p in pods:
            if p.spec.node_name != node_name:
                continue
            if self.store.get("Pod", p.namespace, p.metadata.name) is None:
                continue  # evicted earlier this sync (gang repair overlap)
            if self._tolerates_forever(p):
                continue
            if self.taint_manager.pending_on(p.key()):
                continue  # bounded toleration: countdown owns the decision
            gk = self._gang_key(p)
            if gk is not None:
                if gk not in seen_gangs:
                    seen_gangs.add(gk)
                    gang_keys.append(gk)
                continue
            if pdbs is None:
                pdbs = self.store.list("PodDisruptionBudget")[0]
            result = self.evictions.evict(
                p, reason=f"node {node_name} not ready",
                policy="nodelifecycle", pdbs=pdbs)
            m.node_lifecycle_evictions.inc((mode, self._verdict(result)))
            evicted = evicted or result.evicted
        for gk in gang_keys:
            evicted = self._repair_gang(gk, mode, full_pods) or evicted
        return evicted

    # --- gang-aware slice repair ----------------------------------------------

    def _gang_key(self, pod: v1.Pod) -> Optional[str]:
        from ..gang import POD_GROUP_LABEL

        name = pod.metadata.labels.get(POD_GROUP_LABEL)
        if not name:
            return None
        if self.store.get("PodGroup", pod.namespace, name) is None:
            return None  # labelled but groupless: plain pod semantics
        return f"{pod.namespace}/{name}"

    def _repair_gang(self, key: str, mode: str, pods: List[v1.Pod]) -> bool:
        """Fail the WHOLE gang atomically: every store-bound member (on any
        node, healthy hosts included — a gang missing one member makes no
        progress) goes through the PDB gate all-or-nothing.  The pre-check
        is AGGREGATE — each matching PDB must have budget for every member
        it covers at once (per-member dry-runs can't see the shared
        drain), so one exhausted budget defers the entire repair to a
        later sync; nothing is half-evicted.  Exactly-once: the deletes
        are the store's atomic pops, a repaired gang has no bound members
        left to trigger a second repair, and ``gang_repairs_total`` counts
        only a COMPLETED repair (a raced mid-loop refusal leaves the
        remainder for the next sync's pass, which counts the one repair
        when it finishes the job)."""
        from ..gang import POD_GROUP_LABEL

        ns, _, name = key.partition("/")
        members = [
            p for p in pods
            if p.metadata.labels.get(POD_GROUP_LABEL) == name
            and p.namespace == ns and p.spec.node_name
            and self.store.get("Pod", p.namespace, p.metadata.name)
            is not None
        ]
        if not members:
            return False
        pdbs = self.store.list("PodDisruptionBudget")[0]
        demand: Dict[str, int] = {}
        budget: Dict[str, int] = {}
        for p in members:
            for pdb in self.evictions.matching_pdbs(p, pdbs):
                k = f"{pdb.metadata.namespace}/{pdb.metadata.name}"
                demand[k] = demand.get(k, 0) + 1
                budget[k] = pdb.disruptions_allowed
        for k, need in sorted(demand.items()):
            if need > budget[k]:
                m.node_lifecycle_evictions.inc((mode, "refused"))
                klog.V(2).info_s(
                    "Gang repair deferred: PDB lacks budget for the "
                    "whole gang", group=key, pdb=k, need=need,
                    allowed=budget[k])
                return False
        evicted_any = False
        complete = True
        for p in members:
            result = self.evictions.evict(
                p, reason=f"gang {key} member lost its node",
                policy="nodelifecycle", pdbs=pdbs)
            m.node_lifecycle_evictions.inc((mode, self._verdict(result)))
            evicted_any = evicted_any or result.evicted
            if not result.evicted and self._verdict(result) != "missing":
                complete = False  # raced refusal/fault: finish next sync
        if not complete:
            klog.V(2).info_s("Gang repair incomplete; remaining members "
                             "retry next sync", group=key)
            return evicted_any
        if self.gangs is not None:
            # directory hook FIRST: its _fail_group may write its own
            # phase (Unschedulable for still-waiting members) — the
            # controller's Pending write below is the final word, not a
            # value the hook silently stomps
            self.gangs.repair(key, "node lost; gang requeued by lifecycle")
        pg = self.store.get("PodGroup", ns, name)
        if pg is not None and pg.phase != v1.POD_GROUP_PENDING:
            pg.phase = v1.POD_GROUP_PENDING
            try:
                self.store.update("PodGroup", pg)
            except Exception as e:
                # best-effort phase write, same contract as the directory's
                klog.V(1).info_s("Gang repair phase write failed",
                                 group=key,
                                 error=f"{type(e).__name__}: {e}")
        m.gang_repairs.inc()
        klog.V(2).info_s("Gang repaired: all bound members evicted, "
                         "group requeues whole", group=key,
                         members=len(members))
        return evicted_any
