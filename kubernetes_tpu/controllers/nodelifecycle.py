"""Node lifecycle controller: failure detection + elastic rescheduling.

Reference: pkg/controller/nodelifecycle/node_lifecycle_controller.go:351 —
monitors node Lease heartbeats (kubelet renews every ¼ lease duration,
pkg/kubelet/kubelet.go:809-810); a node whose lease is stale past the grace
period is marked NotReady and gets the NoExecute taint
node.kubernetes.io/unreachable; its pods are evicted (deleted) so workload
controllers recreate them and the scheduler places them elsewhere — the
elastic-recovery loop of SURVEY §5.
"""

from __future__ import annotations

import time
from typing import Dict

from ..api import objects as v1
from ..sim.store import ObjectStore

UNREACHABLE_TAINT = "node.kubernetes.io/unreachable"
NOT_READY_TAINT = "node.kubernetes.io/not-ready"
DEFAULT_GRACE_PERIOD = 40.0  # node-monitor-grace-period default


def _set_condition(node: v1.Node, cond_type: str, status: str):
    for c in node.status.conditions:
        if c.get("type") == cond_type:
            c["status"] = status
            return
    node.status.conditions.append({"type": cond_type, "status": status})


class NodeLifecycleController:
    def __init__(self, store: ObjectStore, grace_period: float = DEFAULT_GRACE_PERIOD,
                 clock=time.monotonic, eviction_api=None):
        from ..descheduler.evictions import EvictionAPI

        self.store = store
        self.grace = grace_period
        self.clock = clock
        # every pod-killing path goes through the shared eviction gate
        # (descheduler/evictions.py): a not-ready node's sync can no longer
        # zero out a PDB-protected workload in one pass.  DOCUMENTED
        # DEVIATION from the reference taint manager, which deletes
        # NoExecute-evicted pods unconditionally; refused pods survive this
        # sync and retry on later syncs as budget replenishes.
        self.evictions = eviction_api or EvictionAPI(store, clock=clock)

    def sync_once(self) -> bool:
        changed = False
        now = self.clock()
        nodes, _ = self.store.list("Node")
        for node in nodes:
            lease = self.store.get("Lease", "kube-node-lease", node.metadata.name)
            stale = lease is None or (now - lease.renew_time) > self.grace
            tainted = any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
            if stale and lease is not None and not tainted:
                node.spec.taints.append(
                    v1.Taint(key=UNREACHABLE_TAINT, effect=v1.TAINT_NO_EXECUTE)
                )
                _set_condition(node, "Ready", "Unknown")
                self.store.update("Node", node)
                self._evict_pods(node.metadata.name)
                changed = True
            elif stale and tainted:
                # retry PDB-refused evictions from earlier syncs: budget
                # replenishes as replacements schedule, and a still-down
                # node must eventually drain without ever violating a PDB
                changed = self._evict_pods(node.metadata.name) or changed
            elif not stale and tainted:
                node.spec.taints = [
                    t for t in node.spec.taints if t.key != UNREACHABLE_TAINT
                ]
                _set_condition(node, "Ready", "True")
                self.store.update("Node", node)
                changed = True
        return changed

    def _evict_pods(self, node_name: str) -> bool:
        """NoExecute taint-manager eviction THROUGH the shared gate: pods
        without a matching toleration are evicted (controllers recreate
        them → rescheduled elsewhere), but a pod whose PodDisruptionBudget
        is exhausted is refused and retried on a later sync — one not-ready
        node can never zero out a protected workload in one pass."""
        pods, _ = self.store.list("Pod")
        evicted = False
        pdbs = None
        for p in pods:
            if p.spec.node_name != node_name:
                continue
            tolerated = any(
                t.key in (UNREACHABLE_TAINT, "") and (
                    t.operator == v1.TOLERATION_OP_EXISTS or not t.key
                ) and t.toleration_seconds is None
                for t in p.spec.tolerations
            )
            if not tolerated:
                if pdbs is None:
                    pdbs = self.store.list("PodDisruptionBudget")[0]
                result = self.evictions.evict(
                    p, reason=f"node {node_name} not ready",
                    policy="nodelifecycle", pdbs=pdbs)
                evicted = evicted or result.evicted
        return evicted
