"""StatefulSet controller (reference: pkg/controller/statefulset/stateful_set.go
+ stateful_set_control.go UpdateStatefulSet).

Semantics kept from the reference, sized to the sim:
  - stable identity: pods are named ``<set>-<ordinal>`` for ordinals
    0..replicas-1 (no random suffix);
  - ORDERED bring-up: the controller creates the next ordinal only after
    every lower ordinal exists AND is scheduled (OrderedReady pod management,
    stateful_set_control.go monotonic path);
  - scale-down removes the highest ordinal first.
"""

from __future__ import annotations

import copy
import re

from ..api import objects as v1
from ..sim.store import ObjectStore
from .replicaset import _owned_pods


class StatefulSetController:
    def __init__(self, store: ObjectStore):
        self.store = store

    def _make_pod(self, st: v1.StatefulSet, ordinal: int) -> v1.Pod:
        pod = v1.Pod()
        pod.metadata.namespace = st.metadata.namespace
        pod.metadata.name = f"{st.metadata.name}-{ordinal}"
        pod.metadata.labels = dict(st.template.labels)
        pod.metadata.owner_references = [
            v1.OwnerReference(
                kind="StatefulSet", name=st.metadata.name,
                uid=st.metadata.uid, controller=True,
            )
        ]
        pod.spec = copy.deepcopy(st.template.spec)
        if not pod.spec.containers:
            pod.spec.containers = [v1.Container(name="c0", image="pause")]
        return pod

    def sync_once(self) -> bool:
        changed = False
        sets, _ = self.store.list("StatefulSet")
        for st in sets:
            pods = _owned_pods(self.store, "StatefulSet", st)
            by_ordinal = {}
            for p in pods:
                m = re.match(rf"^{re.escape(st.metadata.name)}-(\d+)$", p.metadata.name)
                if m:
                    by_ordinal[int(m.group(1))] = p
            # ordered bring-up: create the lowest missing ordinal once every
            # smaller ordinal is present and scheduled
            for i in range(st.replicas):
                p = by_ordinal.get(i)
                if p is None:
                    self.store.create("Pod", self._make_pod(st, i))
                    changed = True
                    break
                if not p.spec.node_name:
                    break  # wait for it to schedule before advancing
            # scale down: highest ordinal first
            for i in sorted(by_ordinal, reverse=True):
                if i >= st.replicas:
                    self.store.delete(
                        "Pod", st.metadata.namespace, by_ordinal[i].metadata.name
                    )
                    changed = True
            ready = sum(1 for p in by_ordinal.values() if p.spec.node_name)
            if (st.status_replicas, st.status_ready_replicas) != (len(by_ordinal), ready):
                st.status_replicas = len(by_ordinal)
                st.status_ready_replicas = ready
                self.store.update("StatefulSet", st)
        return changed
