"""Segment/domain kernels as one-hot einsum contractions.

The scheduling tensor programs keep per-domain count tables ``[..., D]``
(domain = a (topologyKey, value) pair compacted by the encoder) and need two
primitives over them:

  * gather:  ``out[..., n] = table[..., dom[..., n]]``   (counts per node)
  * scatter: ``table[..., dom[..., n]] += vals[..., n]`` (counts per domain)

``jnp.take_along_axis`` / ``.at[].add`` express these directly but XLA lowers
minor-axis element gathers/scatters to serial loops on TPU (~0.4 µs/element —
100 ms for a [128, 2, 1024] lookup).  Contracting against a one-hot of the
domain index instead runs on the MXU: the one-hot is [..., N, D] f32
materialized on the fly (bandwidth-bound, ~bytes/800GB/s), and the lookup is
a batched matvec.  Counts stay exact in f32 up to 2^24.

These are the "segment-sum over dictionary-encoded topology keys" kernels
SURVEY §2.5/§7.6 calls for; a hand-written Pallas version buys nothing over
the single fused einsum XLA already emits, so this is the shipped form.
"""

from __future__ import annotations

import jax.numpy as jnp


def domain_onehot(dom, depth: int, dtype=jnp.float32):
    """``oh[..., n, d] = (dom[..., n] == d)`` — [..., N, D]."""
    return (dom[..., None] == jnp.arange(depth)).astype(dtype)


def domain_gather(table, dom, depth: int | None = None):
    """``out[..., n] = table[..., dom[..., n]]`` without a TPU gather.

    table: [..., D] (int or float); dom: int[..., N] with values in [0, D).
    Returns f32[..., N] (exact for integer tables < 2^24).
    """
    d = depth if depth is not None else table.shape[-1]
    oh = domain_onehot(dom, d)
    return jnp.einsum("...d,...nd->...n", table.astype(jnp.float32), oh)


def domain_scatter_add(vals, dom, depth: int):
    """``out[..., d] = Σ_n vals[..., n] · (dom[..., n] == d)`` — [..., D]."""
    oh = domain_onehot(dom, depth)
    return jnp.einsum("...n,...nd->...d", vals.astype(jnp.float32), oh)


def domain_scatter_add_backend(vals, dom, depth: int):
    """domain_scatter_add with a backend-aware lowering: the one-hot einsum
    materializes a [..., N, D+1] tensor — at hostname topology (D ≈ N) that
    is O(N²) memory traffic PER CALL, which turned the dedup engine's
    per-round class updates into the dominant cost of the preferred-
    affinity suite on the CPU backend (measured 19s of a 20s window).  On
    CPU the native ``.at[].add`` scatter is an O(N) loop; on TPU the einsum
    form wins (minor-axis scatters lower to serial dynamic-slices)."""
    import jax

    if jax.default_backend() != "cpu":
        return domain_scatter_add(vals, dom, depth)
    shape = vals.shape
    v = vals.astype(jnp.float32).reshape(-1, shape[-1])  # [M, N]
    d = jnp.broadcast_to(dom, shape).reshape(-1, shape[-1])
    rows = jnp.arange(v.shape[0])[:, None]
    out = jnp.zeros((v.shape[0], depth), jnp.float32).at[rows, d].add(v)
    return out.reshape(shape[:-1] + (depth,))


def domain_gather_backend(table, dom):
    """domain_gather with a backend-aware lowering: on the CPU backend the
    one-hot materialization ([..., N, D] f32) dominates the lookup it
    implements (XLA CPU does not fuse it away — measured 134MB/cycle for the
    [G, N] affinity-group expansion at 2k nodes), and plain
    ``take_along_axis`` vector-gathers are fast there; on TPU the einsum
    form wins (minor-axis gathers lower to serial loops).  The backend is a
    trace-time constant, so each lowering compiles its own clean program."""
    import jax

    if jax.default_backend() == "cpu":
        idx = jnp.clip(dom, 0, table.shape[-1] - 1).astype(jnp.int32)
        return jnp.take_along_axis(table.astype(jnp.float32), idx, axis=-1)
    return domain_gather(table, dom)


def domain_any(mask, dom, depth: int):
    """``out[..., d] = any_n(mask[..., n] & dom[..., n] == d)`` — bool[..., D]."""
    return domain_scatter_add(mask, dom, depth) > 0.5


def point_scatter_add(table, dom_at, inc):
    """``table[..., dom_at[...]] += inc[...]`` for scalar-per-row indices.

    table: [..., D]; dom_at: int[...]; inc: [...] — the in-scan table bump
    (one placed pod touches one domain per constraint row).
    """
    oh = domain_onehot(dom_at[..., None], table.shape[-1])[..., 0, :]
    return table + (inc[..., None] * oh).astype(table.dtype)
