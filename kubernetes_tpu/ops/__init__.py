"""Device kernels for the scheduling framework's hot ops.

TPU-first design note: XLA lowers per-element gathers/scatters along the
minor axis to serial dynamic-slice loops on TPU — a [B, C, N] domain lookup
measured ~100 ms at 1024 nodes.  Every domain-table op here is instead a
one-hot einsum contraction (the MXU path, microbenchmarked in
tests/test_ops.py), the tensor form of the reference's per-(topologyKey,
value) count maps (pkg/scheduler/framework/plugins/podtopologyspread/
filtering.go:256-289, interpodaffinity/filtering.go:44-55).
"""

from .segment import (
    domain_any,
    domain_gather,
    domain_onehot,
    domain_scatter_add,
    point_scatter_add,
)

__all__ = [
    "domain_any",
    "domain_gather",
    "domain_onehot",
    "domain_scatter_add",
    "point_scatter_add",
]
