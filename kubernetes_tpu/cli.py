"""kubectl-style CLI verbs over the object store (reference L7:
staging/src/k8s.io/kubectl).

In-process client: ``Kubectl(store)`` exposes the core verb set (get, describe,
apply -f, delete, scale, cordon/uncordon, taint, drain) against the sim control
plane; ``main()`` wires argparse for shell use against a state file.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional

from .api import objects as v1
from .sim.store import ObjectStore

KIND_ALIASES = {
    "po": "Pod", "pod": "Pod", "pods": "Pod",
    "no": "Node", "node": "Node", "nodes": "Node",
    "rs": "ReplicaSet", "replicaset": "ReplicaSet", "replicasets": "ReplicaSet",
    "deploy": "Deployment", "deployment": "Deployment", "deployments": "Deployment",
    "job": "Job", "jobs": "Job",
    "svc": "Service", "service": "Service", "services": "Service",
    "pv": "PersistentVolume", "pvc": "PersistentVolumeClaim",
    "sc": "StorageClass", "pdb": "PodDisruptionBudget",
    "pc": "PriorityClass", "priorityclass": "PriorityClass",
    "pg": "PodGroup", "podgroup": "PodGroup", "podgroups": "PodGroup",
    "ng": "NodeGroup", "nodegroup": "NodeGroup", "nodegroups": "NodeGroup",
    "ev": "Event", "events": "Event",
    "resourceclaim": "ResourceClaim", "resourceclaims": "ResourceClaim",
    "deviceclass": "DeviceClass", "deviceclasses": "DeviceClass",
    "resourceslice": "ResourceSlice", "resourceslices": "ResourceSlice",
}

from .api.scheme import SchemeError, default_scheme

_scheme_cache = []


def _scheme():
    """Built lazily: default_scheme() pulls in the controllers package (for
    the HPA type), which apply() needs but get/delete/scale never do."""
    if not _scheme_cache:
        _scheme_cache.append(default_scheme())
    return _scheme_cache[0]


def _render_table(rows: List[List[str]]) -> str:
    """Column-aligned table (header first) — the one place that owns the
    width/ljust/join formatting for get/get_slices/autoscaler_status."""
    widths = [max(len(r[c]) for r in rows) for c in range(len(rows[0]))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(r, widths))
        for r in rows
    )


class Kubectl:
    def __init__(self, store: ObjectStore):
        self.store = store

    # --- dynamic kinds --------------------------------------------------------

    def resolve_kind(self, kind: str) -> str:
        """Alias table first; an unknown name then tries DYNAMIC discovery
        (kubectl's RESTMapper refresh on a no-match): fetch the stored
        CustomResourceDefinitions — over HTTP when the store is the facade
        — and match plural/singular/kind, minting the served type into the
        client-side scheme(s) so list/get decode the custom resources."""
        k = KIND_ALIASES.get(kind.lower(), kind)
        if k != kind or kind in _scheme().kind_types():
            return k
        want = kind.lower()
        try:
            crds, _ = self.store.list("CustomResourceDefinition")
        except Exception as e:
            # store/server without the apiextensions surface: the unknown
            # name falls through to the normal unknown-kind error path
            from .utils import klog

            klog.V(1).info_s("CRD discovery unavailable",
                             kind=kind, err=str(e))
            return k
        for crd in crds:
            names = crd.names
            if want in (names.plural.lower(), names.singular.lower(),
                        names.kind.lower()):
                self._register_dynamic(crd)
                return names.kind
        return k

    def _register_dynamic(self, crd) -> None:
        from .apiextensions.api import CLUSTER_SCOPE, make_kind_type

        typ = make_kind_type(crd)
        schemes = [_scheme()]
        client = getattr(self.store, "_client", None)
        if getattr(client, "scheme", None) is not None \
                and client.scheme is not _scheme():
            schemes.append(client.scheme)
        for s in schemes:
            if crd.names.kind not in s.kind_types():
                s.add_known_type(crd.group, crd.storage_version, typ)
        if crd.scope == CLUSTER_SCOPE:
            # in-place: the store facade aliases the same scoping set
            self.store.CLUSTER_SCOPED.add(crd.names.kind)

    # --- auth -----------------------------------------------------------------

    def can_i(self, verb: str, resource: str, user: str,
              namespace: str = "", name: str = "",
              groups: tuple = ()) -> str:
        """``kubectl auth can-i``: evaluate the stored RBAC policy for an
        arbitrary subject.  Runs the SAME RBACAuthorizer the apiserver
        enforces with, over this client's store view (HTTP or local)."""
        from .auth.rbac import RBACAuthorizer

        allowed = RBACAuthorizer(self.store).authorize(
            user, verb, resource, namespace, name=name, groups=groups)
        return "yes" if allowed else "no"

    # --- get / describe -------------------------------------------------------

    def get(self, kind: str, namespace: Optional[str] = None) -> str:
        if kind.lower() in ("slice", "slices"):
            return self.get_slices()
        kind = self.resolve_kind(kind)
        objs, _ = self.store.list(kind)
        if namespace:
            objs = [o for o in objs if getattr(o.metadata, "namespace", "") == namespace]
        # one Node scan shared by every NodeGroup row's SIZE column (a
        # per-row list would be G full scans on a 5k-node cluster)
        nodes = self.store.list("Node")[0] if kind == "NodeGroup" else None
        rows = [self._row(kind, o, nodes)
                for o in sorted(objs, key=lambda o: o.metadata.name)]
        return _render_table([self._header(kind)] + rows)

    def _header(self, kind: str) -> List[str]:
        entry = _scheme().kind_types().get(kind)
        if entry is not None and getattr(entry[2], "_custom_resource", False):
            return ["NAME", "AGE"]
        return {
            "Pod": ["NAME", "STATUS", "NODE", "PRIORITY"],
            "Node": ["NAME", "READY", "ZONE", "TAINTS", "CPU", "MEMORY"],
            "ReplicaSet": ["NAME", "DESIRED", "CURRENT", "READY"],
            "Deployment": ["NAME", "REPLICAS"],
            "Job": ["NAME", "COMPLETIONS", "SUCCEEDED", "DONE"],
            "PodGroup": ["NAME", "MIN-MEMBER", "PHASE", "TIMEOUT"],
            "NodeGroup": ["NAME", "SIZE", "MIN", "MAX", "TEMPLATE"],
            "ResourceClaim": ["NAME", "STATE", "NODE", "ALLOCATED-DEVICE"],
            "DeviceClass": ["NAME", "SELECTORS"],
            "ResourceSlice": ["NAME", "NODE", "POOL", "DEVICES"],
        }.get(kind, ["NAME"])

    def _row(self, kind: str, o, nodes: Optional[List[v1.Node]] = None) -> List[str]:
        if kind == "Pod":
            return [o.metadata.name, o.status.phase, o.spec.node_name or "<none>",
                    str(o.spec.priority)]
        if kind == "Node":
            ready = next(
                (c.get("status", "?") for c in o.status.conditions
                 if c.get("type") == "Ready"), "?",
            )
            from .controllers.nodelifecycle import ZONE_LABEL

            zone = o.metadata.labels.get(ZONE_LABEL, "<none>")
            return [o.metadata.name, ready, zone,
                    ",".join(f"{t.key}:{t.effect}" for t in o.spec.taints) or "<none>",
                    str(o.status.allocatable.get("cpu", "?")),
                    str(o.status.allocatable.get("memory", "?"))]
        if kind == "ReplicaSet":
            return [o.metadata.name, str(o.replicas), str(o.status_replicas),
                    str(o.status_ready_replicas)]
        if kind == "Deployment":
            return [o.metadata.name, str(o.replicas)]
        if kind == "Job":
            return [o.metadata.name, str(o.completions), str(o.status_succeeded),
                    str(o.completed)]
        if kind == "PodGroup":
            timeout = o.schedule_timeout_seconds
            return [o.metadata.name, str(o.min_member), o.phase,
                    f"{timeout}s" if timeout is not None else "<default>"]
        if kind == "NodeGroup":
            from .autoscaler import member_nodes

            size = len(member_nodes(o, nodes or []))
            tmpl = ",".join(f"{k}={v}" for k, v in sorted(o.capacity.items()))
            if o.slice_size:
                tmpl += f",slice={o.slice_size}"
            return [o.metadata.name, str(size), str(o.min_size),
                    str(o.max_size), tmpl or "<none>"]
        if kind == "ResourceClaim":
            return [o.metadata.name, o.state, o.allocated_node or "<none>",
                    ",".join(o.allocated_devices) or "<none>"]
        if kind == "DeviceClass":
            sel = ",".join(f"{k}={v}" for k, v in sorted(o.selectors.items()))
            return [o.metadata.name, sel or "<none>"]
        if kind == "ResourceSlice":
            return [o.metadata.name, o.node_name or "<none>", o.pool or "<none>",
                    str(len(o.devices))]
        if getattr(o, "_custom_resource", False):
            import time as _time

            age = max(0, int(_time.time() - o.metadata.creation_timestamp))
            return [o.metadata.name,
                    f"{age // 3600}h{(age % 3600) // 60:02d}m" if age >= 3600
                    else f"{age // 60}m{age % 60:02d}s" if age >= 60
                    else f"{age}s"]
        return [o.metadata.name]

    def describe(self, kind: str, namespace: str, name: str) -> str:
        kind = self.resolve_kind(kind)
        o = self.store.get(kind, namespace, name)
        if o is None:
            return f"{kind} {namespace}/{name} not found"
        import dataclasses, json

        if not dataclasses.is_dataclass(o):  # custom resources: wire manifest
            from .api.serialize import to_manifest

            return json.dumps(to_manifest(o, _scheme()), default=str, indent=2)
        return json.dumps(dataclasses.asdict(o), default=str, indent=2)

    # --- apply / delete / scale ----------------------------------------------

    def apply(self, yaml_text: str) -> List[str]:
        try:
            import yaml as _yaml

            docs = list(_yaml.safe_load_all(yaml_text))
        except ImportError:
            import json

            docs = [json.loads(yaml_text)]
        out = []
        for doc in docs:
            if not doc:
                continue
            kind = doc.get("kind")
            try:
                obj = _scheme().decode(doc)
            except SchemeError as e:
                out.append(f"error: {e}")
                continue
            ns = getattr(obj.metadata, "namespace", "")
            if self.store.get(kind, ns, obj.metadata.name) is not None:
                self.store.update(kind, obj)
                out.append(f"{kind.lower()}/{obj.metadata.name} configured")
            else:
                self.store.create(kind, obj)
                out.append(f"{kind.lower()}/{obj.metadata.name} created")
        return out

    def delete(self, kind: str, namespace: str, name: str) -> str:
        kind = self.resolve_kind(kind)
        obj = self.store.delete(kind, namespace, name)
        return (
            f"{kind.lower()}/{name} deleted" if obj is not None
            else f"{kind} {namespace}/{name} not found"
        )

    def scale(self, kind: str, namespace: str, name: str, replicas: int) -> str:
        kind = KIND_ALIASES.get(kind.lower(), kind)
        o = self.store.get(kind, namespace, name)
        if o is None or not hasattr(o, "replicas"):
            return f"cannot scale {kind} {namespace}/{name}"
        o.replicas = replicas
        self.store.update(kind, o)
        return f"{kind.lower()}/{name} scaled to {replicas}"

    def get_json(self, kind: str, namespace: str, name: str) -> str:
        """``get -o json``: the object's wire manifest."""
        from .api.serialize import to_manifest
        import json

        kind = self.resolve_kind(kind)
        o = self.store.get(kind, namespace, name)
        if o is None:
            return f"{kind} {namespace}/{name} not found"
        return json.dumps(to_manifest(o, _scheme()), indent=2)

    def label(self, kind: str, namespace: str, name: str,
              key: str, value: Optional[str]) -> str:
        """``kubectl label``: value None (key-) removes."""
        kind = KIND_ALIASES.get(kind.lower(), kind)
        o = self.store.get(kind, namespace, name)
        if o is None:
            return f"{kind} {namespace}/{name} not found"
        labels = dict(o.metadata.labels or {})
        if value is None:
            labels.pop(key, None)
        else:
            labels[key] = value
        o.metadata.labels = labels
        self.store.update(kind, o)
        return f"{kind.lower()}/{name} labeled"

    def annotate(self, kind: str, namespace: str, name: str,
                 key: str, value: Optional[str]) -> str:
        kind = KIND_ALIASES.get(kind.lower(), kind)
        o = self.store.get(kind, namespace, name)
        if o is None:
            return f"{kind} {namespace}/{name} not found"
        ann = dict(getattr(o.metadata, "annotations", {}) or {})
        if value is None:
            ann.pop(key, None)
        else:
            ann[key] = value
        o.metadata.annotations = ann
        self.store.update(kind, o)
        return f"{kind.lower()}/{name} annotated"

    def patch(self, kind: str, namespace: str, name: str,
              patch_json: str) -> str:
        """``kubectl patch --type=merge``: RFC 7386 merge against the
        manifest, decoded back through the scheme."""
        import json

        from .api.serialize import to_manifest
        from .apiserver.server import _merge

        kind = KIND_ALIASES.get(kind.lower(), kind)
        cur = self.store.get(kind, namespace, name)
        if cur is None:
            return f"{kind} {namespace}/{name} not found"
        merged = _merge(to_manifest(cur, _scheme()), json.loads(patch_json))
        try:
            obj = _scheme().decode(merged)
        except SchemeError as e:
            return f"error: {e}"
        obj.metadata.uid = cur.metadata.uid
        self.store.update(kind, obj)
        return f"{kind.lower()}/{name} patched"

    def rollout_status(self, kind: str, namespace: str, name: str) -> str:
        """``kubectl rollout status`` for Deployments/ReplicaSets: ready vs
        desired (kubectl/pkg/polymorphichelpers/rollout_status.go shape)."""
        kind = KIND_ALIASES.get(kind.lower(), kind)
        o = self.store.get(kind, namespace, name)
        if o is None:
            return f"{kind} {namespace}/{name} not found"
        desired = getattr(o, "replicas", None)
        if desired is None:
            return f"cannot get rollout status for {kind}"
        if kind == "Deployment":
            # ready = the CURRENT-template ReplicaSet's ready count (the
            # reference's updatedReplicas view): owner kind+name checked,
            # and only the RS named for the deployment's template hash —
            # an old RS's still-ready pods must not report a rollout done
            from .controllers.deployment import _template_hash

            current_rs = f"{name}-{_template_hash(o.template)}"
            ready = sum(
                rs.status_ready_replicas
                for rs in self.store.list("ReplicaSet")[0]
                if rs.metadata.namespace == namespace
                and rs.metadata.name == current_rs
                and any(ref.kind == "Deployment" and ref.name == name
                        for ref in (rs.metadata.owner_references or []))
            )
        else:
            ready = getattr(o, "status_ready_replicas", 0)
        if ready >= desired:
            return (f'{kind.lower()} "{name}" successfully rolled out '
                    f"({ready}/{desired} updated replicas are available)")
        return (f"Waiting for rollout to finish: {ready} of {desired} "
                f"updated replicas are available...")

    # --- node ops -------------------------------------------------------------

    def cordon(self, name: str, on: bool = True) -> str:
        node = self.store.get("Node", "", name)
        if node is None:
            return f"node {name} not found"
        node.spec.unschedulable = on
        self.store.update("Node", node)
        return f"node/{name} {'cordoned' if on else 'uncordoned'}"

    def taint(self, name: str, key: str, value: str = "",
              effect: str = v1.TAINT_NO_SCHEDULE, remove: bool = False) -> str:
        node = self.store.get("Node", "", name)
        if node is None:
            return f"node {name} not found"
        node.spec.taints = [t for t in node.spec.taints if t.key != key]
        if not remove:
            node.spec.taints.append(v1.Taint(key=key, value=value, effect=effect))
        self.store.update("Node", node)
        return f"node/{name} tainted"

    def drain(self, name: str, dry_run: bool = False) -> str:
        """``kubectl drain``: cordon + evict every pod through the shared
        eviction gate (descheduler/evictions.py) — PDB-refused pods stay
        put and are reported, never force-deleted.  ``--dry-run`` evaluates
        the gate without cordoning or deleting anything."""
        from .descheduler.evictions import EvictionAPI

        node = self.store.get("Node", "", name)
        if node is None:
            return f"node {name} not found"
        if not dry_run:
            self.cordon(name, True)
        gate = EvictionAPI(self.store)
        pods, _ = self.store.list("Pod")
        n = 0
        blocked: List[str] = []
        failed: List[str] = []
        # --server mode: the store is an HTTP facade — route REAL evictions
        # through the server's eviction subresource so the PDB gate runs
        # under the SERVER's budget lock (a client-local check-then-delete
        # would race every other server-side eviction path); dry-run stays
        # a read-only client-side preview either way
        evict_remote = (getattr(self.store, "evict_pod", None)
                        if not dry_run else None)
        for p in pods:
            if p.spec.node_name != name:
                continue
            if evict_remote is not None:
                import urllib.error

                try:
                    evict_remote(p.namespace, p.metadata.name)
                    n += 1
                except urllib.error.HTTPError as e:
                    if e.code == 429:
                        blocked.append(f"{p.namespace}/{p.metadata.name} "
                                       f"(disruption budget)")
                    elif e.code != 404:  # already gone is not a failure
                        failed.append(f"{p.namespace}/{p.metadata.name} "
                                      f"(HTTP {e.code})")
                continue
            r = gate.evict(p, reason=f"drain node {name}", policy="drain",
                           dry_run=dry_run)
            if r.evicted or (dry_run and r.allowed):
                n += 1
            elif not r.allowed:
                blocked.append(f"{p.namespace}/{p.metadata.name} "
                               f"(pdb {r.blocking_pdb})")
            else:
                # allowed but not evicted: store fault (or already gone) —
                # never report it as drained
                failed.append(f"{p.namespace}/{p.metadata.name} "
                              f"({r.reason})")
        verb = "would evict" if dry_run else "evicted"
        out = f"node/{name} drained ({n} pods {verb})"
        if blocked:
            out += "; blocked by disruption budget: " + ", ".join(blocked)
        if failed:
            out += "; failed: " + ", ".join(failed)
        return out

    # --- autoscaler status ----------------------------------------------------

    def autoscaler_status(self, controller=None) -> str:
        """``ktpu autoscaler status``: per-group size vs bounds, current
        unschedulable demand, and (when an in-process controller is
        given) its last sync's scale decisions."""
        from .autoscaler import member_nodes
        from .gang import POD_GROUP_LABEL

        groups, _ = self.store.list("NodeGroup")
        nodes, _ = self.store.list("Node")
        pods, _ = self.store.list("Pod")
        unbound = [p for p in pods if not p.spec.node_name]
        gang_unbound = sum(
            1 for p in unbound if POD_GROUP_LABEL in p.metadata.labels)
        rows = [["GROUP", "SIZE", "MIN", "MAX", "HEADROOM"]]
        for g in sorted(groups, key=lambda g: g.metadata.name):
            size = len(member_nodes(g, nodes))
            rows.append([g.metadata.name, str(size), str(g.min_size),
                         str(g.max_size), str(max(g.max_size - size, 0))])
        out = _render_table(rows)
        out += (f"\npending: {len(unbound)} unbound pods "
                f"({gang_unbound} gang members)")
        if controller is not None and controller.last_decisions:
            out += "\nlast sync:"
            for d in controller.last_decisions:
                out += (f"\n  {d.direction} {d.group or '-'} "
                        f"{d.result} ({d.note})")
        if controller is not None:
            out += "\n" + self._shard_topology_line(
                getattr(controller, "scheduler", None))
        return out

    # --- device / shard topology ----------------------------------------------

    def _shard_topology_line(self, scheduler=None) -> str:
        """One-line shard summary shared by autoscaler status + topology."""
        mesh = getattr(scheduler, "mesh", None)
        if mesh is None:
            return "node-axis sharding: off"
        enc = scheduler.encoder
        n_dev = int(mesh.devices.size)
        axis = ",".join(mesh.axis_names)
        return (f"node-axis sharding: on — {n_dev} devices over axis "
                f"'{axis}', node tier {enc._n} rows "
                f"({enc._n // n_dev}/shard)")

    def topology(self, scheduler=None) -> str:
        """``ktpu topology``: the device mesh view — backend devices, the
        node-axis shard spec in effect, and node-tier rows per shard (what
        the production-scale path actually partitions)."""
        import jax

        rows = [["DEVICE", "PLATFORM", "PROCESS"]]
        for d in jax.devices():
            rows.append([str(d.id), d.platform,
                         str(getattr(d, "process_index", 0))])
        out = _render_table(rows)
        nodes, _ = self.store.list("Node")
        out += f"\n{len(nodes)} Node objects"
        out += "\n" + self._shard_topology_line(scheduler)
        if scheduler is None:
            out += (" (no in-process scheduler: pass one for the live "
                    "mesh/tier view; KubeSchedulerConfiguration "
                    "nodeAxisSharding selects the policy)")
        return out

    # --- readiness view -------------------------------------------------------

    def readyz_status(self, readyz=None) -> str:
        """``ktpu readyz``: the scheduler replica's readiness, with
        per-component cold-start rebuild progress while a reconstruction is
        in flight (component_base.healthz.Readyz — the same source the
        apiserver's /readyz serves).  Without an in-process Readyz there is
        nothing rebuilding: ready."""
        if readyz is None:
            return "ok"
        ok, comps = readyz.check()
        rows = [["COMPONENT", "PROGRESS", "READY"]]
        for name in sorted(comps):
            done, total = comps[name]
            rows.append([name, f"{done}/{total}",
                         "true" if done >= total else "false"])
        out = _render_table(rows) if len(rows) > 1 else ""
        head = "ok" if ok else "NotReady"
        return f"{head}\n{out}" if out else head

    # --- node lifecycle / partition-tolerance view ------------------------------

    def nodehealth(self, controller=None, metrics=None) -> str:
        """``ktpu nodehealth``: per-zone disruption state, Ready/NotReady
        counts, and eviction-queue depth, plus the pending
        tolerationSeconds countdowns and the lifecycle eviction totals.

        Reads the live ``NodeLifecycleController`` when given (in-process
        wiring); otherwise the ``node_lifecycle_*`` metric series —
        ``metrics`` accepts a pre-parsed {(name, labels): value} dict (the
        --server path feeds /metrics through ``metrics.registry.parse_text``),
        else the in-process default registry serves.  Node counts always
        come from the store's Node objects (READY is the condition the
        lifecycle controller maintains)."""
        from .api.objects import node_is_ready
        from .controllers.nodelifecycle import ZONE_LABEL, ZONE_STATE_CODE

        code_name = {v: k for k, v in ZONE_STATE_CODE.items()}
        nodes, _ = self.store.list("Node")
        counts: Dict[str, List[int]] = {}
        for n in nodes:
            zone = n.metadata.labels.get(ZONE_LABEL, "")
            c = counts.setdefault(zone, [0, 0])
            c[0 if node_is_ready(n) else 1] += 1
        if metrics is None and controller is None:
            from .metrics.registry import default_registry, parse_text, render_text

            metrics = parse_text(render_text(default_registry))
        zones = set(counts)
        if controller is not None:
            zones |= set(controller.zones)
            pending = len(controller.taint_manager)
        else:
            zones |= {lab[0] for (name, lab) in metrics
                      if name == "node_lifecycle_zone_state" and lab}
            pending = None
        rows = [["ZONE", "STATE", "READY", "NOTREADY", "EVICTION-QUEUE"]]
        for zone in sorted(zones):
            ready, not_ready = counts.get(zone, [0, 0])
            if controller is not None:
                state = controller.zone_mode(zone)
                z = controller.zones.get(zone)
                depth = len(z.queue) if z is not None else 0
            else:
                # the unlabeled zone ("") loses its label value in the
                # render_text→parse_text round trip (label="" parses to
                # the empty tuple) — look both keys up so --server output
                # agrees with the live-controller view
                keys = [(zone,)] + ([()] if zone == "" else [])

                def series(name, keys=keys):
                    return next((metrics[(name, k)] for k in keys
                                 if (name, k) in metrics), 0)

                state = code_name.get(
                    int(series("node_lifecycle_zone_state")), "Normal")
                depth = int(series("node_lifecycle_eviction_queue_depth"))
            rows.append([zone or "<none>", state, str(ready),
                         str(not_ready), str(depth)])
        out = _render_table(rows)
        if pending is not None:
            out += f"\npending tolerationSeconds countdowns: {pending}"
        if controller is None:
            totals = {lab: v for (name, lab), v in metrics.items()
                      if name == "node_lifecycle_evictions_total" and lab}
        else:
            from .metrics import scheduler_metrics as m

            totals = m.node_lifecycle_evictions.items()
        for lab in sorted(totals):
            out += (f"\nevictions {lab[0]}/{lab[1]}: "
                    f"{totals[lab]:g}")
        return out

    # --- span-trace / SLO observatory ------------------------------------------

    def trace_dump(self, exporter=None, last: int = 8,
                   max_pods_per_tree: int = 12) -> str:
        """``ktpu trace``: the last N attempt span trees from an in-process
        ``InMemoryExporter`` (the scheduler tracer's ring), each rendered
        with per-span offsets/durations plus the per-pod phase records the
        attempt root carries.  Spans are in-memory only — there is no
        --server form; wire the exporter in-process (the perf harness and
        tests do)."""
        if exporter is None:
            return ("no in-process span exporter wired: construct the "
                    "scheduler with tracer=Tracer(exporters="
                    "[InMemoryExporter()]) and pass that exporter here")
        from .component_base.trace import render_tree

        trees = exporter.trees(last=last, root_name="attempt")
        if not trees:
            return "no attempt spans recorded"
        out: List[str] = []
        for root, children in trees:
            # trees() already built the children index once for the whole
            # ring — reuse it instead of re-deriving per root
            out.append(render_tree(root, children=children))
            recs = root.attrs.get("pod_phases") or []
            for r in recs[:max_pods_per_tree]:
                out.append(
                    f"    pod {r['pod']}: dispatch {r['dispatch'] * 1e3:.1f}ms"
                    f" device {r['device'] * 1e3:.1f}ms"
                    f" bind {r['bind'] * 1e3:.1f}ms"
                    f" total {r['total'] * 1e3:.1f}ms ({r['outcome']})")
            if len(recs) > max_pods_per_tree:
                out.append(f"    … {len(recs) - max_pods_per_tree} more pods")
        return "\n".join(out)

    _ATTEMPT_HIST = "scheduler_scheduling_attempt_duration_seconds"
    _PHASE_HIST = "scheduler_attempt_phase_duration_seconds"

    def slo(self, metrics=None) -> str:
        """``ktpu slo``: current p50/p90/p99 per attempt phase from the
        live ``scheduler_attempt_phase_duration_seconds`` histograms, or —
        with ``metrics`` (the --server path: /metrics fed through
        ``registry.parse_text``) — recomputed from the bucket exposition.
        The footer compares the sum of the attempt-tiling phase p50s
        (dispatch+device+bind) against the end-to-end attempt p50: a gap
        means unattributed wall-clock."""
        rows = [["PHASE", "P50-MS", "P90-MS", "P99-MS", "COUNT"]]
        p50 = {}
        attempt_p50 = attempt_n = 0.0
        if metrics is None:
            from .metrics import scheduler_metrics as m

            h = m.attempt_phase_duration
            for labels in sorted(h._counts):
                phase = labels[0] if labels else "?"
                p50[phase] = h.quantile(0.50, labels)
                rows.append([phase, f"{p50[phase] * 1e3:.3f}",
                             f"{h.quantile(0.90, labels) * 1e3:.3f}",
                             f"{h.quantile(0.99, labels) * 1e3:.3f}",
                             str(h.count(labels))])
            ah = m.scheduling_attempt_duration
            attempt_p50, attempt_n = ah.quantile(0.50), ah.count()
        else:
            from .metrics.registry import (bucket_counts_from_series,
                                           quantile_from_counts)

            per = bucket_counts_from_series(metrics, self._PHASE_HIST)
            for labels in sorted(per):
                uppers, counts = per[labels]
                phase = labels[0] if labels else "?"
                p50[phase] = quantile_from_counts(uppers, counts, 0.50)
                rows.append([phase, f"{p50[phase] * 1e3:.3f}",
                             f"{quantile_from_counts(uppers, counts, 0.90) * 1e3:.3f}",
                             f"{quantile_from_counts(uppers, counts, 0.99) * 1e3:.3f}",
                             str(sum(counts))])
            att = bucket_counts_from_series(metrics, self._ATTEMPT_HIST)
            if () in att:
                uppers, counts = att[()]
                attempt_p50 = quantile_from_counts(uppers, counts, 0.50)
                attempt_n = sum(counts)
        if len(rows) == 1:
            return "no attempt-phase observations recorded"
        out = _render_table(rows)
        tiling = sum(p50.get(k, 0.0) for k in ("dispatch", "device", "bind"))
        out += (f"\nattempt p50: {attempt_p50 * 1e3:.3f}ms over "
                f"{attempt_n:g} attempts; "
                f"sum of tiling-phase p50s: {tiling * 1e3:.3f}ms")
        if attempt_p50 > 0:
            out += f" (coverage {tiling / attempt_p50:.2f}x)"
        return out

    # --- control-plane durability / flow-control view --------------------------

    def controlplane_status(self, wal=None, watch_cache=None, flow=None,
                            metrics=None, replication=None) -> str:
        """``ktpu controlplane status``: the durable-control-plane gauges —
        WAL size/records/last-fsync-rv (how much survives kill -9), watch
        cache ring occupancy/oldest-rv (what a watcher can resume from
        without a relist), the flow-control inflight/rejected counts
        (who is being shed, and why), and the replication block: each
        replica's role, applied_rv/leader_rv/lag watermark, and
        ship-stream health (``replication`` accepts a list of
        sim/replication.FollowerReplica for the live path).

        Reads live objects when given (in-process wiring); otherwise the
        metric series they emit — ``metrics`` accepts a pre-parsed
        {(name, labels): value} dict (the --server path feeds /metrics
        through ``metrics.registry.parse_text``), else the in-process
        default registry serves."""
        if metrics is None:
            from .metrics.registry import default_registry, parse_text, render_text

            metrics = parse_text(render_text(default_registry))

        def series(name, label=None):
            return metrics.get((name, (label,) if label else ()), 0.0)

        rows = [["COMPONENT", "FIELD", "VALUE"]]
        if wal is not None:
            rows.append(["wal", "size-bytes", str(wal.size_bytes)])
            rows.append(["wal", "records", str(wal.records_appended)])
            rows.append(["wal", "last-fsync-rv", str(wal.last_fsync_rv)])
        else:
            rows.append(["wal", "size-bytes",
                         f"{series('wal_size_bytes'):g}"])
            total = sum(v for (n, _), v in metrics.items()
                        if n == "wal_records_total")
            rows.append(["wal", "records", f"{total:g}"])
            rows.append(["wal", "last-fsync-rv",
                         f"{series('wal_last_fsync_rv'):g}"])
        if watch_cache is not None:
            rows.append(["watch-cache", "ring-occupancy",
                         str(watch_cache.ring_occupancy)])
            rows.append(["watch-cache", "oldest-rv",
                         str(watch_cache.oldest_rv)])
            rows.append(["watch-cache", "current-rv",
                         str(watch_cache.current_rv())])
        else:
            rows.append(["watch-cache", "ring-occupancy",
                         f"{series('watch_cache_ring_occupancy'):g}"])
            rows.append(["watch-cache", "oldest-rv",
                         f"{series('watch_cache_oldest_rv'):g}"])
        for kind in ("mutating", "readonly"):
            if flow is not None:
                gate = getattr(flow, kind)
                rows.append([f"flow-{kind}", "inflight",
                             str(gate.inflight())])
                rows.append([f"flow-{kind}", "queued", str(gate.queued())])
            else:
                rows.append([f"flow-{kind}", "inflight",
                             f"{series('apiserver_inflight_requests', kind):g}"])
        rejected = {lab[0]: v for (n, lab), v in metrics.items()
                    if n == "apiserver_rejected_requests_total" and lab}
        for reason in sorted(rejected):
            rows.append(["flow-rejected", reason, f"{rejected[reason]:g}"])
        if not rejected:
            rows.append(["flow-rejected", "total", "0"])
        # --- replication block: per-replica role + watermark + ship health
        if replication is not None:
            for rep in replication:
                rows.append([f"replica-{rep.name}", "role", rep.role])
                rows.append([f"replica-{rep.name}", "applied-rv",
                             str(rep.applied_rv())])
                rows.append([f"replica-{rep.name}", "leader-rv",
                             str(rep.leader_rv())])
                rows.append([f"replica-{rep.name}", "lag-rv",
                             str(rep.lag_rv())])
                rows.append([f"replica-{rep.name}", "ship-errors",
                             str(rep.ship_errors)])
        else:
            # metrics fallback: applied/lag are per-replica gauges, role is
            # the (replica, role)=1 series, ship errors count per reason
            applied = {lab[0]: v for (n, lab), v in metrics.items()
                       if n == "replication_applied_rv" and lab}
            lag = {lab[0]: v for (n, lab), v in metrics.items()
                   if n == "replication_lag_rv" and lab}
            roles = {lab[0]: lab[1] for (n, lab), v in metrics.items()
                     if n == "apiserver_role" and len(lab) == 2 and v >= 1}
            for name in sorted(set(applied) | set(roles)):
                rows.append([f"replica-{name}", "role",
                             roles.get(name, "unknown")])
                rows.append([f"replica-{name}", "applied-rv",
                             f"{applied.get(name, 0.0):g}"])
                rows.append([f"replica-{name}", "lag-rv",
                             f"{lag.get(name, 0.0):g}"])
        ship_err = {lab[0]: v for (n, lab), v in metrics.items()
                    if n == "replication_ship_errors_total" and lab}
        for reason in sorted(ship_err):
            rows.append(["ship-errors", reason, f"{ship_err[reason]:g}"])
        # --- wire block: per-codec negotiation counts + the encode-once
        # cache's hit rate (apiserver_wire_encode_total{codec,cached} —
        # hits are bytes served without a serialization; a healthy
        # thousand-watcher plane runs near 1.0)
        requests = {lab[0]: v for (n, lab), v in metrics.items()
                    if n == "apiserver_wire_requests_total" and lab}
        for codec in sorted(requests):
            rows.append(["wire", f"requests-{codec}",
                         f"{requests[codec]:g}"])
        if not requests:
            rows.append(["wire", "requests", "0"])
        encodes = {lab: v for (n, lab), v in metrics.items()
                   if n == "apiserver_wire_encode_total" and len(lab) == 2}
        hits = sum(v for lab, v in encodes.items() if lab[1] == "true")
        total = sum(encodes.values())
        rows.append(["wire", "encode-cache-hit-rate",
                     f"{hits / total:.3f}" if total else "n/a"])
        return _render_table(rows)

    # --- slice fragmentation view ---------------------------------------------

    def get_slices(self, slice_label: Optional[str] = None,
                   chip_resource: str = "google.com/tpu") -> str:
        """``ktpu get slices``: free-chips-per-slice — what the
        defragmenter sees.  FREE-CHIPS sums per-host free chips (the
        ``google.com/tpu`` extended resource when a host advertises it,
        whole CPUs otherwise); FRAGMENTATION is the share of those free
        chips stranded on partially-occupied hosts — the capacity a
        whole-slice gang cannot use until the descheduler compacts it."""
        from .api.resource import compute_pod_resource_request
        from .gang import SLICE_LABEL

        slice_label = slice_label or SLICE_LABEL
        nodes, _ = self.store.list("Node")
        pods, _ = self.store.list("Pod")
        used_by_node: Dict[str, float] = {}
        pods_by_node: Dict[str, int] = {}
        node_chip = {}
        for node in nodes:
            alloc = node.status.allocatable
            if chip_resource in alloc:
                node_chip[node.metadata.name] = ("ext", chip_resource)
            else:
                node_chip[node.metadata.name] = ("cpu", "cpu")
        for p in pods:
            nn = p.spec.node_name
            if not nn or nn not in node_chip:
                continue
            r = compute_pod_resource_request(p)
            kind_, res = node_chip[nn]
            chips = (float(r.scalar_resources.get(res, 0)) if kind_ == "ext"
                     else r.milli_cpu / 1000.0)
            used_by_node[nn] = used_by_node.get(nn, 0.0) + chips
            pods_by_node[nn] = pods_by_node.get(nn, 0) + 1
        slices: Dict[str, List[v1.Node]] = {}
        for node in nodes:
            val = node.metadata.labels.get(slice_label)
            if val is not None:
                slices.setdefault(val, []).append(node)
        from .api.resource import parse_quantity

        rows = [["NAME", "HOSTS", "FREE-HOSTS", "FREE-CHIPS",
                 "FRAGMENTATION"]]
        for name in sorted(slices):
            free_total = 0.0
            free_on_empty = 0.0
            empty_hosts = 0
            for node in slices[name]:
                kind_, res = node_chip[node.metadata.name]
                alloc = node.status.allocatable
                cap = (float(parse_quantity(alloc.get(res, 0)))
                       if kind_ == "ext"
                       else float(parse_quantity(alloc.get("cpu", 0))))
                free = max(cap - used_by_node.get(node.metadata.name, 0.0),
                           0.0)
                free_total += free
                if pods_by_node.get(node.metadata.name, 0) == 0:
                    empty_hosts += 1
                    free_on_empty += free
            frag = (1.0 - free_on_empty / free_total) if free_total > 0 \
                else 0.0
            rows.append([
                name, str(len(slices[name])), str(empty_hosts),
                f"{free_total:g}", f"{frag:.0%}",
            ])
        return _render_table(rows)


def main(argv=None):  # pragma: no cover - thin shell wrapper
    import argparse

    ap = argparse.ArgumentParser(prog="ktpu")
    ap.add_argument(
        "-s", "--server",
        help="apiserver URL (kubectl --server): verbs run over HTTP "
             "instead of an in-process store",
    )
    ap.add_argument("--user", default="",
                    help="identity sent as X-Remote-User (server mode)")
    ap.add_argument("--group", action="append", default=[],
                    help="group sent as X-Remote-Group (repeatable)")
    sub = ap.add_subparsers(dest="verb", required=True)
    g = sub.add_parser("get")
    g.add_argument("kind")
    g.add_argument("name", nargs="?")
    g.add_argument("-o", "--output", choices=["json"])
    g.add_argument("-n", "--namespace")
    a = sub.add_parser("apply")
    a.add_argument("-f", "--filename", required=True)
    for verb in ("label", "annotate"):
        p = sub.add_parser(verb)
        p.add_argument("kind"); p.add_argument("name")
        p.add_argument("kv", help="key=value, or key- to remove")
        # namespaced objects live under "default" unless told otherwise
        # (cluster-scoped kinds coerce the namespace to "" in the store)
        p.add_argument("-n", "--namespace", default="default")
    p = sub.add_parser("patch")
    p.add_argument("kind"); p.add_argument("name")
    p.add_argument("-p", "--patch", required=True)
    p.add_argument("-n", "--namespace", default="default")
    p = sub.add_parser("rollout")
    p.add_argument("action", choices=["status"])
    p.add_argument("kind"); p.add_argument("name")
    p.add_argument("-n", "--namespace", default="default")
    p = sub.add_parser("drain")
    p.add_argument("node")
    p.add_argument("--dry-run", action="store_true",
                   help="evaluate the eviction gate, evict nothing")
    p = sub.add_parser("autoscaler")
    p.add_argument("action", choices=["status"])
    p = sub.add_parser("controlplane")
    p.add_argument("action", choices=["status"])
    sub.add_parser("nodehealth")
    sub.add_parser("topology")
    sub.add_parser("readyz")
    p = sub.add_parser(
        "trace",
        help="dump attempt span trees (IN-PROCESS only: spans live in the "
             "scheduler process's InMemoryExporter — call "
             "Kubectl.trace_dump(exporter) there; the shell form prints "
             "the wiring hint; for remote quantiles use `slo --server`)")
    p.add_argument("-l", "--last", type=int, default=8,
                   help="how many attempt span trees to dump")
    sub.add_parser("slo")
    p = sub.add_parser("auth", help="kubectl auth can-i against stored RBAC")
    p.add_argument("action", choices=["can-i"])
    p.add_argument("can_verb", metavar="verb")
    p.add_argument("resource")
    p.add_argument("--as", dest="as_user", required=True,
                   help="subject to evaluate (kubectl --as)")
    p.add_argument("--as-group", dest="as_groups", action="append",
                   default=[], help="group membership (repeatable)")
    p.add_argument("-n", "--namespace", default="")
    p.add_argument("--name", default="",
                   help="resourceName-scoped check (e.g. a single object)")
    for verb in ("cordon", "uncordon"):
        p = sub.add_parser(verb)
        p.add_argument("node")
    args = ap.parse_args(argv)
    if args.server:
        from .apiserver import HTTPApiClient
        from .apiserver.client import HTTPStoreFacade

        store = HTTPStoreFacade(HTTPApiClient(
            args.server, user=args.user, groups=tuple(args.group)))
    else:
        store = ObjectStore()
    k = Kubectl(store)
    if args.verb == "get":
        if args.name and args.output == "json":
            print(k.get_json(args.kind, args.namespace or "default",
                             args.name))
        elif args.name:
            print(k.describe(args.kind, args.namespace or "default",
                             args.name))
        else:
            print(k.get(args.kind, args.namespace))
    elif args.verb == "apply":
        with open(args.filename) as f:
            for line in k.apply(f.read()):
                print(line)
    elif args.verb in ("label", "annotate"):
        if "=" not in args.kv and args.kv.endswith("-"):
            key, value = args.kv[:-1], None  # key- removes
        else:
            key, _, value = args.kv.partition("=")
        fn = k.label if args.verb == "label" else k.annotate
        print(fn(args.kind, args.namespace, args.name, key, value))
    elif args.verb == "patch":
        print(k.patch(args.kind, args.namespace, args.name, args.patch))
    elif args.verb == "rollout":
        print(k.rollout_status(args.kind, args.namespace, args.name))
    elif args.verb == "drain":
        print(k.drain(args.node, dry_run=args.dry_run))
    elif args.verb == "autoscaler":
        print(k.autoscaler_status())
    elif args.verb == "controlplane":
        if args.server:
            # the server process owns the WAL/cache/flow objects; its
            # /metrics exposition carries their series
            import urllib.request

            from .metrics.registry import parse_text

            with urllib.request.urlopen(f"{args.server}/metrics") as r:
                print(k.controlplane_status(
                    metrics=parse_text(r.read().decode())))
        else:
            print(k.controlplane_status())
    elif args.verb == "nodehealth":
        if args.server:
            # zone state / queue depth live in the serving process; its
            # /metrics exposition carries the node_lifecycle_* series
            import urllib.request

            from .metrics.registry import parse_text

            with urllib.request.urlopen(f"{args.server}/metrics") as r:
                print(k.nodehealth(metrics=parse_text(r.read().decode())))
        else:
            print(k.nodehealth())
    elif args.verb == "trace":
        print(k.trace_dump(last=args.last))
    elif args.verb == "slo":
        if args.server:
            # the scheduler process serving /metrics carries the
            # attempt-phase bucket exposition; quantiles recompute here
            import urllib.request

            from .metrics.registry import parse_text

            with urllib.request.urlopen(f"{args.server}/metrics") as r:
                print(k.slo(metrics=parse_text(r.read().decode())))
        else:
            print(k.slo())
    elif args.verb == "topology":
        print(k.topology())
    elif args.verb == "readyz":
        if args.server:
            # the apiserver's /readyz carries the wired Readyz's rendering
            import urllib.error
            import urllib.request

            try:
                with urllib.request.urlopen(f"{args.server}/readyz") as r:
                    print(r.read().decode())
            except urllib.error.HTTPError as e:  # 503 NotReady body
                print(e.read().decode())
        else:
            print(k.readyz_status())
    elif args.verb == "auth":
        print(k.can_i(args.can_verb, args.resource, args.as_user,
                      namespace=args.namespace, name=args.name,
                      groups=tuple(args.as_groups)))
    elif args.verb in ("cordon", "uncordon"):
        print(k.cordon(args.node, on=args.verb == "cordon"))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
