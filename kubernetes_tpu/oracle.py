"""Sequential host oracle: reference-exact plugin semantics in plain Python.

This is the parity baseline the batched device path is tested against
(SURVEY.md §4 testing lesson, §7 step 4).  Every function mirrors the cited
reference code with exact integer arithmetic (int64 semantics), one (pod, node)
at a time, using host NodeInfo state — the straight-line reimplementation of
what the Go scheduler computes with 16 goroutines.

Known, documented deviations of the DEVICE path vs this oracle (not bugs here):
  - resource unit quantization (KiB/MiB rounding, state/units.py)
  - host-port hostIP wildcard rules (device is conservative, encoding.py note)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Tuple

from .api import objects as v1
from .api.labels import (
    affinity_term_matches,
    match_label_selector,
    match_node_selector,
)
from .api.resource import (
    Resource,
    compute_pod_resource_request,
    compute_pod_resource_request_non_zero,
)
from .state.node_info import NodeInfo, PodInfo, _pod_host_ports, host_ports_conflict

MAX_NODE_SCORE = 100
UNSCHEDULABLE_TAINT = "node.kubernetes.io/unschedulable"
MIN_IMG = 23 * 1024 * 1024
MAX_IMG_PER_CONTAINER = 1000 * 1024 * 1024


@dataclass
class OracleConfig:
    """Default plugin set + weights (apis/config/v1beta3/default_plugins.go:32-51)."""

    fit_strategy: str = "LeastAllocated"
    fit_resources: Dict[str, int] = field(default_factory=lambda: {"cpu": 1, "memory": 1})
    hard_pod_affinity_weight: int = 1
    weights: Dict[str, int] = field(
        default_factory=lambda: {
            "TaintToleration": 3,
            "NodeAffinity": 2,
            "PodTopologySpread": 2,
            "InterPodAffinity": 2,
            "NodeResourcesFit": 1,
            "NodeResourcesBalancedAllocation": 1,
            "ImageLocality": 1,
        }
    )
    enable_min_domains: bool = True


# --- individual plugin semantics (filter) ------------------------------------


def fits_resources(pod: v1.Pod, info: NodeInfo) -> bool:
    """fit.go:255-328 fitsRequest."""
    req = compute_pod_resource_request(pod)
    alloc, used = info.allocatable, info.requested
    if len(info.pods) + 1 > alloc.allowed_pod_number:
        return False
    checks = [
        (req.milli_cpu, alloc.milli_cpu - used.milli_cpu),
        (req.memory, alloc.memory - used.memory),
        (req.ephemeral_storage, alloc.ephemeral_storage - used.ephemeral_storage),
    ]
    for want, free in checks:
        if want > 0 and want > free:
            return False
    for name, want in req.scalar_resources.items():
        if want > 0 and want > alloc.scalar_resources.get(name, 0) - used.scalar_resources.get(name, 0):
            return False
    return True


def tolerates_all_hard_taints(pod: v1.Pod, node: v1.Node) -> bool:
    """taint_toleration.go:64-82 (NoSchedule/NoExecute only)."""
    for taint in node.spec.taints:
        if taint.effect == v1.TAINT_PREFER_NO_SCHEDULE:
            continue
        if not any(t.tolerates(taint) for t in pod.spec.tolerations):
            return False
    return True


def node_affinity_fits(pod: v1.Pod, node: v1.Node) -> bool:
    """nodeaffinity Filter: nodeSelector AND requiredDuringScheduling."""
    if pod.spec.node_selector:
        for k, want in pod.spec.node_selector.items():
            if node.metadata.labels.get(k) != want:
                return False
    aff = pod.spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required is not None:
        if not match_node_selector(aff.node_affinity.required, node):
            return False
    return True


def node_name_fits(pod: v1.Pod, node: v1.Node) -> bool:
    return not pod.spec.node_name or pod.spec.node_name == node.metadata.name


def node_ports_fit(pod: v1.Pod, info: NodeInfo) -> bool:
    return not host_ports_conflict(_pod_host_ports(pod), info.used_ports)


def node_schedulable(pod: v1.Pod, node: v1.Node) -> bool:
    if not node.spec.unschedulable:
        return True
    fake = v1.Taint(key=UNSCHEDULABLE_TAINT, effect=v1.TAINT_NO_SCHEDULE)
    return any(t.tolerates(fake) for t in pod.spec.tolerations)


# --- topology spread ----------------------------------------------------------


def _spread_constraints(pod: v1.Pod, when: str) -> List[v1.TopologySpreadConstraint]:
    return [c for c in pod.spec.topology_spread_constraints if c.when_unsatisfiable == when]


def _count_matching(info: NodeInfo, selector, ns: str) -> int:
    """countPodsMatchSelector: same namespace, non-terminating."""
    n = 0
    for pi in info.pods:
        p = pi.pod
        if p.namespace != ns or p.metadata.deletion_timestamp is not None:
            continue
        if selector is not None and match_label_selector(selector, p.metadata.labels):
            n += 1
    return n


def _spread_counts(
    pod: v1.Pod, node_infos: List[NodeInfo], constraints
) -> Tuple[Dict[Tuple[str, str], int], Dict[str, int]]:
    """TpPairToMatchNum over affinity-eligible nodes holding all keys
    (filtering.go:256-289); also per-key domain counts."""
    pair_counts: Dict[Tuple[str, str], int] = {}
    domains: Dict[str, int] = {}
    for info in node_infos:
        node = info.node
        if node is None or not node_affinity_fits(pod, node):
            continue
        if any(c.topology_key not in node.metadata.labels for c in constraints):
            continue
        for c in constraints:
            pair = (c.topology_key, node.metadata.labels[c.topology_key])
            if pair not in pair_counts:
                pair_counts[pair] = 0
                domains[c.topology_key] = domains.get(c.topology_key, 0) + 1
            pair_counts[pair] += _count_matching(info, c.label_selector, pod.namespace)
    return pair_counts, domains


def topology_spread_fits(
    pod: v1.Pod, info: NodeInfo, node_infos: List[NodeInfo],
    enable_min_domains: bool = True,
    prefilter=None,
) -> bool:
    """filtering.go:343-358. ``prefilter`` carries the per-pod counts computed
    once per cycle (PreFilter), mirroring the reference's CycleState reuse."""
    constraints = _spread_constraints(pod, v1.DO_NOT_SCHEDULE)
    if not constraints:
        return True
    node = info.node
    if prefilter is None:
        prefilter = _spread_counts(pod, node_infos, constraints)
    pair_counts, domains = prefilter
    for c in constraints:
        if c.topology_key not in node.metadata.labels:
            return False
        self_match = 1 if (
            c.label_selector is not None
            and match_label_selector(c.label_selector, pod.metadata.labels)
        ) else 0
        key_counts = [v for (k, _), v in pair_counts.items() if k == c.topology_key]
        min_match = min(key_counts) if key_counts else (1 << 31)
        if enable_min_domains and c.min_domains:
            if domains.get(c.topology_key, 0) < c.min_domains:
                min_match = 0
        match_num = pair_counts.get(
            (c.topology_key, node.metadata.labels[c.topology_key]), 0
        )
        if match_num + self_match - min_match > c.max_skew:
            return False
    return True


def topology_spread_scores(
    pod: v1.Pod, feasible: List[NodeInfo], node_infos: List[NodeInfo]
) -> Dict[str, int]:
    """scoring.go PreScore+Score+NormalizeScore over the feasible set."""
    constraints = _spread_constraints(pod, v1.SCHEDULE_ANYWAY)
    if not constraints:
        # NormalizeScore still runs on the all-zero plane: maxScore == 0 → every
        # node gets MaxNodeScore (scoring.go:245-248) — uniform, rank-neutral
        return {ni.node_name: MAX_NODE_SCORE for ni in feasible}
    # init: pairs among feasible nodes having all keys; ignored nodes
    ignored = set()
    pair_counts: Dict[Tuple[str, str], int] = {}
    topo_size = {c.topology_key: 0 for c in constraints}
    for info in feasible:
        labels = info.node.metadata.labels
        if any(c.topology_key not in labels for c in constraints):
            ignored.add(info.node_name)
            continue
        for c in constraints:
            pair = (c.topology_key, labels[c.topology_key])
            if pair not in pair_counts:
                pair_counts[pair] = 0
                topo_size[c.topology_key] += 1
    # count over all affinity-eligible nodes, restricted to known pairs
    for info in node_infos:
        node = info.node
        if node is None or not node_affinity_fits(pod, node):
            continue
        labels = node.metadata.labels
        if any(c.topology_key not in labels for c in constraints):
            continue
        for c in constraints:
            pair = (c.topology_key, labels[c.topology_key])
            if pair in pair_counts:
                pair_counts[pair] += _count_matching(info, c.label_selector, pod.namespace)
    weights = {
        key: math.log(sz + 2) for key, sz in topo_size.items()
    }
    raw: Dict[str, Optional[int]] = {}
    for info in feasible:
        name = info.node_name
        if name in ignored:
            raw[name] = None
            continue
        score = 0.0
        labels = info.node.metadata.labels
        for c in constraints:
            if c.topology_key in labels:
                cnt = pair_counts.get((c.topology_key, labels[c.topology_key]), 0)
                score += cnt * weights[c.topology_key] + (c.max_skew - 1)
        raw[name] = int(round(score))
    vals = [s for s in raw.values() if s is not None]
    if not vals:
        return {n: 0 for n in raw}
    mx, mn = max(vals), min(vals)
    out = {}
    for name, s in raw.items():
        if s is None:
            out[name] = 0
        elif mx == 0:
            out[name] = MAX_NODE_SCORE
        else:
            out[name] = MAX_NODE_SCORE * (mx + mn - s) // mx
    return out


# --- inter-pod affinity -------------------------------------------------------


def _term_matches_all(terms, owner: v1.Pod, target: v1.Pod, ns_labels) -> bool:
    if not terms:
        return False
    return all(affinity_term_matches(t, owner, target, ns_labels) for t in terms)


@dataclass
class InterPodPreFilterState:
    """preFilterState (filtering.go:44-55): the three topologyPair→count maps
    plus the incoming pod's parsed terms, built ONCE per cycle."""

    pod_info: PodInfo
    exist_anti_pairs: Dict[Tuple[str, str], int] = field(default_factory=dict)
    aff_counts: Dict[Tuple[str, str], int] = field(default_factory=dict)
    anti_counts: Dict[Tuple[str, str], int] = field(default_factory=dict)
    self_match_all: bool = False


def interpod_prefilter(
    pod: v1.Pod, node_infos: List[NodeInfo],
    namespace_labels: Optional[Mapping[str, Mapping[str, str]]] = None,
) -> InterPodPreFilterState:
    pi = PodInfo.of(pod)
    s = InterPodPreFilterState(pod_info=pi)
    # existing pods' required anti-affinity vs incoming (getExistingAntiAffinityCounts)
    for other in node_infos:
        if other.node is None:
            continue
        olabels = other.node.metadata.labels
        for epi in other.pods_with_required_anti_affinity:
            for term in epi.required_anti_affinity_terms:
                if affinity_term_matches(term, epi.pod, pod, namespace_labels):
                    tv = olabels.get(term.topology_key)
                    if tv is not None:
                        key = (term.topology_key, tv)
                        s.exist_anti_pairs[key] = s.exist_anti_pairs.get(key, 0) + 1
        # incoming's maps (getIncomingAffinityAntiAffinityCounts)
        if pi.required_affinity_terms or pi.required_anti_affinity_terms:
            for epi in other.pods:
                if pi.required_affinity_terms and _term_matches_all(
                    pi.required_affinity_terms, pod, epi.pod, namespace_labels
                ):
                    for term in pi.required_affinity_terms:
                        tv = olabels.get(term.topology_key)
                        if tv is not None:
                            key = (term.topology_key, tv)
                            s.aff_counts[key] = s.aff_counts.get(key, 0) + 1
                for term in pi.required_anti_affinity_terms:
                    if affinity_term_matches(term, pod, epi.pod, namespace_labels):
                        tv = olabels.get(term.topology_key)
                        if tv is not None:
                            key = (term.topology_key, tv)
                            s.anti_counts[key] = s.anti_counts.get(key, 0) + 1
    s.self_match_all = _term_matches_all(
        pi.required_affinity_terms, pod, pod, namespace_labels
    )
    return s


def interpod_affinity_fits(
    pod: v1.Pod, info: NodeInfo, node_infos: List[NodeInfo],
    namespace_labels: Optional[Mapping[str, Mapping[str, str]]] = None,
    prefilter: Optional[InterPodPreFilterState] = None,
) -> bool:
    """filtering.go:308-360 (three satisfy* checks) against the prefilter maps."""
    s = prefilter or interpod_prefilter(pod, node_infos, namespace_labels)
    pi = s.pod_info
    labels = info.node.metadata.labels

    # satisfyExistingPodsAntiAffinity (:308-320)
    if s.exist_anti_pairs:
        for key, value in labels.items():
            if s.exist_anti_pairs.get((key, value), 0) > 0:
                return False

    # satisfyPodAntiAffinity (:323-335)
    for term in pi.required_anti_affinity_terms:
        tv = labels.get(term.topology_key)
        if tv is not None and s.anti_counts.get((term.topology_key, tv), 0) > 0:
            return False

    # satisfyPodAffinity (:338-360)
    if pi.required_affinity_terms:
        pods_exist = True
        for term in pi.required_affinity_terms:
            tv = labels.get(term.topology_key)
            if tv is None:
                return False
            if s.aff_counts.get((term.topology_key, tv), 0) <= 0:
                pods_exist = False
        if not pods_exist:
            return bool(not s.aff_counts and s.self_match_all)
    return True


def interpod_affinity_scores(
    pod: v1.Pod, feasible: List[NodeInfo], node_infos: List[NodeInfo],
    hard_weight: int = 1,
    namespace_labels: Optional[Mapping[str, Mapping[str, str]]] = None,
) -> Dict[str, int]:
    """scoring.go PreScore/Score/NormalizeScore."""
    pi = PodInfo.of(pod)
    has_pref = bool(pi.preferred_affinity_terms or pi.preferred_anti_affinity_terms)
    pair_score: Dict[Tuple[str, str], float] = {}

    def bump(term, w, node):
        tv = node.metadata.labels.get(term.topology_key)
        if tv is not None:
            pair = (term.topology_key, tv)
            pair_score[pair] = pair_score.get(pair, 0.0) + w

    for other in node_infos:
        node = other.node
        if node is None or not node.metadata.labels:
            continue
        pods = other.pods if has_pref else other.pods_with_affinity
        for epi in pods:
            # incoming pod's preferred terms vs existing pod
            for wt in pi.preferred_affinity_terms:
                if affinity_term_matches(wt.pod_affinity_term, pod, epi.pod, namespace_labels):
                    bump(wt.pod_affinity_term, wt.weight, node)
            for wt in pi.preferred_anti_affinity_terms:
                if affinity_term_matches(wt.pod_affinity_term, pod, epi.pod, namespace_labels):
                    bump(wt.pod_affinity_term, -wt.weight, node)
            # existing pod's hard affinity (symmetric weight)
            if hard_weight > 0:
                for term in epi.required_affinity_terms:
                    if affinity_term_matches(term, epi.pod, pod, namespace_labels):
                        bump(term, hard_weight, node)
            # existing pod's preferred terms vs incoming
            for wt in epi.preferred_affinity_terms:
                if affinity_term_matches(wt.pod_affinity_term, epi.pod, pod, namespace_labels):
                    bump(wt.pod_affinity_term, wt.weight, node)
            for wt in epi.preferred_anti_affinity_terms:
                if affinity_term_matches(wt.pod_affinity_term, epi.pod, pod, namespace_labels):
                    bump(wt.pod_affinity_term, -wt.weight, node)

    raw = {}
    for info in feasible:
        labels = info.node.metadata.labels
        s = 0.0
        for (key, val), w in pair_score.items():
            if labels.get(key) == val:
                s += w
        raw[info.node_name] = int(s)
    if not pair_score:
        return {n: 0 for n in raw}
    mx, mn = max(raw.values()), min(raw.values())
    diff = mx - mn
    return {
        n: int(MAX_NODE_SCORE * (s - mn) / diff) if diff > 0 else 0
        for n, s in raw.items()
    }


# --- scoring (simple plugins) -------------------------------------------------


def least_allocated_score(pod: v1.Pod, info: NodeInfo, resources: Dict[str, int]) -> int:
    req = compute_pod_resource_request_non_zero(pod)
    score = 0
    wsum = 0
    for name, w in resources.items():
        alloc = info.allocatable.get(name)
        if alloc == 0:
            continue
        used = info.non_zero_requested.get(name) + req.get(name)
        if name not in ("cpu", "memory", "ephemeral-storage") and req.get(name) == 0:
            continue
        r = 0 if used > alloc else (alloc - used) * MAX_NODE_SCORE // alloc
        score += r * w
        wsum += w
    return score // wsum if wsum else 0


def balanced_allocation_score(pod: v1.Pod, info: NodeInfo, resources: Dict[str, int]) -> int:
    req = compute_pod_resource_request(pod)
    fractions = []
    for name in resources:
        alloc = info.allocatable.get(name)
        if alloc == 0:
            continue
        if name not in ("cpu", "memory", "ephemeral-storage") and req.get(name) == 0:
            continue
        f = (info.requested.get(name) + req.get(name)) / alloc
        fractions.append(min(f, 1.0))
    if not fractions:
        return 0
    if len(fractions) == 2:
        std = abs(fractions[0] - fractions[1]) / 2
    elif len(fractions) > 2:
        mean = sum(fractions) / len(fractions)
        std = math.sqrt(sum((f - mean) ** 2 for f in fractions) / len(fractions))
    else:
        std = 0.0
    return int((1 - std) * MAX_NODE_SCORE)


def taint_toleration_score(pod: v1.Pod, node: v1.Node) -> int:
    """Count of intolerable PreferNoSchedule taints (raw, pre-normalize)."""
    tols = [
        t for t in pod.spec.tolerations
        if not t.effect or t.effect == v1.TAINT_PREFER_NO_SCHEDULE
    ]
    n = 0
    for taint in node.spec.taints:
        if taint.effect != v1.TAINT_PREFER_NO_SCHEDULE:
            continue
        if not any(t.tolerates(taint) for t in tols):
            n += 1
    return n


def node_affinity_score(pod: v1.Pod, node: v1.Node) -> int:
    aff = pod.spec.affinity
    if not aff or not aff.node_affinity:
        return 0
    from .api.labels import match_node_selector_term

    s = 0
    for term in aff.node_affinity.preferred:
        if match_node_selector_term(term.preference, node):
            s += term.weight
    return s


def image_locality_score(pod: v1.Pod, info: NodeInfo, node_infos: List[NodeInfo]) -> int:
    total = sum(1 for ni in node_infos if ni.node is not None)
    spread: Dict[str, int] = {}
    for ni in node_infos:
        for img in ni.image_states:
            spread[img] = spread.get(img, 0) + 1
    s = 0
    for c in pod.spec.containers:
        if c.image in info.image_states:
            s += int(info.image_states[c.image] * spread.get(c.image, 0) / max(total, 1))
    n_containers = max(len(pod.spec.containers), 1)
    max_t = MAX_IMG_PER_CONTAINER * n_containers
    s = min(max(s, MIN_IMG), max_t)
    return MAX_NODE_SCORE * (s - MIN_IMG) // (max_t - MIN_IMG)


def default_normalize(raw: Dict[str, int], reverse: bool = False) -> Dict[str, int]:
    mx = max(raw.values(), default=0)
    if mx == 0:
        return {k: (MAX_NODE_SCORE if reverse else 0) for k in raw}
    out = {}
    for k, s in raw.items():
        v = s * MAX_NODE_SCORE // mx
        out[k] = MAX_NODE_SCORE - v if reverse else v
    return out


# --- the oracle scheduler -----------------------------------------------------


class Oracle:
    """One-pod-at-a-time reference scheduler over host NodeInfos."""

    def __init__(self, cfg: Optional[OracleConfig] = None,
                 namespace_labels: Optional[Mapping[str, Mapping[str, str]]] = None):
        self.cfg = cfg or OracleConfig()
        self.namespace_labels = namespace_labels

    def feasible_nodes(self, pod: v1.Pod, node_infos: List[NodeInfo]) -> List[NodeInfo]:
        # PreFilter once per pod (the reference's CycleState), Filter per node
        hard_constraints = _spread_constraints(pod, v1.DO_NOT_SCHEDULE)
        spread_state = (
            _spread_counts(pod, node_infos, hard_constraints)
            if hard_constraints else None
        )
        ipa_state = interpod_prefilter(pod, node_infos, self.namespace_labels)
        out = []
        for info in node_infos:
            node = info.node
            if node is None:
                continue
            if not node_name_fits(pod, node):
                continue
            if not v1.node_is_ready(node):
                # node-lifecycle mask: a NotReady host is out of the
                # schedulable universe entirely (no toleration escape —
                # matches the device path's node_valid & node_ready gate)
                continue
            if not node_schedulable(pod, node):
                continue
            if not node_affinity_fits(pod, node):
                continue
            if not tolerates_all_hard_taints(pod, node):
                continue
            if not node_ports_fit(pod, info):
                continue
            if not fits_resources(pod, info):
                continue
            if not topology_spread_fits(
                pod, info, node_infos, self.cfg.enable_min_domains,
                prefilter=spread_state,
            ):
                continue
            if not interpod_affinity_fits(
                pod, info, node_infos, self.namespace_labels, prefilter=ipa_state
            ):
                continue
            out.append(info)
        return out

    def score_nodes(
        self, pod: v1.Pod, feasible: List[NodeInfo], node_infos: List[NodeInfo]
    ) -> Dict[str, int]:
        cfg = self.cfg
        w = cfg.weights
        totals = {ni.node_name: 0 for ni in feasible}

        fit_raw = {
            ni.node_name: least_allocated_score(pod, ni, cfg.fit_resources)
            for ni in feasible
        }
        bal_raw = {
            ni.node_name: balanced_allocation_score(pod, ni, cfg.fit_resources)
            for ni in feasible
        }
        taint_raw = default_normalize(
            {ni.node_name: taint_toleration_score(pod, ni.node) for ni in feasible},
            reverse=True,
        )
        na_raw = default_normalize(
            {ni.node_name: node_affinity_score(pod, ni.node) for ni in feasible}
        )
        img_raw = {
            ni.node_name: image_locality_score(pod, ni, node_infos) for ni in feasible
        }
        ts = topology_spread_scores(pod, feasible, node_infos)
        ipa = interpod_affinity_scores(
            pod, feasible, node_infos, cfg.hard_pod_affinity_weight,
            self.namespace_labels,
        )
        for name in totals:
            totals[name] = (
                w.get("NodeResourcesFit", 1) * fit_raw[name]
                + w.get("NodeResourcesBalancedAllocation", 1) * bal_raw[name]
                + w.get("TaintToleration", 3) * taint_raw[name]
                + w.get("NodeAffinity", 2) * na_raw[name]
                + w.get("ImageLocality", 1) * img_raw[name]
                + w.get("PodTopologySpread", 2) * ts.get(name, 0)
                + w.get("InterPodAffinity", 2) * ipa.get(name, 0)
            )
        return totals

    def schedule_one(self, pod: v1.Pod, node_infos: List[NodeInfo]) -> Optional[str]:
        """Filter + score + first-max selection (deterministic tie-break by node
        order, matching the device path's lowest-row argmax)."""
        feasible = self.feasible_nodes(pod, node_infos)
        if not feasible:
            return None
        scores = self.score_nodes(pod, feasible, node_infos)
        best, best_score = None, None
        for info in node_infos:  # node order = snapshot order for tie parity
            name = info.node_name
            if name in scores and (best_score is None or scores[name] > best_score):
                best, best_score = name, scores[name]
        return best

    def schedule_batch(
        self, pods: List[v1.Pod], node_infos: List[NodeInfo]
    ) -> List[Optional[str]]:
        """Sequential schedule-and-assume over a pod list (mutates node_infos)."""
        out = []
        by_name = {ni.node_name: ni for ni in node_infos}
        for pod in pods:
            target = self.schedule_one(pod, node_infos)
            out.append(target)
            if target is not None:
                pod.spec.node_name = target
                by_name[target].add_pod(pod)
        return out


def volume_binding_feasible(pod: v1.Pod, node: v1.Node, listers) -> bool:
    """Straight-line reference semantics for the VolumeBinding Filter
    (volumebinding/binder.go FindPodVolumes): for every PVC of the pod —

      bound PVC        → its PV's nodeAffinity must match the node;
      unbound, class absent or Immediate → unschedulable (the PV controller
                         owns it; volume_binding.go PreFilter);
      WaitForFirstConsumer + provisioner → node must satisfy the class's
                         AllowedTopologies (topology-aware provisioning);
      WaitForFirstConsumer, no provisioner → some available PV of the class
                         must fit (capacity ≥ request, access modes ⊆, not
                         claimed elsewhere) with nodeAffinity matching.

    The parity tests drive this against the device-path mask over randomized
    volume clusters (SURVEY §4 testing lesson).
    """
    from .api.labels import match_node_selector
    from .api.resource import parse_quantity
    from .plugins.volumes import _pod_pvcs

    for claim in _pod_pvcs(pod):
        pvc = listers.pvc(pod.namespace, claim)
        if pvc is None:
            return False
        if pvc.volume_name:
            pv = listers.pv(pvc.volume_name)
            if pv is None:
                return False
            if pv.node_affinity is not None and not match_node_selector(
                pv.node_affinity, node
            ):
                return False
            continue
        sc = listers.storage_class(pvc.storage_class_name or "")
        if sc is None or sc.volume_binding_mode != v1.VOLUME_BINDING_WAIT:
            return False
        if sc.provisioner:
            if sc.allowed_topologies is not None and not match_node_selector(
                sc.allowed_topologies, node
            ):
                return False
            continue
        claim_key = f"{pod.namespace}/{claim}"
        want = parse_quantity(pvc.requested_storage or 0)
        ok = False
        for pv in listers.pvs():
            if (pv.storage_class_name or "") != (pvc.storage_class_name or ""):
                continue
            if pv.claim_ref is not None and pv.claim_ref != claim_key:
                continue
            if parse_quantity(pv.capacity.get("storage", 0)) < want:
                continue
            if pvc.access_modes and not set(pvc.access_modes) <= set(
                pv.access_modes or pvc.access_modes
            ):
                continue
            if pv.node_affinity is not None and not match_node_selector(
                pv.node_affinity, node
            ):
                continue
            ok = True
            break
        if not ok:
            return False
    return True
