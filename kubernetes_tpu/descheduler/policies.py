"""Descheduler policies: candidate eviction-set enumeration.

Reference: sigs.k8s.io/descheduler (RemovePodsViolatingTopologySpread,
the node-drain flow of kubectl drain + the NoExecute taint manager) and
the north-star framing: "which evictions free a slice at least cost" is a
batched counterfactual solve (descheduler/planner.py) — the policies here
only ENUMERATE candidate plans; the controller scores each one on device
and applies the cheapest viable plan through the eviction gate.

All three policies are PDB-aware by construction: a candidate whose
victims include a budget-blocked pod is either skipped (defrag needs the
WHOLE slice, so one protected straggler disqualifies the slice) or the
protected pod is simply left out (drain defers it to a later sync).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..api import objects as v1
from ..api.labels import match_label_selector
from ..gang import POD_GROUP_LABEL

# Nodes annotated with this (value "true") are drained by NodeDrainPolicy:
# cordoned, then evicted through the gate over however many syncs the PDB
# budgets take.  ``ktpu drain`` performs the same flow imperatively.
DRAIN_ANNOTATION = "descheduler.tpu.kubernetes.io/drain"


@dataclass
class CandidatePlan:
    """One candidate eviction set, pre-scoring."""

    policy: str
    victims: List[v1.Pod]
    # pods the plan intends to make schedulable (counterfactually solved
    # with the victims masked); empty = no placement requirement (drain)
    pending: List[v1.Pod] = field(default_factory=list)
    # victim clones appended to a SECOND solve on the winning plan only,
    # scoring "replacement placements found" without perturbing the
    # parity-grade pending-only solve
    replacements: List[v1.Pod] = field(default_factory=list)
    note: str = ""  # target slice / node / constraint, for logs
    # plans sharing a group compete (the controller applies the cheapest
    # viable plan PER group per sync): defrag groups by waiting gang,
    # spread by constraint, drain by node — so one sync can serve several
    # independent demands within the eviction budget
    group: str = ""
    # what the plan frees (the slice / node name), carried explicitly so
    # earmarking logic never parses the human-readable note
    target: str = ""
    # every pending pod must place for the plan to be viable (defrag);
    # False = best-effort (spread repair validates via post_check instead)
    require_all_pending: bool = True
    # optional extra validation over the predicted placements
    post_check: Optional[Callable[[Dict[str, Optional[str]]], bool]] = None
    # drain plans skip the counterfactual solve entirely
    no_solve: bool = False


class PolicyContext:
    """What a policy may read: the store, the gang directory (demand), and
    the eviction gate (for PDB pre-checks only — policies never evict).
    ``dry_run`` mirrors the controller's mode: a previewing policy must
    not write side effects (the drain cordon) either."""

    def __init__(self, store, gangs, evictions, clock, dry_run=False):
        self.store = store
        self.gangs = gangs
        self.evictions = evictions
        self.clock = clock
        self.dry_run = dry_run
        self._pdbs = None

    @property
    def pdbs(self):
        if self._pdbs is None:
            self._pdbs = self.store.list("PodDisruptionBudget")[0]
        return self._pdbs


def clone_for_replacement(pod: v1.Pod) -> v1.Pod:
    """A what-if stand-in for an evicted/displaced pod's
    controller-recreated replacement: same spec/labels, fresh identity,
    unbound.  Public: the cluster autoscaler's scale-down proof uses the
    same stand-in (exported via descheduler/__init__)."""
    clone = copy.deepcopy(pod)
    clone.metadata.uid = f"whatif-{pod.uid}"
    clone.metadata.name = f"whatif-{pod.metadata.name}"
    clone.spec.node_name = ""
    clone.status.nominated_node_name = ""
    return clone



def _evictable(ctx: PolicyContext, pod: v1.Pod) -> bool:
    """Policy-side pre-filter: never plan around pods the gate would
    refuse, pods already terminating, or DaemonSet-owned pods (their
    controller immediately re-places them on the same node)."""
    if pod.metadata.deletion_timestamp is not None:
        return False
    if any(ref.kind == "DaemonSet"
           for ref in pod.metadata.owner_references or []):
        return False
    return ctx.evictions.can_evict(pod, ctx.pdbs)


class SliceDefragmentation:
    """Compact stragglers off TPU slices so waiting gangs get whole
    ``tpu.kubernetes.io/slice`` groups — driven by GangDirectory demand.

    For up to ``max_gangs_per_sync`` waiting gangs (oldest first), every
    slice whose stragglers are all evictable yields one candidate plan
    (evict the stragglers, pending = the gang's unbound members), grouped
    by gang so the controller applies one minimal viable plan PER gang per
    sync.  Slices are earmarked as they're claimed — a gang that already
    has a whole-free slice available earmarks it and proposes nothing
    (the scheduler just hasn't bound it yet; evicting more would be pure
    over-disruption), and later gangs' candidates exclude slices earlier
    gangs claimed."""

    name = "defrag"

    def __init__(self, slice_label: Optional[str] = None,
                 max_candidate_slices: int = 4,
                 max_gangs_per_sync: int = 8):
        from ..gang import SLICE_LABEL

        self.slice_label = slice_label or SLICE_LABEL
        self.max_candidate_slices = max_candidate_slices
        self.max_gangs_per_sync = max_gangs_per_sync

    def propose(self, ctx: PolicyContext) -> List[CandidatePlan]:
        gangs = self._waiting_gangs(ctx)
        if not gangs:
            return []
        nodes, _ = ctx.store.list("Node")
        by_slice: Dict[str, List[v1.Node]] = {}
        for node in nodes:
            val = node.metadata.labels.get(self.slice_label)
            if val is not None:
                by_slice.setdefault(val, []).append(node)
        pods, _ = ctx.store.list("Pod")
        bound_by_node: Dict[str, List[v1.Pod]] = {}
        for p in pods:
            if p.spec.node_name:
                bound_by_node.setdefault(p.spec.node_name, []).append(p)
        plans: List[CandidatePlan] = []
        earmarked: set = set()
        for group_key, members in gangs[: self.max_gangs_per_sync]:
            member_uids = {p.uid for p in members}
            need = sum(1 for p in members if not p.spec.node_name)
            candidates: List[CandidatePlan] = []
            has_free = False
            for slice_name, slice_nodes in sorted(by_slice.items()):
                if slice_name in earmarked:
                    continue
                if len(slice_nodes) < need:
                    # an undersized slice (hosts drained/deleted) can
                    # never seat the gang one-per-host: neither a free
                    # claim nor an eviction candidate
                    continue
                stragglers: List[v1.Pod] = []
                blocked = False
                for node in slice_nodes:
                    if node.spec.unschedulable:
                        blocked = True  # cordoned host: can't host the gang
                        break
                    for p in bound_by_node.get(node.metadata.name, []):
                        if p.uid in member_uids:
                            continue
                        if POD_GROUP_LABEL in p.metadata.labels:
                            # NEVER evict another gang's member to seat
                            # this one (destroying a placed gang to free a
                            # slice is strictly worse than waiting) — the
                            # slice is disqualified outright
                            blocked = True
                            break
                        stragglers.append(p)
                    if blocked:
                        break
                if blocked:
                    continue
                if not stragglers:
                    # a whole-free slice is already available: the gang is
                    # waiting on the scheduler, not on fragmentation —
                    # claim it and evict nothing for this gang
                    earmarked.add(slice_name)
                    has_free = True
                    break
                if not all(_evictable(ctx, p) for p in stragglers):
                    continue  # one protected straggler disqualifies it
                candidates.append(CandidatePlan(
                    policy=self.name,
                    group=group_key,
                    target=slice_name,
                    victims=list(stragglers),
                    pending=[p for p in members if not p.spec.node_name],
                    replacements=[clone_for_replacement(p)
                                  for p in stragglers],
                    note=f"slice {slice_name} for gang {group_key}",
                ))
            if has_free or not candidates:
                continue
            candidates.sort(key=lambda pl: len(pl.victims))
            candidates = candidates[: self.max_candidate_slices]
            # claim the cheapest candidate's slice so later gangs don't
            # compete for the same stragglers within this sync
            earmarked.add(candidates[0].target)
            plans.extend(candidates)
        return plans

    def _waiting_gangs(self, ctx: PolicyContext):
        groups, _ = ctx.store.list("PodGroup")
        pods, _ = ctx.store.list("Pod")
        waiting = []
        for pg in groups:
            if pg.phase == v1.POD_GROUP_SCHEDULED:
                continue
            members = [
                p for p in pods
                if p.namespace == pg.namespace
                and p.metadata.labels.get(POD_GROUP_LABEL) == pg.name
            ]
            unbound = [p for p in members if not p.spec.node_name]
            if not unbound or len(members) < pg.min_member:
                continue  # below quorum: freeing a slice can't help yet
            waiting.append((pg.metadata.creation_timestamp or 0.0,
                            pg.key(), members))
        waiting.sort(key=lambda t: (t[0], t[1]))
        return [(key, members) for _, key, members in waiting]


class SpreadViolationRepair:
    """Evict one pod from the most-crowded domain of a drifted
    ``PodTopologySpread`` constraint (actual skew exceeds maxSkew — the
    IgnoredDuringExecution gap churn opens), PROVIDED the counterfactual
    solve lands its replacement in a strictly less-crowded domain."""

    name = "spread"

    def propose(self, ctx: PolicyContext) -> List[CandidatePlan]:
        pods, _ = ctx.store.list("Pod")
        nodes, _ = ctx.store.list("Node")
        node_by_name = {n.metadata.name: n for n in nodes}
        plans: List[CandidatePlan] = []
        seen = set()
        for pod in pods:
            if not pod.spec.node_name:
                continue
            for tsc in pod.spec.topology_spread_constraints:
                if tsc.when_unsatisfiable != v1.DO_NOT_SCHEDULE:
                    continue
                sig = (pod.namespace, tsc.topology_key,
                       _selector_sig(tsc.label_selector))
                if sig in seen:
                    continue
                seen.add(sig)
                plan = self._repair_one(ctx, pod, tsc, pods, node_by_name)
                if plan is not None:
                    plans.append(plan)
        return plans

    def _repair_one(self, ctx, owner, tsc, pods, node_by_name):
        counts: Dict[str, int] = {}
        domain_pods: Dict[str, List[v1.Pod]] = {}
        for node in node_by_name.values():
            val = node.metadata.labels.get(tsc.topology_key)
            if val is not None:
                counts.setdefault(val, 0)
        if len(counts) < 2:
            return None
        for p in pods:
            if not p.spec.node_name or p.namespace != owner.namespace:
                continue
            node = node_by_name.get(p.spec.node_name)
            if node is None:
                continue
            val = node.metadata.labels.get(tsc.topology_key)
            if val is None:
                continue
            if tsc.label_selector is not None and match_label_selector(
                    tsc.label_selector, p.metadata.labels):
                counts[val] += 1
                domain_pods.setdefault(val, []).append(p)
        if not counts:
            return None
        max_dom = max(counts, key=lambda d: (counts[d], d))
        skew = counts[max_dom] - min(counts.values())
        if skew <= tsc.max_skew:
            return None
        # youngest matching pod in the crowded domain that the gate allows
        candidates = sorted(
            (p for p in domain_pods.get(max_dom, [])
             if _evictable(ctx, p)),
            key=lambda p: -(p.metadata.creation_timestamp or 0.0),
        )
        if not candidates:
            return None
        victim = candidates[0]
        clone = clone_for_replacement(victim)
        crowded_nodes = {
            n.metadata.name for n in node_by_name.values()
            if n.metadata.labels.get(tsc.topology_key) == max_dom
        }

        def replacement_leaves_domain(placements) -> bool:
            target = placements.get(clone.uid)
            return target is not None and target not in crowded_nodes

        return CandidatePlan(
            policy=self.name, victims=[victim], pending=[clone],
            group=f"{owner.namespace}/{tsc.topology_key}/"
                  f"{_selector_sig(tsc.label_selector)}",
            note=f"{tsc.topology_key} skew {skew} > {tsc.max_skew} "
                 f"in {max_dom}",
            require_all_pending=True,
            post_check=replacement_leaves_domain,
        )


class NodeDrainPolicy:
    """Cordon + evict for maintenance: nodes carrying the drain annotation
    are cordoned, then their pods leave through the gate — PDB-refused
    pods simply stay for a later sync (budget replenishes as replacements
    schedule elsewhere), so a drain can never zero a protected workload."""

    name = "drain"

    def propose(self, ctx: PolicyContext) -> List[CandidatePlan]:
        nodes, _ = ctx.store.list("Node")
        pods, _ = ctx.store.list("Pod")
        by_node: Dict[str, List[v1.Pod]] = {}
        for p in pods:
            if p.spec.node_name:
                by_node.setdefault(p.spec.node_name, []).append(p)
        plans: List[CandidatePlan] = []
        for node in nodes:
            if node.metadata.annotations.get(DRAIN_ANNOTATION) != "true":
                continue
            if not node.spec.unschedulable and not ctx.dry_run:
                node.spec.unschedulable = True  # cordon first
                ctx.store.update("Node", node)
            victims = [
                p for p in by_node.get(node.metadata.name, [])
                if _evictable(ctx, p)
            ]
            if not victims:
                continue
            plans.append(CandidatePlan(
                policy=self.name, victims=victims,
                group=node.metadata.name, target=node.metadata.name,
                note=f"drain {node.metadata.name}", no_solve=True,
            ))
        return plans


def _selector_sig(sel: Optional[v1.LabelSelector]) -> tuple:
    if sel is None:
        return ()
    return (
        tuple(sorted(sel.match_labels.items())),
        tuple((e.key, e.operator, tuple(e.values))
              for e in sel.match_expressions),
    )


def default_policies() -> List[object]:
    return [SliceDefragmentation(), SpreadViolationRepair(),
            NodeDrainPolicy()]
