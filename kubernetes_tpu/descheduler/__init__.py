"""Descheduler subsystem: PDB-aware eviction gate + device-resident
defragmentation planner + policy controller loop.

Layer map (COMPONENTS.md has the upstream-analogue table):
  evictions.py  — the single gate every pod-killing path goes through
                  (Eviction subresource analog, PDB-consulting)
  planner.py    — counterfactual batched assignment over a forked
                  DeviceSnapshot (DryRunPreemption analog, one pod×node
                  solve per plan)
  policies.py   — slice defragmentation / spread-violation repair /
                  node drain candidate enumeration
  controller.py — the rate-limited propose→score→apply loop
"""

from .controller import DeschedulerController, ScoredPlan
from .evictions import EvictionAPI, EvictionResult
from .planner import Prediction, WhatIfPlanner
from .policies import (
    DRAIN_ANNOTATION,
    CandidatePlan,
    NodeDrainPolicy,
    SliceDefragmentation,
    SpreadViolationRepair,
    clone_for_replacement,
    default_policies,
)

__all__ = [
    "DeschedulerController",
    "ScoredPlan",
    "EvictionAPI",
    "EvictionResult",
    "Prediction",
    "WhatIfPlanner",
    "DRAIN_ANNOTATION",
    "CandidatePlan",
    "NodeDrainPolicy",
    "SliceDefragmentation",
    "SpreadViolationRepair",
    "clone_for_replacement",
    "default_policies",
]
