"""Device-resident what-if planner: counterfactual batched assignment.

Since the whatif unification this module is a thin port: the fork-and-
resolve machinery (snapshot forking, engine routing, the vmapped solve)
lives in ``kubernetes_tpu/whatif`` — ONE engine shared with the cluster
autoscaler and preemption's dry-run fan-out — and ``WhatIfPlanner`` keeps
the descheduler-facing contract on top of it.

Parity contract (tests/test_descheduler.py): because the engine re-runs
the scheduler's exact assignment semantics (same engine routing, same
gang all-or-nothing mask, same deterministic tie-breaks) over a fork that
matches what the encoder will contain once the victims are really
evicted, the predicted placements equal the scheduler's actual
post-eviction bindings bit-for-bit — provided the cluster doesn't change
in between and the planner runs while the scheduler is quiescent (no
in-flight pipelined batches; the descheduler controller loop runs between
cycles, where that holds by construction).

Affinity-carrying victims are SUPPORTED (the historical WhatIfPlanner
refused them): the fork masks the victim's term-count contributions out
of the incremental ``aff_*`` tables (state/affinity_index.py), exactly
the delta a real eviction's encoder sync applies — parity pinned in
test_planner_masks_affinity_victims.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..api import objects as v1
from ..metrics import scheduler_metrics as m
from ..whatif import ForkSpec, Prediction, WhatIfEngine

__all__ = ["Prediction", "WhatIfPlanner"]


class WhatIfPlanner:
    """Counterfactual solver bound to a live TPUScheduler (shares its
    cache/encoder/compiler through the whatif engine)."""

    def __init__(self, scheduler):
        self.sched = scheduler
        self.engine = WhatIfEngine(scheduler)

    def order_pending(self, pods: Sequence[v1.Pod]) -> List[v1.Pod]:
        """The queue's pop order (gang-cohesive priority sort) so the
        counterfactual batch matches what the real scheduler will pop."""
        return self.engine.order_pending(pods)

    def predict(self, pending: Sequence[v1.Pod],
                victims: Sequence[v1.Pod]) -> Optional[Prediction]:
        """One batched pod×node solve: where would ``pending`` land if
        ``victims`` were evicted?  Returns None when the solve cannot be
        trusted (batch overflow, in-flight pipelined work) — callers must
        treat that as "no plan", never as "no fit"."""
        t0 = self.sched.clock()
        pred = self.engine.evaluate_one(pending, ForkSpec(
            victims=list(victims), note="descheduler"))
        if pred is not None:
            m.descheduler_planner_duration.observe(
                max(self.sched.clock() - t0, 0.0))
        return pred
