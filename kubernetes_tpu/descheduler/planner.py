"""Device-resident what-if planner: counterfactual batched assignment.

The dry-run analogue of the preemption evaluator's ``DryRunPreemption``
(framework/preemption, preemption.go:546) — but instead of per-node host
loops cloning NodeInfos, a candidate eviction set is masked out of a
FORKED ``DeviceSnapshot`` by one small scatter program and the scheduler's
existing fused batched-assignment program re-runs against the fork: one
pod×node solve per plan answers "if these victims were evicted, where
would the waiting pods land?" for a whole pending batch at once.

Parity contract (tests/test_descheduler.py): because the solve reuses the
EXACT jitted cycle program (same engine routing, same gang all-or-nothing
mask, same deterministic tie-breaks) over a fork that matches what the
encoder will contain once the victims are really evicted, the predicted
placements equal the scheduler's actual post-eviction bindings
bit-for-bit — provided the cluster doesn't change in between and the
planner runs while the scheduler is quiescent (no in-flight pipelined
batches; the descheduler controller loop runs between cycles, where that
holds by construction).

Known fidelity limit (documented, not silent): the incremental affinity
tables (DeviceSnapshot.aff_*) are NOT masked — a victim that carries
pod-(anti)affinity terms leaves its term counts in the fork, so plans
whose victims anchor affinity state can mispredict.  The in-tree policies
only pick affinity-free victims; ``predict`` refuses otherwise.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..api import objects as v1
from ..metrics import scheduler_metrics as m
from ..state.units import pow2_round_up as _pow2


@dataclass
class Prediction:
    """One counterfactual solve's outcome."""

    placements: Dict[str, Optional[str]]  # pod uid → node name (None = no fit)
    pods: List[v1.Pod] = field(default_factory=list)  # solve order (= queue order)
    masked_victims: int = 0

    @property
    def placed(self) -> int:
        return sum(1 for n in self.placements.values() if n is not None)

    @property
    def unplaced(self) -> int:
        return sum(1 for n in self.placements.values() if n is None)


@jax.jit
def _fork_snapshot(dsnap, vic_pod_rows, vic_node_rows):
    """Mask a victim set out of a DeviceSnapshot (pure; originals survive —
    the scatters are not donated, so the scheduler's live buffers are
    untouched).

    ``vic_pod_rows`` i32[K] (-1 pad) are scheduled-pod rows to invalidate;
    ``vic_node_rows`` i32[K] (0 pad, ignored where pod row is -1) are each
    victim's host node row, whose ``requested``/``non_zero_requested``
    drop by the victim's own request vector — exactly the state the
    encoder reaches after a real eviction's cache sync (per-pod unit
    vectors are exact integers, so subtraction equals re-encoding).
    Duplicate pad rows are safe: the validity mask is a scatter-max and
    the resource deltas are zero-weighted where the pod row is padding.
    """
    p = dsnap.pod_valid.shape[0]
    n = dsnap.requested.shape[0]
    ok = vic_pod_rows >= 0
    prow = jnp.clip(vic_pod_rows, 0, p - 1)
    nrow = jnp.clip(vic_node_rows, 0, n - 1)
    vic_mask = jnp.zeros(p, dtype=bool).at[prow].max(ok)
    pod_valid = dsnap.pod_valid & ~vic_mask
    okc = ok[:, None]
    requested = dsnap.requested.at[nrow].add(
        jnp.where(okc, -dsnap.pod_request[prow], 0))
    non_zero = dsnap.non_zero_requested.at[nrow].add(
        jnp.where(okc, -dsnap.pod_non_zero[prow], 0))
    return dataclasses.replace(
        dsnap, pod_valid=pod_valid, requested=requested,
        non_zero_requested=non_zero)


class _MaskedEncoderView:
    """Read-only encoder facade with the victim set masked in the HOST
    mirrors — handed to ``host_prepare`` so host-side plugin state (the
    Coscheduling anchor-slice plane's free-capacity scan, any host reader
    of ``requested``/``pod_valid``) sees the same counterfactual the
    device fork encodes.  Everything else delegates to the live encoder."""

    def __init__(self, encoder, vic_pod_rows: Sequence[int],
                 vic_node_rows: Sequence[int]):
        self._enc = encoder
        requested = encoder.requested.copy()
        non_zero = encoder.non_zero_requested.copy()
        pod_valid = encoder.pod_valid.copy()
        for pr, nr in zip(vic_pod_rows, vic_node_rows):
            requested[nr] -= encoder.pod_request[pr]
            non_zero[nr] -= encoder.pod_non_zero[pr]
            pod_valid[pr] = False
        self.requested = requested
        self.non_zero_requested = non_zero
        self.pod_valid = pod_valid

    def __getattr__(self, name):
        return getattr(self._enc, name)


class _QueueShim:
    """Just enough QueuedPodInfo surface for the gang less-fn."""

    __slots__ = ("pod", "initial_attempt_timestamp")

    def __init__(self, pod: v1.Pod):
        self.pod = pod
        self.initial_attempt_timestamp = pod.metadata.creation_timestamp or 0.0


class WhatIfPlanner:
    """Counterfactual solver bound to a live TPUScheduler (shares its
    cache/encoder/compiler and — critically — its compiled programs)."""

    def __init__(self, scheduler):
        self.sched = scheduler

    def order_pending(self, pods: Sequence[v1.Pod]) -> List[v1.Pod]:
        """The queue's pop order (gang-cohesive priority sort) so the
        counterfactual batch matches what the real scheduler will pop."""
        import functools

        less = self.sched.gangs.less
        shims = [_QueueShim(p) for p in pods]
        shims.sort(key=functools.cmp_to_key(
            lambda a, b: -1 if less(a, b) else (1 if less(b, a) else 0)))
        return [s.pod for s in shims]

    def predict(self, pending: Sequence[v1.Pod],
                victims: Sequence[v1.Pod]) -> Optional[Prediction]:
        """One batched pod×node solve: where would ``pending`` land if
        ``victims`` were evicted?  Returns None when the solve cannot be
        trusted (affinity-carrying victim, batch overflow) — callers must
        treat that as "no plan", never as "no fit"."""
        sched = self.sched
        if not pending or len(pending) > sched.batch_size:
            return None
        if getattr(sched, "_inflight_q", None):
            # quiescence precondition (module doc): an in-flight pipelined
            # batch holds placements the fork can't see (device-resident
            # deltas, assumes not yet snapshotted) — refuse rather than
            # mispredict; the controller flushes in-flight work first
            return None
        t0 = sched.clock()
        changed = sched.cache.update_snapshot(sched.snapshot)
        sched.encoder.sync(sched.snapshot, changed)
        enc = sched.encoder
        vic_pod_rows: List[int] = []
        vic_node_rows: List[int] = []
        for vic in victims:
            if _has_affinity_terms(vic):
                return None  # aff_* tables are not masked — see module doc
            pr = enc.pod_rows.get(vic.uid)
            nr = enc.node_rows.get(vic.spec.node_name)
            if pr is None or nr is None:
                continue  # not encoded (already gone / never bound): no-op
            vic_pod_rows.append(pr)
            vic_node_rows.append(nr)
        # compile BEFORE the device upload (same order as _dispatch_batch):
        # first-seen topology keys register at compile time and backfill
        # node_topo rows the upload must carry
        pods = self.order_pending(pending)
        batch = sched.compiler.compile(pods, pad_to=sched.batch_size)
        profile = sched._profile_of(pods[0])
        fw = sched._framework(profile)
        jt = sched._jitted_by[profile]
        dsnap = enc.to_device()
        k = _pow2(max(len(vic_pod_rows), 1), 8)
        prow = np.full(k, -1, dtype=np.int32)
        nrow = np.zeros(k, dtype=np.int32)
        if vic_pod_rows:
            prow[: len(vic_pod_rows)] = vic_pod_rows
            nrow[: len(vic_node_rows)] = vic_node_rows
        forked = _fork_snapshot(dsnap, prow, nrow)
        view = _MaskedEncoderView(enc, vic_pod_rows, vic_node_rows)
        sched.gangs.stage_batch(pods)
        gang_seg = sched.gangs.gang_segments(pods, batch.size)
        host_auxes = fw.host_prepare(
            batch, sched.snapshot, view,
            namespace_labels=sched.namespace_labels)
        nom_rows, nom_req = sched._nominated_arrays({p.uid for p in pods})
        (res, _auxes, _dsnap_out, _dyn_out, _diag), _engine = \
            sched._run_assignment(
                jt, batch, forked, None, nom_rows, nom_req, host_auxes,
                gang_seg=gang_seg,
            )
        # the forked dsnap is NEVER committed back to the encoder — the
        # scheduler's real device state is untouched by the what-if
        rows = np.asarray(res.node_row)[: len(pods)]
        name_of = enc.row_to_name()
        placements: Dict[str, Optional[str]] = {}
        for pod, row in zip(pods, rows):
            placements[pod.uid] = (
                name_of.get(int(row)) if int(row) >= 0 else None)
        m.descheduler_planner_duration.observe(
            max(sched.clock() - t0, 0.0))
        return Prediction(placements=placements, pods=pods,
                          masked_victims=len(vic_pod_rows))


def _has_affinity_terms(pod: v1.Pod) -> bool:
    aff = pod.spec.affinity
    if aff is None:
        return False
    pa, paa = aff.pod_affinity, aff.pod_anti_affinity
    return bool(pa and (pa.required or pa.preferred)) or bool(
        paa and (paa.required or paa.preferred))
