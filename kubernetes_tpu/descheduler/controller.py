"""Descheduler controller loop: propose → score on device → apply.

The run-once interface matches the other controllers (sync_once), so the
loop can ride ControllerManager or be driven directly by tests/harness.
Unlike the pure-store controllers it holds a scheduler reference: the
what-if planner reuses the scheduler's encoder and compiled assignment
programs for its counterfactual solves, and MUST therefore run while the
scheduler is quiescent (between cycles; the sim's drivers alternate
scheduler cycles and controller syncs on one thread, where that holds).

Plan application is fail-stop: victims are evicted one gate call at a
time, and the FIRST refusal or store fault abandons the remainder of the
plan (metric outcome "abandoned") — a mid-plan fault leaves every
surviving victim in place and the cluster schedulable; the next sync
re-plans from the actual state instead of resuming a stale victim list.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api import objects as v1
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from .evictions import EvictionAPI
from .planner import Prediction, WhatIfPlanner
from .policies import CandidatePlan, PolicyContext, default_policies


@dataclass
class ScoredPlan:
    plan: CandidatePlan
    viable: bool
    prediction: Optional[Prediction] = None
    slices_freed: int = 0
    replacements_found: int = 0

    @property
    def displaced(self) -> int:
        return len(self.plan.victims)


class DeschedulerController:
    name = "descheduler"

    def __init__(self, store, scheduler, eviction_api: Optional[EvictionAPI] = None,
                 policies: Optional[List[object]] = None,
                 dry_run: bool = False,
                 max_evictions_per_sync: int = 16,
                 min_interval: float = 0.0,
                 clock=None,
                 slice_label: Optional[str] = None):
        from ..gang import SLICE_LABEL

        self.store = store
        self.scheduler = scheduler
        self.clock = clock or getattr(scheduler, "clock", time.monotonic)
        self.evictions = eviction_api or EvictionAPI(
            store, recorder=getattr(scheduler, "recorder", None),
            clock=self.clock)
        self.planner = WhatIfPlanner(scheduler)
        self.policies = list(policies) if policies is not None \
            else default_policies()
        self.dry_run = dry_run
        # rate limiting: a hard per-sync eviction cap plus a minimum
        # spacing between eviction-performing syncs — a descheduler must
        # disrupt at a bounded pace, never storm a recovering cluster
        self.max_evictions_per_sync = max_evictions_per_sync
        self.min_interval = min_interval
        self._last_active = float("-inf")
        self.slice_label = slice_label or SLICE_LABEL
        # dry-run observability: last sync's scored plans per policy
        self.last_plans: Dict[str, ScoredPlan] = {}
        # per-sync cache of the slice → bound-pod-uids occupancy map
        # (see _slices_freed); None = rebuild on next use
        self._occupancy: Optional[Dict[str, List[str]]] = None

    # --- scoring --------------------------------------------------------------

    def score(self, plan: CandidatePlan) -> ScoredPlan:
        """Score one candidate: the parity-grade pending-only solve decides
        viability (and the predicted placements the dry-run reports); the
        plan's scoreboard is (slices freed, pods displaced, replacement
        placements found) per the dry-run contract."""
        if plan.no_solve:
            return ScoredPlan(plan=plan, viable=bool(plan.victims),
                              slices_freed=self._slices_freed(plan))
        return self._scored(plan, self.planner.predict(plan.pending,
                                                       plan.victims))

    def _scored(self, plan: CandidatePlan,
                prediction: Optional[Prediction]) -> ScoredPlan:
        """Viability verdict from a (possibly group-vmapped) prediction."""
        if prediction is None:
            return ScoredPlan(plan=plan, viable=False)
        viable = True
        if plan.require_all_pending and prediction.unplaced:
            viable = False
        if viable and plan.post_check is not None:
            viable = bool(plan.post_check(prediction.placements))
        return ScoredPlan(
            plan=plan, viable=viable, prediction=prediction,
            slices_freed=self._slices_freed(plan),
        )

    def _best_in_group(self, group: List[CandidatePlan],
                       budget: int):
        """Cheapest viable plan of one competing group →
        ``(ScoredPlan | None, budget_limited)``.

        The pre-round-9 cost-ordered scan ran ONE device solve per
        candidate — candidate k+1's solve only launched after candidate
        k's verdict came home, a full device round per candidate.  A
        group's solvable candidates share a pending set by construction
        (same waiting gang / same drifted constraint), so they now go
        through ONE vmapped ``WhatIfEngine.evaluate`` ([K, B, N]) and the
        verdicts are read back in cost order — same winner, one device
        round per group.  ``no_solve`` plans (drain) and groups whose
        candidates somehow carry different pending sets keep the
        sequential path."""
        group = sorted(group, key=lambda pl: len(pl.victims))
        budget_limited = False
        prepared: List[CandidatePlan] = []
        for plan in group:
            if plan.no_solve and len(plan.victims) > budget:
                # drain evictions are independent (no all-or-nothing
                # placement to enable): chunk to the budget so a big node
                # drains across syncs instead of never
                plan = dataclasses.replace(
                    plan, victims=plan.victims[:budget])
            if len(plan.victims) > budget:
                budget_limited = True
                continue
            prepared.append(plan)
        solvable = [p for p in prepared if not p.no_solve and p.pending]
        preds: Dict[int, Prediction] = {}
        if len(solvable) > 1 and all(
            [q.uid for q in p.pending] == [q.uid for q in solvable[0].pending]
            for p in solvable[1:]
        ):
            got = self._predict_group(solvable)
            if got is not None:
                preds = got
        for plan in prepared:
            if plan.no_solve:
                scored = ScoredPlan(plan=plan, viable=bool(plan.victims),
                                    slices_freed=self._slices_freed(plan))
            elif id(plan) in preds:
                scored = self._scored(plan, preds[id(plan)])
            else:
                scored = self.score(plan)
            if scored.viable:
                # cost-ordered verdict walk: the first viable plan is the
                # group's minimal victim set — costlier candidates' (already
                # computed) predictions are simply never consulted
                return scored, budget_limited
        return None, budget_limited

    def _predict_group(
        self, solvable: List[CandidatePlan]
    ) -> Optional[Dict[int, Prediction]]:
        """All of a group's candidate victim sets as ONE vmapped K-fork
        evaluate over the shared pending batch; None when the engine
        refuses (in-flight work, oversize batch) — callers fall back to
        per-plan scoring, which will refuse identically."""
        from ..whatif import ForkSpec

        t0 = self.clock()
        preds = self.planner.engine.evaluate(
            list(solvable[0].pending),
            [ForkSpec(victims=list(p.victims), note="descheduler")
             for p in solvable],
        )
        if preds is None:
            return None
        m.descheduler_planner_duration.observe(
            max(self.clock() - t0, 0.0))
        return {id(p): pr for p, pr in zip(solvable, preds)}

    def _score_replacements(self, scored: ScoredPlan) -> None:
        """Second solve on the WINNING plan only: pending + victim clones,
        counting how many displaced workloads find a home elsewhere.  Kept
        out of the viability solve so clone placement can never perturb
        the parity-grade prediction."""
        plan = scored.plan
        if not plan.replacements:
            return
        combined = self.planner.predict(
            list(plan.pending) + list(plan.replacements), plan.victims)
        if combined is None:
            return
        scored.replacements_found = sum(
            1 for clone in plan.replacements
            if combined.placements.get(clone.uid) is not None)

    def _slices_freed(self, plan: CandidatePlan) -> int:
        """Slices whose every bound pod is in the victim set — what the
        plan turns into whole-free slice groups.  The occupancy map is
        plan-independent and rebuilt at most once per sync (sync_once
        invalidates it; a sync can score dozens of candidates over the
        same store state)."""
        victims = {v.uid for v in plan.victims}
        occupants = self._occupancy
        if occupants is None:
            nodes, _ = self.store.list("Node")
            pods, _ = self.store.list("Pod")
            occupants = {}
            slice_of: Dict[str, str] = {}
            for node in nodes:
                val = node.metadata.labels.get(self.slice_label)
                if val is not None:
                    slice_of[node.metadata.name] = val
                    occupants.setdefault(val, [])
            for p in pods:
                sl = slice_of.get(p.spec.node_name or "")
                if sl is not None:
                    occupants[sl].append(p.uid)
            self._occupancy = occupants
        return sum(
            1 for sl, uids in occupants.items()
            if uids and all(uid in victims for uid in uids)
        )

    # --- the loop -------------------------------------------------------------

    def sync_once(self) -> bool:
        now = self.clock()
        if now - self._last_active < self.min_interval:
            return False
        # planner quiescence: a pipelined scheduler may return from
        # schedule_cycle with batches still in flight — complete them
        # (empty-queue cycles fetch + bind without new dispatch work)
        # before any counterfactual solve; if the pipeline won't drain,
        # skip this sync rather than plan against invisible placements
        for _ in range(4):
            if not getattr(self.scheduler, "_inflight_q", None):
                break
            self.scheduler.schedule_cycle()
        if getattr(self.scheduler, "_inflight_q", None):
            return False
        budget = self.max_evictions_per_sync
        self.last_plans = {}
        self._occupancy = None  # fresh store state this sync
        changed = False
        for policy in self.policies:
            if budget <= 0:
                break
            try:
                plans = policy.propose(PolicyContext(
                    self.store, self.scheduler.gangs, self.evictions,
                    self.clock, dry_run=self.dry_run))
            except Exception as e:
                # one broken policy must not take the loop down
                klog.V(1).info_s("Descheduler policy propose failed",
                                 policy=policy.name,
                                 error=f"{type(e).__name__}: {e}")
                continue
            # plans sharing a group compete; the cheapest VIABLE plan per
            # group applies, so one sync serves several independent
            # demands (one slice per waiting gang, one repair per drifted
            # constraint, one drain per annotated node) within the budget
            by_group: Dict[str, List[CandidatePlan]] = {}
            for i, plan in enumerate(plans):
                by_group.setdefault(plan.group or f"#{i}", []).append(plan)
            any_viable = False
            budget_limited = False
            for group in by_group.values():
                if budget <= 0:
                    budget_limited = True
                    break
                best, limited = self._best_in_group(group, budget)
                budget_limited = budget_limited or limited
                if best is None:
                    continue
                any_viable = True
                self._score_replacements(best)
                self.last_plans[policy.name] = best
                if self.dry_run:
                    m.descheduler_plans.inc((policy.name, "dry_run"))
                    klog.V(2).info_s(
                        "Descheduler dry-run plan", policy=policy.name,
                        note=best.plan.note, victims=best.displaced,
                        slices_freed=best.slices_freed,
                        replacements=best.replacements_found)
                    continue
                applied = self._apply(best)
                changed = changed or applied > 0
                budget -= applied
                if applied:
                    self._last_active = now
            if plans and not any_viable and not budget_limited:
                # only genuine no-placement outcomes count as no_fit —
                # plans skipped by the rate limiter were never solved
                m.descheduler_plans.inc((policy.name, "no_fit"))
        return changed

    def _apply(self, scored: ScoredPlan) -> int:
        """Evict the plan's victims through the gate; fail-stop on the
        first refusal or fault (outcome "abandoned")."""
        plan = scored.plan
        applied = 0
        for victim in plan.victims:
            try:
                result = self.evictions.evict(
                    victim, reason=plan.note, policy=plan.policy)
            except Exception as e:
                klog.V(1).info_s("Descheduler eviction fault; plan abandoned",
                                 policy=plan.policy, pod=victim.key(),
                                 error=f"{type(e).__name__}: {e}")
                m.descheduler_plans.inc((plan.policy, "abandoned"))
                return applied
            if not result.evicted:
                # a refusal mid-plan (budget raced since scoring) or a
                # store fault surfaced as a result: stop here — the next
                # sync re-plans from live state
                klog.V(1).info_s("Descheduler plan abandoned",
                                 policy=plan.policy, pod=victim.key(),
                                 reason=result.reason)
                m.descheduler_plans.inc((plan.policy, "abandoned"))
                return applied
            applied += 1
            # kill-point: some victims evicted, the rest of the plan (and
            # the whole controller) dies — the fail-stop contract means a
            # recovered process re-plans from live state and never resumes
            # this victim list; already-evicted pods are gone exactly once
            from ..chaos.faults import maybe_crash

            maybe_crash("crash.mid_plan_apply")
        self._occupancy = None  # evictions changed the occupancy map
        m.descheduler_plans.inc((plan.policy, "applied"))
        klog.V(2).info_s("Descheduler plan applied", policy=plan.policy,
                         note=plan.note, victims=applied,
                         slices_freed=scored.slices_freed,
                         replacements=scored.replacements_found)
        return applied
