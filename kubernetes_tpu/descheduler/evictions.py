"""The shared eviction gate — every pod-killing path goes through here.

Reference: pkg/registry/core/pod/storage/eviction.go (the Eviction
subresource REST handler): an eviction request checks every matching
PodDisruptionBudget's ``status.disruptionsAllowed``, and either deletes the
pod (atomically draining one unit of budget so a burst of evictions within
one disruption-controller resync interval cannot overshoot) or refuses with
429 TooManyRequests.  The disruption controller
(controllers/disruption.py) replenishes budgets as replacements schedule.

Callers in-tree:
  - controllers/nodelifecycle.py — NoExecute eviction from the zone-queue
    node sweeps, the tolerationSeconds timed queue, and atomic gang
    repairs (refused pods survive the sweep and retry when budget
    replenishes; upstream's taint manager deletes unconditionally —
    documented deviation, see ISSUE 5's one-sync-zeroes-a-PDB bug),
  - scheduler preemption (_run_post_filter) — ``override_pdb=True``: the
    dry-run already *minimized* PDB violations in ranking, and upstream
    preemption may violate budgets as a last resort, so the gate records
    the violation ("overridden") instead of refusing,
  - descheduler policies (descheduler/controller.py),
  - ``ktpu drain`` and the apiserver's POST pods/{name}/eviction route.

Exactly-once: the pod delete is the store's atomic pop — a pod already
gone returns result "missing" and consumes no budget, so two racing paths
can never both count an eviction for the same pod.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

from ..analysis import lockcheck
from ..api import objects as v1
from ..api.labels import match_label_selector
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m


@dataclass
class EvictionResult:
    """Outcome of one gate pass.

    ``allowed`` is the PDB-gate verdict (True in dry-run when the eviction
    WOULD proceed); ``evicted`` is whether the pod was actually deleted;
    ``reason`` explains a refusal; ``blocking_pdb`` names the exhausted
    budget ("ns/name") when refused or overridden."""

    allowed: bool
    evicted: bool = False
    reason: str = ""
    blocking_pdb: Optional[str] = None


# ONE process-wide budget lock shared by every EvictionAPI instance: the
# callers each construct their own gate over the same store (scheduler,
# apiserver, nodelifecycle, descheduler, CLI), and the read-modify-write
# on a PDB's disruptionsAllowed must serialize ACROSS them — two paths
# both observing disruptionsAllowed == 1 must not evict two pods against
# a budget of one.  (Per-instance locks would only serialize a caller
# against itself.)
_BUDGET_LOCK = lockcheck.maybe_wrap(threading.Lock(),
                                    "EvictionAPI._budget_lock")


class EvictionAPI:
    """PDB-consulting eviction gate over an ObjectStore-shaped store."""

    def __init__(self, store, recorder=None, clock=time.monotonic):
        self._store = store
        self._recorder = recorder
        self._clock = clock
        self._lock = _BUDGET_LOCK

    # --- gate queries ---------------------------------------------------------

    def matching_pdbs(
        self, pod: v1.Pod,
        pdbs: Optional[Sequence[v1.PodDisruptionBudget]] = None,
    ) -> List[v1.PodDisruptionBudget]:
        if pdbs is None:
            pdbs = self._store.list("PodDisruptionBudget")[0]
        return [
            p for p in pdbs
            if p.metadata.namespace == pod.namespace
            and p.selector is not None
            and match_label_selector(p.selector, pod.metadata.labels)
        ]

    def blocking_pdb(
        self, pod: v1.Pod,
        pdbs: Optional[Sequence[v1.PodDisruptionBudget]] = None,
    ) -> Optional[v1.PodDisruptionBudget]:
        """The first matching PDB with no disruption budget left, else None."""
        for p in self.matching_pdbs(pod, pdbs):
            if p.disruptions_allowed <= 0:
                return p
        return None

    def can_evict(
        self, pod: v1.Pod,
        pdbs: Optional[Sequence[v1.PodDisruptionBudget]] = None,
    ) -> bool:
        return self.blocking_pdb(pod, pdbs) is None

    # --- the gate -------------------------------------------------------------

    def evict(
        self,
        pod: v1.Pod,
        reason: str = "",
        policy: str = "api",
        dry_run: bool = False,
        override_pdb: bool = False,
        pdbs: Optional[Sequence[v1.PodDisruptionBudget]] = None,
    ) -> EvictionResult:
        """One eviction through the gate.

        ``pdbs`` lets batch callers (preemption's per-victim loop) reuse
        one PDB list instead of re-listing per pod; the budget write-back
        still goes through the store.  ``override_pdb`` proceeds past an
        exhausted budget but records it (result "overridden").
        """
        with self._lock:
            if self._store.get("Pod", pod.namespace,
                               pod.metadata.name) is None:
                # the reference 404s before any PDB math; this is also the
                # exactly-once guard for racing eviction paths
                m.descheduler_evictions.inc((policy, "missing"))
                return EvictionResult(allowed=True, evicted=False,
                                      reason="pod already gone")
            if pdbs is None:
                # ONE list per eviction, shared by the gate check and the
                # budget drain — both run under the budget lock
                pdbs = self._store.list("PodDisruptionBudget")[0]
            blocking = self.blocking_pdb(pod, pdbs)
            if blocking is not None and not override_pdb:
                why = (f"Cannot evict pod as it would violate the pod's "
                       f"disruption budget "
                       f"{blocking.metadata.namespace}/"
                       f"{blocking.metadata.name}")
                m.descheduler_evictions.inc((policy, "refused"))
                self._event(pod, "Warning", "EvictionBlocked",
                            f"{why} ({reason})" if reason else why)
                return EvictionResult(
                    allowed=False, reason=why,
                    blocking_pdb=blocking.metadata.namespace + "/"
                    + blocking.metadata.name)
            if dry_run:
                m.descheduler_evictions.inc((policy, "dry_run"))
                return EvictionResult(allowed=True)
            # drain one budget unit from every matching PDB NOW (the
            # reference decrements disruptionsAllowed in the same
            # GuaranteedUpdate as the delete): a burst inside one
            # disruption-controller resync interval sees the drained value
            self._consume_budget(pod, pdbs)
            try:
                gone = self._store.delete(
                    "Pod", pod.namespace, pod.metadata.name)
            except Exception as e:
                # store fault past the client's own retries: surface it as
                # a result (callers abandon their plan) — the budget unit
                # stays drained until the next disruption-controller sync,
                # which recomputes it from live pods (safe: under-, never
                # over-admits disruptions)
                m.descheduler_evictions.inc((policy, "error"))
                klog.V(1).info_s("Eviction store delete failed",
                                 pod=pod.key(), policy=policy,
                                 error=f"{type(e).__name__}: {e}")
                return EvictionResult(
                    allowed=True, evicted=False,
                    reason=f"store delete failed: {type(e).__name__}: {e}")
            if gone is None:
                m.descheduler_evictions.inc((policy, "missing"))
                return EvictionResult(allowed=True, evicted=False,
                                      reason="pod already gone")
            result = "overridden" if blocking is not None else "evicted"
            m.descheduler_evictions.inc((policy, result))
            self._event(pod, "Normal", "Evicted",
                        f"Evicted by {policy}: {reason}" if reason
                        else f"Evicted by {policy}")
            return EvictionResult(
                allowed=True, evicted=True,
                blocking_pdb=(blocking.metadata.namespace + "/"
                              + blocking.metadata.name)
                if blocking is not None else None)

    def _consume_budget(self, pod: v1.Pod, pdbs) -> None:
        for pdb in self.matching_pdbs(pod, pdbs):
            if pdb.disruptions_allowed <= 0:
                continue  # overridden eviction: nothing left to drain
            pdb.disruptions_allowed -= 1
            try:
                self._store.update("PodDisruptionBudget", pdb)
            except Exception as e:
                # best-effort write-back: the disruption controller's next
                # sync recomputes the status from live pods either way
                klog.V(2).info_s("PDB budget write-back failed",
                                 pdb=f"{pdb.metadata.namespace}/"
                                     f"{pdb.metadata.name}",
                                 error=f"{type(e).__name__}: {e}")

    def _event(self, pod: v1.Pod, etype: str, evreason: str, msg: str) -> None:
        if self._recorder is None:
            return
        try:
            self._recorder.eventf(pod, etype, evreason, msg)
        except Exception as e:
            # the recorder is best-effort by contract (client/events.py);
            # an event write must never fail the eviction itself
            klog.V(2).info_s("Eviction event drop",
                             pod=pod.key(),
                             error=f"{type(e).__name__}: {e}")
