"""API scheme: (group/version, kind) → type registry with dispatch decode.

Reference: staging/src/k8s.io/apimachinery/pkg/runtime (runtime.Scheme —
AddKnownTypes, ObjectKinds, the decode path every component uses to turn
manifests into typed objects).  This build's types carry their own
``from_dict`` converters; the scheme adds what they lack alone:

  - GVK dispatch: one ``decode(manifest)`` entry for any registered kind;
  - apiVersion validation: a manifest claiming the wrong GROUP for its kind
    is rejected (kind "Deployment" under "batch/v1" is an error, exactly as
    the reference scheme would fail to find the GVK), while version drift
    within the right group is tolerated the way the internal types here are
    version-agnostic (one internal type per kind, like apimachinery's
    internal versions);
  - discoverability: ``recognized()`` lists every (apiVersion, kind).
"""

from __future__ import annotations

import threading

from typing import Dict, List, Tuple, Type

from . import objects as v1


class SchemeError(Exception):
    pass


class Scheme:
    def __init__(self, converter=None):
        # kind → (group, canonical version, type).  The registry is read
        # from watch-decode threads (client watch_kind → decode) while
        # late registrations may still land on the main thread, so every
        # _kinds access holds _lock — registration is startup-cheap and
        # decode's lookup is one dict get under an uncontended lock.
        self._lock = threading.Lock()
        self._kinds: Dict[str, Tuple[str, str, Type]] = {}
        # bumped on every add/remove so consumers caching derived maps
        # (apiserver resource routing) can invalidate without a callback
        # registry — the CRD registrar makes the kind set dynamic
        self.generation = 0
        # spoke-version conversion registry (api/conversion.py); None = the
        # scheme serves canonical versions only
        self.converter = converter

    def add_known_type(self, group: str, version: str, typ: Type) -> "Scheme":
        """AddKnownTypes analog; the type's ``kind`` attribute names it.
        Duplicate kinds are rejected so a later registration cannot silently
        shadow an earlier one."""
        with self._lock:
            prev = self._kinds.get(typ.kind)
            if prev is not None and prev[2] is not typ:
                raise SchemeError(
                    f"kind {typ.kind!r} already registered for group "
                    f"{prev[0]!r} as {prev[2].__name__}"
                )
            if prev is not None and prev[:2] != (group, version):
                # one GVK per type: re-registering the same type under a
                # different group/version would silently change which
                # apiVersion decode() validates against
                raise SchemeError(
                    f"type {typ.__name__} already registered as "
                    f"({prev[0]!r}, {prev[1]!r}); cannot re-register as "
                    f"({group!r}, {version!r})"
                )
            if prev is None:
                self.generation += 1
            self._kinds[typ.kind] = (group, version, typ)
        return self

    def remove_known_type(self, kind: str):
        """Unregister a kind (CRD deletion).  Returns the removed type, or
        None when the kind was not registered — removal is idempotent so a
        replayed CRD-delete converges instead of erroring."""
        with self._lock:
            entry = self._kinds.pop(kind, None)
            if entry is not None:
                self.generation += 1
        return None if entry is None else entry[2]

    def gv_of(self, typ: Type):
        """(group, version) a type is served under, or None (ObjectKinds)."""
        with self._lock:
            entry = self._kinds.get(getattr(typ, "kind", None))
        if entry is None or entry[2] is not typ:
            return None
        return entry[0], entry[1]

    def kind_types(self) -> Dict[str, Tuple[str, str, Type]]:
        """Snapshot of kind → (group, version, type) — the registrar and
        the apiserver's routing rebuild read it; pair with ``generation``
        to cache derived maps."""
        with self._lock:
            return dict(self._kinds)

    def recognized(self) -> List[str]:
        with self._lock:
            entries = list(self._kinds.items())
        return sorted(
            f"{g + '/' if g else ''}{ver}:{kind}"
            for kind, (g, ver, _t) in entries
        )

    def decode(self, manifest: dict):
        """Typed object from a manifest dict, validating kind + apiVersion
        group.  An absent apiVersion is tolerated (the internal types are
        version-agnostic); a WRONG group is an error — that manifest would
        not decode under the reference scheme either."""
        kind = manifest.get("kind")
        if not kind:
            raise SchemeError("manifest has no kind")
        with self._lock:
            entry = self._kinds.get(kind)
            known = sorted(self._kinds) if entry is None else ()
        if entry is None:
            raise SchemeError(
                f"no kind {kind!r} is registered "
                f"(known: {', '.join(known)})"
            )
        group, _version, typ = entry
        api = manifest.get("apiVersion", "")
        if api:
            # a registered SPOKE version converts to the canonical (hub)
            # manifest first (api/conversion.py — the apimachinery
            # conversion path every decode runs through)
            if self.converter is not None and self.converter.has(kind, api):
                manifest = self.converter.to_hub(kind, api, manifest)
                api = manifest.get("apiVersion", "")
            mgroup = api.split("/", 1)[0] if "/" in api else ""
            if mgroup != group:
                want = f"{group + '/' if group else ''}<version>"
                raise SchemeError(
                    f"kind {kind} belongs to group {want!r}, "
                    f"manifest says apiVersion {api!r}"
                )
        return typ.from_dict(manifest)

    def convert_manifest(self, obj_or_manifest, target_api_version: str):
        """Re-serve an object (or its canonical manifest) at a registered
        spoke apiVersion — the read side of conversion (a client asking for
        autoscaling/v1 gets the v1 shape of a v2-stored object)."""
        from .serialize import to_manifest

        manifest = (obj_or_manifest if isinstance(obj_or_manifest, dict)
                    else to_manifest(obj_or_manifest, self))
        kind = manifest.get("kind")
        canonical = manifest.get("apiVersion", "")
        if target_api_version == canonical:
            return manifest
        if self.converter is None or not self.converter.has(
                kind, target_api_version):
            raise SchemeError(
                f"kind {kind!r} is not served at {target_api_version!r}")
        return self.converter.from_hub(kind, target_api_version, manifest)


def default_scheme() -> Scheme:
    """All served kinds (the analog of each API group's AddToScheme), with
    the in-tree spoke-version conversions attached."""
    from .conversion import default_converter

    s = Scheme(converter=default_converter())
    for typ in (v1.Pod, v1.Node, v1.Service, v1.PersistentVolume,
                v1.PersistentVolumeClaim, v1.Namespace, v1.ResourceQuota,
                v1.Endpoints, v1.ServiceAccount):
        s.add_known_type("", "v1", typ)
    s.add_known_type("discovery.k8s.io", "v1", v1.EndpointSlice)
    s.add_known_type("batch", "v1", v1.CronJob)
    s.add_known_type("storage.k8s.io", "v1", v1.StorageClass)
    s.add_known_type("storage.k8s.io", "v1", v1.CSINode)
    s.add_known_type("policy", "v1", v1.PodDisruptionBudget)
    # the eviction subresource body (descheduler/evictions.py is the gate)
    s.add_known_type("policy", "v1", v1.Eviction)
    s.add_known_type("scheduling.k8s.io", "v1", v1.PriorityClass)
    # coscheduling CRD (sigs.k8s.io/scheduler-plugins) — the gang unit
    s.add_known_type("scheduling.x-k8s.io", "v1alpha1", v1.PodGroup)
    # dynamic resource allocation (resource.k8s.io — DeviceClass selectors,
    # per-node ResourceSlice inventories, ResourceClaim allocation results)
    from ..dra.api import (DeviceClass, ResourceClaim, ResourceClaimTemplate,
                           ResourceSlice)

    for typ in (DeviceClass, ResourceClaim, ResourceClaimTemplate,
                ResourceSlice):
        s.add_known_type("resource.k8s.io", "v1alpha2", typ)
    # cluster-autoscaler capacity unit (kubernetes_tpu/autoscaler)
    from ..autoscaler.api import NodeGroup

    s.add_known_type("autoscaling.x-k8s.io", "v1alpha1", NodeGroup)
    for typ in (v1.ReplicaSet, v1.Deployment, v1.StatefulSet, v1.DaemonSet):
        s.add_known_type("apps", "v1", typ)
    s.add_known_type("batch", "v1", v1.Job)
    from ..controllers.podautoscaler import HorizontalPodAutoscaler

    s.add_known_type("autoscaling", "v2", HorizontalPodAutoscaler)
    # tenant-definable kinds (apiextensions-apiserver): the CRD object
    # itself is a built-in; the kinds it DEFINES are installed dynamically
    # by apiextensions/registrar.py
    from ..apiextensions.api import CustomResourceDefinition

    s.add_known_type("apiextensions.k8s.io", "v1", CustomResourceDefinition)
    from ..auth.api import (ClusterRole, ClusterRoleBinding, Role,
                            RoleBinding)

    for typ in (Role, ClusterRole, RoleBinding, ClusterRoleBinding):
        s.add_known_type("rbac.authorization.k8s.io", "v1", typ)
    return s
