"""Versioned-manifest conversion machinery (hub-and-spoke).

Reference: staging/src/k8s.io/apimachinery/pkg/runtime conversion +
per-group conversion funcs (e.g. pkg/apis/autoscaling/v1/conversion.go,
which maps autoscaling/v1's targetCPUUtilizationPercentage onto the
internal metrics list).  The internal types here are version-agnostic (one
type per kind, like apimachinery's internal versions), so the hub is the
CANONICAL manifest (the apiVersion the scheme serves the kind under) and
each registered spoke version carries two manifest→manifest functions:

    to_hub(spoke_manifest)  -> canonical manifest
    from_hub(hub_manifest)  -> spoke manifest

``Scheme.decode`` routes a spoke-version manifest through ``to_hub`` before
the type's ``from_dict``; ``convert_manifest`` re-serves any object's
manifest at a requested spoke version.  Round-trip (spoke → hub → spoke)
preserves every field a spoke can express, the same contract apimachinery's
fuzzed round-trip tests pin.
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, Tuple


class ConversionError(Exception):
    pass


class VersionConverter:
    """Registry of spoke versions per kind."""

    def __init__(self):
        # (kind, spoke apiVersion) → (to_hub, from_hub)
        self._spokes: Dict[Tuple[str, str], Tuple[Callable, Callable]] = {}

    def register(self, kind: str, spoke_api_version: str,
                 to_hub: Callable[[dict], dict],
                 from_hub: Callable[[dict], dict]) -> "VersionConverter":
        key = (kind, spoke_api_version)
        if key in self._spokes:
            raise ConversionError(f"conversion {key} already registered")
        self._spokes[key] = (to_hub, from_hub)
        return self

    def spoke_versions(self, kind: str):
        return sorted(v for (k, v) in self._spokes if k == kind)

    def has(self, kind: str, api_version: str) -> bool:
        return (kind, api_version) in self._spokes

    def to_hub(self, kind: str, api_version: str, manifest: dict) -> dict:
        fn = self._spokes.get((kind, api_version))
        if fn is None:
            raise ConversionError(
                f"no conversion from {api_version!r} for kind {kind!r}")
        return fn[0](copy.deepcopy(manifest))

    def from_hub(self, kind: str, api_version: str, manifest: dict) -> dict:
        fn = self._spokes.get((kind, api_version))
        if fn is None:
            raise ConversionError(
                f"no conversion to {api_version!r} for kind {kind!r}")
        return fn[1](copy.deepcopy(manifest))


# --- the in-tree spoke conversions ------------------------------------------


def _hpa_v1_to_hub(m: dict) -> dict:
    """autoscaling/v1 → autoscaling/v2: targetCPUUtilizationPercentage
    becomes the single cpu Resource metric (the reference's
    pkg/apis/autoscaling/v1/conversion.go direction)."""
    spec = m.get("spec") or {}
    target = spec.pop("targetCPUUtilizationPercentage", None)
    if target is not None:
        spec["metrics"] = [{
            "type": "Resource",
            "resource": {"name": "cpu",
                         "target": {"type": "Utilization",
                                    "averageUtilization": int(target)}},
        }]
    m["spec"] = spec
    m["apiVersion"] = "autoscaling/v2"
    status = m.get("status")
    if status and "currentCPUUtilizationPercentage" in status:
        cur = status.pop("currentCPUUtilizationPercentage")
        status["currentMetrics"] = [{
            "type": "Resource",
            "resource": {"name": "cpu",
                         "current": {"averageUtilization": int(cur)}},
        }]
    return m


def _hpa_v1_from_hub(m: dict) -> dict:
    """autoscaling/v2 → autoscaling/v1: only the cpu-utilization Resource
    metric survives (exactly what the v1 schema can express; other metric
    types are dropped, as the reference conversion stores them in an
    annotation this build does not round-trip)."""
    spec = m.get("spec") or {}
    for mtr in spec.pop("metrics", []) or []:
        res = mtr.get("resource") or {}
        tgt = res.get("target") or {}
        if res.get("name") == "cpu" and "averageUtilization" in tgt:
            spec["targetCPUUtilizationPercentage"] = int(
                tgt["averageUtilization"])
            break
    m["spec"] = spec
    m["apiVersion"] = "autoscaling/v1"
    status = m.get("status")
    if status:
        for mtr in status.pop("currentMetrics", []) or []:
            res = mtr.get("resource") or {}
            cur = res.get("current") or {}
            if res.get("name") == "cpu" and "averageUtilization" in cur:
                status["currentCPUUtilizationPercentage"] = int(
                    cur["averageUtilization"])
                break
    return m


def _rename_api_version(target: str) -> Callable[[dict], dict]:
    def fn(m: dict) -> dict:
        m["apiVersion"] = target
        return m
    return fn


def default_converter() -> VersionConverter:
    c = VersionConverter()
    # the real structural conversion the reference ships for autoscaling
    c.register("HorizontalPodAutoscaler", "autoscaling/v1",
               _hpa_v1_to_hub, _hpa_v1_from_hub)
    # graduated-as-is groups: the v1beta1 schemas are field-identical to v1
    # (the reference conversions are generated identity functions); the
    # spoke exists so old manifests decode and old clients are served
    c.register("CronJob", "batch/v1beta1",
               _rename_api_version("batch/v1"),
               _rename_api_version("batch/v1beta1"))
    c.register("PodDisruptionBudget", "policy/v1beta1",
               _rename_api_version("policy/v1"),
               _rename_api_version("policy/v1beta1"))
    c.register("EndpointSlice", "discovery.k8s.io/v1beta1",
               _rename_api_version("discovery.k8s.io/v1"),
               _rename_api_version("discovery.k8s.io/v1beta1"))
    return c
