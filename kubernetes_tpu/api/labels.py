"""Host-side label/selector evaluation.

Reference semantics: apimachinery ``labels.Selector`` / ``metav1.LabelSelectorAsSelector``
and core v1 ``NodeSelectorRequirement`` matching (component-helpers
scheduling/corev1/nodeaffinity). These host-side evaluators are the parity oracle for
the compiled tensor versions in ``state/selectors.py``.
"""

from __future__ import annotations

from typing import Mapping, Optional

from .objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    LabelSelector,
    NodeSelector,
    NodeSelectorTerm,
    Node,
)


def match_label_selector(
    selector: Optional[LabelSelector], labels: Mapping[str, str]
) -> bool:
    """metav1 LabelSelector match: None → matches nothing; empty → everything."""
    if selector is None:
        return False
    for k, v in selector.match_labels.items():
        if labels.get(k) != v:
            return False
    for req in selector.match_expressions:
        has = req.key in labels
        val = labels.get(req.key)
        if req.operator == OP_IN:
            if not has or val not in req.values:
                return False
        elif req.operator == OP_NOT_IN:
            if has and val in req.values:
                return False
        elif req.operator == OP_EXISTS:
            if not has:
                return False
        elif req.operator == OP_DOES_NOT_EXIST:
            if has:
                return False
        else:
            return False
    return True


def _match_node_selector_requirement(req, labels: Mapping[str, str]) -> bool:
    has = req.key in labels
    val = labels.get(req.key)
    if req.operator == OP_IN:
        return has and val in req.values
    if req.operator == OP_NOT_IN:
        # apimachinery labels.Requirement.Matches: NotIn matches when the key is
        # absent (reference: labels/selector.go Matches, selection.NotIn case).
        return (not has) or val not in req.values
    if req.operator == OP_EXISTS:
        return has
    if req.operator == OP_DOES_NOT_EXIST:
        return not has
    if req.operator in (OP_GT, OP_LT):
        # Reference: nodeaffinity.go — both label value and the single requirement
        # value must parse as integers.
        if not has or len(req.values) != 1:
            return False
        try:
            lhs = int(val)
            rhs = int(req.values[0])
        except (TypeError, ValueError):
            return False
        return lhs > rhs if req.operator == OP_GT else lhs < rhs
    return False


def match_node_selector_term(
    term: NodeSelectorTerm, node: Node
) -> bool:
    """All expressions AND all fields must match (empty term matches nothing)."""
    if not term.match_expressions and not term.match_fields:
        return False
    for req in term.match_expressions:
        if not _match_node_selector_requirement(req, node.metadata.labels):
            return False
    for req in term.match_fields:
        # Only metadata.name is a valid field selector (reference nodeaffinity.go).
        fields = {"metadata.name": node.metadata.name}
        if not _match_node_selector_requirement(req, fields):
            return False
    return True


def affinity_term_matches(
    term,
    owner_pod,
    target_pod,
    namespace_labels: Optional[Mapping[str, Mapping[str, str]]] = None,
) -> bool:
    """framework.AffinityTerm.Matches semantics (framework/types.go):

    target matches when (target.ns ∈ term.namespaces — defaulted to owner's ns when
    both namespaces and namespaceSelector are unset — OR namespaceSelector matches
    the target namespace's labels) AND labelSelector matches target's labels.
    An empty-but-set namespaceSelector selects every namespace.
    """
    ns_ok = False
    if term.namespaces:
        ns_ok = target_pod.namespace in term.namespaces
    elif term.namespace_selector is None:
        ns_ok = target_pod.namespace == owner_pod.namespace
    if not ns_ok and term.namespace_selector is not None:
        # an empty-but-set selector matches every namespace — match_label_selector
        # already returns True for the empty non-None selector
        labels = (namespace_labels or {}).get(target_pod.namespace, {})
        ns_ok = match_label_selector(term.namespace_selector, labels)
    if not ns_ok:
        return False
    return match_label_selector(term.label_selector, target_pod.metadata.labels)


def match_node_selector(selector: Optional[NodeSelector], node: Node) -> bool:
    """Terms OR together; nil selector matches everything, empty terms list nothing."""
    if selector is None:
        return True
    return any(
        match_node_selector_term(t, node) for t in selector.node_selector_terms
    )
