"""Host-side label/selector evaluation.

Reference semantics: apimachinery ``labels.Selector`` / ``metav1.LabelSelectorAsSelector``
and core v1 ``NodeSelectorRequirement`` matching (component-helpers
scheduling/corev1/nodeaffinity). These host-side evaluators are the parity oracle for
the compiled tensor versions in ``state/selectors.py``.
"""

from __future__ import annotations

from typing import Mapping, Optional

from .objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    LabelSelector,
    NodeSelector,
    NodeSelectorTerm,
    Node,
)


def match_label_selector(
    selector: Optional[LabelSelector], labels: Mapping[str, str]
) -> bool:
    """metav1 LabelSelector match: None → matches nothing; empty → everything."""
    if selector is None:
        return False
    for k, v in selector.match_labels.items():
        if labels.get(k) != v:
            return False
    for req in selector.match_expressions:
        has = req.key in labels
        val = labels.get(req.key)
        if req.operator == OP_IN:
            if not has or val not in req.values:
                return False
        elif req.operator == OP_NOT_IN:
            if has and val in req.values:
                return False
        elif req.operator == OP_EXISTS:
            if not has:
                return False
        elif req.operator == OP_DOES_NOT_EXIST:
            if has:
                return False
        else:
            return False
    return True


def _match_node_selector_requirement(req, labels: Mapping[str, str]) -> bool:
    has = req.key in labels
    val = labels.get(req.key)
    if req.operator == OP_IN:
        return has and val in req.values
    if req.operator == OP_NOT_IN:
        # apimachinery labels.Requirement.Matches: NotIn matches when the key is
        # absent (reference: labels/selector.go Matches, selection.NotIn case).
        return (not has) or val not in req.values
    if req.operator == OP_EXISTS:
        return has
    if req.operator == OP_DOES_NOT_EXIST:
        return not has
    if req.operator in (OP_GT, OP_LT):
        # Reference: nodeaffinity.go — both label value and the single requirement
        # value must parse as integers.
        if not has or len(req.values) != 1:
            return False
        try:
            lhs = int(val)
            rhs = int(req.values[0])
        except (TypeError, ValueError):
            return False
        return lhs > rhs if req.operator == OP_GT else lhs < rhs
    return False


def match_node_selector_term(
    term: NodeSelectorTerm, node: Node
) -> bool:
    """All expressions AND all fields must match (empty term matches nothing)."""
    if not term.match_expressions and not term.match_fields:
        return False
    for req in term.match_expressions:
        if not _match_node_selector_requirement(req, node.metadata.labels):
            return False
    for req in term.match_fields:
        # Only metadata.name is a valid field selector (reference nodeaffinity.go).
        fields = {"metadata.name": node.metadata.name}
        if not _match_node_selector_requirement(req, fields):
            return False
    return True


def match_node_selector(selector: Optional[NodeSelector], node: Node) -> bool:
    """Terms OR together; nil selector matches everything, empty terms list nothing."""
    if selector is None:
        return True
    return any(
        match_node_selector_term(t, node) for t in selector.node_selector_terms
    )
