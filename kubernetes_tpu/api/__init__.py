"""API object model (reference L0: staging/src/k8s.io/api + apimachinery).

``api.wire`` is the binary wire codec + per-client content negotiation
(the protobuf-serializer analogue, round 19) — imported as a module, not
re-exported names, so the codec surface stays one namespace.
"""

from . import wire  # noqa: F401
from .objects import (  # noqa: F401
    Affinity,
    Container,
    ContainerImage,
    ContainerPort,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    CSINode,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodDisruptionBudget,
    PodSpec,
    PodStatus,
    Deployment,
    Job,
    PodTemplateSpec,
    PreferredSchedulingTerm,
    PriorityClass,
    ReplicaSet,
    ResourceRequirements,
    Service,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    Volume,
    WeightedPodAffinityTerm,
)
from .resource import (  # noqa: F401
    Resource,
    compute_pod_resource_request,
    compute_pod_resource_request_non_zero,
    parse_quantity,
    quantity_to_int,
    quantity_to_milli,
)
from .labels import (  # noqa: F401
    match_label_selector,
    match_node_selector,
    match_node_selector_term,
)
